"""graphcheck FLOPs pass: analytical per-primitive FLOPs from the jaxpr.

Why it exists (ROADMAP item 1, "honest MFU"): the only FLOPs source the
repo had was XLA's cost model (`compiled.cost_analysis()`), whose
availability varies by backend/version — which is exactly why `mfu` has
been null on every round where capture failed. Shapes don't vary: a
`dot_general`'s FLOPs are arithmetic over its avals, a conv's over its
output grid and kernel. This pass walks the closed jaxpr (recursing
through pjit/custom-grad calls, multiplying scanned bodies by their trip
count) and counts:

- `dot`: 2 * batch * M * N * K per dot_general;
- `conv`: 2 * out_elements * kernel_spatial * (C_in / feature_groups)
  per conv_general_dilated (the backward data/filter convs are plain
  conv eqns in the differentiated jaxpr, so fwd+bwd is counted
  naturally, remat recompute included);
- `elementwise`/`reduce`: 1 FLOP per output (resp. input) element for
  the plain arithmetic primitives — keeps parity with the XLA cost
  model tight on conv nets where BN/activation traffic is a few
  percent. Transcendentals (exp/log/...) are deliberately *excluded*:
  XLA books them under "transcendental", not "flops", and the parity
  check compares against "flops".

`while` bodies can't be statically counted (trip count is dynamic);
they are counted ONCE and surfaced in `caveats` — a lying silent zero
is worse than a flagged lower bound. `cond` takes the max branch.

The result cross-checks against the cost model where capture succeeds
(graphcheck's flops pass findings) and becomes `mfu_analytic`'s
numerator in the trainer/multichip bench lanes when capture fails.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional, Tuple

# 1-FLOP-per-element arithmetic primitives (XLA cost-model "flops" class).
# Selects and comparisons ARE counted: the guard-armed train step wraps
# every state leaf in jnp.where (param-sized select_n trees), and XLA
# books those as flops — excluding them put the armed ViT-B step 35%
# under the cost model. Transcendentals (exp/log/erf/...) stay excluded:
# XLA reports them under "transcendental", not "flops".
_ELEMENTWISE_1FLOP = frozenset({
    "add", "sub", "mul", "div", "max", "min", "neg", "abs", "rem",
    "add_any", "square", "integer_pow", "pow", "rsqrt", "sqrt",
    "select_n", "eq", "ne", "lt", "le", "gt", "ge", "and", "or", "xor",
    "not", "is_finite", "sign", "floor", "ceil", "round",
})
_REDUCE_PRIMS = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
    "reduce_and", "reduce_or", "argmax", "argmin",
})
# cross-shard collectives: data movement, zero math. Registered so the
# pipelined step's stage rotation (ppermute) and the gradient syncs can
# never read as uncounted compute; tracked in eqn_counts["collective"].
_COLLECTIVE_PRIMS = frozenset({
    "ppermute", "pshuffle", "psum", "psum2", "pmax", "pmin", "pgather",
    "all_gather", "all_to_all", "reduce_scatter", "axis_index",
})


def _prod(xs) -> int:
    out = 1
    for x in xs:
        out *= int(x)
    return out


# --- pallas_call costing ----------------------------------------------------
#
# A `pallas_call` is opaque to this walk (its body is a kernel jaxpr whose
# eqns describe ONE grid program, not the whole op), so an unregistered
# Pallas kernel would silently undercount `mfu_analytic` — exactly the
# lying-numerator failure this pass exists to prevent. Every in-tree
# kernel therefore registers a per-kernel FLOPs hook here, keyed by the
# kernel FUNCTION name (eqn.params["name_and_src_info"].name), computing
# from the eqn's avals; a pallas_call with no hook becomes a finding in
# `check_flops` (and `--selftest` seeds one to prove the detector works).

PALLAS_FLOPS_HOOKS: Dict[str, Callable[[Any], float]] = {}


def register_pallas_flops(kernel_name: str,
                          fn: Callable[[Any], float]) -> None:
    """Register `fn(eqn) -> flops` for the Pallas kernel function named
    `kernel_name` (docs/KERNELS.md § adding a kernel)."""
    PALLAS_FLOPS_HOOKS[kernel_name] = fn


def pallas_kernel_name(eqn) -> str:
    nsi = eqn.params.get("name_and_src_info")
    return (getattr(nsi, "name", None) or eqn.params.get("name")
            or "<unknown>")


def _pw_kernel_flops(eqn) -> float:
    # ops/pallas_fused._pw_bn_act_kernel: x (M, Cin) @ w (Cin, Cout)
    # + per-row bias/act epilogue
    x, w = (v.aval for v in eqn.invars[:2])
    m, cin = x.shape
    cout = w.shape[-1]
    return 2.0 * m * cin * cout + 2.0 * m * cout


def _conv_kernel_flops(eqn) -> float:
    # ops/pallas_fused._conv_bn_act_kernel: taps MXU matmuls per output
    # element (w flattened (taps, Cin, Cout)) + bias/act epilogue
    w = eqn.invars[1].aval
    out = eqn.outvars[0].aval
    taps, cin, _ = w.shape
    out_elems = _prod(out.shape)
    return 2.0 * out_elems * taps * cin + 2.0 * out_elems


def _dw_kernel_flops(eqn) -> float:
    # ops/pallas_depthwise._dw_kernel / pallas_fused._dw_bn_act_kernel:
    # taps VPU FMAs per output element (k flattened (taps, C)); the
    # epilogue variant adds bias+act
    k = eqn.invars[1].aval
    out = eqn.outvars[0].aval
    return 2.0 * _prod(out.shape) * k.shape[0] + 2.0 * _prod(out.shape)


def _attn_flops(matmuls: int) -> Callable[[Any], float]:
    # ops/pallas_attention kernels: q/k (BH, Nq, D)/(BH, Nk, D); each
    # "matmul" is a (Nq, Nk) x D contraction class (fwd: QK^T + PV = 2;
    # dq: S recompute + dP + dS@K = 3; dkv: S + dV + dP + dK = 4)
    def hook(eqn) -> float:
        q, k = (v.aval for v in eqn.invars[:2])
        bh, nq, d = q.shape
        nk = k.shape[1]
        return 2.0 * matmuls * bh * nq * nk * d

    return hook


# in-tree kernels (keyed by kernel function name)
PALLAS_FLOPS_HOOKS.update({
    "_pw_bn_act_kernel": _pw_kernel_flops,
    "_conv_bn_act_kernel": _conv_kernel_flops,
    "_dw_bn_act_kernel": _dw_kernel_flops,
    "_dw_kernel": _dw_kernel_flops,
    "_fwd_kernel": _attn_flops(2),
    "_bwd_dq_kernel": _attn_flops(3),
    "_bwd_dkv_kernel": _attn_flops(4),
})


def dot_general_flops(eqn) -> float:
    """2 * batch * M * N * K from the eqn's avals + dimension_numbers."""
    lhs, rhs = (v.aval for v in eqn.invars[:2])
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    batch = _prod(lhs.shape[d] for d in lb)
    contract = _prod(lhs.shape[d] for d in lc)
    m = _prod(lhs.shape[d] for d in range(len(lhs.shape))
              if d not in set(lc) | set(lb))
    n = _prod(rhs.shape[d] for d in range(len(rhs.shape))
              if d not in set(rc) | set(rb))
    return 2.0 * batch * m * n * contract


def _conv_valid_taps(out_size: int, k: int, stride: int, pad_lo: int,
                     lhs_dil: int, rhs_dil: int, in_size: int) -> int:
    """Real multiply-adds along one spatial dim: XLA's cost model counts
    only taps that land on actual input elements — padding positions and
    the zeros interleaved by lhs_dilation (backward-data convs) cost
    nothing, so an analytic count that ignores them overshoots SAME-padded
    nets by ~15% and backward passes by more."""
    span = (in_size - 1) * lhs_dil + 1
    taps = 0
    for o in range(out_size):
        base = o * stride - pad_lo
        for d in range(k):
            p = base + d * rhs_dil
            if 0 <= p < span and p % lhs_dil == 0:
                taps += 1
    return taps


def conv_flops(eqn) -> float:
    """2 * batch * C_out * (C_in / feature_groups) * valid_taps, exactly
    the real-multiply-add count the XLA cost model reports."""
    lhs, rhs = (v.aval for v in eqn.invars[:2])
    out = eqn.outvars[0].aval
    dn = eqn.params["dimension_numbers"]
    strides = eqn.params["window_strides"]
    padding = eqn.params["padding"]
    lhs_dil = eqn.params.get("lhs_dilation") or (1,) * len(strides)
    rhs_dil = eqn.params.get("rhs_dilation") or (1,) * len(strides)
    taps = 1
    for i, (ld, rd) in enumerate(zip(dn.lhs_spec[2:], dn.rhs_spec[2:])):
        taps *= _conv_valid_taps(
            out.shape[dn.out_spec[2 + i]], rhs.shape[rd], strides[i],
            padding[i][0], lhs_dil[i], rhs_dil[i], lhs.shape[ld])
    batch = out.shape[dn.out_spec[0]]
    c_out = out.shape[dn.out_spec[1]]
    c_in_per_group = rhs.shape[dn.rhs_spec[1]]  # already / feature_groups
    return 2.0 * batch * c_out * c_in_per_group * taps


def _sub_closed(params_value) -> List[Any]:
    """ClosedJaxpr values inside one eqn-param value (tuples recursed)."""
    from jax._src import core as jcore

    out = []
    if isinstance(params_value, jcore.ClosedJaxpr):
        out.append(params_value)
    elif isinstance(params_value, (tuple, list)):
        for v in params_value:
            out.extend(_sub_closed(v))
    return out


def jaxpr_flops(closed_jaxpr) -> Dict[str, Any]:
    """Analytical FLOPs of a closed jaxpr: total + per-class breakdown +
    caveats (unstatically-countable constructs encountered)."""
    counts = {"dot": 0.0, "conv": 0.0, "elementwise": 0.0, "reduce": 0.0,
              "pallas": 0.0}
    eqn_counts = {"dot_general": 0, "conv_general_dilated": 0,
                  "pallas_call": 0, "collective": 0}
    caveats: List[str] = []
    unregistered: List[str] = []
    hook_errors: List[str] = []

    def walk(jaxpr, mult: float) -> None:
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if name == "pallas_call":
                kname = pallas_kernel_name(eqn)
                hook = PALLAS_FLOPS_HOOKS.get(kname)
                eqn_counts["pallas_call"] += 1
                if hook is None:
                    # a silent zero here would quietly deflate
                    # mfu_analytic — surface it (check_flops turns the
                    # list into findings)
                    unregistered.append(kname)
                else:
                    try:
                        counts["pallas"] += mult * float(hook(eqn))
                    except Exception as e:  # noqa: BLE001 - see below
                        # hooks key on bare kernel-function names; a name
                        # collision hands this hook an eqn whose avals it
                        # can't parse. That must surface as a finding,
                        # never crash the whole graphcheck run or book a
                        # wrong count for the colliding kernel.
                        hook_errors.append(
                            f"{kname}: {type(e).__name__}: {e}")
                continue
            if name == "dot_general":
                counts["dot"] += mult * dot_general_flops(eqn)
                eqn_counts["dot_general"] += 1
            elif name == "conv_general_dilated":
                counts["conv"] += mult * conv_flops(eqn)
                eqn_counts["conv_general_dilated"] += 1
            elif name in _ELEMENTWISE_1FLOP:
                counts["elementwise"] += mult * _prod(
                    eqn.outvars[0].aval.shape)
            elif name in _REDUCE_PRIMS:
                counts["reduce"] += mult * _prod(eqn.invars[0].aval.shape)
            elif name == "scan":
                inner = eqn.params["jaxpr"]
                walk(inner.jaxpr, mult * int(eqn.params.get("length", 1)))
            elif name == "shard_map":
                # SPMD-manual region (parallel/pipeline.py's stage
                # pipeline): the body is an OPEN Jaxpr param describing
                # ONE shard's program — the generic ClosedJaxpr recursion
                # below misses it, silently zeroing the whole pipelined
                # trunk out of mfu_analytic. Every manual mesh slice runs
                # the body once, so global FLOPs = body x manual-shard
                # count. (This counts the pipeline's fill/drain garbage
                # ticks too: they execute on the MXU, so they belong in
                # an achieved-utilization numerator — the waste is
                # reported separately as pipeline_bubble_frac.)
                mesh = eqn.params.get("mesh")
                auto = eqn.params.get("auto") or frozenset()
                shards = 1
                if mesh is not None:
                    for ax, sz in dict(mesh.shape).items():
                        if ax not in auto:
                            shards *= int(sz)
                walk(eqn.params["jaxpr"], mult * shards)
            elif name in _COLLECTIVE_PRIMS:
                # cross-shard data movement, zero math: ppermute is the
                # pipeline's stage rotation, psum/all_gather the gradient
                # sync. Counted for visibility, never as FLOPs — but
                # REGISTERED here so a new collective can't fall into the
                # generic recursion and look like an uncounted op.
                eqn_counts["collective"] += 1
            elif name == "while":
                # dynamic trip count: count the body ONCE, flag it
                caveats.append("while_loop counted once (dynamic trip "
                               "count)")
                walk(eqn.params["body_jaxpr"].jaxpr, mult)
            elif name == "cond":
                branch_totals = []
                for br in eqn.params["branches"]:
                    sub = jaxpr_flops(br)
                    branch_totals.append(sub)
                    caveats.extend(sub["caveats"])
                if branch_totals:
                    best = max(branch_totals,
                               key=lambda s: s["flops_total"])
                    for k in counts:
                        counts[k] += mult * best["by_class"][k]
                    for k in eqn_counts:
                        eqn_counts[k] += best["eqn_counts"][k]
                    unregistered.extend(best["unregistered_pallas"])
                    hook_errors.extend(best["pallas_hook_errors"])
            else:
                # generic recursion: pjit / remat / custom_jvp / custom_vjp
                # / closed_call all carry their body as ClosedJaxpr params
                for v in eqn.params.values():
                    for sub in _sub_closed(v):
                        walk(sub.jaxpr, mult)

    walk(closed_jaxpr.jaxpr, 1.0)
    total = sum(counts.values())
    return {
        "flops_total": total,
        "by_class": counts,
        "eqn_counts": eqn_counts,
        "caveats": sorted(set(caveats)),
        "unregistered_pallas": sorted(set(unregistered)),
        "pallas_hook_errors": sorted(set(hook_errors)),
    }


def check_flops(closed_jaxpr, costmodel_flops: Optional[float],
                rtol: float = 0.25, partitions: int = 1,
                ) -> Tuple[List[dict], Dict[str, Any]]:
    """The pass: analytic count + cross-check against the XLA cost model
    when capture succeeded. A finding means the two FLOPs sources disagree
    past `rtol` — one of them is lying, and MFU headlines built on either
    are not trustworthy until resolved. No cost model = no finding (the
    analytic number simply becomes the only source, `mfu_source:
    analytic`).

    `partitions`: device count the compiled program was partitioned over.
    The analytic count is GLOBAL (the whole jaxpr, counted once), while
    `cost_analysis()` reports the per-partition program — the cross-check
    compares global/partitions against it. Approximate by construction:
    replicated work (the optimizer update) runs whole on every partition
    but is spread by the division; `rtol` absorbs it."""
    analytic = jaxpr_flops(closed_jaxpr)
    summary = dict(analytic)
    summary["costmodel_flops"] = costmodel_flops
    summary["partitions"] = int(partitions)
    findings: List[dict] = []
    for kname in analytic["unregistered_pallas"]:
        findings.append({
            "pass": "flops",
            "site": f"pallas_call:{kname}",
            "message": (
                f"pallas_call kernel {kname!r} has no registered FLOPs "
                "hook: the analytic count books it as ZERO, silently "
                "deflating mfu_analytic — register one via "
                "gc_flops.register_pallas_flops (docs/KERNELS.md § "
                "adding a kernel)"),
            "details": {"kernel": kname},
        })
    for err in analytic["pallas_hook_errors"]:
        findings.append({
            "pass": "flops",
            "site": f"pallas_call:{err.split(':', 1)[0]}",
            "message": (
                f"registered FLOPs hook failed on pallas_call ({err}): "
                "likely a kernel-function NAME COLLISION handing the hook "
                "avals it can't parse — rename the kernel or register a "
                "hook that matches it (docs/KERNELS.md § adding a "
                "kernel); its FLOPs are booked as zero until fixed"),
            "details": {"error": err},
        })
    if costmodel_flops and analytic["flops_total"] > 0:
        per_part = analytic["flops_total"] / max(int(partitions), 1)
        rel = abs(per_part - costmodel_flops) / max(costmodel_flops, 1.0)
        summary["costmodel_rel_err"] = round(rel, 4)
        if rel > rtol:
            findings.append({
                "pass": "flops",
                "site": "whole-program",
                "message": (
                    f"analytic FLOPs {per_part:.3e} (global "
                    f"{analytic['flops_total']:.3e} / {partitions} "
                    f"partition(s)) vs XLA cost model "
                    f"{costmodel_flops:.3e} disagree by "
                    f"{rel:.1%} (> {rtol:.0%}): one of the two MFU "
                    "numerators is wrong"),
                "details": {"analytic": analytic["flops_total"],
                            "costmodel": costmodel_flops,
                            "partitions": int(partitions),
                            "rel_err": rel},
            })
    if summary["caveats"]:
        summary["lower_bound"] = True
    # guard against NaN/inf arithmetic surprises: the denominator of a
    # headline metric must be a finite positive number or absent
    if not math.isfinite(summary["flops_total"]):
        findings.append({
            "pass": "flops", "site": "whole-program",
            "message": "analytic FLOPs overflowed to a non-finite value",
            "details": {},
        })
    return findings, summary
