"""pva-tpu-tsan front: stress scenario, report plumbing, console script.

Three jobs:

- **Bundled stress scenario** (`run_stress`): arm the sanitizer, then
  exercise every threaded layer the way production does — prefetcher churn
  over a synthetic loader (full epoch + mid-flight break), a concurrent
  micro-batcher with a mid-flight close, the fleet tier (router + replica
  schedulers under mixed-priority clients with a hot-swap cutover and a
  membership flap racing the health poller), TrackerHub fan-out with a
  raising tracker (the disable-on-failure path), flight-recorder
  record/dump re-entrancy, and a forced watchdog stall — and report what
  the run proved. Zero findings on this scenario is a CI gate (`bench.py --smoke`,
  `scripts/analyze.sh`), same contract as `pva-tpu-lint`.
- **Report plumbing** (`publish`/`tsan_snapshot`): findings land in the
  obs registry (`pva_tsan_races`, `pva_tsan_lock_cycles` gauges), the
  flight-recorder ring, and `pva-tpu-doctor diagnose()`.
- **`pva-tpu-tsan` CLI**: runs the scenario (exit 0 clean / 1 findings /
  2 usage) or `--selftest` (the seeded race + seeded ABBA cycle fixtures
  MUST be detected — exit 0 iff the sanitizer still has teeth).

The scenario swaps FRESH obs singletons (collector, recorder) in for its
duration: instances created before arming hold raw, untracked locks, and
accesses guarded by an invisible lock would read as unguarded (a false
positive by construction, not evidence).
"""

from __future__ import annotations

import argparse
import json
import sys
import tempfile
import time
from typing import Callable, List, Optional

from pytorchvideo_accelerate_tpu.analysis import tsan as tsan_mod
from pytorchvideo_accelerate_tpu.utils.sync import (
    make_lock,
    make_queue,
    make_thread,
    shared_state,
)


# --- seeded fixtures (the sanitizer's own regression teeth) -----------------

@shared_state("counter")
class _RaceFixture:
    """Deliberately broken: two threads increment `counter` bare."""

    def __init__(self):
        self.counter = 0


def seeded_race(rounds: int = 200) -> dict:
    """A textbook unsynchronized read-modify-write; the report MUST carry a
    race on `_RaceFixture.counter`."""
    rt = tsan_mod.arm()
    try:
        fx = _RaceFixture()

        def bump():
            for _ in range(rounds):
                fx.counter += 1

        ts = [make_thread(target=bump, name=f"race-{i}", daemon=True)
              for i in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    finally:
        rt.disarm()
    return rt.collect()


def seeded_lock_cycle() -> dict:
    """A -> B in one thread, B -> A in another: the classic ABBA order
    inversion; the report MUST carry a lock cycle."""
    rt = tsan_mod.arm()
    try:
        la = make_lock("tsan-fixture.A")
        lb = make_lock("tsan-fixture.B")

        def ab():
            with la:
                with lb:
                    pass

        def ba():
            with lb:
                with la:
                    pass

        # sequential threads: the ORDER graph records the inversion without
        # risking an actual deadlock in the test process
        for fn, name in ((ab, "abba-1"), (ba, "abba-2")):
            t = make_thread(target=fn, name=name, daemon=True)
            t.start()
            t.join()
    finally:
        rt.disarm()
    return rt.collect()


def queue_handoff_fixture(rounds: int = 50) -> dict:
    """Ownership transfer through a queue — the pattern the prefetcher and
    batcher live on. MUST report zero findings (put→get happens-before)."""
    rt = tsan_mod.arm()
    try:
        q = make_queue()

        def produce():
            for _ in range(rounds):
                fx = _RaceFixture()
                fx.counter = 1  # producer writes...
                q.put(fx)
            q.put(None)

        t = make_thread(target=produce, name="handoff-producer", daemon=True)
        t.start()
        while True:
            fx = q.get()
            if fx is None:
                break
            fx.counter += 1  # ...consumer reads+writes after the handoff
        t.join()
    finally:
        rt.disarm()
    return rt.collect()


# --- the bundled stress scenario --------------------------------------------

# batcher/scheduler-facing engine double (bucket geometry + a host-side
# forward with a small measurable service time, so flushes coalesce and
# the stats layers run full speed without jax) — the ONE shared stub
from pytorchvideo_accelerate_tpu.serving.stub import (  # noqa: E402
    StubEngine as _StubEngine,
)


def _tiny_transform(frames, rng=None):
    """(T, H, W, 3) uint8 -> a float32 'video' leaf, small enough that the
    whole scenario moves kilobytes, not megabytes."""
    import numpy as np

    return {"video": (frames[:4, :8, :8, :].astype(np.float32) / 255.0)}


def _stress_prefetcher(watchdog, log: Callable[[str], None]) -> None:
    """Prefetcher churn: full epoch, then a mid-flight break (the shutdown
    path: stop flag, worker join, queue drain) — twice over for rollover."""
    from pytorchvideo_accelerate_tpu.data.device_prefetch import (
        DevicePrefetcher,
    )
    from pytorchvideo_accelerate_tpu.data.pipeline import (
        ClipLoader,
        SyntheticClipSource,
    )
    from pytorchvideo_accelerate_tpu.parallel.mesh import make_mesh

    mesh = make_mesh()
    source = SyntheticClipSource(_tiny_transform, num_videos=16,
                                 num_classes=4, raw_frames=4, raw_size=(8, 8))
    loader = ClipLoader(source, global_batch_size=8, shuffle=True,
                        num_workers=2, prefetch_batches=1)
    pf = DevicePrefetcher(loader, mesh, depth=2, watchdog=watchdog)
    try:
        n = 0
        for _ in pf.epoch(0):
            n += 1
        log(f"[tsan] prefetcher epoch complete ({n} batches)")
        for _ in pf.epoch(1):
            break  # mid-flight shutdown: generator close tears down worker
        _ = pf.pop_wait(), pf.max_resident
    finally:
        loader.close()


def _stress_dataplane(log: Callable[[str], None]) -> None:
    """Disaggregated data-plane churn: a RemoteClipFeed with two IN-THREAD
    DecodeWorkers over loopback sockets — the credit/ack machinery (reader
    threads moving batches into the reorder buffer, the consumer releasing
    window slots, `_pump_locked` leasing from three call sites) under real
    interleavings, plus the two hazard paths: a mid-flight generator abort
    (stale-generation frames racing the reset) and a worker death mid-epoch
    (the re-lease path racing live receipts)."""
    import socket

    from pytorchvideo_accelerate_tpu.data.pipeline import ClipLoader
    from pytorchvideo_accelerate_tpu.dataplane import spec as dpspec
    from pytorchvideo_accelerate_tpu.dataplane.feed import RemoteClipFeed
    from pytorchvideo_accelerate_tpu.dataplane.worker import DecodeWorker

    tspec = dict(num_frames=2, training=True, crop_size=16,
                 min_short_side_scale=18, max_short_side_scale=22)
    spec = dpspec.synthetic_spec(tspec, num_videos=16, num_classes=4,
                                 seed=3, raw_frames=4, raw_size=[24, 32])
    loader = ClipLoader(dpspec.build_source(spec), global_batch_size=4,
                        shuffle=True, num_workers=1, seed=3)
    feed = RemoteClipFeed(loader, spec, spawn=0, credits=2,
                          batch_timeout_s=30.0)
    workers = []
    for k in range(2):
        s = socket.create_connection(feed.address)
        t = make_thread(target=DecodeWorker(s, decode_threads=1).run,
                        name=f"dataplane-worker-{k}", daemon=True)
        t.start()
        workers.append((t, s))
    try:
        feed.wait_for_workers(2, timeout=30.0)
        n = sum(1 for batch, _ in feed.epoch_items(0, from_start=True)
                if batch is not None)
        # mid-flight abort: the finally's generation bump races frames the
        # workers already have in flight
        aborted = feed.epoch_items(1, from_start=True)
        next(aborted)
        aborted.close()
        # worker death mid-epoch: close one worker's socket and drain —
        # the reader's re-lease runs against the survivor's receipts
        it = feed.epoch_items(2, from_start=True)
        next(it)
        workers[0][1].close()
        rest = sum(1 for batch, _ in it if batch is not None)
        stats = feed.stats()
        log(f"[tsan] dataplane churn: {n} + {rest + 1} batches, "
            f"{stats['releases']} re-leased, "
            f"{stats['workers_lost']} worker lost")
    finally:
        feed.close()
        loader.close()
        for t, _s in workers:
            t.join(timeout=10.0)


def _stress_batcher(watchdog, log: Callable[[str], None]) -> None:
    """Concurrent submitters against one flush thread, snapshots racing the
    traffic, then a mid-flight close with requests still queued."""
    import numpy as np

    from pytorchvideo_accelerate_tpu.serving.batcher import MicroBatcher
    from pytorchvideo_accelerate_tpu.serving.stats import ServingStats

    stats = ServingStats(window=64)
    mb = MicroBatcher(_StubEngine(), max_wait_ms=1.0, max_queue=64,
                      stats=stats,
                      heartbeat=(watchdog.beat_fn("serve_batcher")
                                 if watchdog else None))
    stats.queue_depth_fn = mb.queue_depth
    clip = {"video": np.zeros((2, 4, 4, 3), np.float32)}
    errors: List[str] = []

    def client(k: int):
        from pytorchvideo_accelerate_tpu.obs import trace as obstrace

        tracer = obstrace.get_tracer()
        for i in range(8):
            try:
                # traced submits: the request context crosses the batcher
                # queue and the flush thread records under it, so the
                # tracer's ring/lock traffic races real concurrency here
                handle = (tracer.start(f"req-{k}", seq=i)
                          if tracer is not None else None)
                with (handle if handle is not None else obstrace.NOOP):
                    fut = mb.submit(clip)
                if i % 2 == 0:
                    fut.result(timeout=5.0)
            except Exception as e:  # noqa: BLE001 - late submits hit close()
                errors.append(f"{type(e).__name__}")
                return

    def snapshotter():
        for _ in range(6):
            stats.snapshot()
            time.sleep(0.002)

    ts = [make_thread(target=client, args=(k,), name=f"serve-client-{k}",
                      daemon=True) for k in range(3)]
    ts.append(make_thread(target=snapshotter, name="stats-snapshotter",
                          daemon=True))
    for t in ts:
        t.start()
    time.sleep(0.02)
    mb.close()  # mid-flight: pending requests fail, not hang
    for t in ts:
        t.join(timeout=10.0)
    snap = stats.snapshot()
    log(f"[tsan] batcher churn: {int(snap['requests'])} served, "
        f"{len(errors)} submits hit the close")


def _stress_fleet(log: Callable[[str], None]) -> None:
    """Fleet-tier churn: two stub-engine `Scheduler` replicas behind the
    pool + router, concurrent mixed-priority clients, a hot-swap cutover
    racing live launches, membership flaps racing the health poller, and
    fleet-snapshot readers racing everything — the registered
    Scheduler/ReplicaPool/Router/LoadGen state under real interleavings."""
    import numpy as np

    from pytorchvideo_accelerate_tpu.fleet.pool import (
        LocalReplica,
        ReplicaPool,
    )
    from pytorchvideo_accelerate_tpu.fleet.router import Router
    from pytorchvideo_accelerate_tpu.fleet.scheduler import Scheduler
    from pytorchvideo_accelerate_tpu.obs.registry import Registry
    from pytorchvideo_accelerate_tpu.serving.stats import ServingStats

    # fresh private registries: the process-default Registry predates the
    # armed window (raw locks -> false positives by construction)
    replicas = []
    for i in range(2):
        stats = ServingStats(window=64, registry=Registry())
        sched = Scheduler(_StubEngine(), stats=stats, max_queue=64,
                          batch_max_wait_ms=1.0, name=f"tsan-{i}")
        replicas.append(LocalReplica(f"tsan-{i}", sched))
    pool = ReplicaPool(replicas, health_interval_s=0.02,
                       registry=Registry())
    router = Router(pool, registry=Registry())
    clip = {"video": np.zeros((2, 4, 4, 3), np.float32)}
    served: List[str] = []

    def client(k: int):
        from pytorchvideo_accelerate_tpu.obs import trace as obstrace

        tracer = obstrace.get_tracer()
        for i in range(8):
            try:
                # traced routing: context rides router dispatch ->
                # scheduler queue -> launch under hot-swap/membership churn
                handle = (tracer.start(f"fleet-req-{k}", seq=i)
                          if tracer is not None else None)
                with (handle if handle is not None else obstrace.NOOP):
                    fut = router.submit(
                        clip,
                        priority=("batch" if (k + i) % 3 else "realtime"))
                if i % 2 == 0:
                    fut.result(timeout=5.0)
                    served.append("ok")
            except Exception:  # noqa: BLE001 - close() races late submits
                return

    def swapper():
        time.sleep(0.005)
        try:  # cutover BETWEEN launches, racing the clients
            replicas[0].scheduler.swap_engine(_StubEngine())
        except Exception:
            pass
        pool.mark_down(replicas[1])  # flap membership under traffic; the
        time.sleep(0.03)             # poller restores it (health is fine)

    def snapshotter():
        for _ in range(5):
            router.fleet_snapshot()
            time.sleep(0.003)

    ts = [make_thread(target=client, args=(k,), name=f"fleet-client-{k}",
                      daemon=True) for k in range(3)]
    ts.append(make_thread(target=swapper, name="fleet-swapper", daemon=True))
    ts.append(make_thread(target=snapshotter, name="fleet-snapshotter",
                          daemon=True))
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=10.0)
    router.close()
    log(f"[tsan] fleet churn: {len(served)} awaited results through a "
        "hot-swap + membership flap")


def _stress_stream(log: Callable[[str], None]) -> None:
    """Streaming-session churn (docs/SERVING.md § streaming): concurrent
    clients establish/advance/end sessions through the affinity router
    (windows attached — the re-establish-anywhere contract) while a
    hot-swap cutover replaces the session-capable engine mid-stream and
    the health poller flaps membership under the affinity map. The
    registered SessionTable/StubStreamEngine/Router-affinity state under
    real interleavings, plus a direct table-churn thread racing the
    launch path (lease/evict/adopt against advance/sweep)."""
    import numpy as np

    from pytorchvideo_accelerate_tpu.fleet.pool import (
        LocalReplica,
        ReplicaPool,
    )
    from pytorchvideo_accelerate_tpu.fleet.router import Router
    from pytorchvideo_accelerate_tpu.fleet.scheduler import Scheduler
    from pytorchvideo_accelerate_tpu.obs.registry import Registry
    from pytorchvideo_accelerate_tpu.serving.stats import ServingStats
    from pytorchvideo_accelerate_tpu.serving.stub import StubStreamEngine
    from pytorchvideo_accelerate_tpu.streaming.session import SessionTable

    replicas = []
    for i in range(2):
        stats = ServingStats(window=64, registry=Registry())
        sched = Scheduler(StubStreamEngine(), stats=stats, max_queue=64,
                          batch_max_wait_ms=1.0, name=f"tsan-stream-{i}")
        replicas.append(LocalReplica(f"tsan-stream-{i}", sched))
    pool = ReplicaPool(replicas, health_interval_s=0.02,
                       registry=Registry())
    router = Router(pool, registry=Registry())
    T, S, HW = 4, 2, 4
    served: List[str] = []

    def client(k: int):
        rng = np.random.default_rng(k)
        win = rng.standard_normal((T, HW, HW, 3)).astype(np.float32)
        sid = f"tsan-sess-{k}"
        for i in range(8):
            frames = rng.standard_normal((S, HW, HW, 3)).astype(np.float32)
            win = np.concatenate([win[S:], frames], axis=0)
            try:
                fut = router.submit(
                    {"video": frames},
                    session={"sid": sid, "window": win, "stride": S,
                             "end": i == 7})
                if i % 2 == 0:
                    fut.result(timeout=5.0)
                    served.append("ok")
            except Exception:  # noqa: BLE001 - close() races late submits
                return

    def swapper():
        time.sleep(0.005)
        try:  # session-capable green engine cuts over mid-stream
            replicas[0].scheduler.swap_engine(StubStreamEngine(tag=1.0))
        except Exception:
            pass
        pool.mark_down(replicas[1])  # flap membership under live affinity
        time.sleep(0.03)

    # direct table churn: the lease/evict/adopt surface racing itself the
    # way a busy establish path + TTL sweeper + hot-swap adopt would
    table = SessionTable(ttl_s=0.01, registry=Registry(),
                         name="tsan-table")
    table.register_pool(("g",), capacity=3)
    twin = SessionTable(ttl_s=0.01, registry=Registry(), name="tsan-twin")

    def table_churn(k: int):
        for i in range(20):
            sid = f"t{k}-{i % 4}"
            try:
                table.establish(sid, ("g",), stride=1, window=4)
            except Exception:  # budget full: the admission verdict
                pass
            table.advanced(sid, 1)
            if i % 3 == 0:
                table.sweep()
            if i % 5 == 0:
                table.end(sid)
            if i % 7 == 0:
                twin.adopt(table)

    ts = [make_thread(target=client, args=(k,), name=f"stream-client-{k}",
                      daemon=True) for k in range(3)]
    ts.append(make_thread(target=swapper, name="stream-swapper",
                          daemon=True))
    ts += [make_thread(target=table_churn, args=(k,),
                       name=f"stream-table-{k}", daemon=True)
           for k in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=10.0)
    router.close()
    log(f"[tsan] stream churn: {len(served)} awaited labels through a "
        "hot-swap + membership flap + table churn")


def _stress_autoscale(log: Callable[[str], None]) -> None:
    """Fleet-control churn (fleet/control/): an `Autoscaler` ticking in
    its own thread — spawn/drain/re-home racing live session dispatch,
    the health poller, and a `CanaryController` rollout/evaluate/rollback
    on the same pool — plus history/snapshot readers racing the control
    loops. The registered Autoscaler/CanaryController @shared_state
    fields (target, history, EWMAs, strikes, blues) under real
    interleavings."""
    import numpy as np

    from pytorchvideo_accelerate_tpu.fleet.control import (
        Autoscaler,
        CanaryController,
    )
    from pytorchvideo_accelerate_tpu.fleet.pool import (
        LocalReplica,
        ReplicaPool,
    )
    from pytorchvideo_accelerate_tpu.fleet.router import Router
    from pytorchvideo_accelerate_tpu.fleet.scheduler import Scheduler
    from pytorchvideo_accelerate_tpu.obs.registry import Registry
    from pytorchvideo_accelerate_tpu.serving.stats import ServingStats
    from pytorchvideo_accelerate_tpu.serving.stub import StubStreamEngine

    def mk_replica(name: str) -> LocalReplica:
        stats = ServingStats(window=64, registry=Registry())
        sched = Scheduler(StubStreamEngine(), stats=stats, max_queue=64,
                          batch_max_wait_ms=1.0, name=name)
        return LocalReplica(name, sched, stats=stats)

    replicas = [mk_replica(f"tsan-auto-{i}") for i in range(3)]
    pool = ReplicaPool(replicas, health_interval_s=0.02,
                       registry=Registry())
    router = Router(pool, registry=Registry())
    spawn_n = {"n": 0}

    def spawn():
        spawn_n["n"] += 1
        return mk_replica(f"tsan-auto-sp-{spawn_n['n']}")

    # watermarks close together so BOTH decisions fire under the bursty
    # clients below: what matters here is the interleaving coverage of
    # spawn/drain/re-home against live traffic, not where the fleet lands
    asc = Autoscaler(router, spawn_fn=spawn, min_replicas=1,
                     max_replicas=4, slo_p99_ms=1e9, queue_high=1.0,
                     queue_low=0.5, cooldown_s=0.01, interval_s=0.005,
                     ewma_alpha=1.0, drain_grace_s=0.05,
                     dead_after_ticks=2)
    T, S, HW = 4, 2, 4
    served: List[str] = []

    def client(k: int):
        rng = np.random.default_rng(k)
        win = rng.standard_normal((T, HW, HW, 3)).astype(np.float32)
        sid = f"tsan-auto-sess-{k}"
        for i in range(8):
            frames = rng.standard_normal((S, HW, HW, 3)).astype(np.float32)
            win = np.concatenate([win[S:], frames], axis=0)
            try:
                # window attached: a drain's re-home lands mid-burst and
                # the session must re-establish on whatever survives
                fut = router.submit(
                    {"video": frames},
                    session={"sid": sid, "window": win, "stride": S,
                             "end": i == 7})
                if i % 2 == 0:
                    fut.result(timeout=5.0)
                    served.append("ok")
            except Exception:  # noqa: BLE001 - close() races late submits
                return

    def canary():
        cc = CanaryController(router, fraction=0.34, threshold=0.2,
                              rollback_after=2, prewarm=False)
        try:  # rollout/evaluate race the autoscaler draining its victims
            cc.start_rollout(lambda r: StubStreamEngine(tag=1.0),
                             label="tsan-green")
            for _ in range(2):
                cc.evaluate()
                time.sleep(0.005)
            if cc.state == "canary":
                cc.rollback()
        except Exception:  # noqa: BLE001 - a drained canary set is legal
            pass

    def snapshotter():
        for _ in range(6):
            router.fleet_snapshot()
            asc.actions_since(0.0)
            time.sleep(0.003)

    asc.start()  # the control loop ticks in ITS thread for the whole leg
    ts = [make_thread(target=client, args=(k,), name=f"auto-client-{k}",
                      daemon=True) for k in range(3)]
    ts.append(make_thread(target=canary, name="auto-canary", daemon=True))
    ts.append(make_thread(target=snapshotter, name="auto-snapshotter",
                          daemon=True))
    for t in ts:
        t.start()
    time.sleep(0.02)
    pool.mark_down(replicas[1])  # flap membership under the control loop;
    for t in ts:                 # the poller restores it (health is fine)
        t.join(timeout=10.0)
    asc.close()
    router.close()
    log(f"[tsan] autoscale churn: {len(served)} awaited labels through "
        f"{len(asc.history)} control action(s) + a canary cycle "
        f"({spawn_n['n']} spawned)")


def _stress_hbmobs(log: Callable[[str], None]) -> None:
    """pva-tpu-hbm churn (obs/memory.py, obs/history.py, obs/alerts.py):
    MemoryLedger register/release from two threads — the ring lease/evict
    shape — racing an AlertEngine ticking scrape ticks into the shared
    MetricsHistory ring plus a forced alert flap (burn past the objective,
    then the hysteresis clear), with snapshot readers interleaved. The
    registered MemoryLedger/MetricsHistory/AlertEngine @shared_state
    fields under real interleavings."""
    from pytorchvideo_accelerate_tpu.obs.alerts import AlertEngine, AlertRule
    from pytorchvideo_accelerate_tpu.obs.history import MetricsHistory
    from pytorchvideo_accelerate_tpu.obs.memory import MemoryLedger
    from pytorchvideo_accelerate_tpu.obs.registry import Registry

    reg = Registry()
    led = MemoryLedger(registry=reg, stats_fn=lambda: {
        "bytes_in_use": 1 << 20, "peak_bytes_in_use": 1 << 20,
        "bytes_limit": 1 << 30})
    load = reg.gauge("pva_stress_load", "synthetic burn driver")
    hist = MetricsHistory(registry=reg, capacity=32)
    eng = AlertEngine(hist, [AlertRule(
        name="flap", kind="gauge", key="pva_stress_load", objective=1.0,
        fast_s=0.5, slow_s=1.0, hold_clear=1)], registry=reg)

    def churn(k: int):
        for i in range(60):
            led.register(f"ring:{k}", 4096, declared=4000)
            if i % 3 == 0:
                led.snapshot()
            led.release(f"ring:{k}", 4096, declared=4000)

    def ticker():
        for i in range(30):
            # burn for the middle third, calm either side: one full
            # fire -> hold -> clear excursion under churn
            load.set(5.0 if 10 <= i < 20 else 0.0)
            eng.tick()
            eng.snapshot()

    ts = [make_thread(target=churn, args=(k,), name=f"hbm-churn-{k}",
                      daemon=True) for k in range(2)]
    ts.append(make_thread(target=ticker, name="hbm-ticker", daemon=True))
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    log(f"[tsan] hbm obs churn: ledger at {led.attributed_bytes()} B "
        f"attributed, {hist.total_ticks()} history ticks, "
        f"{eng.fires('flap')} flap fire(s)")


def _stress_trackers(log: Callable[[str], None]) -> None:
    """TrackerHub fan-out from two threads with a tracker that raises: the
    disable-on-failure path mutates the tracker list under traffic."""
    from pytorchvideo_accelerate_tpu.trainer.tracking import Tracker, TrackerHub

    class _Boom(Tracker):
        name = "boom"

        def start(self, run_name, config):
            pass

        def log(self, values, step):
            raise RuntimeError("tracker deliberately failing")

    class _Count(Tracker):
        name = "count"

        def __init__(self):
            self.n = 0

        def start(self, run_name, config):
            pass

        def log(self, values, step):
            self.n += 1

    hub = TrackerHub("", logging_dir="")
    counter = _Count()
    hub.trackers.extend([_Boom(), counter])

    def logs(k: int):
        for i in range(10):
            hub.log({"x": float(i)}, step=k * 10 + i)

    ts = [make_thread(target=logs, args=(k,), name=f"tracker-{k}",
                      daemon=True) for k in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    hub.finish()
    log(f"[tsan] tracker fan-out survived a raising tracker "
        f"({counter.n} logs reached the healthy one)")


def _stress_recorder_watchdog(tmpdir: str,
                              log: Callable[[str], None]):
    """Flight-recorder churn + dump re-entrancy, and a watchdog whose stall
    path is forced deterministically (no real 30s hang needed). The forced
    stall fires while the churn threads are mid-flight, so the dump path
    (watchdog lock -> ring lock -> collector lock) genuinely races live
    recorder traffic. The returned watchdog is still RUNNING with a fast
    poll so check() keeps executing concurrently with the batcher and
    prefetcher legs — the caller owns wd.stop()."""
    from pytorchvideo_accelerate_tpu import obs
    from pytorchvideo_accelerate_tpu.obs.flight_recorder import FlightRecorder

    rec = FlightRecorder(capacity=64)
    wd = obs.Watchdog(30.0, output_dir=tmpdir, recorder=rec,
                      collector=obs.get_collector(), poll_s=0.02).start()
    wd.heartbeat("main")

    def churn(k: int):
        for i in range(40):
            rec.record("span", f"stress-{k}", i=i)
        rec.dump(f"{tmpdir}/flight_{k}.json")

    ts = [make_thread(target=churn, args=(k,), name=f"recorder-{k}",
                      daemon=True) for k in range(2)]
    for t in ts:
        t.start()
    # forced stall: pretend 2 minutes elapsed — exercises the stall dump
    # (stderr stacks + ring dump) against the still-running churn threads
    stalled = wd.check(now=time.monotonic() + 120.0)
    for t in ts:
        t.join()
    rec.set_capacity(32)
    wd.heartbeat("main")  # recovery re-arms the one-shot
    log(f"[tsan] watchdog forced-stall fired for {stalled}; "
        f"ring at {len(rec.snapshot())} events")
    return wd


def run_stress(smoke: bool = True,
               log: Optional[Callable[[str], None]] = None) -> dict:
    """Arm, run every layer's stress leg, disarm, return the report dict:
    {races, cycles, suppressed, lock_order_edges, fields_tracked, ...}.

    `smoke` keeps shapes/iterations tiny (the CI lane); the full mode just
    repeats the churn legs for more interleavings.
    """
    from pytorchvideo_accelerate_tpu.obs import flight_recorder, spans

    from pytorchvideo_accelerate_tpu.obs import trace as obstrace

    log = log or (lambda msg: None)
    rounds = 1 if smoke else 3
    t0 = time.perf_counter()
    rt = tsan_mod.arm()
    # fresh obs singletons: pre-arm instances hold raw (untracked) locks,
    # which would make their guarded accesses look unguarded — swap in
    # factory-built twins for the scenario, restore after
    old_collector, old_recorder = spans._DEFAULT, flight_recorder._DEFAULT
    try:
        flight_recorder._DEFAULT = flight_recorder.FlightRecorder()
        spans._DEFAULT = spans.SpanCollector(
            enabled=True, recorder=flight_recorder._DEFAULT)
        # distributed tracing ARMED for the whole scenario (created inside
        # the armed window, so the Tracer's lock and @shared_state fields
        # are tracked): the batcher/fleet clients below start sampled
        # roots, so trace capture/attach and ring appends genuinely race
        # the flush threads — the "gates stay clean with tracing armed"
        # obligation, exercised rather than asserted
        obstrace.configure_tracing(1.0, seed=0, capacity=512)
        with tempfile.TemporaryDirectory(prefix="pva_tsan_") as tmpdir:
            for _ in range(rounds):
                wd = _stress_recorder_watchdog(tmpdir, log)
                try:
                    # live watchdog: its poll thread runs check() every
                    # 20ms concurrently with the legs' heartbeats/churn
                    _stress_batcher(wd, log)
                    _stress_fleet(log)
                    _stress_stream(log)
                    _stress_autoscale(log)
                    _stress_hbmobs(log)
                    _stress_trackers(log)
                    _stress_prefetcher(wd, log)
                    _stress_dataplane(log)
                finally:
                    wd.stop()
            # drain the scenario collector the way the trainer would
            spans._DEFAULT.pop_window()
    finally:
        spans._DEFAULT, flight_recorder._DEFAULT = old_collector, old_recorder
        obstrace.disable_tracing()
        rt.disarm()
    report = rt.collect()
    report["elapsed_s"] = round(time.perf_counter() - t0, 3)
    report["smoke"] = bool(smoke)
    log(f"[tsan] scenario done in {report['elapsed_s']}s: "
        f"{len(report['races'])} race(s), {len(report['cycles'])} "
        f"cycle(s), {len(report['suppressed'])} suppressed, "
        f"{report['accesses']} accesses over {report['fields_tracked']} "
        f"fields, {report['lock_order_edges']} lock-order edges")
    return report


# --- report plumbing --------------------------------------------------------

def finding_count(report: dict) -> int:
    """What the CI gates count: hard findings only (suppressed/benign races
    are auditable, not fatal — same stance as lint suppressions)."""
    return len(report.get("races", ())) + len(report.get("cycles", ()))


def publish(report: dict) -> None:
    """Mirror a report into the process obs spine: gauges in the default
    registry + one flight-ring event per finding (crash dumps then carry
    the sanitizer's verdict alongside the timeline)."""
    from pytorchvideo_accelerate_tpu import obs

    reg = obs.get_registry()
    reg.gauge("pva_tsan_races",
              "data races found by the last pva-tpu-tsan run").set(
                  len(report.get("races", ())))
    reg.gauge("pva_tsan_lock_cycles",
              "lock-order cycles found by the last pva-tpu-tsan run").set(
                  len(report.get("cycles", ())))
    rec = obs.get_recorder()
    for r in report.get("races", ()):
        rec.record("tsan", "race", field=r["field"], thread=r["thread"],
                   op=r["op"])
    for c in report.get("cycles", ()):
        rec.record("tsan", "lock-cycle", cycle=c["cycle"])


def tsan_snapshot() -> dict:
    """Doctor view (`pva-tpu-doctor` diagnose()): the current/last runtime's
    lock-order graph, live held locks per thread, and finding counts."""
    rt = tsan_mod.get_tsan()
    if rt is None:
        return {"armed": False, "ran": False}
    out = rt.snapshot()
    out["ran"] = True
    out["cycles"] = len(rt.lock_cycles())
    return out


def format_report(report: dict, max_stack: int = 6) -> str:
    lines: List[str] = []
    for r in report.get("races", ()):
        lines.append(
            f"RACE {r['field']}: {r['op']} by {r['thread']} holding "
            f"{r['locks_held'] or 'no locks'}; last write by "
            f"{r['last_write_thread']} "
            f"({'locked' if r['last_write_locked'] else 'bare'})")
        lines.extend("    " + ln for ln in r["stack"][-max_stack:])
    for c in report.get("cycles", ()):
        lines.append(f"LOCK CYCLE {c['cycle']}")
        for e in c["edges"]:
            lines.append(f"    edge {e['edge']} (seen {e['count']}x, "
                         f"first by {e['thread']}):")
            lines.extend("        " + ln for ln in e["stack"][-max_stack:])
    for s in report.get("suppressed", ()):
        lines.append(f"suppressed (benign) {s['field']}: "
                     f"{s['suppressed_reason']}")
    lines.append(
        f"pva-tpu-tsan: {finding_count(report)} finding(s) — "
        f"{len(report.get('races', ()))} race(s), "
        f"{len(report.get('cycles', ()))} lock cycle(s), "
        f"{len(report.get('suppressed', ()))} suppressed; "
        f"{report.get('accesses', 0)} accesses, "
        f"{report.get('lock_order_edges', 0)} lock-order edges")
    return "\n".join(lines)


# --- CLI --------------------------------------------------------------------

def selftest(log: Callable[[str], None]) -> int:
    """The sanitizer must still catch what it exists to catch: seeded race
    detected, seeded ABBA cycle detected, queue handoff NOT flagged."""
    ok = True
    r = seeded_race()
    if not any("_RaceFixture.counter" in x["field"] for x in r["races"]):
        log("FAIL: seeded data race not detected")
        ok = False
    c = seeded_lock_cycle()
    if not c["cycles"]:
        log("FAIL: seeded ABBA lock cycle not detected")
        ok = False
    h = queue_handoff_fixture()
    if finding_count(h):
        log("FAIL: queue handoff false-alarmed")
        ok = False
    log("selftest: " + ("ok (race detected, cycle detected, handoff clean)"
                        if ok else "FAILED"))
    return 0 if ok else 1


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="pva-tpu-tsan",
        description="dynamic lockset race + lock-order deadlock sanitizer "
                    "over the threaded data/train/serve layers; see "
                    "docs/STATIC_ANALYSIS.md")
    ap.add_argument("--smoke", action="store_true",
                    help="one round of the stress scenario (the CI lane)")
    ap.add_argument("--selftest", action="store_true",
                    help="verify the sanitizer still detects its seeded "
                         "race/cycle fixtures (and stays quiet on the "
                         "queue-handoff pattern)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code not in (0, None) else 0

    def log(msg: str) -> None:
        print(msg, file=sys.stderr, flush=True)

    if args.selftest:
        return selftest(log)

    # the scenario's device work (prefetcher H2D) must not wedge a CLI run
    # on a half-attached accelerator: CPU unless the caller overrides
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    report = run_stress(smoke=args.smoke, log=log)
    publish(report)
    if args.format == "json":
        print(json.dumps(report, indent=1, default=str))
    else:
        print(format_report(report))
    return 1 if finding_count(report) else 0


if __name__ == "__main__":
    sys.exit(main())
