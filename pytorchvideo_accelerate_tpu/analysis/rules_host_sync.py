"""Rule `host-sync`: device-value fetches inside declared-hot modules.

The bug class (VERDICT r2 weak #4, and the reason DeferredStepLogger
exists): one innocent `.item()` / `float(metrics["loss"])` /
`np.asarray(device_array)` in the step loop blocks the host on the step
just dispatched, serializing the async-dispatch pipeline — a silent
throughput cliff that survives review because it is legal Python. The
pjit-at-scale writeups (arXiv:2204.06514, arXiv:2104.06272) both call out
host-device sync removal as a first-order throughput lever.

Scope: only modules in HOT_MODULES (the steady-state train/serve path).
Cold modules fetch values freely — that is what values are for.

What fires, on a hot module:

- `.item()`, `.block_until_ready()`, `jax.device_get(...)` — always;
- `np.asarray(...)` / `np.array(...)` — converting to numpy forces the
  D2H transfer when the argument is a device array (host-side numpy
  arguments are false positives by design: suppress with a reason);
- `float(X)` / `int(X)` where X is a call / subscript / attribute chain —
  the shapes device scalars arrive in (`metrics["loss"]`,
  `self.state.step`, `global_norm(...)`). Plain-`Name` arguments are NOT
  flagged (config parsing, loop counters).

Escapes, in preference order: move the fetch off the hot path (deferred
logging, epoch-end drain), add the (module, qualname) to
ALLOWED_SYNC_SITES if the function IS a designed fetch point, or
annotate the line `# pva: disable=host-sync -- <why>`.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Sequence, Set, Tuple

from pytorchvideo_accelerate_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Rule,
    call_name,
    walk_with_qualname,
)

# the steady-state hot path: train step loop, compiled steps, device
# prefetch, serving forward/flush, and the telemetry primitives that run
# inside all of them. Paths are posix suffixes matched against the file
# being linted.
HOT_MODULES: Tuple[str, ...] = (
    "trainer/loop.py",
    "trainer/steps.py",
    "trainer/tracking.py",
    "trainer/metrics.py",
    "data/device_prefetch.py",
    "serving/engine.py",
    "serving/batcher.py",
    "obs/spans.py",
)

# designed value-fetch points: functions whose PURPOSE is the sync, so
# flagging them line by line would be noise. Keyed by module suffix ->
# set of qualnames. Everything else uses line suppressions with reasons.
ALLOWED_SYNC_SITES: Dict[str, Set[str]] = {
    # XLA cost-model capture: runs once per process, right after the first
    # step, against an already-warm executable cache
    "trainer/loop.py": {"Trainer._capture_step_flops"},
    # the batched epoch-end drains — the designed alternative to per-step
    # fetching (metrics accumulate device scalars, one device_get at read)
    "trainer/metrics.py": {"SumMetrics._drain", "MeanLoss._drain",
                           "MeanLoss.update"},
    # the response fetch: a serving forward exists to produce host logits
    "serving/engine.py": {"InferenceEngine.predict"},
}

_FETCH_ATTRS = ("item", "block_until_ready")
_NUMPY_FETCHES = ("np.asarray", "np.array", "numpy.asarray", "numpy.array",
                  "onp.asarray", "onp.array")
_DEVICE_GET = ("jax.device_get", "device_get")
# float(X)/int(X) argument shapes that can hold a device scalar
_ARRAYISH = (ast.Call, ast.Subscript, ast.Attribute)


class HostSyncRule(Rule):
    name = "host-sync"
    description = ("host-device sync (.item/float()/np.asarray/device_get/"
                   "block_until_ready) inside a hot-path module")

    def __init__(self, hot_modules: Sequence[str] = HOT_MODULES,
                 allowed_sites: Dict[str, Set[str]] = ALLOWED_SYNC_SITES):
        self.hot_modules = tuple(hot_modules)
        self.allowed_sites = dict(allowed_sites)

    def _allowed(self, module: ModuleInfo, qualname: str) -> bool:
        for suffix, names in self.allowed_sites.items():
            if module.posix_path.endswith(suffix) and qualname in names:
                return True
        return False

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        if not module.matches(self.hot_modules):
            return
        for node, qualname in walk_with_qualname(module.tree):
            if not isinstance(node, ast.Call):
                continue
            if self._allowed(module, qualname):
                continue
            name = call_name(node)
            tail = name.rsplit(".", 1)[-1]
            if tail in _FETCH_ATTRS and isinstance(node.func, ast.Attribute):
                yield self.finding(
                    module, node,
                    f"`.{tail}()` blocks on the device result in a hot "
                    "module — defer the fetch (DeferredStepLogger / "
                    "epoch-end drain) or suppress with a reason")
            elif name in _DEVICE_GET:
                yield self.finding(
                    module, node,
                    "`jax.device_get` in a hot module syncs host and "
                    "device — batch the fetch off the hot path")
            elif name in _NUMPY_FETCHES:
                yield self.finding(
                    module, node,
                    f"`{name}(...)` forces a D2H transfer when handed a "
                    "device array — if the argument is host-side numpy, "
                    "suppress with that reason")
            elif (name in ("float", "int") and node.args
                    and isinstance(node.args[0], _ARRAYISH)):
                yield self.finding(
                    module, node,
                    f"`{name}(...)` on a call/subscript/attribute value "
                    "blocks if it holds a device scalar — defer the fetch "
                    "or suppress with the reason it is sync-safe")
