"""`knob-read`: every config knob must be read somewhere outside config.py.

The config surface is 14 dataclass blocks and growing one per subsystem
PR; a knob that nothing reads is worse than dead code — users set it,
nothing changes, and the silence reads as "the feature is broken". This
rule inverts the usual direction of a dead-code check: it fires ON
`config.py` (one module, so the package scan below runs once per lint
pass) and asks, for every field of every `*Config` dataclass, whether
ANY module in the package outside `config.py` and `tests/` mentions that
field name as an attribute read (`cfg.train.log_every_steps`, through
whatever local alias the caller bound — alias-proof because attribute
TAILS don't care about the receiver) or as a `getattr`/string-key
constant.

Name-presence is deliberately coarse (two knobs sharing a name are
jointly satisfied by one reader) — coarse in the false-NEGATIVE
direction, which is the right polarity for a `findings == 0` gate.
A knob added ahead of its consumer carries a suppression with a reason::

    new_knob: int = 0  # pva: disable=knob-read -- consumed by PR N's ...

so forward declarations stay auditable instead of silently rotting.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, Optional, Set

from pytorchvideo_accelerate_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Rule,
    call_name,
)

_CONFIG_FILES = ("pytorchvideo_accelerate_tpu/config.py",)


def _field_reads_in_tree(pkg_dir: str) -> Set[str]:
    """Every attribute tail / getattr-string / subscript-string constant
    mentioned by package modules other than config.py (tests never count:
    a knob only a test reads is still dead)."""
    reads: Set[str] = set()
    for root, dirs, files in os.walk(pkg_dir):
        dirs[:] = [d for d in dirs if d not in ("__pycache__", "tests")]
        for fname in files:
            if not fname.endswith(".py") or fname == "config.py":
                continue
            path = os.path.join(root, fname)
            try:
                with open(path, "r", encoding="utf-8") as fh:
                    tree = ast.parse(fh.read())
            except (OSError, SyntaxError):
                continue
            for node in ast.walk(tree):
                if isinstance(node, ast.Attribute):
                    reads.add(node.attr)
                elif isinstance(node, ast.Call) \
                        and call_name(node).rsplit(".", 1)[-1] in (
                            "getattr", "get"):
                    for arg in node.args:
                        if isinstance(arg, ast.Constant) \
                                and isinstance(arg.value, str):
                            reads.add(arg.value)
                elif isinstance(node, ast.Subscript) \
                        and isinstance(node.slice, ast.Constant) \
                        and isinstance(node.slice.value, str):
                    reads.add(node.slice.value)
    return reads


class KnobReadRule(Rule):
    name = "knob-read"
    description = ("config dataclass field is never read outside "
                   "config.py/tests — a dead knob users can set with no "
                   "effect; wire it or suppress with the consuming PR")

    def __init__(self) -> None:
        # one package scan per lint run (the rule only fires on config.py,
        # but keep a cache in case a run lints several copies)
        self._scan_cache: Dict[str, Set[str]] = {}

    def _reads_for(self, module: ModuleInfo) -> Optional[Set[str]]:
        pkg_dir = os.path.dirname(os.path.abspath(module.path))
        if not os.path.isdir(pkg_dir):
            return None
        if pkg_dir not in self._scan_cache:
            self._scan_cache[pkg_dir] = _field_reads_in_tree(pkg_dir)
        return self._scan_cache[pkg_dir]

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        if not module.matches(_CONFIG_FILES):
            return
        reads = self._reads_for(module)
        if reads is None:  # fixture paths with no real package around them
            reads = set()
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.ClassDef) \
                    or not node.name.endswith("Config"):
                continue
            for stmt in node.body:
                if not isinstance(stmt, ast.AnnAssign) \
                        or not isinstance(stmt.target, ast.Name):
                    continue
                fname = stmt.target.id
                if fname.startswith("_") or fname in reads:
                    continue
                yield self.finding(
                    module, stmt,
                    f"config knob `{node.name}.{fname}` is never read "
                    "outside config.py/tests — a user can set it and "
                    "nothing changes; wire it into its subsystem or "
                    "suppress with the PR that will consume it")
