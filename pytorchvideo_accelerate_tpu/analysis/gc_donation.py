"""graphcheck donation pass: verify buffer donation actually aliased.

`jax.jit(step, donate_argnums=0)` is a *request*: jax matches each
donated input leaf to an output with the identical aval and records the
pair in the compiled module's `input_output_alias` map; XLA then reuses
the input buffer for the output. Two failure modes are silent:

- **declared-but-not-aliased**: a donated leaf found no aval-matching
  output (a dtype/shape drift — e.g. an optimizer leaf that upcasts, or
  a state field the step stopped returning). jax prints one easily-lost
  warning at lowering time and then permanently double-buffers that
  leaf; at production model sizes that is params-sized HBM gone.
- **donatable-but-undeclared**: a state leaf that *could* alias (same
  aval in, same out) but was never declared — bytes left on the table.

This pass reads both directly from the artifacts: declared donation from
`lowered.args_info` (per-leaf `.donated`), achieved aliasing from the
compiled HLO's `input_output_alias={...}` header, and candidate outputs
from the jaxpr's out avals. Flat leaf order == HLO parameter order for
jit-compiled functions (checked against the alias map's parameter
numbers; a mismatch degrades to a summary caveat instead of lying).
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

# one alias entry: "{out_index...}: (param_number, {param_index}, kind)"
_ALIAS_ENTRY_RE = re.compile(
    r"\{([0-9, ]*)\}\s*:\s*\(\s*(\d+)\s*,\s*\{[0-9, ]*\}\s*"
    r"(?:,\s*(may-alias|must-alias))?\s*\)")


def _balanced_braces(text: str, start: int) -> str:
    """Contents of the brace group opening at text[start] == '{' (the
    alias map nests braces, so a non-greedy regex stops too early)."""
    depth = 0
    for i in range(start, len(text)):
        if text[i] == "{":
            depth += 1
        elif text[i] == "}":
            depth -= 1
            if depth == 0:
                return text[start + 1:i]
    return ""


def parse_input_output_aliases(hlo_text: str) -> Dict[int, int]:
    """{param_number: output_index} from a compiled HLO module header.
    Empty dict when the module declares no aliasing."""
    marker = "input_output_alias="
    pos = hlo_text.find(marker)
    if pos < 0:
        return {}
    body = _balanced_braces(hlo_text, pos + len(marker))
    out: Dict[int, int] = {}
    for entry in _ALIAS_ENTRY_RE.finditer(body):
        out_idx = int(entry.group(1).split(",")[0] or 0)
        out[int(entry.group(2))] = out_idx
    return out


def _np_dtype(dtype):
    """np.dtype where possible; jax extended dtypes (PRNG keys) pass
    through unconverted — they still expose name/itemsize."""
    try:
        return np.dtype(dtype)
    except TypeError:
        return dtype


def _leaf_bytes(shape, dtype) -> int:
    return (int(np.prod(shape, dtype=np.int64))
            * int(getattr(dtype, "itemsize", 4)))


def _flatten_args_info(args_info) -> List[Tuple[str, tuple, Any, bool]]:
    """[(path, shape, dtype, donated)] in flat (HLO parameter) order."""
    import jax

    leaves_with_path = jax.tree_util.tree_flatten_with_path(args_info)[0]
    out = []
    for path, info in leaves_with_path:
        name = jax.tree_util.keystr(path)
        out.append((name, tuple(info.shape), _np_dtype(info.dtype),
                    bool(getattr(info, "donated", False))))
    return out


def check_donation(
    fn,
    args: Sequence[Any],
    state_argnums: Sequence[int] = (0,),
    lowered=None,
    compiled=None,
    out_avals: Optional[Sequence[Any]] = None,
) -> Tuple[List[dict], Dict[str, Any]]:
    """Run the donation pass over one jitted function + example args.

    `state_argnums`: which positional args hold reusable state (the
    donatable-but-undeclared scan is scoped to them — batches and rng
    keys are consumed per call, not round-tripped, so an "undeclared"
    report on them would be noise).

    Pre-lowered/compiled artifacts can be passed in to avoid paying the
    trace/compile twice when the caller already has them."""
    import jax

    if lowered is None:
        lowered = fn.lower(*args)
    if compiled is None:
        compiled = lowered.compile()
    leaves = _flatten_args_info(lowered.args_info)
    if out_avals is None:
        out_avals = jax.tree_util.tree_leaves(
            jax.eval_shape(fn, *args))
    try:
        hlo = compiled.as_text()
    except Exception as e:  # backend without text dump: degrade loudly
        return [], {"error": f"compiled HLO unavailable: {e}",
                    "declared": sum(1 for l in leaves if l[3])}
    aliases = parse_input_output_aliases(hlo)

    # which flat leaf indices belong to the state argnums
    arg_leaf_ranges: List[Tuple[int, int]] = []
    i = 0
    for a in args:
        n = len(jax.tree_util.tree_leaves(a))
        arg_leaf_ranges.append((i, i + n))
        i += n
    total_leaves = i
    state_idx = set()
    for argnum in state_argnums:
        lo, hi = arg_leaf_ranges[argnum]
        state_idx.update(range(lo, hi))

    findings: List[dict] = []
    caveats: List[str] = []
    if total_leaves != len(leaves):
        caveats.append(
            f"args_info leaf count {len(leaves)} != flat arg leaves "
            f"{total_leaves}; parameter mapping unverified")

    declared = [i for i, l in enumerate(leaves) if l[3]]
    aliased = sorted(aliases)
    bytes_donated = 0
    bytes_failed = 0
    for i in declared:
        name, shape, dtype, _ = leaves[i]
        nbytes = _leaf_bytes(shape, dtype)
        if i in aliases:
            bytes_donated += nbytes
        else:
            bytes_failed += nbytes
            findings.append({
                "pass": "donation",
                "site": name,
                "message": (
                    f"declared donation NOT aliased: {name} "
                    f"{dtype}{list(shape)} ({nbytes} B) was donated but "
                    "the compiled module aliases no output to it — the "
                    "buffer is silently double-buffered (aval drift "
                    "between the input leaf and every output)"),
                "details": {"param": i, "bytes": nbytes},
            })

    # donatable-but-undeclared: unclaimed output avals greedily matched
    # against undeclared state leaves by (shape, dtype)
    claimed_outputs = set(aliases.values())
    pool: Dict[Tuple[tuple, str], int] = {}
    for oi, aval in enumerate(out_avals):
        if oi in claimed_outputs:
            continue
        key = (tuple(aval.shape), str(_np_dtype(aval.dtype)))
        pool[key] = pool.get(key, 0) + 1
    bytes_undeclared = 0
    undeclared = 0
    for i in sorted(state_idx):
        name, shape, dtype, donated = leaves[i]
        if donated:
            continue
        key = (shape, str(dtype))
        if pool.get(key, 0) > 0:
            pool[key] -= 1
            nbytes = _leaf_bytes(shape, dtype)
            bytes_undeclared += nbytes
            undeclared += 1
            findings.append({
                "pass": "donation",
                "site": name,
                "message": (
                    f"donatable but undeclared: state leaf {name} "
                    f"{dtype}{list(shape)} has a matching output aval but "
                    f"no donation — {nbytes} B of HBM left double-"
                    "buffered (add it to donate_argnums)"),
                "details": {"param": i, "bytes": nbytes},
            })

    summary = {
        "declared": len(declared),
        "aliased": len(aliased),
        "declared_unaliased": len(declared) - sum(
            1 for i in declared if i in aliases),
        "undeclared_donatable": undeclared,
        "bytes_donated": int(bytes_donated),
        "bytes_failed": int(bytes_failed),
        "bytes_undeclared": int(bytes_undeclared),
        "caveats": caveats,
    }
    return findings, summary
