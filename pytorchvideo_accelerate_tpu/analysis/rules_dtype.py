"""Rule `dtype-literal`: f32 casts in model/ops hot modules must route
through the precision policy seam.

The models compute in bf16 by policy (`create_model(mixed_precision)`),
and the deliberate f32 islands — classifier heads, softmax logits, loss
math, reference accumulations — go through `precision.f32_island` so the
policy stays auditable (and the graphcheck dtype pass can allowlist the
islands by qualname). A bare `x.astype(jnp.float32)` or
`jnp.asarray(x, jnp.float32)` in a hot model/ops module is either an
accidental upcast (doubles the tensor's bytes, halves its MXU rate,
silently — exactly the class of bug analysis/gc_dtype.py audits in the
compiled graph) or an undeclared island; both must become explicit.

Scope: the model/ops hot modules only (`DTYPE_HOT_MODULES`). `dtype=`
*defaults* on module dataclass fields and parameter-init dtypes are not
casts and are not flagged — the rule targets value conversions. The
detection is alias-proof in the thread-factory style: module aliases
(`import jax.numpy as J`, `import numpy`) and from-import as-names
(`from jax.numpy import float32 as f32`) cannot launder a cast past the
gate. Suppressions follow the house syntax:
`# pva: disable=dtype-literal -- reason`.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Set

from pytorchvideo_accelerate_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Rule,
    dotted_name,
)

# the model/ops hot path: every module whose tensors ride the compiled
# train/serve step. The precision seam itself is exempt (it IS the rails).
DTYPE_HOT_MODULES = (
    "models/common.py",
    "models/heads.py",
    "models/resnet3d.py",
    "models/slowfast.py",
    "models/x3d.py",
    "models/r2plus1d.py",
    "models/csn.py",
    "models/mvit.py",
    "models/videomae.py",
    "ops/attention.py",
    "ops/depthwise.py",
    "ops/pallas_attention.py",
    "ops/pallas_depthwise.py",
    "ops/pallas_fused.py",
    "ops/kbench_refs.py",
    "serving/quantize.py",
)

# modules whose `float32` attribute is the flagged literal
_F32_MODULES = ("jax.numpy", "numpy", "jnp", "np")

# call names that perform a cast when handed a dtype argument
_CAST_CALLS = ("asarray", "array")


def _f32_module_aliases(tree: ast.AST) -> Set[str]:
    """Every local name `jax.numpy` / `numpy` is bound to: plain
    imports, `import jax.numpy as J` / `import numpy as n`, AND the
    `from jax import numpy [as X]` spelling — any of them could launder
    a cast otherwise."""
    out = set(_F32_MODULES)
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name in ("jax.numpy", "numpy") and alias.asname:
                    out.add(alias.asname)
        elif isinstance(node, ast.ImportFrom) and node.module == "jax":
            for alias in node.names:
                if alias.name == "numpy":
                    out.add(alias.asname or alias.name)
    return out


def _f32_name_aliases(tree: ast.AST) -> Set[str]:
    """Local names bound by `from jax.numpy|numpy import float32 [as f]`."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module in (
                "jax.numpy", "numpy"):
            for alias in node.names:
                if alias.name == "float32":
                    out.add(alias.asname or alias.name)
    return out


class DtypeLiteralRule(Rule):
    name = "dtype-literal"
    description = ("bare jnp.float32/np.float32 cast in a model/ops hot "
                   "module — route through precision.f32_island so the "
                   "bf16 policy's designed f32 islands stay auditable")

    def __init__(self, hot_modules=DTYPE_HOT_MODULES):
        self.hot_modules = tuple(hot_modules)

    def _is_f32_literal(self, node: ast.AST, modules: Set[str],
                        names: Set[str]) -> bool:
        if isinstance(node, ast.Attribute) and node.attr == "float32":
            return dotted_name(node.value) in modules
        if isinstance(node, ast.Name):
            return node.id in names
        return False

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        if not module.matches(self.hot_modules):
            return
        modules = _f32_module_aliases(module.tree)
        names = _f32_name_aliases(module.tree)

        def f32(node):
            return self._is_f32_literal(node, modules, names)

        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            dtype_args = [kw.value for kw in node.keywords
                          if kw.arg == "dtype"]
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "astype"):
                # x.astype(jnp.float32) — positional or keyword
                if any(f32(a) for a in node.args) or any(map(f32, dtype_args)):
                    yield self.finding(
                        module, node,
                        "bare `.astype(float32)` cast in a hot module: use "
                        "`precision.f32_island(x)` so the designed f32 "
                        "island is explicit (docs/STATIC_ANALYSIS.md)")
                continue
            dn = dotted_name(node.func)
            head, _, tail = dn.rpartition(".")
            if tail in _CAST_CALLS and head in modules:
                # jnp.asarray(x, jnp.float32) / np.array(x, dtype=np.float32)
                cands = list(node.args[1:2]) + dtype_args
                if any(f32(a) for a in cands):
                    yield self.finding(
                        module, node,
                        f"`{dn}(..., float32)` cast in a hot module: use "
                        "`precision.f32_island(x)` so the designed f32 "
                        "island is explicit (docs/STATIC_ANALYSIS.md)")
