"""`pva-tpu-spmdcheck` — collective-schedule divergence: static + dynamic.

ROADMAP item 4 turns the single-process forced-host emulation into a
real pod: N processes that must issue IDENTICAL ordered collective
schedules, where one host skipping a `psum` behind a
`process_index()==0` branch deadlocks everyone with no evidence. This
module is the pair of tools that proves divergence-freedom BEFORE that
PR lands, and gates it forever:

- **Static pass** (`run_spmdcheck`): the `rules_spmd` rules — four
  `spmd-divergence` finding kinds (divergent-predicate,
  branch-asymmetry, skip-path, ckpt-discipline) plus the
  `spmd-coverage` audit (every raw collective primitive inside a
  hangcheck `collective_section`) — over the hot modules. Pure
  stdlib-ast, runs with no jax anywhere.
- **Dynamic counterpart** (`parallel/schedule_recorder.py`): the
  installed recorder logs every `collective_section` entry per host;
  `diff_schedules` reports the first cross-host divergence with both
  hosts' trailing windows. The MULTICHIP bench lane records + diffs
  emulated hosts every run and headlines `spmd_schedule_divergence`.

CLI: `pva-tpu-spmdcheck [paths...]` — exit 0 clean, 1 findings, 2
usage/crash. `--selftest` seeds one violation per static kind, one
covered/uncovered primitive pair, and one injected schedule divergence
through the REAL armed `collective_section`; every seed MUST be
detected and every clean twin MUST stay clean.

Gates: `spmdcheck_findings == 0` in `bench.py --smoke` and
`scripts/analyze.sh`; `pva_spmd_findings` /
`pva_spmd_schedule_divergence` gauges + flight-ring events;
`pva-tpu-doctor diagnose()` carries `spmd_snapshot()`. See
docs/STATIC_ANALYSIS.md § spmdcheck.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Any, Dict, List, Optional, Sequence

from pytorchvideo_accelerate_tpu.analysis.core import lint_source, run_lint
from pytorchvideo_accelerate_tpu.analysis.rules_spmd import (
    DIVERGENCE_KINDS,
    spmd_rules,
)

# the hot-module surface the rules gate on lives entirely inside the
# package tree; linting the whole package keeps the entrypoint stable as
# hot modules are added
DEFAULT_PATHS = ("pytorchvideo_accelerate_tpu",)

_LAST_REPORT: Optional[dict] = None


def run_spmdcheck(paths: Optional[Sequence[str]] = None,
                  log=None) -> dict:
    """Run the static pass; returns the report dict (stashed for
    `spmd_snapshot`, published to obs)."""
    global _LAST_REPORT
    t0 = time.perf_counter()
    paths = list(paths or DEFAULT_PATHS)
    findings = run_lint(paths, spmd_rules())
    by_rule: Dict[str, int] = {}
    by_kind: Dict[str, int] = {}
    for f in findings:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
        if f.rule == "spmd-divergence":
            kind = f.message.split(":", 1)[0]
            if kind in DIVERGENCE_KINDS:
                by_kind[kind] = by_kind.get(kind, 0) + 1
    report: Dict[str, Any] = {
        "paths": paths,
        "findings_total": len(findings),
        "by_rule": by_rule,
        "by_kind": by_kind,
        "findings": [
            {"path": f.path, "line": f.line, "col": f.col,
             "rule": f.rule, "message": f.message} for f in findings],
        "elapsed_s": round(time.perf_counter() - t0, 2),
    }
    if log:
        log(f"[spmdcheck] {len(findings)} finding(s) over {paths} "
            f"in {report['elapsed_s']}s")
    _LAST_REPORT = report
    publish(report)
    return report


def finding_count(report: dict) -> int:
    return int(report.get("findings_total", 0))


def format_report(report: dict, max_findings: int = 20) -> str:
    lines = [f"pva-tpu-spmdcheck: {report.get('findings_total', 0)} "
             f"finding(s) over {', '.join(report.get('paths', []))} "
             f"in {report.get('elapsed_s')}s "
             f"(by_rule={report.get('by_rule') or {}})"]
    for i, f in enumerate(report.get("findings", ())):
        if i >= max_findings:
            lines.append("  ... (truncated)")
            break
        lines.append(f"  {f['path']}:{f['line']}:{f['col']}: "
                     f"[{f['rule']}] {f['message']}")
    return "\n".join(lines)


def publish(report: dict) -> None:
    """`pva_spmd_findings` gauge + a flight-ring event (the
    graphcheck/tsan publish discipline; telemetry stays optional). The
    dynamic half's `pva_spmd_schedule_divergence` gauge is published by
    `schedule_recorder.publish_schedule_report`."""
    try:
        from pytorchvideo_accelerate_tpu import obs

        obs.get_registry().gauge(
            "pva_spmd_findings",
            "total findings of the last pva-tpu-spmdcheck static pass "
            "(spmd-divergence kinds + spmd-coverage)",
        ).set(report.get("findings_total", 0))
        obs.get_recorder().record(
            "spmd", "static pass",
            findings=report.get("findings_total", 0),
            by_rule=report.get("by_rule") or {},
            elapsed_s=report.get("elapsed_s"))
    except Exception:  # telemetry must never fail the pass
        pass


def spmd_snapshot() -> dict:
    """Doctor view (utils/device_doctor.diagnose): the last in-process
    static pass + the live recorder's schedule counts, or ran=False."""
    rec_snap = None
    try:
        from pytorchvideo_accelerate_tpu.parallel.schedule_recorder import (
            current_recorder,
        )

        rec = current_recorder()
        if rec is not None:
            rec_snap = rec.snapshot()
    except Exception:  # pragma: no cover - snapshot must never raise
        pass
    if _LAST_REPORT is None:
        return {"ran": False, "recorder": rec_snap}
    rep = _LAST_REPORT
    return {
        "ran": True,
        "findings_total": rep.get("findings_total", 0),
        "by_rule": rep.get("by_rule") or {},
        "by_kind": rep.get("by_kind") or {},
        "elapsed_s": rep.get("elapsed_s"),
        "finding_heads": [f["message"][:160]
                          for f in rep.get("findings", ())][:10],
        "recorder": rec_snap,
    }


# --- selftest fixtures ------------------------------------------------------
# All anchored at a hot-module path so the rules engage; each positive
# seed has a clean twin (and the suppression syntax is exercised once
# per rule name).

_FIXTURE_PATH = "pytorchvideo_accelerate_tpu/trainer/_spmd_fixture.py"

_SEED_DIVERGENT = """\
import jax
from pytorchvideo_accelerate_tpu.parallel.collectives import host_broadcast

def resume(x):
    if jax.process_index() == 0:
        host_broadcast(x)
"""

_CLEAN_DIVERGENT = """\
import jax
from pytorchvideo_accelerate_tpu.parallel.collectives import host_broadcast

def resume(x):
    if jax.process_count() > 1:
        host_broadcast(x)
"""

_SUPPRESSED_DIVERGENT = """\
import jax
from pytorchvideo_accelerate_tpu.parallel.collectives import host_broadcast

def resume(x):
    if jax.process_index() == 0:
        host_broadcast(x)  # pva: disable=spmd-divergence -- selftest seed
"""

_SEED_ASYMMETRY = """\
from pytorchvideo_accelerate_tpu.parallel.collectives import host_broadcast

def maybe(x, manifest):
    if load_manifest(manifest):
        host_broadcast(x)
    else:
        log_skip(manifest)
"""

_CLEAN_ASYMMETRY = """\
from pytorchvideo_accelerate_tpu.parallel.collectives import host_broadcast

def maybe(x, manifest):
    if load_manifest(manifest):
        host_broadcast(x)
    else:
        host_broadcast(x)
"""

_SEED_SKIP = """\
import os
from pytorchvideo_accelerate_tpu.parallel.collectives import host_broadcast

def sync(x):
    if not os.path.exists("/tmp/marker"):
        return None
    host_broadcast(x)
"""

_CLEAN_SKIP = """\
from pytorchvideo_accelerate_tpu.parallel.collectives import host_broadcast

def sync(x, ready):
    if not ready:
        return None
    host_broadcast(x)
"""

_SEED_CKPT = """\
from pytorchvideo_accelerate_tpu.reliability.atomic import atomic_write_json

def export(tree, path):
    atomic_write_json(path, tree)
"""

_CLEAN_CKPT = """\
from pytorchvideo_accelerate_tpu.parallel.distributed import is_main_process
from pytorchvideo_accelerate_tpu.reliability.atomic import atomic_write_json

def export(tree, path):
    if is_main_process():
        atomic_write_json(path, tree)
"""

_SEED_DERIVED = """\
import jax
from pytorchvideo_accelerate_tpu.parallel.collectives import host_broadcast

def _bcast_helper(x):
    host_broadcast(x)

def run(x):
    if jax.process_index() == 0:
        _bcast_helper(x)
"""

_SEED_COVERAGE = """\
from jax.experimental import multihost_utils

def barrier():
    multihost_utils.sync_global_devices("fence")
"""

_CLEAN_COVERAGE = """\
from jax.experimental import multihost_utils
from pytorchvideo_accelerate_tpu.parallel.hangcheck import collective_section

def barrier():
    with collective_section("barrier", name="fence"):
        multihost_utils.sync_global_devices("fence")
"""

_SUPPRESSED_COVERAGE = """\
from jax.experimental import multihost_utils

def barrier():
    multihost_utils.sync_global_devices("fence")  # pva: disable=spmd-coverage -- selftest seed
"""


def _lint_fixture(source: str):
    return lint_source(source, _FIXTURE_PATH, spmd_rules())


def selftest(log=print) -> int:
    """Seed one violation per static kind + the coverage audit + one
    injected schedule divergence through the REAL armed
    `collective_section`; every seed MUST be detected and every clean
    twin MUST stay clean. Returns failure count."""
    failures = 0

    def expect(cond: bool, what: str):
        nonlocal failures
        if cond:
            log(f"[selftest] PASS {what}")
        else:
            failures += 1
            log(f"[selftest] FAIL {what}")

    def kinds(findings):
        return [f.message.split(":", 1)[0] for f in findings
                if f.rule == "spmd-divergence"]

    # (1) divergent-predicate
    f = _lint_fixture(_SEED_DIVERGENT)
    expect("divergent-predicate" in kinds(f),
           "static: collective under process_index() branch detected")
    expect(not _lint_fixture(_CLEAN_DIVERGENT),
           "static: uniform process_count() guard stays clean")
    expect(not _lint_fixture(_SUPPRESSED_DIVERGENT),
           "static: spmd-divergence suppression silences the seed")

    # (2) branch-asymmetry
    f = _lint_fixture(_SEED_ASYMMETRY)
    expect("branch-asymmetry" in kinds(f),
           "static: one-armed collective under dynamic test detected")
    expect(not _lint_fixture(_CLEAN_ASYMMETRY),
           "static: collective-symmetric arms stay clean")

    # (3) skip-path
    f = _lint_fixture(_SEED_SKIP)
    expect("skip-path" in kinds(f),
           "static: early return under fs probe skipping a collective "
           "detected")
    expect(not _lint_fixture(_CLEAN_SKIP),
           "static: uniform early return stays clean")

    # (4) ckpt-discipline
    f = _lint_fixture(_SEED_CKPT)
    expect("ckpt-discipline" in kinds(f),
           "static: unguarded checkpoint-artifact write detected")
    expect(not _lint_fixture(_CLEAN_CKPT),
           "static: is_main_process()-guarded write stays clean")

    # one-level interprocedural carrier
    f = _lint_fixture(_SEED_DERIVED)
    expect(any("_bcast_helper" in x.message for x in f),
           "static: helper that issues collectives carries the site one "
           "call level up")

    # coverage audit
    f = _lint_fixture(_SEED_COVERAGE)
    expect(any(x.rule == "spmd-coverage" for x in f),
           "static: raw primitive outside collective_section detected")
    expect(not _lint_fixture(_CLEAN_COVERAGE),
           "static: collective_section-wrapped primitive stays clean")
    expect(not _lint_fixture(_SUPPRESSED_COVERAGE),
           "static: spmd-coverage suppression silences the seed")

    # dynamic: identical emulated schedules clean; an injected skip MUST
    # be caught at the exact op, through the real collective_section hook
    from pytorchvideo_accelerate_tpu.parallel.hangcheck import (
        collective_section,
    )
    from pytorchvideo_accelerate_tpu.parallel.schedule_recorder import (
        CollectiveScheduleRecorder,
        diff_schedules,
        install_schedule_recorder,
        uninstall_schedule_recorder,
    )

    rec = CollectiveScheduleRecorder()
    install_schedule_recorder(rec)
    try:
        for h in range(2):
            with rec.as_host(f"host={h}/2"):
                for i in range(3):
                    with collective_section("step_dispatch", step=i):
                        pass
                with collective_section("epoch_sync"):
                    pass
        clean = diff_schedules(rec.schedules())
        expect(not clean["diverged"]
               and clean["lengths"] == {"host=0/2": 4, "host=1/2": 4},
               "dynamic: identical emulated schedules diff clean")

        rec.clear()
        for h in range(2):
            with rec.as_host(f"host={h}/2"):
                with collective_section("step_dispatch", step=0):
                    pass
                if h == 0:  # host 1 SKIPS the epoch_sync — the bug shape
                    with collective_section("epoch_sync"):
                        pass
                with collective_section("ckpt_save", step=0):
                    pass
        bad = diff_schedules(rec.schedules())
        first = bad.get("first_divergence") or {}
        hosts = first.get("hosts") or {}
        expect(bad["diverged"] and first.get("tick") == 1
               and (hosts.get("host=0/2") or [None, None])[1] == "epoch_sync"
               and (hosts.get("host=1/2") or [None, None])[1] == "ckpt_save",
               "dynamic: injected skipped-collective divergence detected "
               "at the exact op")
        expect(len((first.get("window") or {}).get("host=0/2", ())) >= 2,
               "dynamic: divergence report carries trailing windows")
    finally:
        uninstall_schedule_recorder()

    # disarmed = structurally silent: no recorder, no records
    before = rec.counts()
    with collective_section("step_dispatch", step=99):
        pass
    expect(rec.counts() == before,
           "dynamic: disarmed collective_section records nothing")

    return failures


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="pva-tpu-spmdcheck",
        description="collective-schedule divergence analysis over the "
                    "hot modules: divergent predicates, asymmetric "
                    "branches, skip paths, checkpoint-write discipline, "
                    "collective_section coverage "
                    "(docs/STATIC_ANALYSIS.md § spmdcheck)")
    ap.add_argument("paths", nargs="*", default=list(DEFAULT_PATHS),
                    help="files/trees to analyze (default: the package)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--selftest", action="store_true",
                    help="seed one violation per rule kind plus an "
                         "injected schedule divergence; exit 0 only when "
                         "every one is detected")
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code not in (0, None) else 0

    def log(*a):
        print(*a, file=sys.stderr, flush=True)

    if args.selftest:
        failures = selftest(log=log)
        if failures:
            log(f"pva-tpu-spmdcheck --selftest: {failures} seeded "
                "violation(s) NOT detected")
            return 1
        log("pva-tpu-spmdcheck --selftest: all seeded violations "
            "detected; clean constructions clean")
        return 0

    try:
        report = run_spmdcheck(paths=args.paths, log=log)
    except Exception as e:
        log(f"pva-tpu-spmdcheck: analysis failed: "
            f"{type(e).__name__}: {e}")
        return 2
    if args.format == "json":
        print(json.dumps(report, default=str))
    else:
        print(format_report(report))
    return 1 if report["findings_total"] else 0


if __name__ == "__main__":
    sys.exit(main())
