"""Rule `recompile`: call patterns that retrigger XLA compilation.

XLA compiles one executable per (function, static args, input shapes).
Two legal-Python patterns silently turn that into a compile per call:

1. **Unmarked static-looking arguments.** Calling a jitted function with
   a Python literal, `len(...)`, or a `.shape`-derived value as a
   positional argument traces a fresh executable every time the value
   changes (and weak-type churn can recompile even when it doesn't).
   Those arguments belong in `static_argnums`/`static_argnames` — or
   should be baked into the closure at build time, which is what
   `make_train_step` and friends do.

2. **jit-in-loop.** `jax.jit(f)` inside a `for`/`while` body constructs
   a FRESH jit wrapper per iteration — each with its own empty compile
   cache, so every iteration pays a full trace+compile (the classic
   "why is my serving loop 1000x slow" bug; the engine's keyed
   `self._fns` cache exists precisely to avoid this).

Static detection is heuristic by construction: it tracks names bound to
`jax.jit(...)` / `pjit(...)` results inside one module (`f = jax.jit(g)`
and `self.f = jax.jit(g)`) and inspects calls through those names. The
runtime counterpart (`analysis/recompile_guard.py` -> the
`pva_train_recompiles` gauge, asserted zero in `bench.py --smoke`) gives
the rule teeth beyond what syntax can prove.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set, Tuple

from pytorchvideo_accelerate_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Rule,
    call_name,
)

_JIT_NAMES = ("jit", "pjit")


def _is_jit_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and call_name(node).rsplit(".", 1)[-1] in _JIT_NAMES)


def _static_argnums(call: ast.Call) -> Tuple[Set[int], bool]:
    """(positions marked static, has_static_argnames) for a jit(...) call.
    Unparseable (computed) markings disable flagging for that callable —
    the rule must not guess."""
    nums: Set[int] = set()
    has_names = False
    parseable = True
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            vals = (kw.value.elts if isinstance(kw.value, (ast.Tuple, ast.List))
                    else [kw.value])
            for v in vals:
                if isinstance(v, ast.Constant) and isinstance(v.value, int):
                    nums.add(v.value)
                else:
                    parseable = False
        elif kw.arg == "static_argnames":
            has_names = True
    if not parseable:
        return nums, True  # treat as "anything may be static": stay quiet
    return nums, has_names


def _shape_derived(node: ast.AST) -> bool:
    """Does the expression read `.shape` / `.ndim` / call `len()` anywhere?"""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in ("shape", "ndim"):
            return True
        if isinstance(sub, ast.Call) and call_name(sub) == "len":
            return True
    return False


class RecompileHazardRule(Rule):
    name = "recompile"
    description = ("jitted callables fed unmarked static-looking args, or "
                   "jax.jit constructed inside a loop")

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        # pass 1: names bound to jit(...) results, with their static markers
        jitted: Dict[str, Tuple[Set[int], bool]] = {}
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Assign) or not _is_jit_call(node.value):
                continue
            statics = _static_argnums(node.value)
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    jitted[tgt.id] = statics
                elif (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    jitted["self." + tgt.attr] = statics

        yield from self._check_calls(module, jitted)
        yield from self._check_loops(module)

    def _check_calls(self, module: ModuleInfo,
                     jitted: Dict[str, Tuple[Set[int], bool]]
                     ) -> Iterable[Finding]:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            statics = jitted.get(call_name(node))
            if statics is None:
                continue
            static_nums, has_names = statics
            if has_names:
                continue  # named statics: positions unknowable, stay quiet
            for i, arg in enumerate(node.args):
                if i in static_nums:
                    continue
                if isinstance(arg, ast.Constant) and isinstance(
                        arg.value, (int, float, bool)):
                    yield self.finding(
                        module, arg,
                        f"literal positional arg {i} to jitted "
                        f"`{call_name(node)}` is traced as a weak-typed "
                        "array — mark it static_argnums or close over it "
                        "at build time")
                elif _shape_derived(arg):
                    yield self.finding(
                        module, arg,
                        f"shape/len-derived positional arg {i} to jitted "
                        f"`{call_name(node)}` recompiles on every new "
                        "geometry — mark it static_argnums (intended) or "
                        "derive it inside the traced function")

    def _check_loops(self, module: ModuleInfo) -> Iterable[Finding]:
        loop_bodies: List[ast.AST] = []
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.For, ast.While)):
                loop_bodies.append(node)
        seen: Set[int] = set()
        for loop in loop_bodies:
            for node in ast.walk(loop):
                if node is loop or id(node) in seen:
                    continue
                # a nested def/lambda inside the loop body runs per CALL,
                # not per iteration — jit there is the cached-factory
                # pattern, not the hazard
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.Lambda)):
                    for sub in ast.walk(node):
                        seen.add(id(sub))
                    continue
                if _is_jit_call(node):
                    seen.add(id(node))
                    yield self.finding(
                        module, node,
                        "`jit(...)` constructed inside a loop builds a "
                        "fresh wrapper (and empty compile cache) per "
                        "iteration — hoist it out or cache it by key "
                        "(serving/engine.py `_fns` is the pattern)")
