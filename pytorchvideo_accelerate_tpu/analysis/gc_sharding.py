"""graphcheck sharding pass: find implicit regathers of sharded inputs.

GSPMD propagates the in-shardings through the graph and inserts whatever
collectives make each eqn's operands compatible — silently. Most of what
it inserts is the plan (the DP gradient psum, halo exchanges); the
hazard is the *implicit full regather*: an eqn whose operand shardings
cannot be reconciled, so a batch- or model-sharded tensor is all-gathered
onto every device right in the hot path (HBM spike + ICI traffic that
no source line admits to).

This pass re-propagates the in-shardings statically over the closed
jaxpr — dim→axes maps flowing through elementwise/broadcast/transpose/
reshape/reduce/dot/conv/scan/pjit — and flags the three reconciliation
points where a regather is forced rather than chosen:

- `dot_general` whose contracting dims are sharded on one operand but
  not matching on the other (one side must be gathered before the
  matmul; the agreeing case — both sides sharded alike — is the normal
  psum-after-partial-matmul plan and is NOT flagged);
- `reshape` that destroys a sharded dim's block structure (the sharded
  dim is not the major factor of its reshape group, or the new major
  extent doesn't tile by it) — GSPMD must relayout the full tensor;
- `concatenate` along a sharded dim.

Everything it can't model (gather/while/dynamic slicing) conservatively
drops the mapping instead of guessing: a lost mapping can only cause
false NEGATIVES downstream, never a false alarm — the right polarity
for a gate that must hold `graphcheck_findings == 0` on the clean tree.
Reverses (`rev`) keep their mapping un-flagged: GSPMD lowers a reversal
of a sharded dim to a one-hop collective permute (the mixup flipped-
batch idiom), not a regather.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

DimMap = Dict[int, Tuple[str, ...]]  # dim index -> mesh axis names

_ELEMENTWISE_SAFE = True  # same-shape eqns merge operand maps


def _frames(eqn) -> List[Tuple[str, str]]:
    try:
        from jax._src import source_info_util

        return [(f.function_name, os.path.basename(f.file_name))
                for f in source_info_util.user_frames(eqn.source_info)]
    except Exception:
        return []


def _site(eqn) -> str:
    fr = _frames(eqn)
    if not fr:
        return "<unknown>"
    func, base = fr[0]
    return f"{base}:{func}"


def spec_to_dim_map(spec, ndim: int) -> DimMap:
    """PartitionSpec -> {dim: axes}; None/missing entries dropped."""
    out: DimMap = {}
    if spec is None:
        return out
    for d, entry in enumerate(tuple(spec)[:ndim]):
        if entry is None:
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        if axes:
            out[d] = tuple(str(a) for a in axes)
    return out


def sharding_dim_map(sharding, ndim: int) -> DimMap:
    """NamedSharding -> dim map; anything else (SingleDevice, GSPMD
    without a spec) -> empty (conservative)."""
    spec = getattr(sharding, "spec", None)
    return spec_to_dim_map(spec, ndim)


def _bytes_of(aval) -> int:
    itemsize = int(getattr(getattr(aval, "dtype", None), "itemsize", 4))
    return int(np.prod(aval.shape, dtype=np.int64)) * itemsize


def _reshape_groups(in_shape, out_shape):
    """Greedy factor groups [(in_dims, out_dims)] with equal products."""
    groups = []
    i = j = 0
    ni, nj = len(in_shape), len(out_shape)
    while i < ni or j < nj:
        gi, gj = [i], [j] if j < nj else []
        pi = in_shape[i] if i < ni else 1
        pj = out_shape[j] if j < nj else 1
        i += 1
        j += 1
        while pi != pj:
            if pi < pj:
                if i >= ni:
                    break
                pi *= in_shape[i]
                gi.append(i)
                i += 1
            else:
                if j >= nj:
                    break
                pj *= out_shape[j]
                gj.append(j)
                j += 1
        groups.append((gi, gj))
    return groups


def check_sharding(closed_jaxpr, in_dim_maps: Sequence[DimMap],
                   allowlist: Optional[Set[str]] = None,
                   min_bytes: int = 1 << 16,
                   ) -> Tuple[List[dict], Dict[str, Any]]:
    """Propagate `in_dim_maps` (one per flat jaxpr input, in order) and
    flag forced regathers. `min_bytes`: ignore regathers of small
    tensors (a gathered scalar/bias is noise; the hazard is clip-sized
    and params-sized tensors)."""
    from jax._src import core as jcore

    allowlist = allowlist or set()
    findings: List[dict] = []
    seen: Set[str] = set()
    stats = {"tracked_inputs": sum(1 for m in in_dim_maps if m),
             "dot_regathers": 0, "reshape_losses": 0, "concat_regathers": 0}

    def sub_closed(value):
        out = []
        if isinstance(value, jcore.ClosedJaxpr):
            out.append(value)
        elif isinstance(value, (tuple, list)):
            for v in value:
                out.extend(sub_closed(v))
        return out

    def emit(kind: str, stat: str, eqn, message: str, nbytes: int):
        site = _site(eqn)
        fr = _frames(eqn)
        if any(f in allowlist or b in allowlist or f"{b}:{f}" in allowlist
               for f, b in fr):
            return
        key = f"{kind}@{site}"
        if key in seen:
            return
        seen.add(key)
        stats[stat] += 1
        findings.append({
            "pass": "sharding", "site": site,
            "message": message,
            "details": {"kind": kind, "bytes": nbytes,
                        "frames": [f"{b}:{f}" for f, b in fr[:4]]},
        })

    def walk(jaxpr, env: Dict[Any, DimMap]) -> None:
        def get(v) -> DimMap:
            if isinstance(v, jcore.Literal):
                return {}
            return env.get(v, {})

        def put(v, m: DimMap) -> None:
            if m:
                env[v] = m

        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            ins = eqn.invars
            outs = eqn.outvars
            if name == "dot_general":
                lm, rm = get(ins[0]), get(ins[1])
                (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
                for ld, rd in zip(lc, rc):
                    la, ra = lm.get(ld), rm.get(rd)
                    if la == ra:
                        continue  # agreeing (incl. both-None): psum plan
                    shard_side, aval = ((("lhs", ins[0].aval) if la else
                                         ("rhs", ins[1].aval)))
                    nbytes = _bytes_of(aval)
                    if nbytes < min_bytes:
                        continue
                    axes = la or ra
                    emit(
                        "dot-contract", "dot_regathers", eqn,
                        f"dot_general at {_site(eqn)} contracts a dim "
                        f"sharded over {axes} on its {shard_side} "
                        f"({aval.dtype}{list(aval.shape)}, {nbytes} B) "
                        "while the other operand is not sharded to match "
                        "— GSPMD must all-gather one side before the "
                        "matmul (implicit full regather in the hot path)",
                        nbytes)
                # out: batch dims then lhs free then rhs free
                om: DimMap = {}
                pos = 0
                for ld in lb:
                    if ld in lm:
                        om[pos] = lm[ld]
                    pos += 1
                for d in range(len(ins[0].aval.shape)):
                    if d in set(lc) | set(lb):
                        continue
                    if d in lm:
                        om[pos] = lm[d]
                    pos += 1
                for d in range(len(ins[1].aval.shape)):
                    if d in set(rc) | set(rb):
                        continue
                    if d in rm:
                        om[pos] = rm[d]
                    pos += 1
                put(outs[0], om)
                continue
            if name == "conv_general_dilated":
                dn = eqn.params["dimension_numbers"]
                lm = get(ins[0])
                om = {}
                if dn.lhs_spec[0] in lm:
                    om[dn.out_spec[0]] = lm[dn.lhs_spec[0]]
                put(outs[0], om)
                continue
            if name == "reshape":
                m = get(ins[0])
                if not m:
                    continue
                in_shape = tuple(ins[0].aval.shape)
                out_shape = tuple(outs[0].aval.shape)
                om = {}
                groups = _reshape_groups(in_shape, out_shape)
                for d, axes in m.items():
                    grp = next((g for g in groups if d in g[0]), None)
                    if grp is None or not grp[1]:
                        continue
                    major_in = grp[0][0]
                    major_out = grp[1][0]
                    in_d = in_shape[d]
                    out_first = out_shape[major_out]
                    if d == major_in and in_d > 0 and (
                            out_first % in_d == 0 or in_d % out_first == 0):
                        om[major_out] = axes
                        continue
                    nbytes = _bytes_of(ins[0].aval)
                    if nbytes < min_bytes:
                        continue
                    emit(
                        "reshape-loss", "reshape_losses", eqn,
                        f"reshape at {_site(eqn)} "
                        f"{list(in_shape)}->{list(out_shape)} destroys the "
                        f"block structure of dim {d} sharded over {axes} "
                        f"({nbytes} B): GSPMD must relayout the full "
                        "tensor (implicit regather)",
                        nbytes)
                put(outs[0], om)
                continue
            if name == "concatenate":
                dim = eqn.params["dimension"]
                maps = [get(v) for v in ins]
                for v, m in zip(ins, maps):
                    if dim in m:
                        nbytes = _bytes_of(v.aval)
                        if nbytes >= min_bytes:
                            emit(
                                "concat-sharded-dim", "concat_regathers",
                                eqn,
                                f"concatenate at {_site(eqn)} joins along "
                                f"dim {dim} sharded over {m[dim]} "
                                f"({nbytes} B): the shards must be "
                                "gathered to lay out the result",
                                nbytes)
                om = {}
                for m in maps:
                    for d, axes in m.items():
                        if d != dim:
                            om.setdefault(d, axes)
                put(outs[0], om)
                continue
            if name == "transpose":
                m = get(ins[0])
                perm = eqn.params["permutation"]
                put(outs[0], {j: m[perm[j]] for j in range(len(perm))
                              if perm[j] in m})
                continue
            if name == "broadcast_in_dim":
                m = get(ins[0])
                bd = eqn.params["broadcast_dimensions"]
                put(outs[0], {bd[d]: axes for d, axes in m.items()
                              if d < len(bd)})
                continue
            if name in ("reduce_sum", "reduce_max", "reduce_min",
                        "reduce_prod", "reduce_and", "reduce_or",
                        "argmax", "argmin"):
                m = get(ins[0])
                axes_p = eqn.params.get("axes", ())
                dropped = set(axes_p)
                om = {}
                for d, ax in m.items():
                    if d in dropped:
                        continue  # reduction over a shard = psum, fine
                    om[d - sum(1 for a in dropped if a < d)] = ax
                put(outs[0], om)
                continue
            if name in ("reduce_window_max", "reduce_window_sum",
                        "select_and_scatter_add"):
                src = ins[-1] if name == "select_and_scatter_add" else ins[0]
                m = get(src)
                wd = eqn.params.get("window_dimensions")
                if wd is not None:
                    put(outs[0], {d: ax for d, ax in m.items()
                                  if d < len(wd) and wd[d] == 1})
                continue
            if name in ("rev", "convert_element_type", "copy",
                        "stop_gradient", "select_n", "pad"):
                src = ins[1] if name == "select_n" and len(ins) > 1 else ins[0]
                put(outs[0], dict(get(src)))
                continue
            if name == "sharding_constraint":
                sh = eqn.params.get("sharding")
                m = sharding_dim_map(sh, len(outs[0].aval.shape))
                put(outs[0], m or dict(get(ins[0])))
                continue
            if name == "scan":
                inner = eqn.params["jaxpr"]
                n_c = eqn.params["num_consts"]
                n_k = eqn.params["num_carry"]
                sub_env: Dict[Any, DimMap] = {}
                for k, (iv, sv) in enumerate(
                        zip(ins, inner.jaxpr.invars)):
                    m = get(iv)
                    if k >= n_c + n_k:  # xs: leading scan axis sliced off
                        m = {d - 1: ax for d, ax in m.items() if d > 0}
                    sub_env[sv] = m
                walk(inner.jaxpr, sub_env)
                for k, (ov, so) in enumerate(
                        zip(outs, inner.jaxpr.outvars)):
                    if isinstance(so, jcore.Literal):
                        continue
                    m = sub_env.get(so, {})
                    if k >= n_k:  # ys: stacked along a new leading axis
                        m = {d + 1: ax for d, ax in m.items()}
                    put(ov, m)
                continue
            subs = []
            for v in eqn.params.values():
                subs.extend(sub_closed(v))
            if len(subs) == 1 and len(subs[0].jaxpr.invars) == len(ins):
                inner = subs[0]
                sub_env = {sv: get(iv)
                           for iv, sv in zip(ins, inner.jaxpr.invars)}
                walk(inner.jaxpr, sub_env)
                for ov, so in zip(outs, inner.jaxpr.outvars):
                    if not isinstance(so, jcore.Literal):
                        put(ov, sub_env.get(so, {}))
                continue
            # same-shape elementwise: merge operand maps (first wins)
            out_shape = tuple(getattr(outs[0].aval, "shape", ()))
            if _ELEMENTWISE_SAFE and all(
                    tuple(getattr(v.aval, "shape", ())) == out_shape
                    for v in ins if not isinstance(v, jcore.Literal)):
                om = {}
                for v in ins:
                    for d, ax in get(v).items():
                        om.setdefault(d, ax)
                for ov in outs:
                    if tuple(getattr(ov.aval, "shape", ())) == out_shape:
                        put(ov, dict(om))
                continue
            # unknown structure: drop the mapping (conservative — can
            # only suppress findings, never invent one)

    env: Dict[Any, DimMap] = {}
    for v, m in zip(closed_jaxpr.jaxpr.invars, in_dim_maps):
        if m:
            env[v] = dict(m)
    walk(closed_jaxpr.jaxpr, env)
    return findings, stats
