"""Rule `mesh-discipline`: keep device placement on the mesh rails.

The 2-D (data, model) train mesh (parallel/mesh.py, docs/PARALLELISM.md)
only delivers its contracts — layout-settled states (zero steady-state
recompiles), mesh-portable checkpoints, per-family model-axis rules — when
every placement in the hot path routes through the `parallel/` seams:
`make_mesh`/`make_train_mesh` for mesh construction and
`shard_batch`/`shard_params`/`shard_state` for array placement. A bare
`jax.device_put(...)` in a hot module commits an array to a layout the
sharding rules never saw (the exact uncommitted-leaf class of bug the
`pva_train_recompiles` guard caught in PR 4), and a hand-built
`Mesh(...)` bypasses the axis-name resolution (`batch_axes`/`model_axis`/
`cp_axis`) that keeps the code portable across both mesh layouts.

Scope mirrors the host-sync rule: HOT_MODULES only (the steady-state
train/serve path) — cold modules, tools, and the `parallel/` package
itself (which IS the rails) place arrays freely. The detection mirrors the
thread-factory rule: module aliases (`import jax.sharding as js`) and
from-import as-names (`from jax.sharding import Mesh as M`) cannot launder
a construction past the gate. Suppressions follow the house syntax:
`# pva: disable=mesh-discipline -- reason`.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Set

from pytorchvideo_accelerate_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Rule,
    call_name,
)
from pytorchvideo_accelerate_tpu.analysis.rules_host_sync import HOT_MODULES

# jax modules whose `Mesh` / `device_put` attributes are the flagged ones
_JAX_MODULES = ("jax", "jax.sharding", "jax.experimental")


def _jax_module_aliases(tree: ast.AST) -> Set[str]:
    """Every local name a jax module is bound to: "jax", "jax.sharding",
    plus `import jax.sharding as js` / `import jax as j` aliases."""
    out = set(_JAX_MODULES)
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name in _JAX_MODULES and alias.asname:
                    out.add(alias.asname)
    return out


def _from_import_aliases(tree: ast.AST) -> Dict[str, str]:
    """local name -> flagged symbol, for `from jax[.sharding] import X [as Y]`."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module in _JAX_MODULES:
            for alias in node.names:
                if alias.name in ("Mesh", "device_put"):
                    out[alias.asname or alias.name] = alias.name
    return out


_ADVICE = {
    "Mesh": ("construct meshes through `parallel.mesh.make_train_mesh` / "
             "`make_mesh` so axis names resolve portably "
             "(batch_axes/model_axis/cp_axis)"),
    "device_put": ("place arrays through `parallel.sharding` "
                   "(shard_batch/shard_params/shard_state) so the layout "
                   "is committed under the mesh rules — a bare placement "
                   "here is the silent-recompile class of bug the "
                   "pva_train_recompiles guard exists for"),
}


class MeshDisciplineRule(Rule):
    name = "mesh-discipline"
    description = ("bare jax.device_put / jax.sharding.Mesh construction in "
                   "a hot module — route through parallel/mesh.py + "
                   "parallel/sharding.py")

    def __init__(self, hot_modules=HOT_MODULES):
        self.hot_modules = tuple(hot_modules)

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        if not module.matches(self.hot_modules):
            return
        modules = _jax_module_aliases(module.tree)
        froms = _from_import_aliases(module.tree)
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            dn = call_name(node)
            sym = None
            if "." in dn:
                head, tail = dn.rsplit(".", 1)
                if head in modules and tail in ("Mesh", "device_put"):
                    sym = tail
            elif dn in froms:
                sym = froms[dn]
            if sym is not None:
                yield self.finding(
                    module, node, f"`{dn}(...)` in a hot module: {_ADVICE[sym]}")
