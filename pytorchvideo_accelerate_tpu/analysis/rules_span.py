"""Rule `span-discipline`: `obs.span(...)` must be a `with` context.

`obs.span("name")` RETURNS a context manager; it times nothing until
entered. A bare statement call — `obs.span("step")` on its own line,
usually a refactor leftover where the `with` got lost — silently records
zero spans while reading like instrumentation, which then skews the
per-window breakdown (`obs/unattributed_s` grows and nobody knows why).

Flagged: expression statements whose value is a call to a bare or
dotted `span(...)` (the result is discarded on the spot). Returning,
assigning, or entering the span are all legitimate and untouched
(`obs/spans.py`'s own `span()` facade returns one).
"""

from __future__ import annotations

import ast
from typing import Iterable

from pytorchvideo_accelerate_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Rule,
    call_name,
)


class SpanDisciplineRule(Rule):
    name = "span-discipline"
    description = "span(...) call whose context manager is discarded unused"

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Expr)
                    and isinstance(node.value, ast.Call)):
                continue
            name = call_name(node.value)
            if name.rsplit(".", 1)[-1] == "span":
                yield self.finding(
                    module, node,
                    f"`{name}(...)` returns a context manager that is "
                    "discarded here — it times nothing until entered; "
                    "write `with " + (name or "span") + "(...):`")
