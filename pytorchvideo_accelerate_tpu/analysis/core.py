"""Rule engine for the pva-tpu-lint static-analysis pass.

Everything here is stdlib-`ast` + `tokenize`: the linter must run in CI,
in `bench.py --smoke`, and inside `pva-tpu-doctor` without importing jax
(or the package under analysis — a module with a broken import must still
be lintable).

The moving parts:

- `Finding`: one violation (path, line, col, rule, message).
- `Rule`: a named check over one parsed module (`ModuleInfo`), yielding
  findings. Concrete rules live in the `rules_*` siblings and register
  through `default_rules()`.
- Suppressions: `# pva: disable=<rule>[,<rule>...][ -- reason]` on the
  FIRST line of the flagged statement silences those rules for that line
  (`all` silences everything). The reason text after ` -- ` is surfaced
  by `utils/device_doctor.lint_snapshot()` so outstanding suppressions
  stay auditable instead of rotting silently.
- `run_lint(paths)`: walk files/trees, parse once, run every rule,
  filter suppressed findings, return the rest sorted.

Why `tokenize` for suppressions: a regex over raw lines would match the
marker inside string literals (this file itself would self-flag); comment
TOKENS cannot lie about being comments.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

SUPPRESS_RE = re.compile(
    r"#\s*pva:\s*disable=([A-Za-z0-9_,\- ]+?)(?:\s+--\s+(.*))?\s*$")


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    path: str
    line: int
    col: int
    rule: str
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"


@dataclass
class Suppression:
    """One `# pva: disable=...` comment (line-scoped)."""

    line: int
    rules: Tuple[str, ...]  # ("all",) silences every rule on the line
    reason: str = ""

    def covers(self, rule: str) -> bool:
        return "all" in self.rules or rule in self.rules


@dataclass
class ModuleInfo:
    """One parsed module handed to every rule."""

    path: str  # display path (as given / walked)
    tree: ast.AST
    source: str
    suppressions: Dict[int, Suppression] = field(default_factory=dict)

    @property
    def posix_path(self) -> str:
        return self.path.replace(os.sep, "/")

    def matches(self, suffixes: Sequence[str]) -> bool:
        """Does this module's path end with any of the given suffixes
        (posix-style, e.g. "trainer/loop.py")?"""
        p = self.posix_path
        return any(p.endswith(s) for s in suffixes)


class Rule:
    """A named static check. Subclasses yield `Finding`s from `check`."""

    name: str = ""
    description: str = ""

    def check(self, module: ModuleInfo) -> Iterable[Finding]:  # pragma: no cover
        raise NotImplementedError

    def finding(self, module: ModuleInfo, node: ast.AST, message: str) -> Finding:
        return Finding(module.path, getattr(node, "lineno", 1),
                       getattr(node, "col_offset", 0), self.name, message)


# --- shared AST helpers (used by every rules_* sibling) ---------------------

def dotted_name(node: ast.AST) -> str:
    """"jax.jit" for Attribute/Name chains; "" for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def call_name(node: ast.Call) -> str:
    return dotted_name(node.func)


def walk_pruned(node: ast.AST, prune=()) -> Iterator[ast.AST]:
    """ast.walk that does NOT descend into child nodes of the `prune`
    types (the pruned node itself is still yielded). `ast.walk` + an
    `isinstance` skip does not do this — it yields the skipped node's
    descendants anyway, which is exactly wrong for scope analysis."""
    for child in ast.iter_child_nodes(node):
        yield child
        if not isinstance(child, prune):
            yield from walk_pruned(child, prune)


def walk_with_qualname(tree: ast.AST) -> Iterator[Tuple[ast.AST, str]]:
    """Yield (node, qualname-of-enclosing-scope) for every node; qualname
    is the "Class.method" chain of ClassDef/FunctionDef ancestors ("" at
    module level)."""

    def rec(node: ast.AST, scope: Tuple[str, ...]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                yield child, ".".join(scope)
                yield from rec(child, scope + (child.name,))
            else:
                yield child, ".".join(scope)
                yield from rec(child, scope)

    yield from rec(tree, ())


# --- suppression parsing ----------------------------------------------------

def iter_suppressions(source: str) -> Iterator[Suppression]:
    """Every `# pva: disable=...` comment in `source`, by line."""
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            rules = tuple(r.strip() for r in m.group(1).split(",") if r.strip())
            yield Suppression(tok.start[0], rules, (m.group(2) or "").strip())
    except tokenize.TokenError:
        # unterminated something: the ast parse will report it properly
        return


# --- runner -----------------------------------------------------------------

def default_rules() -> List[Rule]:
    """The shipped rule set (one import site so the CLI, the tests, the
    bench smoke gate, and the doctor all lint with identical rules)."""
    from pytorchvideo_accelerate_tpu.analysis.rules_dtype import DtypeLiteralRule
    from pytorchvideo_accelerate_tpu.analysis.rules_host_sync import HostSyncRule
    from pytorchvideo_accelerate_tpu.analysis.rules_knob import KnobReadRule
    from pytorchvideo_accelerate_tpu.analysis.rules_ledger import (
        LedgerDisciplineRule,
    )
    from pytorchvideo_accelerate_tpu.analysis.rules_lock import LockDisciplineRule
    from pytorchvideo_accelerate_tpu.analysis.rules_mesh import MeshDisciplineRule
    from pytorchvideo_accelerate_tpu.analysis.rules_recompile import RecompileHazardRule
    from pytorchvideo_accelerate_tpu.analysis.rules_span import SpanDisciplineRule
    from pytorchvideo_accelerate_tpu.analysis.rules_thread import (
        ThreadFactoryRule,
        ThreadJoinRule,
    )
    from pytorchvideo_accelerate_tpu.analysis.rules_trace import (
        TracePropagationRule,
    )
    from pytorchvideo_accelerate_tpu.analysis.rules_tracer import TracerLeakRule

    return [HostSyncRule(), RecompileHazardRule(), LockDisciplineRule(),
            TracerLeakRule(), SpanDisciplineRule(), ThreadFactoryRule(),
            ThreadJoinRule(), MeshDisciplineRule(), TracePropagationRule(),
            DtypeLiteralRule(), LedgerDisciplineRule(), KnobReadRule()]


def parse_module(source: str, path: str) -> ModuleInfo:
    tree = ast.parse(source, filename=path)
    sup = {s.line: s for s in iter_suppressions(source)}
    # a suppression on the FIRST line of a multi-line statement covers the
    # statement's own lines: findings anchor at sub-nodes (a wrapped call
    # arg lands on a continuation line), and the documented placement must
    # still silence them. Compound statements (def/for/with/if/...) extend
    # only across their HEADER — a comment on a block opener must never
    # silently disable a rule for the whole body (that would break the
    # line-scoped contract). Exact-line comments keep priority.
    if sup:
        covered = dict(sup)
        for node in ast.walk(tree):
            if not isinstance(node, ast.stmt):
                continue
            s = sup.get(node.lineno)
            end = getattr(node, "end_lineno", None)
            if s is None or end is None:
                continue
            body = getattr(node, "body", None)
            if isinstance(body, list) and body:
                end = body[0].lineno - 1  # header lines only
            for line in range(node.lineno + 1, end + 1):
                covered.setdefault(line, s)
        sup = covered
    return ModuleInfo(path=path, tree=tree, source=source, suppressions=sup)


def lint_source(source: str, path: str = "<string>",
                rules: Optional[Sequence[Rule]] = None) -> List[Finding]:
    """Lint one source string (the fixture-test entry point). `path` drives
    the hot-module matching, so fixtures fake a package-relative path."""
    rules = list(rules) if rules is not None else default_rules()
    try:
        module = parse_module(source, path)
    except SyntaxError as e:
        return [Finding(path, e.lineno or 1, e.offset or 0, "parse-error",
                        f"not parseable: {e.msg}")]
    findings: List[Finding] = []
    for rule in rules:
        for f in rule.check(module):
            sup = module.suppressions.get(f.line)
            if sup is not None and sup.covers(f.rule):
                continue
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def iter_py_files(paths: Sequence[str]) -> Iterator[str]:
    """Expand files/directories into .py files (sorted, pycache skipped)."""
    for path in paths:
        if os.path.isdir(path):
            for root, dirs, files in os.walk(path):
                dirs[:] = sorted(d for d in dirs
                                 if d != "__pycache__"
                                 and not d.startswith("."))
                for fn in sorted(files):
                    if fn.endswith(".py"):
                        yield os.path.join(root, fn)
        else:
            yield path


def run_lint(paths: Sequence[str],
             rules: Optional[Sequence[Rule]] = None) -> List[Finding]:
    """Lint every .py file under `paths`; returns all unsuppressed findings
    (empty list == clean tree, the CI/bench gate)."""
    rules = list(rules) if rules is not None else default_rules()
    findings: List[Finding] = []
    for fp in iter_py_files(paths):
        try:
            with open(fp, encoding="utf-8") as f:
                source = f.read()
        except OSError as e:
            findings.append(Finding(fp, 1, 0, "parse-error",
                                    f"unreadable: {e}"))
            continue
        findings.extend(lint_source(source, path=fp, rules=rules))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings
