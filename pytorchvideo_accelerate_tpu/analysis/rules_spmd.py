"""`pva-tpu-spmdcheck` static rules: collective-schedule divergence.

A TPU pod is N processes executing ONE program: every host must issue the
identical ordered sequence of collectives, or the pod deadlocks with no
evidence (the host that skipped a `psum` behind a `process_index()==0`
branch sits in the next collective while everyone else waits in the
previous one). These rules patrol the hot modules (`trainer/`,
`parallel/`, `data/`, `launch.py`) for the statically-visible shapes of
that bug class:

`spmd-divergence` — four finding kinds, one suppression name
(`# pva: disable=spmd-divergence -- reason`):

- ``divergent-predicate``: a collective site reachable under a predicate
  that can evaluate differently per host — `process_index()` /
  `is_main_process()`, filesystem probes (`os.path.exists`, `listdir`,
  `glob`), env reads, host RNG, wall-clock — in any enclosing
  branch/loop, or inside an `except` handler (exception paths are
  per-host by nature).
- ``branch-asymmetry``: a collective in one arm of an `if` whose sibling
  arm exists but issues none, when the test is not a static
  configuration expression (uniform-by-construction tests — plain
  names/attributes/constants and whitelisted builtins — stay clean).
- ``skip-path``: an early `return`/`raise`/`continue`/`break` under a
  host-divergent predicate (or inside an `except` handler) that can skip
  — or, via a loop back-edge, repeat — a collective later in the same
  function.
- ``ckpt-discipline``: a checkpoint-artifact WRITE primitive
  (`atomic_write`/`atomic_write_json`/`save_converted`) not guarded by
  the process-0 discipline (`is_main_process()` /
  `jax.process_index() == 0`): N hosts racing the same shared-dir file
  is artifact corruption. (The inverse error — an orbax *collective*
  save under a process-0 guard — is a ``divergent-predicate`` finding.)

`spmd-coverage` — the hangcheck coverage audit: every RAW host-blocking
collective primitive (`multihost_utils.process_allgather` /
`broadcast_one_to_all` / `sync_global_devices`, the orbax manager's
`save`/`restore`/`wait_until_finished` barriers) must sit lexically
inside a `with collective_section(...)` so per-host stall attribution
(parallel/hangcheck.py) and schedule recording
(parallel/schedule_recorder.py) see it. The repo's wrapped helpers
(`host_broadcast`, `Checkpointer.save`, `sync_global_devices` in
`parallel/distributed.py`) satisfy this inside their own bodies; call
sites need nothing.

Alias-proof the thread-factory/dtype-literal way: detection keys on the
distinctive call TAILS (`host_broadcast` however imported, dotted or
bare, through `self.`-bound aliases), with receiver-name filters only
where a tail is generic (`.save`/`.restore`/`.wait` count only on
checkpoint-ish receivers). Interprocedural ONE level via the qualname
helpers: a function that directly issues a collective marks every
same-module call to it as a collective site too.

POLARITY (the gc_sharding doctrine): every heuristic here errs toward
false NEGATIVES — a missed site can only let a finding escape, never
raise a false alarm — the right polarity for a gate that must hold
`findings == 0` on the clean tree. Known limits (documented in
docs/STATIC_ANALYSIS.md): predicates are judged as single expressions
(no dataflow taint — `flag = os.path.exists(p)` ... `if flag:` escapes),
and in-graph `lax` collectives are only flagged when issued from
host-level code (anything under a `jit`/`shard_map`-traced function is
one program by construction).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

from pytorchvideo_accelerate_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Rule,
    call_name,
    walk_with_qualname,
)

_PKG_MARKER = "pytorchvideo_accelerate_tpu/"

# the modules the multi-host runtime will execute on every host; new
# host-level collective callers join this list (ISSUE 20 / ROADMAP 4)
_HOT_DIRS = ("/trainer/", "/parallel/", "/data/")
_HOT_FILES = ("pytorchvideo_accelerate_tpu/launch.py",)

# --- site vocabulary --------------------------------------------------------

# repo helpers that wrap their own collective_section (coverage holds
# inside their bodies; call sites are divergence-checked only)
_WRAPPED_TAILS = ("host_broadcast", "host_allgather", "host_reduce_sum",
                  "sync_global_devices", "check_desync")
# raw multihost primitives: the coverage audit's targets
_PRIMITIVE_TAILS = ("process_allgather", "broadcast_one_to_all")
# generic barrier tails that count only on checkpoint-ish receivers
_CKPT_BARRIER_TAILS = ("save", "restore", "wait", "wait_until_finished",
                       "close")
# in-graph collectives — host-issued only (traced scopes are exempt)
_LAX_TAILS = ("psum", "pmean", "pmax", "pmin", "all_gather", "ppermute",
              "ppermute_ring", "all_to_all")
# checkpoint-artifact write primitives (reliability/atomic.py,
# models/convert.py) — the process-0-discipline targets
_WRITE_TAILS = ("atomic_write", "atomic_write_json", "save_converted")

# call tails that build traced (single-program) scopes: a function handed
# to any of these is compiled once for the whole pod and cannot diverge
_TRACER_TAILS = ("jit", "pjit", "shard_map", "pmap", "vmap", "grad",
                 "value_and_grad", "scan", "remat", "checkpoint",
                 "eval_shape", "make_jaxpr", "named_call", "custom_vjp",
                 "custom_jvp", "while_loop", "fori_loop", "cond", "switch")

# --- host-divergent predicate atoms ----------------------------------------

_IDENTITY_TAILS = ("process_index", "is_main_process")
_FS_TAILS = ("exists", "isfile", "isdir", "islink", "getsize", "getmtime",
             "listdir", "scandir", "glob", "iglob")
_CLOCK_TAILS = ("time", "monotonic", "perf_counter", "time_ns")
_RNG_HINT = "random"
# calls that are uniform across hosts by construction — never divergent,
# and uniform enough to keep a branch test "static" for the asymmetry
# check
_UNIFORM_CALL_TAILS = ("process_count", "device_count",
                       "local_device_count", "isinstance", "issubclass",
                       "len", "getattr", "hasattr", "callable", "int",
                       "float", "str", "bool", "min", "max", "abs", "any",
                       "all", "sorted", "tuple", "list", "dict", "set",
                       "type", "round", "range", "enumerate", "zip")

_KIND_DIVERGENT = "divergent-predicate"
_KIND_ASYMMETRY = "branch-asymmetry"
_KIND_SKIP = "skip-path"
_KIND_CKPT = "ckpt-discipline"
DIVERGENCE_KINDS = (_KIND_DIVERGENT, _KIND_ASYMMETRY, _KIND_SKIP,
                    _KIND_CKPT)


def _is_hot(module: ModuleInfo) -> bool:
    p = module.posix_path
    if _PKG_MARKER not in p:
        return False
    return any(d in p for d in _HOT_DIRS) or module.matches(_HOT_FILES)


def _call_tail(node: ast.Call) -> str:
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    if isinstance(node.func, ast.Name):
        return node.func.id
    return ""


def _head_last(node: ast.Call) -> str:
    """Last segment of the receiver chain ("self._mgr.save" -> "_mgr");
    "" for bare names / call-valued receivers."""
    dn = call_name(node)
    if "." not in dn:
        return ""
    return dn.rsplit(".", 2)[-2]


def _ckptish(name: str) -> bool:
    low = name.lower()
    return any(m in low for m in ("ckpt", "checkpoint", "mgr"))


def _divergent_reason(expr: ast.AST) -> Optional[str]:
    """Why this expression can evaluate differently per host, or None."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            tail = _call_tail(node)
            dn = call_name(node)
            if tail in _UNIFORM_CALL_TAILS:
                continue
            if tail in _IDENTITY_TAILS:
                return f"per-host identity ({tail}())"
            if tail in _FS_TAILS or dn == "open":
                return f"per-host filesystem state ({dn or tail}())"
            if tail == "getenv" or "environ" in dn:
                return f"per-host environment ({dn or tail})"
            if tail in _CLOCK_TAILS and dn.startswith("time."):
                return f"wall clock ({dn}())"
            if _RNG_HINT in dn.lower() and "jax" not in dn.lower():
                return f"host RNG ({dn}())"
        elif isinstance(node, ast.Attribute):
            if node.attr in _IDENTITY_TAILS:
                return f"per-host identity (.{node.attr})"
            if node.attr == "environ":
                return "per-host environment (environ)"
        elif isinstance(node, ast.Name):
            if node.id in _IDENTITY_TAILS:
                return f"per-host identity ({node.id})"
    return None


def _test_is_static(expr: ast.AST) -> bool:
    """True when a branch test is uniform-by-construction: constants,
    names, attribute chains, and whitelisted uniform builtins only. Any
    other call makes the test dynamic (the asymmetry check then demands
    symmetric collectives)."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            if _call_tail(node) not in _UNIFORM_CALL_TAILS:
                return False
    return True


# --- site collection --------------------------------------------------------

@dataclass
class _Site:
    node: ast.AST
    kind: str  # "wrapped" | "primitive" | "section" | "lax_host" | "derived"
    label: str
    scope: str


def _parent_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    return {child: parent
            for parent in ast.walk(tree)
            for child in ast.iter_child_nodes(parent)}


def _traced_scopes(tree: ast.AST) -> Tuple[Set[str], Set[ast.AST]]:
    """(names of functions handed to tracers or jit-decorated,
    lambda/def nodes appearing inline in tracer calls)."""
    names: Set[str] = set()
    nodes: Set[ast.AST] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _call_tail(node) in _TRACER_TAILS:
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Name):
                    names.add(arg.id)
                elif isinstance(arg, ast.Lambda):
                    nodes.add(arg)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                d = dec.func if isinstance(dec, ast.Call) else dec
                dn = ""
                if isinstance(d, (ast.Attribute, ast.Name)):
                    dn = d.attr if isinstance(d, ast.Attribute) else d.id
                if dn in _TRACER_TAILS:
                    names.add(node.name)
                elif dn == "partial" and isinstance(dec, ast.Call):
                    for a in dec.args:
                        if isinstance(a, (ast.Attribute, ast.Name)):
                            t = (a.attr if isinstance(a, ast.Attribute)
                                 else a.id)
                            if t in _TRACER_TAILS:
                                names.add(node.name)
    return names, nodes


def _in_traced_scope(node: ast.AST, parents: Dict[ast.AST, ast.AST],
                     traced_names: Set[str],
                     traced_nodes: Set[ast.AST], tail: str) -> bool:
    anc = parents.get(node)
    while anc is not None:
        if isinstance(anc, ast.Lambda) and anc in traced_nodes:
            return True
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if anc.name in traced_names:
                return True
            # thin-wrapper exemption: `def psum(...): return lax.psum(...)`
            # (parallel/collectives.py) — the wrapper IS the public name
            if anc.name.startswith(tail) or tail.startswith(anc.name):
                return True
        anc = parents.get(anc)
    return False


def _classify_call(node: ast.Call) -> Optional[Tuple[str, str]]:
    """(kind, label) for one call, ignoring traced-scope analysis."""
    tail = _call_tail(node)
    if not tail:
        return None
    dn = call_name(node) or tail
    if tail in _WRAPPED_TAILS:
        # distributed.sync_global_devices wraps internally; the raw
        # multihost_utils one is a primitive needing a lexical section
        if tail == "sync_global_devices" and "multihost" in dn:
            return "primitive", dn
        return "wrapped", dn
    if tail in _PRIMITIVE_TAILS:
        return "primitive", dn
    if tail in _CKPT_BARRIER_TAILS:
        head = _head_last(node)
        if not _ckptish(head):
            return None
        if "mgr" in head.lower():
            return "primitive", dn
        return "wrapped", dn
    return None


def _is_section_with(node: ast.With) -> bool:
    for item in node.items:
        ce = item.context_expr
        if isinstance(ce, ast.Call) and _call_tail(ce) == "collective_section":
            return True
    return False


def collect_sites(module: ModuleInfo) -> List[_Site]:
    """Every host-blocking collective site in the module, including
    one-level interprocedural call sites of functions that directly
    issue collectives."""
    parents = _parent_map(module.tree)
    traced_names, traced_nodes = _traced_scopes(module.tree)
    sites: List[_Site] = []
    carrier_scopes: Set[str] = set()
    for node, scope in walk_with_qualname(module.tree):
        if isinstance(node, ast.With) and _is_section_with(node):
            sites.append(_Site(node, "section", "collective_section", scope))
            carrier_scopes.add(scope)
            continue
        if not isinstance(node, ast.Call):
            continue
        cls = _classify_call(node)
        if cls is not None:
            sites.append(_Site(node, cls[0], cls[1], scope))
            carrier_scopes.add(scope)
            continue
        tail = _call_tail(node)
        if tail in _LAX_TAILS and not _in_traced_scope(
                node, parents, traced_names, traced_nodes, tail):
            sites.append(_Site(node, "lax_host", call_name(node) or tail,
                               scope))
            carrier_scopes.add(scope)
    # one-level interprocedural: calls to same-module functions that
    # directly issue collectives are collective sites themselves
    carriers = {s.rsplit(".", 1)[-1] for s in carrier_scopes if s}
    if carriers:
        direct_nodes = {s.node for s in sites}
        for node, scope in walk_with_qualname(module.tree):
            if (isinstance(node, ast.Call) and node not in direct_nodes
                    and _call_tail(node) in carriers
                    and scope.rsplit(".", 1)[-1] != _call_tail(node)):
                sites.append(_Site(
                    node, "derived",
                    f"{_call_tail(node)}() [issues collectives]", scope))
    return sites


# --- ancestor-context checks ------------------------------------------------

def _branch_ancestors(node: ast.AST, parents: Dict[ast.AST, ast.AST]
                      ) -> Iterator[Tuple[ast.AST, ast.AST]]:
    """(ancestor, direct-child-on-path) pairs up to the enclosing
    function/class/module boundary. Lambdas are transparent (a guard
    outside a `lambda: atomic_write(...)` still guards the write)."""
    prev, anc = node, parents.get(node)
    while anc is not None and not isinstance(
            anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                  ast.Module)):
        yield anc, prev
        prev, anc = anc, parents.get(anc)


def _divergent_context(node: ast.AST, parents: Dict[ast.AST, ast.AST]
                       ) -> Optional[str]:
    """The innermost host-divergent enclosing context, or None."""
    for anc, prev in _branch_ancestors(node, parents):
        if isinstance(anc, ast.ExceptHandler):
            return "exception path (per-host exception handling diverges)"
        if isinstance(anc, ast.If) and (prev in anc.body
                                        or prev in anc.orelse):
            reason = _divergent_reason(anc.test)
            if reason:
                return reason
        elif isinstance(anc, ast.While) and prev in anc.body:
            reason = _divergent_reason(anc.test)
            if reason:
                return reason
        elif isinstance(anc, ast.For) and prev in anc.body:
            reason = _divergent_reason(anc.iter)
            if reason:
                return f"loop over {reason}"
    return None


def _main_guarded(node: ast.AST, parents: Dict[ast.AST, ast.AST]) -> bool:
    """Is this write under (or behind an early-return of) the process-0
    discipline in its enclosing function?"""

    def is_main_test(expr: ast.AST) -> bool:
        for n in ast.walk(expr):
            if isinstance(n, ast.Call) \
                    and _call_tail(n) in ("is_main_process",):
                return True
            if isinstance(n, ast.Compare):
                sides = [n.left] + list(n.comparators)
                has_pidx = any(isinstance(s, ast.Call)
                               and _call_tail(s) == "process_index"
                               for s in sides)
                has_zero = any(isinstance(s, ast.Constant) and s.value == 0
                               for s in sides)
                if has_pidx and has_zero:
                    return True
        return False

    func: Optional[ast.AST] = None
    for anc, prev in _branch_ancestors(node, parents):
        if isinstance(anc, ast.If) and is_main_test(anc.test):
            return True
    # early-bail pattern: `if not is_main_process(): return` earlier in
    # the same function body
    anc = parents.get(node)
    while anc is not None and not isinstance(
            anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
        anc = parents.get(anc)
    func = anc
    if func is None:
        return False
    line = getattr(node, "lineno", 0)
    for stmt in ast.walk(func):
        if (isinstance(stmt, ast.If) and getattr(stmt, "lineno", 1 << 30) < line
                and any(isinstance(s, (ast.Return, ast.Raise))
                        for s in stmt.body)
                and is_main_test(stmt.test)
                and isinstance(stmt.test, ast.UnaryOp) is False):
            # `if process_index() != 0: return` / `if not is_main: return`
            if _guard_excludes_nonzero(stmt.test):
                return True
    return False


def _guard_excludes_nonzero(expr: ast.AST) -> bool:
    """`not is_main_process()` or `process_index() != 0`-shaped tests."""
    if isinstance(expr, ast.UnaryOp) and isinstance(expr.op, ast.Not):
        for n in ast.walk(expr.operand):
            if isinstance(n, ast.Call) \
                    and _call_tail(n) == "is_main_process":
                return True
        return False
    if isinstance(expr, ast.Compare) and len(expr.ops) == 1 \
            and isinstance(expr.ops[0], ast.NotEq):
        sides = [expr.left] + list(expr.comparators)
        has_pidx = any(isinstance(s, ast.Call)
                       and _call_tail(s) == "process_index" for s in sides)
        has_zero = any(isinstance(s, ast.Constant) and s.value == 0
                       for s in sides)
        return has_pidx and has_zero
    return False


# --- rules ------------------------------------------------------------------

class SpmdDivergenceRule(Rule):
    name = "spmd-divergence"
    description = ("collective schedule can diverge across hosts: a "
                   "collective under a host-divergent predicate or in an "
                   "asymmetric branch arm, an early exit skipping a later "
                   "collective, or an unguarded checkpoint-artifact write "
                   "(multi-host pod deadlock / artifact corruption)")

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        if not _is_hot(module):
            return
        parents = _parent_map(module.tree)
        sites = collect_sites(module)
        site_nodes = {s.node: s for s in sites}

        # (1) divergent-predicate reachability, per site
        for s in sites:
            reason = _divergent_context(s.node, parents)
            if reason:
                yield self.finding(
                    module, s.node,
                    f"{_KIND_DIVERGENT}: collective `{s.label}` is "
                    f"reachable under a host-divergent predicate — "
                    f"{reason} — one host can skip it and deadlock the "
                    "pod; hoist the collective out of the branch (or "
                    "suppress with a reason)")

        # (2) collective in one branch arm but not the sibling
        for node, scope in walk_with_qualname(module.tree):
            if not isinstance(node, ast.If) or not node.orelse:
                continue
            if _test_is_static(node.test):
                continue

            def arm_sites(stmts) -> List[_Site]:
                found = []
                for st in stmts:
                    for n in ast.walk(st):
                        if n in site_nodes:
                            found.append(site_nodes[n])
                return found

            body_s, else_s = arm_sites(node.body), arm_sites(node.orelse)
            if bool(body_s) != bool(else_s):
                present = body_s or else_s
                arm = "if" if body_s else "else"
                yield self.finding(
                    module, node,
                    f"{_KIND_ASYMMETRY}: collective "
                    f"`{present[0].label}` in the `{arm}` arm has no "
                    "counterpart in the sibling arm and the test is not "
                    "a static config expression — hosts taking different "
                    "arms issue different schedules; make the arms "
                    "collective-symmetric (or suppress with a reason)")

        # (3) early exit that can skip (or repeat) a later collective
        sites_by_scope: Dict[str, List[_Site]] = {}
        for s in sites:
            sites_by_scope.setdefault(s.scope, []).append(s)
        for node, scope in walk_with_qualname(module.tree):
            if not isinstance(node, (ast.Return, ast.Raise, ast.Continue,
                                     ast.Break)):
                continue
            in_scope = sites_by_scope.get(scope, ())
            if not in_scope:
                continue
            line = getattr(node, "lineno", 0)
            if isinstance(node, (ast.Continue, ast.Break)):
                # a loop back-edge can REPEAT earlier sites in the loop
                # body, so any site in the same scope counts
                later = list(in_scope)
            else:
                later = [s for s in in_scope
                         if getattr(s.node, "lineno", 0) > line]
            if not later:
                continue
            reason = _divergent_context(node, parents)
            if not reason:
                continue
            kw = {ast.Return: "return", ast.Raise: "raise",
                  ast.Continue: "continue", ast.Break: "break"}[type(node)]
            yield self.finding(
                module, node,
                f"{_KIND_SKIP}: early `{kw}` under {reason} can skip "
                f"collective `{later[0].label}` (line "
                f"{getattr(later[0].node, 'lineno', '?')}) on some hosts "
                "while others issue it — the pod wedges in mismatched "
                "collectives; make the exit uniform across hosts (or "
                "suppress with a reason)")

        # (4) checkpoint-artifact writes without the process-0 discipline
        for node, scope in walk_with_qualname(module.tree):
            if not isinstance(node, ast.Call):
                continue
            if _call_tail(node) not in _WRITE_TAILS:
                continue
            if _main_guarded(node, parents):
                continue
            yield self.finding(
                module, node,
                f"{_KIND_CKPT}: checkpoint-artifact write "
                f"`{call_name(node) or _call_tail(node)}(...)` is not "
                "guarded by the process-0 discipline — N hosts racing "
                "the same shared-directory file corrupt the artifact; "
                "guard with `is_main_process()` (all hosts may still "
                "compute, only process 0 writes) or suppress with a "
                "reason")


class SpmdCoverageRule(Rule):
    name = "spmd-coverage"
    description = ("raw host-blocking collective primitive outside any "
                   "hangcheck collective_section — stall attribution and "
                   "schedule recording cannot see it")

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        if not _is_hot(module):
            return
        parents = _parent_map(module.tree)
        for s in collect_sites(module):
            if s.kind != "primitive":
                continue
            covered = any(
                isinstance(anc, ast.With) and _is_section_with(anc)
                for anc, _ in _branch_ancestors(s.node, parents))
            if not covered:
                yield self.finding(
                    module, s.node,
                    f"raw collective primitive `{s.label}(...)` is not "
                    "wrapped in a hangcheck `collective_section` — a "
                    "wedge here is unattributable and the schedule "
                    "recorder never sees the op; wrap it (or suppress "
                    "with a reason)")


def spmd_rules() -> List[Rule]:
    """The spmdcheck rule set (analysis/spmdcheck.py runs these; they are
    NOT in `default_rules` — the spmdcheck CLI/gate owns them, the
    graphcheck precedent)."""
    return [SpmdDivergenceRule(), SpmdCoverageRule()]
