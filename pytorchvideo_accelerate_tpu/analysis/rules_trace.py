"""Rule `trace-propagation`: cross-thread handoffs must carry trace context.

The distributed tracer (obs/trace.py) only works if every cross-thread
handoff on the hot path ships the active `TraceContext` along with the
payload: the producer calls `trace.capture()` and the consumer re-enters it
with `trace.attach(...)` (or `Tracer.activate`). A handoff that forgets
either half silently TRUNCATES every trace flowing through it — the worst
observability bug, because nothing errors; the merged timeline just stops
at that hop and the p99 you were chasing dereferences to nothing.

What counts as a handoff site, inside the traced hot modules
(`TRACE_HANDOFF_MODULES` = rules_host_sync.HOT_MODULES + the fleet tier):

- `make_thread(...)` — a worker thread is born without its parent's
  context unless the target was handed a captured one;
- `.put(...)` / `.put_nowait(...)` on a queue BOUND from `make_queue(...)`
  in the same module — the payload crosses threads here.

The rule fires on every such site when the MODULE contains no call to the
capture/attach helpers at all (a module that propagates anywhere is
assumed to have made a deliberate choice per site; one that never imports
the helpers has simply not been wired). Alias-proof like `thread-factory`:
`import utils.sync as s; s.make_thread(...)`, from-import as-names of
`make_thread`/`make_queue`, and `from ...obs import trace as t` /
`from ...obs.trace import capture as grab` are all resolved.

Escapes: wire the handoff (preferred), or suppress a genuinely
context-free handoff with `# pva: disable=trace-propagation -- <why>`
(e.g. a health poller that carries no request).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set, Tuple

from pytorchvideo_accelerate_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Rule,
    call_name,
)
from pytorchvideo_accelerate_tpu.analysis.rules_host_sync import HOT_MODULES

# the hot modules PLUS the fleet tier's handoff surfaces (scheduler queue,
# router dispatch, replica pool worker threads) PLUS the disaggregated
# data plane's socket handoffs (feed leases/reader threads, worker frames)
# PLUS the streaming-session tier (session-state handoffs: hot-swap state
# carry, table adoption — the send sites a swap-time trace must not
# truncate at)
TRACE_HANDOFF_MODULES: Tuple[str, ...] = HOT_MODULES + (
    "fleet/scheduler.py",
    "fleet/router.py",
    "fleet/pool.py",
    "fleet/loadgen.py",
    "fleet/hotswap.py",
    "dataplane/feed.py",
    "dataplane/worker.py",
    "streaming/engine.py",
    "streaming/session.py",
)

# session-handoff call tails treated as cross-context put sites (like
# `send_frame`): a hot-swap carrying a whole session table between
# engines is exactly the hop whose swap-timeline trace an operator needs
_SESSION_HANDOFF_TAILS = ("carry_state_from", "adopt")

# helper call tails that prove the module participates in propagation.
# current_traceparent/format_traceparent are the cross-PROCESS halves (a
# traceparent shipped in a wire frame or HTTP header) and, being
# module-level functions, reachable via bare from-imports — which is why
# they live here; continue_trace is a Tracer METHOD only, so the
# any-receiver dotted check in `check` is its sole (and sufficient) match.
_HELPER_TAILS = ("capture", "attach", "activate",
                 "current_traceparent", "format_traceparent")


def _sync_aliases(tree: ast.AST, names: Tuple[str, ...]) -> Dict[str, str]:
    """Local name -> factory name for from-imports of utils.sync (absolute
    or relative): `from ...utils.sync import make_thread as mt`."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if (isinstance(node, ast.ImportFrom) and node.module
                and (node.module == "sync"
                     or node.module.endswith(".sync"))):
            for alias in node.names:
                if alias.name in names:
                    out[alias.asname or alias.name] = alias.name
    return out


def _sync_module_aliases(tree: ast.AST) -> Set[str]:
    """Every local name bound to the utils.sync MODULE: `import
    pytorchvideo_accelerate_tpu.utils.sync as s` or
    `from ...utils import sync [as s]`."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.endswith("utils.sync"):
                    out.add(alias.asname or alias.name.split(".")[0])
        elif (isinstance(node, ast.ImportFrom) and node.module
              and (node.module == "utils" or node.module.endswith(".utils"))):
            for alias in node.names:
                if alias.name == "sync":
                    out.add(alias.asname or "sync")
    return out


def _trace_module_aliases(tree: ast.AST) -> Set[str]:
    """Every local name bound to the obs.trace MODULE: `from ...obs import
    trace [as t]` or `import ...obs.trace as t`."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.endswith("obs.trace"):
                    out.add(alias.asname or alias.name.split(".")[0])
        elif (isinstance(node, ast.ImportFrom) and node.module
              and (node.module == "obs" or node.module.endswith(".obs"))):
            for alias in node.names:
                if alias.name == "trace":
                    out.add(alias.asname or "trace")
    return out


def _trace_helper_names(tree: ast.AST) -> Set[str]:
    """Bare local names from-imported out of obs.trace whose originals are
    propagation helpers: `from ...obs.trace import capture as grab`."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.ImportFrom) and node.module
                and (node.module == "trace"
                     or node.module.endswith(".trace"))):
            for alias in node.names:
                if alias.name in _HELPER_TAILS:
                    out.add(alias.asname or alias.name)
    return out


def _factory_call_kind(node: ast.Call, fn_aliases: Dict[str, str],
                       mod_aliases: Set[str]) -> str:
    """"make_thread"/"make_queue" when this call constructs one (bare
    alias or dotted through a sync-module alias), else ""."""
    dn = call_name(node)
    if dn in fn_aliases:
        return fn_aliases[dn]
    if "." in dn:
        head, tail = dn.rsplit(".", 1)
        if head in mod_aliases and tail in ("make_thread", "make_queue"):
            return tail
    return ""


def _binding_name(node: ast.AST) -> str:
    """Canonical assign-target name: "x" or "self.x" ("" otherwise)."""
    if isinstance(node, ast.Name):
        return node.id
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return f"self.{node.attr}"
    return ""


class TracePropagationRule(Rule):
    name = "trace-propagation"
    description = ("make_thread/queue handoff in a traced hot module that "
                   "drops the current trace context (module never calls "
                   "trace.capture/attach)")

    def __init__(self, modules: Tuple[str, ...] = TRACE_HANDOFF_MODULES):
        self.modules = tuple(modules)

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        if not module.matches(self.modules):
            return
        tree = module.tree
        fn_aliases = _sync_aliases(tree, ("make_thread", "make_queue"))
        mod_aliases = _sync_module_aliases(tree)
        trace_mods = _trace_module_aliases(tree)
        helper_bare = _trace_helper_names(tree)

        # does this module call ANY propagation helper? (`has_span`
        # tracked separately: opening a span joins the ACTIVE trace on
        # the current thread, which satisfies same-thread session-state
        # handoffs but not a cross-thread/queue hop — those still need
        # the capture/attach pair)
        propagates = False
        has_span = False
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            dn = call_name(node)
            if dn in helper_bare:
                propagates = True
                break
            if "." in dn:
                head, tail = dn.rsplit(".", 1)
                if head in trace_mods and tail in _HELPER_TAILS:
                    propagates = True
                    break
                if head in trace_mods and tail == "span":
                    has_span = True
                # the cross-process helpers have distinctive names and are
                # typically called on a Tracer INSTANCE
                # (`get_tracer().continue_trace(...)`), so any receiver
                # counts — unlike the generic capture/attach tails, which
                # stay module-scoped to avoid laundering by coincidence
                if tail in ("continue_trace", "current_traceparent",
                            "format_traceparent"):
                    propagates = True
                    break
        if propagates:
            return

        # queue bindings: names assigned from make_queue(...) module-wide
        # (name-based, like thread-join's binding resolution — a parameter
        # carrying the same name in a worker matches on purpose)
        queue_names: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and isinstance(node.value,
                                                           ast.Call):
                if _factory_call_kind(node.value, fn_aliases,
                                      mod_aliases) == "make_queue":
                    for tgt in node.targets:
                        bn = _binding_name(tgt)
                        if bn:
                            queue_names.add(bn)
                            if bn.startswith("self."):
                                queue_names.add(bn[5:])

        sites: List[Tuple[ast.AST, str]] = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if _factory_call_kind(node, fn_aliases,
                                  mod_aliases) == "make_thread":
                sites.append((node, "make_thread(...) starts a worker"))
                continue
            dn = call_name(node)
            if dn == "send_frame" or dn.endswith(".send_frame"):
                # the data plane's cross-PROCESS put site: a wire frame
                # (dataplane/wire.py) leaving this process without a
                # traceparent truncates the trace at the process boundary
                sites.append(
                    (node, "`send_frame(...)` crosses a process boundary"))
                continue
            tail = dn.rsplit(".", 1)[-1]
            if tail in _SESSION_HANDOFF_TAILS and not has_span:
                # the streaming tier's session-state handoff (hot-swap
                # state carry / table adoption): session rings move
                # between engines during a swap — an uninstrumented
                # carry leaves the swap invisible in the merged
                # timeline. A same-thread handoff is satisfied by a
                # `trace.span(...)` over the carry (has_span above).
                sites.append(
                    (node, f"`{tail}(...)` hands session state across "
                           "engines without a span over the carry"))
                continue
            f = node.func
            if (isinstance(f, ast.Attribute)
                    and f.attr in ("put", "put_nowait")):
                base = _binding_name(f.value)
                if base and (base in queue_names
                             or f"self.{base}" in queue_names
                             or (base.startswith("self.")
                                 and base[5:] in queue_names)):
                    sites.append(
                        (node, f"`{base}.{f.attr}(...)` crosses threads"))
        for node, what in sites:
            yield self.finding(
                module, node,
                f"{what} in a traced hot module, but this module never "
                "calls obs.trace capture/attach — every trace through "
                "this handoff is silently truncated; capture() at the "
                "producer and attach() at the consumer (or suppress a "
                "genuinely context-free handoff with a reason)")
