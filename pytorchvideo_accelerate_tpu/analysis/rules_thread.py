"""Rules `thread-factory` and `thread-join`: keep threads sanitizable.

`thread-factory`: direct `threading.Lock`/`RLock`/`Condition`/`Thread`
construction inside package modules. Every one of these must go through
the `utils/sync.py` factory (`make_lock`/`make_rlock`/`make_condition`/
`make_thread`) so `pva-tpu-tsan` can wrap the primitive when armed — a
lock the sanitizer cannot see makes every access it guards look unguarded
(a false positive) and its acquisition order invisible (a false negative).
Exempt: `utils/sync.py` and `analysis/tsan.py`, which ARE the interception
layer and must build the raw primitives. Events and semaphores stay direct:
they carry no lockset and the sanitizer does not model them.

`thread-join`: a NON-daemon thread (factory-made or not) started without a
reachable `.join(...)` on its binding anywhere in the module. A non-daemon
thread with no join is a shutdown hazard: process exit blocks on it
forever, which is exactly the wedge the watchdog exists to diagnose.
Daemon threads (`daemon=True` at the construction site) are exempt — the
package convention is daemon workers + explicit joins in close paths.

Both scope to package modules only (`pytorchvideo_accelerate_tpu/` in the
path): test fixtures and user scripts construct primitives freely.
Suppressions follow the house syntax: `# pva: disable=<rule> -- reason`.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from pytorchvideo_accelerate_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Rule,
    call_name,
)

_PKG_MARKER = "pytorchvideo_accelerate_tpu/"
_FACTORY_EXEMPT = ("pytorchvideo_accelerate_tpu/utils/sync.py",
                   "pytorchvideo_accelerate_tpu/analysis/tsan.py")
_KINDS = ("Lock", "RLock", "Condition", "Thread")
_FACTORY_OF = {"Lock": "make_lock", "RLock": "make_rlock",
               "Condition": "make_condition", "Thread": "make_thread"}


def _in_package(module: ModuleInfo) -> bool:
    return _PKG_MARKER in module.posix_path


def _threading_aliases(tree: ast.AST) -> Dict[str, str]:
    """local name -> primitive kind, for `from threading import X [as Y]`."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "threading":
            for alias in node.names:
                if alias.name in _KINDS:
                    out[alias.asname or alias.name] = alias.name
    return out


def _threading_modules(tree: ast.AST) -> Set[str]:
    """Every local name the threading module is bound to: "threading" plus
    any `import threading as th` alias."""
    out = {"threading"}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "threading":
                    out.add(alias.asname or alias.name)
    return out


def _make_thread_aliases(tree: ast.AST) -> Set[str]:
    """Local names bound to the factory's make_thread by a from-import of
    utils.sync (absolute or relative) — `import ... as mt` must not let a
    non-daemon, never-joined thread slip past thread-join."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.ImportFrom) and node.module
                and (node.module == "sync" or node.module.endswith(".sync"))):
            for alias in node.names:
                if alias.name == "make_thread":
                    out.add(alias.asname or alias.name)
    return out


class ThreadFactoryRule(Rule):
    name = "thread-factory"
    description = ("direct threading.Lock/RLock/Condition/Thread "
                   "construction in a package module — use the "
                   "utils/sync.py factory so pva-tpu-tsan can intercept")

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        if not _in_package(module) or module.matches(_FACTORY_EXEMPT):
            return
        aliases = _threading_aliases(module.tree)
        modules = _threading_modules(module.tree)
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            dn = call_name(node)
            kind = None
            if "." in dn:
                head, tail = dn.rsplit(".", 1)
                if head in modules and tail in _KINDS:
                    kind = tail
            elif dn in aliases:
                kind = aliases[dn]
            if kind is not None:
                yield self.finding(
                    module, node,
                    f"`{dn}(...)` constructs a raw threading.{kind}; use "
                    f"`utils.sync.{_FACTORY_OF[kind]}(...)` so the dynamic "
                    "sanitizer can track it when armed")


def _binding_of(call: ast.Call,
                parents: Dict[ast.AST, ast.AST]) -> Optional[str]:
    """Canonical name the thread object is bound to: "self.X", "X", or
    None (anonymous — constructed and used inline)."""
    parent = parents.get(call)
    if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
        tgt = parent.targets[0]
        if isinstance(tgt, ast.Name):
            return tgt.id
        if (isinstance(tgt, ast.Attribute) and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"):
            return f"self.{tgt.attr}"
    return None


class ThreadJoinRule(Rule):
    name = "thread-join"
    description = ("non-daemon thread started without a reachable "
                   "`.join()` — blocks process shutdown forever")

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        if not _in_package(module) or module.matches(_FACTORY_EXEMPT):
            return
        aliases = _threading_aliases(module.tree)
        modules = _threading_modules(module.tree)
        factory = _make_thread_aliases(module.tree) | {"make_thread"}
        parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(module.tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node
        # every name/attr a .join() is called on, module-wide: "t.join()"
        # -> "t", "self._thread.join()" -> "self._thread"
        joined: Set[str] = set()
        creations: List[Tuple[ast.Call, str]] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "join":
                base = f.value
                if isinstance(base, ast.Name):
                    joined.add(base.id)
                elif (isinstance(base, ast.Attribute)
                      and isinstance(base.value, ast.Name)
                      and base.value.id == "self"):
                    joined.add(f"self.{base.attr}")
                continue
            dn = call_name(node)
            tail = dn.rsplit(".", 1)[-1]
            # a thread constructor however it's spelled: threading.Thread
            # (any module alias), a from-imported Thread (any as-name), the
            # factory make_thread bare/qualified, or its from-import alias
            head = dn.rsplit(".", 1)[0] if "." in dn else ""
            is_thread = (aliases.get(dn) == "Thread"
                         or (head in modules and tail == "Thread")
                         or tail == "make_thread"
                         or dn in factory)
            if not is_thread:
                continue
            daemon = next((kw.value for kw in node.keywords
                           if kw.arg == "daemon"), None)
            if (isinstance(daemon, ast.Constant) and daemon.value is True):
                continue  # daemon threads cannot block shutdown
            creations.append((node, dn))
        for call, dn in creations:
            binding = _binding_of(call, parents)
            if binding is not None and binding in joined:
                continue
            yield self.finding(
                module, call,
                f"non-daemon thread from `{dn}(...)` "
                + (f"bound to `{binding}` " if binding else "")
                + "is never joined in this module — pass daemon=True or "
                  "join it on the shutdown path")
