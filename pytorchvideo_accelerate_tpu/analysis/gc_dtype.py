"""graphcheck dtype pass: catch silent bf16→f32 upcasts feeding compute.

The policy (`create_model(mixed_precision="bf16")`) is bf16 compute /
fp32 params, with *designed* f32 islands (heads, loss math, norm
statistics) routed through `precision.f32_island` and friends. The
failure mode this pass exists for: an undeclared `convert_element_type`
bf16→f32 whose result reaches a `dot_general`/`conv_general_dilated` —
the matmul then runs at the f32 MXU rate with doubled operand bytes,
and nothing in the Python source says so (the AST-level `dtype-literal`
rule catches literal casts; this pass catches what the *graph* actually
computes, including casts introduced by library promotion rules).

Mechanics: taint analysis over the closed jaxpr. A bf16→f32 convert
whose source qualnames (from the eqn's traceback) do NOT match the
island allowlist creates taint; taint propagates through f32-valued
equations (and into/out of pjit/scan/custom-grad sub-jaxprs) and dies
at any downcast (the f32 excursion ended before compute consumed it).
A dot/conv with a tainted f32 operand is a finding. The backward pass
is naturally clean: the transpose of a bf16→f32 convert is a f32→bf16
convert, so cotangents re-enter bf16 before the bwd matmuls.

The allowlist entries match frame function names, file basenames, or
"basename:function" — the PR-4 suppression philosophy (explicit,
auditable, reason-adjacent) applied to graph provenance.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Sequence, Set, Tuple

import numpy as np

# designed f32 islands by qualname: the precision seam, the loss/metric
# math of trainer/steps.py, and the view-averaging eval protocol
DEFAULT_F32_ISLANDS = frozenset({
    "f32_island",            # precision.py — THE declared-island seam
    "_loss_and_metrics",     # trainer/steps.py loss head (fp32 CE)
    "_topk_correct",         # trainer/steps.py top-k in fp32
    "multiview_logits",      # steps.py/serving: fp32 logit averaging
    "device_normalize_batch",  # u8->f32 normalize (input staging, not an
    #                            upcast of bf16 compute — defensive entry)
    # the f32-softmax attention island (ops/attention.py): its BACKWARD
    # necessarily re-enters f32 at the probs-downcast boundary (the
    # transpose of `probs.astype(q.dtype)` is a bf16->f32 convert whose
    # cotangent feeds the dV/dQ matmuls) — the autodiff image of the
    # designed island, not a silent upcast. The router entry point is
    # listed too: inlining can leave it as the innermost user frame of
    # the same converts.
    "dense_attention",
    "fused_attention",
    "dot_product_attention",
    # fused conv/norm/act kernel tier (ops/pallas_fused.py): the file IS
    # the accumulator island — every cast in it routes through
    # f32_island/end_island (the dtype-literal lint rule enforces that at
    # source level), its custom_vjp backwards re-enter f32 at the designed
    # epilogue boundaries (the autodiff image of the islands, exactly the
    # pallas_attention precedent above)
    "pallas_fused.py",
    # models/common.py fused-site helpers: BN batch statistics and the
    # train-mode affine+act tail are accumulator f32 islands by design
    # (nn.BatchNorm computes its stats in f32 too — this is the same
    # policy made explicit); end_island is the precision-seam downcast
    # whose TRANSPOSE is a designed upcast of the cotangent
    "fused_train_norm_act",
    "batch_norm_stats",
    "end_island",
    # serving weight dequantization (serving/quantize.py): int8 -> f32
    # scale multiply -> one downcast to the compute dtype; the upcast
    # starts from int8, never from bf16 compute, but inlining can
    # attribute the scale math here
    "dequantize_tree",
})


def _frames(eqn) -> List[Tuple[str, str]]:
    """[(function_name, file_basename)] user frames, innermost first."""
    try:
        from jax._src import source_info_util

        return [(f.function_name, os.path.basename(f.file_name))
                for f in source_info_util.user_frames(eqn.source_info)]
    except Exception:
        return []


def _allowlisted(frames: Sequence[Tuple[str, str]],
                 allowlist: Set[str]) -> bool:
    for func, base in frames:
        if (func in allowlist or base in allowlist
                or f"{base}:{func}" in allowlist):
            return True
    return False


def _site(frames: Sequence[Tuple[str, str]]) -> str:
    if not frames:
        return "<unknown>"
    func, base = frames[0]
    return f"{base}:{func}"


def _is_dtype(aval, dtype) -> bool:
    try:
        return np.dtype(aval.dtype) == np.dtype(dtype)
    except TypeError:  # extended dtypes (PRNG keys)
        return False


def _sub_closed(value) -> List[Any]:
    from jax._src import core as jcore

    out = []
    if isinstance(value, jcore.ClosedJaxpr):
        out.append(value)
    elif isinstance(value, (tuple, list)):
        for v in value:
            out.extend(_sub_closed(v))
    return out


def check_dtype(closed_jaxpr, policy: str = "bf16",
                allowlist: Set[str] = DEFAULT_F32_ISLANDS,
                ) -> Tuple[List[dict], Dict[str, Any]]:
    """Run the taint analysis; returns (findings, summary). `policy`
    other than bf16/fp16 means there is no bf16 compute to upcast —
    the pass reports a no-op summary (fp32 parity lanes)."""
    from jax._src import core as jcore

    findings: List[dict] = []
    stats = {"converts_up": 0, "converts_allowlisted": 0,
             "tainted_dots": 0, "tainted_convs": 0}
    if policy not in ("bf16", "fp16"):
        return findings, {**stats, "policy": policy, "skipped": True}

    seen_sites: Set[str] = set()

    def walk(jaxpr, taint: Dict[Any, bool]) -> None:
        def get(v) -> bool:
            return (not isinstance(v, jcore.Literal)) and taint.get(v, False)

        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            op_taint = any(get(v) for v in eqn.invars)
            if name == "convert_element_type":
                src, dst = eqn.invars[0].aval, eqn.outvars[0].aval
                if (_is_dtype(src, np.dtype("bfloat16"))
                        and _is_dtype(dst, np.float32)):
                    frames = _frames(eqn)
                    if _allowlisted(frames, allowlist):
                        stats["converts_allowlisted"] += 1
                        taint[eqn.outvars[0]] = False
                    else:
                        stats["converts_up"] += 1
                        taint[eqn.outvars[0]] = True
                elif not _is_dtype(dst, np.float32):
                    taint[eqn.outvars[0]] = False  # downcast ends the island
                else:
                    taint[eqn.outvars[0]] = op_taint
                continue
            if name in ("dot_general", "conv_general_dilated"):
                tainted_f32 = any(
                    get(v) and _is_dtype(v.aval, np.float32)
                    for v in eqn.invars)
                if tainted_f32:
                    frames = _frames(eqn)
                    if not _allowlisted(frames, allowlist):
                        site = _site(frames)
                        key = f"{name}@{site}"
                        if key not in seen_sites:
                            seen_sites.add(key)
                            kind = ("tainted_dots" if name == "dot_general"
                                    else "tainted_convs")
                            stats[kind] += 1
                            shapes = [
                                f"{v.aval.dtype}{list(v.aval.shape)}"
                                for v in eqn.invars[:2]]
                            findings.append({
                                "pass": "dtype",
                                "site": site,
                                "message": (
                                    f"f32 {name} reached from bf16 data at "
                                    f"{site} (operands {', '.join(shapes)}): "
                                    "a silent upcast is paying f32 MXU rate "
                                    "+ 2x bytes inside a bf16 policy — "
                                    "declare it via precision.f32_island "
                                    "or add the qualname to the island "
                                    "allowlist"),
                                "details": {"primitive": name,
                                            "frames": [f"{b}:{f}" for f, b
                                                       in _frames(eqn)[:4]]},
                            })
                for ov in eqn.outvars:
                    taint[ov] = op_taint and _is_dtype(ov.aval, np.float32)
                continue
            if name == "scan":
                inner = eqn.params["jaxpr"]
                sub_taint: Dict[Any, bool] = {}
                for iv, inner_v in zip(eqn.invars, inner.jaxpr.invars):
                    sub_taint[inner_v] = get(iv)
                walk(inner.jaxpr, sub_taint)
                for ov, inner_o in zip(eqn.outvars, inner.jaxpr.outvars):
                    taint[ov] = ((not isinstance(inner_o, jcore.Literal))
                                 and sub_taint.get(inner_o, False))
                continue
            if name == "shard_map":
                # SPMD-manual region (parallel/pipeline.py's stage
                # pipeline): the body rides as an OPEN Jaxpr param, which
                # the generic ClosedJaxpr recursion below misses — the
                # pipelined trunk would get zero dtype coverage. Operands
                # map 1:1 (per-shard avals, same dtypes).
                inner = eqn.params["jaxpr"]
                sub_taint = {inner_v: get(iv) for iv, inner_v
                             in zip(eqn.invars, inner.invars)}
                walk(inner, sub_taint)
                for ov, inner_o in zip(eqn.outvars, inner.outvars):
                    taint[ov] = ((not isinstance(inner_o, jcore.Literal))
                                 and sub_taint.get(inner_o, False))
                continue
            subs = []
            for v in eqn.params.values():
                subs.extend(_sub_closed(v))
            if len(subs) == 1 and len(subs[0].jaxpr.invars) == len(
                    eqn.invars):
                # pjit / remat / custom_jvp / closed_call: 1:1 operand map
                inner = subs[0]
                sub_taint = {inner_v: get(iv) for iv, inner_v
                             in zip(eqn.invars, inner.jaxpr.invars)}
                walk(inner.jaxpr, sub_taint)
                for ov, inner_o in zip(eqn.outvars, inner.jaxpr.outvars):
                    taint[ov] = ((not isinstance(inner_o, jcore.Literal))
                                 and sub_taint.get(inner_o, False))
                continue
            for ov in eqn.outvars:
                taint[ov] = op_taint and _is_dtype(ov.aval, np.float32)

    walk(closed_jaxpr.jaxpr, {})
    return findings, {**stats, "policy": policy}
