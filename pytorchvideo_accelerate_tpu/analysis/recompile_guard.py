"""Runtime counterpart of the `recompile` lint rule.

The static rule catches the *syntax* of recompile hazards; this guard
catches the *fact*: after warmup, the train step's jit cache must stop
growing. Every steady-state cache miss is a multi-second XLA compile
stall in the middle of training — the failure mode the pjit-at-scale
writeups (arXiv:2204.06514) spend a section on eliminating.

Mechanics: `jax.jit` wrappers expose `_cache_size()` (the number of
compiled executables behind the callable). `arm()` records the size
after the first real step (the legitimate compile); `sample()` reports
growth since then and mirrors it into the `pva_train_recompiles` gauge
of the obs metric registry. Trainer.fit() arms after step one, samples
at every `log_every` drain and epoch end, and surfaces the total in its
perf dict as `train_recompiles` — which bench.py carries on the
headline line and asserts == 0 in `--smoke`.

`_cache_size` is a private-but-stable jax API (0.4.x); if a future jax
drops it the guard degrades to inert (reports None) rather than lying
with a zero, and the static rule keeps standing watch.
"""

from __future__ import annotations

from typing import Any, Optional

GAUGE_NAME = "pva_train_recompiles"


def cache_size(fn: Any) -> Optional[int]:
    """Compiled-executable count behind a jitted callable; None when the
    wrapper doesn't expose one (non-jit callable, future jax)."""
    probe = getattr(fn, "_cache_size", None)
    if probe is None:
        return None
    try:
        return int(probe())
    except Exception:  # a broken probe must never break the step loop
        return None


class RecompileGuard:
    """Steady-state jit-cache-growth monitor for one compiled callable."""

    def __init__(self, fn: Any, registry: Any = None,
                 gauge_name: str = GAUGE_NAME):
        self.fn = fn
        self._baseline: Optional[int] = None
        if registry is None:
            from pytorchvideo_accelerate_tpu.obs import get_registry

            registry = get_registry()
        self._gauge = registry.gauge(
            gauge_name,
            "jit cache entries compiled after warmup (steady state == 0)")
        self._gauge.set(0.0)

    @property
    def armed(self) -> bool:
        return self._baseline is not None

    @property
    def supported(self) -> bool:
        return cache_size(self.fn) is not None

    def arm(self) -> None:
        """Take the post-warmup baseline (call after the first step has
        returned — its compile is the legitimate one)."""
        self._baseline = cache_size(self.fn)

    def sample(self) -> Optional[int]:
        """Cache growth since `arm()` (0 is the healthy reading); updates
        the gauge. None when unarmed or the probe is unavailable."""
        if self._baseline is None:
            return None
        size = cache_size(self.fn)
        if size is None:
            return None
        recompiles = max(0, size - self._baseline)
        self._gauge.set(float(recompiles))
        return recompiles
