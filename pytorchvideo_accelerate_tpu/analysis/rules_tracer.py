"""Rule `tracer-leak`: side effects inside traced (jit/pjit/shard_map) code.

A function handed to `jax.jit` runs as PYTHON exactly once per cache
entry — at trace time. Any side effect in its body (`self.step_count +=
1`, `global LAST_LOSS`, appending to a closed-over list) either
vanishes on cached calls or, worse, stores a *tracer* object that
explodes much later with the infamous leaked-tracer error, far from the
line that caused it. State must flow through arguments and return values
(the `TrainState` convention every step in trainer/steps.py follows).

What counts as "traced" here (module-local, heuristic by design):

- functions decorated with `jit`/`pjit`/`shard_map` (bare or dotted,
  including `partial(jax.jit, ...)` decorators);
- named functions passed as the first argument to a `jit`/`pjit`/
  `shard_map` call anywhere in the module (`return jax.jit(step)` — the
  factory pattern trainer/steps.py uses).

Inside a traced function (nested defs included, with their own locals):

- stores/augments to `self.*` or to attributes of closure variables;
- `global` / `nonlocal` declarations (declaring one is only ever done
  to write it);
- mutating method calls (`.append` etc.) on closure variables.

Reads of closures are fine — closing over the model/mesh is the whole
factory pattern. Local mutation is fine — locals die at trace end.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set

from pytorchvideo_accelerate_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Rule,
    call_name,
    walk_pruned,
)

_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)

_TRACE_WRAPPERS = ("jit", "pjit", "shard_map")
_MUTATORS = frozenset({"append", "appendleft", "extend", "extendleft",
                       "insert", "add", "update", "pop", "remove",
                       "discard", "clear", "setdefault"})


def _is_trace_wrapper(name: str) -> bool:
    return name.rsplit(".", 1)[-1] in _TRACE_WRAPPERS


def _decorated_traced(fn: ast.AST) -> bool:
    for dec in getattr(fn, "decorator_list", []):
        if _is_trace_wrapper(ast.unparse(dec).split("(")[0].strip()):
            return True
        # functools.partial(jax.jit, static_argnums=...) decorators
        if (isinstance(dec, ast.Call)
                and call_name(dec).rsplit(".", 1)[-1] == "partial"
                and dec.args and _is_trace_wrapper(
                    ast.unparse(dec.args[0]).strip())):
            return True
    return False


def _local_names(fn: ast.AST) -> Set[str]:
    """Parameters + every Name ever stored in this function's own body
    (nested defs excluded — they get their own scope when recursed)."""
    names: Set[str] = set()
    args = fn.args
    for a in (list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
              + ([args.vararg] if args.vararg else [])
              + ([args.kwarg] if args.kwarg else [])):
        names.add(a.arg)
    for node in walk_pruned(fn, prune=_FUNCS):
        if isinstance(node, _FUNCS):
            names.add(node.name)  # the nested def's NAME is a local binding
        elif isinstance(node, ast.Name) and isinstance(
                node.ctx, (ast.Store, ast.Del)):
            names.add(node.id)
    return names


class TracerLeakRule(Rule):
    name = "tracer-leak"
    description = ("assignment to self/globals/closures inside a function "
                   "traced by jit/pjit/shard_map")

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        # names passed (first positional) to a trace wrapper anywhere
        traced_names: Set[str] = set()
        for node in ast.walk(module.tree):
            if (isinstance(node, ast.Call) and _is_trace_wrapper(
                    call_name(node)) and node.args
                    and isinstance(node.args[0], ast.Name)):
                traced_names.add(node.args[0].id)

        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if node.name in traced_names or _decorated_traced(node):
                yield from self._scan(module, node, _local_names(node))

    def _scan(self, module: ModuleInfo, fn: ast.AST,
              locals_: Set[str]) -> Iterable[Finding]:
        """Flag non-local side effects in `fn`'s body; recurse into nested
        defs with their locals unioned in (an inner def sees the outer
        trace's variables as closures either way)."""
        body: List[ast.stmt] = fn.body
        for stmt in body:
            nodes = ([stmt] if isinstance(stmt, _FUNCS)
                     else [stmt, *walk_pruned(stmt, prune=_FUNCS)])
            for node in nodes:
                if isinstance(node, _FUNCS):
                    yield from self._scan(module, node,
                                          locals_ | _local_names(node))
                    continue
                if isinstance(node, (ast.Global, ast.Nonlocal)):
                    kind = ("global" if isinstance(node, ast.Global)
                            else "nonlocal")
                    yield self.finding(
                        module, node,
                        f"`{kind} {', '.join(node.names)}` inside a traced "
                        "function: the write happens at trace time only "
                        "(or leaks a tracer) — thread state through "
                        "arguments/returns instead")
                elif isinstance(node, (ast.Assign, ast.AugAssign,
                                       ast.AnnAssign)):
                    targets = (node.targets if isinstance(node, ast.Assign)
                               else [node.target])
                    for tgt in targets:
                        yield from self._flag_target(module, node, tgt,
                                                     locals_)
                elif (isinstance(node, ast.Expr)
                        and isinstance(node.value, ast.Call)):
                    # statement-position call with the result discarded:
                    # the only shape where a mutator call IS the point.
                    # (`updates, _ = tx.update(...)` binds the result —
                    # that's optax's pure update, not dict mutation.)
                    f = node.value.func
                    if (isinstance(f, ast.Attribute)
                            and f.attr in _MUTATORS
                            and isinstance(f.value, ast.Name)
                            and f.value.id not in locals_):
                        yield self.finding(
                            module, node,
                            f"`{f.value.id}.{f.attr}(...)` mutates a "
                            "closure inside a traced function — the "
                            "mutation runs at trace time only and can "
                            "store a leaked tracer")

    def _flag_target(self, module: ModuleInfo, node: ast.AST, tgt: ast.AST,
                     locals_: Set[str]) -> Iterable[Finding]:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for e in tgt.elts:
                yield from self._flag_target(module, node, e, locals_)
            return
        if isinstance(tgt, ast.Subscript):
            tgt = tgt.value  # x[k] = ... writes through x
        root = tgt
        while isinstance(root, ast.Attribute):
            root = root.value
        if not isinstance(root, ast.Name):
            return
        if isinstance(tgt, ast.Attribute):
            if root.id == "self":
                yield self.finding(
                    module, node,
                    f"`self.{tgt.attr} = ...` inside a traced function: "
                    "the store runs at trace time only (or leaks a "
                    "tracer) — return the value instead")
            elif root.id not in locals_:
                yield self.finding(
                    module, node,
                    f"attribute store on closure `{root.id}` inside a "
                    "traced function — side effects don't survive "
                    "tracing; thread state through arguments/returns")
        # bare-Name stores to non-locals are impossible without
        # global/nonlocal (already flagged); subscript stores through a
        # closure name:
        elif isinstance(tgt, ast.Name) and tgt.id not in locals_:
            yield self.finding(
                module, node,
                f"subscript store through closure `{tgt.id}` inside a "
                "traced function — trace-time-only side effect")
