"""The precision policy seam: every deliberate f32 island goes through here.

The models compute in bf16 by policy (`create_model(mixed_precision)`),
params stay fp32, and a handful of sites are *designed* to run in f32
anyway — classifier heads, loss math, softmax logits, norm statistics,
reference accumulations. Those casts used to be bare `x.astype(
jnp.float32)` literals scattered through the model/ops hot modules,
indistinguishable from an accidental upcast that silently doubles a hot
path's bytes and halves its MXU rate.

`f32_island(x)` is the single grep-able cast point: the `dtype-literal`
lint rule (analysis/rules_dtype.py) flags any bare f32 cast in a hot
model/ops module that does not route through it, and the graphcheck
dtype pass (analysis/gc_dtype.py) allowlists compute reached from these
sites by qualname. Adding a new island = calling this helper (the code
states the intent) — not silencing a linter.

Top-level leaf module on purpose: stdlib + jax.numpy only, importable
from both models/ and ops/ without package-init cycles (models/__init__
imports the model files, which import ops/attention — a helper living in
either package would be mid-init exactly when the other needs it).
"""

from __future__ import annotations

import jax.numpy as jnp

# the island dtype is a policy constant, not a per-site choice: everything
# deliberately upcast lands in f32 (TPU has no f64 to drift into)
ISLAND_DTYPE = jnp.float32


def f32_island(x):
    """Cast `x` (jax or numpy array) to float32 at a DESIGNED f32 island.

    Use this instead of a bare `.astype(jnp.float32)` in model/ops hot
    modules — the cast is the same; the seam is what makes the dtype
    policy auditable (dtype-literal rule + graphcheck dtype pass)."""
    return x.astype(ISLAND_DTYPE)


def end_island(x, dtype):
    """Close an f32 island: cast the island's result back to the compute
    dtype `dtype` at the DESIGNED boundary (the single store/downcast of
    a fused-kernel epilogue or accumulator). The counterpart seam to
    `f32_island` — using it states that the f32 excursion ends here on
    purpose, which is what keeps the graphcheck dtype pass's taint from
    ever reaching compute."""
    return x.astype(dtype)


def policy_compute_dtype(mixed_precision: str):
    """Model compute dtype for a TrainConfig.mixed_precision string:
    bf16 for "bf16"/"fp16" (fp16 maps to bf16 on TPU — no loss scaling),
    f32 otherwise. The one resolution point `create_model` uses."""
    return jnp.bfloat16 if mixed_precision in ("bf16", "fp16") else jnp.float32
