"""Least-outstanding-requests router over a `ReplicaPool`.

One router fronts N replicas and speaks the same interface as a
`MicroBatcher`/`Scheduler` (`submit`/`queue_depth`/`drain`/`close`), so an
`InferenceServer` — and therefore the whole PR 6 admission/drain/Retry-After
vocabulary — works unchanged with a fleet behind it.

Routing policy, per request:

- **affinity-then-least-outstanding**: a streaming-session request
  (`session={"sid": ...}`) routes to the replica that HOLDS the session's
  device-resident ring (docs/SERVING.md § streaming) — the affinity map
  is router-tracked, updated at every successful dispatch; when the
  affinity replica is down/shedding, the request falls through to the
  ordinary policy and the ACCEPTING replica becomes the new affinity —
  deterministic re-establish from the request's resendable window makes
  the move client-invisible;
- stateless requests (and affinity fall-through) pick the ROUTABLE
  replica (pool membership, health-gated) with the fewest outstanding
  requests (router-tracked; queue depth would lag and cost an RPC for
  HTTP replicas), FIFO-seq tiebreak;
- a replica-level shed (`QueueFullError`) tries the next-least-loaded
  replica before giving up: one hot replica must not shed traffic the
  rest of the fleet has capacity for. Only when EVERY candidate sheds
  (or none is routable) does the router itself shed — 503 + Retry-After;
- **route-around on death**: a `ReplicaDeadError` (closed scheduler,
  refused/reset connection) marks the replica down in the pool
  immediately and re-dispatches the request — including requests already
  in flight when the replica died (inference is idempotent, so the
  at-least-once retry is safe). A client only sees a failure when the
  whole fleet is gone or retries are exhausted.

Per-replica traffic is published with REPLICA LABELS into an obs registry
(`pva_fleet_routed_total{replica=...}`, `pva_fleet_outstanding{replica=…}`)
and `fleet_snapshot()` merges replica `ServingStats` windows into honest
fleet percentiles (`ServingStats.merge` — pooled samples, not averaged
percentiles, sheds counted exactly once).
"""

from __future__ import annotations

import time
from concurrent.futures import Future
from typing import Dict, List, Optional

from pytorchvideo_accelerate_tpu import obs
from pytorchvideo_accelerate_tpu.obs import trace
from pytorchvideo_accelerate_tpu.fleet.pool import ReplicaDeadError, ReplicaPool
from pytorchvideo_accelerate_tpu.serving.batcher import QueueFullError
from pytorchvideo_accelerate_tpu.serving.stats import ServingStats
from pytorchvideo_accelerate_tpu.utils.logging import get_logger
from pytorchvideo_accelerate_tpu.utils.sync import make_lock, shared_state

logger = get_logger("pva_tpu")


# affinity-map bound: sessions beyond this evict oldest-first — a stream
# that outlives its map entry just re-establishes from its resendable
# window on its next advance (correct, merely one launch less warm)
MAX_AFFINITY_SESSIONS = 65536


@shared_state("_outstanding", "_rr", "_affinity")
class Router:
    """Affinity-then-least-outstanding routing + route-around over a
    `ReplicaPool`."""

    supports_priority = True
    # session envelopes forward through `submit(..., session=...)`; the
    # REPLICA's scheduler decides capability (a non-stream replica answers
    # with a 400-shaped ValueError the client sees)
    supports_sessions = True

    def __init__(self, pool: ReplicaPool, *, retries: Optional[int] = None,
                 retry_after_s: float = 1.0, registry=None):
        self.pool = pool
        if retries is None:
            # single source of truth for the tier default: fleet.route_retries
            from pytorchvideo_accelerate_tpu.config import FleetConfig

            retries = FleetConfig().route_retries
        self.retries = max(int(retries), 0)
        self.retry_after_s = float(retry_after_s)
        self.registry = registry if registry is not None else obs.get_registry()
        self._lock = make_lock("Router._lock")
        self._outstanding: Dict[str, int] = {}
        self._rr = 0  # rotation counter: round-robin among outstanding ties
        # session id -> replica name holding its ring (insertion-ordered
        # dict = oldest-first eviction past the bound)
        self._affinity: Dict[str, str] = {}
        # every series is scoped by the POOL's name: registry metrics are
        # get-or-create by name, so two routers on the process-default
        # registry would otherwise sum each other's sheds/retries into
        # both fleet_snapshots (the pool-gauge lesson, pool.py)
        self._pool_label = pool.name
        self._c_routed = self.registry.counter(
            "pva_fleet_routed_total",
            "requests dispatched, by pool and replica",
            labelnames=("pool", "replica"))
        self._c_retried = self.registry.counter(
            "pva_fleet_retried_total",
            "requests re-dispatched around a death or a replica shed, "
            "by pool", labelnames=("pool",))
        self._c_shed = self.registry.counter(
            "pva_fleet_shed_total",
            "requests shed at the router (no routable capacity), by pool",
            labelnames=("pool",))
        self._g_outstanding = self.registry.gauge(
            "pva_fleet_outstanding",
            "requests in flight, by pool and replica",
            labelnames=("pool", "replica"))
        # multi-model serving: per-model-family traffic under the shared
        # pool (docs/SERVING.md § fleet intelligence). Separate series,
        # not extra labels on the unlabeled ones above — metric label
        # schemas are get-or-create by name and must never be widened
        self._c_model_routed = self.registry.counter(
            "pva_fleet_model_routed_total",
            "requests dispatched, by pool and model family",
            labelnames=("pool", "model"))
        self._c_model_shed = self.registry.counter(
            "pva_fleet_model_shed_total",
            "requests shed at the router, by pool and model family",
            labelnames=("pool", "model"))

    # --- the batcher interface -------------------------------------------

    def submit(self, clip, *, priority: Optional[str] = None,
               deadline_ms: Optional[float] = None,
               session: Optional[dict] = None,
               model: Optional[str] = None) -> Future:
        """Route ONE request; returns a Future that survives replica death
        (re-dispatched) and resolves with logits, `QueueFullError` (shed),
        or the terminal error once retries are exhausted. A `session`
        envelope routes with affinity (see module docstring) and carries
        the resendable window a survivor re-establishes from when the
        affinity replica dies mid-flight. A `model` narrows the candidate
        set to replicas serving that model family (multi-model pools);
        the request sheds — per-model labeled — when none is routable."""
        kwargs: dict = {}
        if priority is not None:
            kwargs["priority"] = priority
        if deadline_ms is not None:
            kwargs["deadline_ms"] = deadline_ms
        if session is not None:
            kwargs["session"] = session
        outer: Future = Future()
        # capture the submitter's trace context ONCE: first dispatch runs
        # on this thread (context already active), but a re-dispatch after
        # a death/shed runs on a done-callback thread with no context —
        # the captured value re-attaches it there (trace.attach)
        self._dispatch(outer, clip, kwargs, self.retries,
                       ctx=trace.capture(), model=model)
        return outer

    def queue_depth(self) -> int:
        """Requests dispatched but not yet settled, router-tracked. NOT a
        per-replica RPC sum: this runs on the admission hot path (the HTTP
        front calls it before reading every request body), and a blocking
        /healthz GET per HttpReplica per request would turn the cheapest
        response into up-to-seconds of blocking under exactly the overload
        admission exists for."""
        with self._lock:
            return sum(self._outstanding.values())

    def drain(self, timeout_s: float = 10.0) -> bool:
        deadline = time.monotonic() + max(timeout_s, 0.0)
        while time.monotonic() < deadline:
            if not self._any_outstanding():
                return True
            time.sleep(0.01)
        # in-flight launches count: reporting drained while one is still
        # mid-predict would let the close() that follows fail its future
        return not self._any_outstanding()

    def close(self) -> None:
        self.pool.close()

    # --- dispatch ---------------------------------------------------------

    def _any_outstanding(self) -> bool:
        with self._lock:
            return any(v > 0 for v in self._outstanding.values())

    def _pick(self, exclude: frozenset, sid: Optional[str] = None,
              model: Optional[str] = None) -> List:
        """Routable replicas, least-outstanding first; ties rotate
        round-robin (an idle fleet must spread load, not pile onto the
        alphabetically-first replica). A session id promotes its affinity
        replica to the FRONT of the order — the rest stay as the shed/
        death fallback chain, so losing the affinity replica degrades to
        ordinary routing instead of failing. A model narrows candidates
        to the replicas serving that family."""
        candidates = [r for r in self.pool.routable()
                      if r.name not in exclude
                      and (model is None
                           or getattr(r, "model", None) == model)]
        if not candidates:
            return []
        with self._lock:
            order = {r.name: self._outstanding.get(r.name, 0)
                     for r in candidates}
            self._rr += 1
            rot = self._rr % len(candidates)
            pinned = self._affinity.get(sid) if sid else None
        rotated = candidates[rot:] + candidates[:rot]
        picked = sorted(rotated, key=lambda r: order[r.name])  # stable sort
        if pinned is not None:
            picked.sort(key=lambda r: r.name != pinned)  # stable: pin first
        return picked

    def _record_affinity(self, sid: str, replica_name: str) -> None:
        with self._lock:
            moved = self._affinity.pop(sid, None)
            self._affinity[sid] = replica_name  # re-insert = LRU refresh
            while len(self._affinity) > MAX_AFFINITY_SESSIONS:
                self._affinity.pop(next(iter(self._affinity)))
        if moved is not None and moved != replica_name:
            logger.info("fleet: session %s re-routed %s -> %s", sid,
                        moved, replica_name)

    def forget_session(self, sid: str) -> None:
        with self._lock:
            self._affinity.pop(sid, None)

    def sessions_on(self, replica_name: str) -> List[str]:
        """Session ids whose affinity pins `replica_name` — the controller
        reads this to re-home a scale-down victim's live streams before
        reaping it (each forgotten session re-establishes elsewhere from
        its resendable window on its next advance)."""
        with self._lock:
            return [sid for sid, holder in self._affinity.items()
                    if holder == replica_name]

    def _track(self, name: str, delta: int) -> None:
        with self._lock:
            n = max(self._outstanding.get(name, 0) + delta, 0)
            self._outstanding[name] = n
            # gauge published under the SAME lock: out-of-order sets from
            # a racing dispatch/settle pair would leave it stale forever
            self._g_outstanding.set(float(n), pool=self._pool_label,
                                    replica=name)

    def _dispatch(self, outer: Future, clip, kwargs, attempts_left: int,
                  exclude: frozenset = frozenset(), ctx=None,
                  model: Optional[str] = None) -> None:
        if outer.cancelled():  # the client gave up (504) before dispatch
            return
        last_shed: Optional[QueueFullError] = None
        session = kwargs.get("session")
        sid = str(session["sid"]) if session and session.get("sid") else None
        for replica in self._pick(exclude, sid=sid, model=model):
            try:
                with trace.attach(ctx):
                    inner = replica.submit(clip, **kwargs)
            except QueueFullError as e:
                last_shed = e  # this replica sheds; try the next one
                continue
            except ReplicaDeadError:
                self.pool.mark_down(replica)
                continue
            if sid is not None:
                # the ACCEPTING replica holds (or will now establish) the
                # session ring: later advances pin here; a fall-through
                # move re-establishes deterministically from the
                # request's resendable window on the new replica
                self._record_affinity(sid, replica.name)
                if session.get("end"):
                    self.forget_session(sid)
            # the request is now engine-bound: mark the outer future
            # RUNNING so a later client cancel (the 504 path) loses the
            # race — exactly the MicroBatcher/Scheduler claim semantics —
            # instead of counting engine-claimed work as a true rejection.
            # running() guard, not try/except: a re-dispatch arrives here
            # already RUNNING, and the stdlib logs CRITICAL before raising
            if not outer.running() and not outer.set_running_or_notify_cancel():
                # cancelled in the dispatch gap: the inner request will
                # complete and be dropped at settle; nothing to deliver
                self._c_routed.inc(pool=self._pool_label,
                               replica=replica.name)
                if model is not None:
                    self._c_model_routed.inc(pool=self._pool_label,
                                             model=model)
                self._track(replica.name, +1)
                inner.add_done_callback(
                    lambda f, r=replica: self._track(r.name, -1))
                return
            self._c_routed.inc(pool=self._pool_label,
                               replica=replica.name)
            if model is not None:
                self._c_model_routed.inc(pool=self._pool_label, model=model)
            self._track(replica.name, +1)
            inner.add_done_callback(
                lambda f, r=replica: self._settle(
                    outer, clip, kwargs, attempts_left, r, f, ctx=ctx,
                    model=model))
            return
        # nothing took it: the ROUTER sheds (every candidate shed or died)
        self._c_shed.inc(pool=self._pool_label)
        if model is not None:
            self._c_model_shed.inc(pool=self._pool_label, model=model)
        err = last_shed if last_shed is not None else QueueFullError(
            "no routable replicas", retry_after_s=self.retry_after_s)
        self._fail(outer, err)

    def _settle(self, outer: Future, clip, kwargs, attempts_left: int,
                replica, inner: Future, ctx=None,
                model: Optional[str] = None) -> None:
        self._track(replica.name, -1)
        if outer.cancelled():
            return
        err = inner.exception()
        if err is None:
            try:
                outer.set_result(inner.result())
            except Exception:  # outer cancelled in the gap
                pass
            return
        if isinstance(err, ReplicaDeadError) and attempts_left > 0:
            # the replica died with this request in flight: route around it
            # and re-dispatch (idempotent inference -> at-least-once retry)
            self.pool.mark_down(replica)
            self._c_retried.inc(pool=self._pool_label)
            logger.warning("fleet: %s died mid-request; re-dispatching",
                           replica.name)
            self._dispatch(outer, clip, kwargs, attempts_left - 1,
                           exclude=frozenset({replica.name}), ctx=ctx,
                           model=model)
            return
        if isinstance(err, ReplicaDeadError):
            self.pool.mark_down(replica)
        if isinstance(err, QueueFullError) and attempts_left > 0:
            # a shed that arrived via the FUTURE (HttpReplica's 503, or a
            # scheduler deadline shed): one hot replica must not shed
            # traffic the rest of the fleet has capacity for — try the
            # next-least-loaded replica before surfacing the 503. The
            # replica is NOT marked down: shedding is it working.
            self._c_retried.inc(pool=self._pool_label)
            self._dispatch(outer, clip, kwargs, attempts_left - 1,
                           exclude=frozenset({replica.name}), ctx=ctx,
                           model=model)
            return
        self._fail(outer, err)

    @staticmethod
    def _fail(outer: Future, err: BaseException) -> None:
        if not outer.done():
            try:
                outer.set_exception(err)
            except Exception:
                pass

    # --- fleet-wide observability ----------------------------------------

    # counter keys summable from a remote replica's /stats snapshot
    _SNAPSHOT_COUNTERS = ("requests", "batches", "errors", "rejected",
                          "rejected_400", "rejected_503", "rejected_504",
                          "shed", "compiled_buckets")

    def fleet_snapshot(self, model: Optional[str] = None) -> Dict[str, float]:
        """Cross-replica aggregate: pooled latency percentiles + summed
        counters (`ServingStats.merge`), plus the router's own counters.
        Router sheds ride as `router_shed` — NEVER folded into the replica
        `shed` sum, so a shed is counted exactly once wherever it
        happened. `model` restricts the replica set (and the labeled
        router counters) to one model family — the per-model view the
        multi-model controller compares against its budget.

        HttpReplica counters are summed from their `/stats` snapshots;
        their raw latency WINDOWS are not available over the wire, so the
        percentiles cover window-bearing (in-process) replicas only —
        `replicas_windowed` says how many that is, so an all-HTTP fleet's
        0.0 percentiles read as "no windows", never as "no latency"."""
        members = [r for r in list(self.pool.replicas)
                   if model is None or getattr(r, "model", None) == model]
        local = [r for r in members
                 if getattr(r, "stats", None) is not None]
        remote = [r for r in members if r not in local]
        with self._lock:
            outstanding = dict(self._outstanding)
            affine = len(self._affinity)
        if model is None:
            shed = self._c_shed.value(pool=self._pool_label)
        else:
            shed = self._c_model_shed.value(pool=self._pool_label,
                                            model=model)
        merged = ServingStats.merge([r.stats for r in local], extra={
            "sessions_affine": float(affine),
            "router_shed": shed,
            "router_retries": self._c_retried.value(pool=self._pool_label),
            "replicas_routable": float(len(
                [r for r in self.pool.routable() if r in members])),
            "replicas_total": float(len(members)),
            "outstanding": float(sum(outstanding.values())),
        })
        for replica in remote:
            snap = replica.snapshot()  # {} when the replica is unreachable
            for key in self._SNAPSHOT_COUNTERS:
                merged[key] = merged.get(key, 0.0) + float(snap.get(key, 0.0))
        merged["replicas"] = float(len(members))
        merged["replicas_windowed"] = float(len(local))
        if model is not None:
            merged["model_routed"] = self._c_model_routed.value(
                pool=self._pool_label, model=model)
        return merged
