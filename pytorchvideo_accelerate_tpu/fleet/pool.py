"""Replica pool: N serving replicas behind health-gated membership.

A replica is one `InferenceEngine` behind one `Scheduler` — in this
process (`LocalReplica`, engines pinned to disjoint device meshes; the
multi-device CI story runs them on a forced-host slice via
`utils/forcehost.py`) or in another process behind HTTP (`HttpReplica`,
the production shape: one `pva-tpu-serve` per host/slice). The pool owns
MEMBERSHIP: a poller thread re-checks every replica's health on
`health_interval_s` — driven by the replica's existing `/healthz`
admission state, so a replica that is merely shedding (`degraded`) stays
routable while a `draining` or dead one leaves the rotation — and the
router reports observed deaths (`mark_down`) for immediate route-around
without waiting out a poll interval. A down replica whose health probe
recovers rejoins automatically.

Process replicas are spawned by the operator (or `spawn_serving_process`
below for CI), never supervised here: restart policy belongs to the
platform (k8s, systemd); the pool's job is to keep traffic off a corpse
and notice a resurrection.
"""

from __future__ import annotations

import json
import subprocess
import sys
import time
import urllib.error
import urllib.request
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from pytorchvideo_accelerate_tpu import obs
from pytorchvideo_accelerate_tpu.obs import trace
from pytorchvideo_accelerate_tpu.serving.batcher import QueueFullError
from pytorchvideo_accelerate_tpu.utils.logging import get_logger
from pytorchvideo_accelerate_tpu.utils.sync import (
    make_lock,
    make_thread,
    shared_state,
)

logger = get_logger("pva_tpu")

# /healthz states that keep a replica in the routable set: "degraded" is a
# replica WORKING as designed (shedding at its own door, still serving) —
# pulling it would turn one replica's overload into fleet capacity loss
ROUTABLE_STATES = ("healthy", "degraded")


class ReplicaDeadError(RuntimeError):
    """The replica cannot take (or finish) this request at the transport
    level — closed scheduler, refused/reset connection. The router treats
    it as a route-around signal, never a client-visible failure."""


class LocalReplica:
    """In-process replica: one engine behind one `Scheduler`."""

    def __init__(self, name: str, scheduler, stats=None,
                 model: Optional[str] = None):
        self.name = name
        self.scheduler = scheduler
        self.stats = stats if stats is not None else scheduler.stats
        # model family served here (multi-model routing key); defaults to
        # the engine's own identity when it declares one
        if model is None:
            try:
                model = getattr(scheduler.current_engine(), "model_name",
                                None)
            except Exception:
                model = None
        self.model = model
        self._draining = False

    def submit(self, clip, **kwargs) -> Future:
        try:
            inner = self.scheduler.submit(clip, **kwargs)
        except (QueueFullError, ValueError):
            raise  # shed (503) and bad-request (400) are not death
        except RuntimeError as e:  # closed scheduler = dead replica
            raise ReplicaDeadError(f"{self.name}: {e}") from e
        # a replica that dies AFTER accepting (close() fails its pending
        # futures) must surface as ReplicaDeadError so the router
        # re-dispatches instead of failing the client. Death is classified
        # by the SCHEDULER's closed latch, never by exception-message
        # sniffing — an engine bug whose text happens to contain "closed"
        # (jax buffer / file errors) must propagate untranslated.
        outer: Future = Future()

        def done(f, name=self.name):
            err = f.exception()
            try:
                if err is None:
                    outer.set_result(f.result())
                elif (isinstance(err, RuntimeError)
                      and not isinstance(err, QueueFullError)
                      and self.scheduler._closed.is_set()):
                    outer.set_exception(
                        ReplicaDeadError(f"{name}: {err}"))
                else:
                    outer.set_exception(err)
            except Exception:  # outer cancelled by the caller
                pass

        inner.add_done_callback(done)
        return outer

    def health(self) -> str:
        if self.scheduler._closed.is_set():
            return "dead"
        return "draining" if self._draining else "healthy"

    def drain(self) -> bool:
        """Scale-down actuator: report `draining` from here on, so the
        pool's poller removes this replica from the rotation within one
        health interval (the admission state machine's terminal state,
        mirrored for the in-process shape). Idempotent."""
        self._draining = True
        return True

    def queue_depth(self) -> int:
        try:
            return self.scheduler.queue_depth()
        except Exception:
            return 0

    def snapshot(self) -> Dict[str, float]:
        return self.stats.snapshot() if self.stats is not None else {}

    def close(self) -> None:
        self.scheduler.close()


class HttpReplica:
    """Process replica behind a `pva-tpu-serve`-style HTTP endpoint.

    `submit` returns a Future resolved by a small worker pool posting
    `/predict`; HTTP 503 resolves to `QueueFullError` (the shed contract,
    Retry-After honored), connection-level failures to `ReplicaDeadError`
    so the router can route around a SIGKILLed process."""

    def __init__(self, name: str, url: str, *, pid: Optional[int] = None,
                 timeout_s: float = 30.0, health_timeout_s: float = 2.0,
                 workers: int = 8, model: Optional[str] = None):
        self.name = name
        self.url = url.rstrip("/")
        self.pid = pid
        self.timeout_s = float(timeout_s)
        self.health_timeout_s = float(health_timeout_s)
        self.model = model  # multi-model routing key (None = unlabeled)
        self._pool = ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix=f"pva-http-{name}")

    def _predict(self, clip, kwargs, ctx=None) -> np.ndarray:
        # the worker thread re-attaches the submitter's trace context (the
        # thread-pool hop would otherwise drop it) and wraps the whole
        # round trip in an `http_hop` span; the outgoing `traceparent`
        # header carries that span's id, so the server's `http_predict`
        # trace parents onto THIS hop in the merged cross-process timeline
        with trace.attach(ctx), trace.span("http_hop", replica=self.name):
            body = {k: np.asarray(v).tolist() for k, v in clip.items()}
            if kwargs.get("priority") is not None:
                body["priority"] = kwargs["priority"]
            if kwargs.get("deadline_ms") is not None:
                body["deadline_ms"] = float(kwargs["deadline_ms"])
            path = "/predict"
            session = kwargs.get("session")
            if session is not None:
                # streaming advance -> the replica's /stream endpoint;
                # the "video" clip carries the s new frames, the session
                # envelope the id (+ resendable window when the caller
                # chose to ship it — the re-establish-anywhere tradeoff,
                # docs/SERVING.md § streaming)
                path = "/stream"
                body["session"] = str(session.get("sid"))
                if session.get("window") is not None:
                    body["window"] = np.asarray(session["window"]).tolist()
                if session.get("stride"):
                    body["stride"] = int(session["stride"])
                if session.get("end"):
                    body["end"] = True
                body["frames"] = body.pop("video", None)
            headers = {"Content-Type": "application/json"}
            tp = trace.current_traceparent()
            if tp:
                headers["traceparent"] = tp
            req = urllib.request.Request(
                self.url + path, data=json.dumps(body).encode(),
                headers=headers)
            try:
                with urllib.request.urlopen(req,
                                            timeout=self.timeout_s) as r:
                    out = json.loads(r.read())
            except urllib.error.HTTPError as e:
                if e.code == 503:
                    retry_after = float(e.headers.get("Retry-After", 1) or 1)
                    raise QueueFullError(f"{self.name}: shed (503)",
                                         retry_after_s=retry_after) from e
                if e.code == 400:
                    raise ValueError(f"{self.name}: bad request: "
                                     f"{e.read()[:200]!r}") from e
                if e.code == 409:
                    # streaming session unknown on this replica and no
                    # window rode along: the caller must resend its window
                    from pytorchvideo_accelerate_tpu.streaming.session import (
                        SessionUnknownError,
                    )

                    raise SessionUnknownError(
                        f"{self.name}: session unknown (409); resend "
                        "window") from e
                raise RuntimeError(f"{self.name}: HTTP {e.code}") from e
            except (urllib.error.URLError, ConnectionError, OSError) as e:
                raise ReplicaDeadError(f"{self.name}: {e}") from e
            return np.asarray(out["logits"], np.float32)

    def submit(self, clip, **kwargs) -> Future:
        # trace context captured HERE (the caller's thread) and shipped to
        # the worker with the payload — the capture/attach handoff pattern
        return self._pool.submit(self._predict, dict(clip), kwargs,
                                 trace.capture())

    def health(self) -> str:
        try:
            with urllib.request.urlopen(self.url + "/healthz",
                                        timeout=self.health_timeout_s) as r:
                return str(json.loads(r.read()).get("status", "healthy"))
        except urllib.error.HTTPError as e:
            if e.code == 503:  # draining replies 503 with a status body
                try:
                    return str(json.loads(e.read()).get("status", "draining"))
                except Exception:
                    return "draining"
            return "dead"
        except Exception:
            return "dead"

    def queue_depth(self) -> int:
        try:
            with urllib.request.urlopen(self.url + "/healthz",
                                        timeout=self.health_timeout_s) as r:
                return int(json.loads(r.read()).get("queue_depth", 0))
        except Exception:
            return 0

    def snapshot(self) -> Dict[str, float]:
        try:
            with urllib.request.urlopen(self.url + "/stats",
                                        timeout=self.health_timeout_s) as r:
                return {k: float(v) for k, v in json.loads(r.read()).items()
                        if isinstance(v, (int, float))}
        except Exception:
            return {}

    def drain(self) -> bool:
        """Scale-down actuator: flip the remote admission state machine to
        DRAINING via the server's POST /drain controller endpoint; the
        replica then 503s /healthz and the poller pulls it from the
        rotation. Returns False (never raises) on an unreachable replica —
        a dead victim needs no drain."""
        req = urllib.request.Request(
            self.url + "/drain", data=b"{}", method="POST",
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(
                    req, timeout=self.health_timeout_s) as r:
                return bool(json.loads(r.read()).get("draining", True))
        except Exception:
            return False

    def close(self) -> None:
        self._pool.shutdown(wait=False)


@shared_state("_down", benign={
    "_closed": "monotonic shutdown latch; poller polls it, a torn read of "
               "a bool is impossible and the worst case is one extra poll"})
class ReplicaPool:
    """Health-gated replica membership + the poller that maintains it."""

    def __init__(self, replicas: Sequence, *, health_interval_s: float = 0.5,
                 registry=None, name: str = "fleet",
                 on_change: Optional[Callable[[str, bool], None]] = None):
        if not replicas:
            raise ValueError("a replica pool needs at least one replica")
        self.replicas: List = list(replicas)
        self.name = name
        self.health_interval_s = max(float(health_interval_s), 0.01)
        self.on_change = on_change
        self._lock = make_lock("ReplicaPool._lock")
        self._down: frozenset = frozenset()
        self._closed = False
        reg = registry if registry is not None else obs.get_registry()
        # labeled per pool: two pools on one registry (a bench lane plus
        # an app fleet) must not fight over one callback slot. close()
        # deregisters THIS pool's label — otherwise the registry closure
        # would pin a closed pool alive and scrape stale membership forever
        self._g_healthy = reg.gauge(
            "pva_fleet_healthy_replicas",
            "replicas currently in the routable set, by pool",
            labelnames=("pool",))
        self._g_healthy.set_function(
            lambda: float(len(self.routable())), pool=self.name)
        self._poller = make_thread(target=self._poll_loop,
                                   name="pva-fleet-health", daemon=True)
        self._poller.start()

    # --- membership -------------------------------------------------------

    def routable(self) -> List:
        with self._lock:
            down = self._down
        return [r for r in self.replicas if r.name not in down]

    def add_replica(self, replica) -> None:
        """Controller actuator (autoscaler scale-up): join the rotation.
        The new member is routable immediately — a fresh spawn already
        passed its bind-line handshake; the poller takes over from here."""
        with self._lock:
            if any(r.name == replica.name for r in self.replicas):
                raise ValueError(f"replica {replica.name!r} already pooled")
            # rebind a copy: the poller and routable() iterate snapshots,
            # so membership flips atomically under the lock
            self.replicas = self.replicas + [replica]
            self._down = frozenset(self._down - {replica.name})
        logger.info("fleet: replica %s joined the pool", replica.name)
        obs.get_recorder().record("fleet", "membership",
                                  replica=replica.name, joined=True)

    def remove_replica(self, replica, *, close: bool = True) -> None:
        """Controller actuator (autoscaler reap): leave the pool for good.
        Unlike `mark_down` this is not a health verdict the poller can
        revert — the replica is gone from membership entirely."""
        with self._lock:
            self.replicas = [r for r in self.replicas
                             if r.name != replica.name]
            self._down = frozenset(self._down - {replica.name})
        logger.info("fleet: replica %s removed from the pool", replica.name)
        obs.get_recorder().record("fleet", "membership",
                                  replica=replica.name, removed=True)
        if close:
            try:
                replica.close()
            except Exception:
                logger.exception("fleet: closing replica %s failed",
                                 replica.name)

    def mark_down(self, replica) -> None:
        """Router-observed death: leave the rotation NOW (the poller would
        take up to one interval to notice); the poller restores membership
        if the replica's health probe recovers."""
        self._set_down(replica.name, True)

    def _set_down(self, name: str, down: bool) -> None:
        changed = False
        with self._lock:
            new = (self._down | {name}) if down else (self._down - {name})
            if new != self._down:
                self._down = frozenset(new)
                changed = True
        if changed:
            logger.warning("fleet: replica %s %s", name,
                           "left the routable set" if down else "rejoined")
            obs.get_recorder().record(
                "fleet", "membership", replica=name,
                routable=not down)
            if self.on_change is not None:
                try:
                    self.on_change(name, not down)
                except Exception:  # observer must not break routing
                    pass

    def _poll_loop(self) -> None:
        while not self._closed:
            for replica in list(self.replicas):
                if self._closed:
                    return
                try:
                    state = replica.health()
                except Exception:  # a broken probe reads as dead
                    state = "dead"
                self._set_down(replica.name, state not in ROUTABLE_STATES)
            time.sleep(self.health_interval_s)

    def close(self) -> None:
        self._closed = True
        self._poller.join(timeout=5.0)
        # drop the registry's closure over this pool: a closed pool has
        # zero routable replicas and must not be kept alive by /metrics
        self._g_healthy.set_function(None, pool=self.name)
        self._g_healthy.set(0.0, pool=self.name)
        for replica in self.replicas:
            try:
                replica.close()
            except Exception:
                logger.exception("fleet: closing replica %s failed",
                                 replica.name)


def read_line_with_deadline(proc, timeout_s: float, *,
                            match: Optional[str] = None,
                            name: str = "pva-proc-read"):
    """First stdout line of a child process — the first containing `match`
    when given — within a deadline, via a daemon reader thread.

    `readline()` blocks forever, so a child that wedges BEFORE printing
    its bind/URL line would otherwise hang the caller past any timeout.
    One implementation for every spawn site (`spawn_serving_process`, the
    chaos replica_kill leg, the bench fleet lane's traced replica) so the
    wedge-safe protocol cannot drift between them. Returns `(line, eof)`:
    line None on deadline or EOF, eof True when the child's stdout closed
    without the wanted line (a died-or-redirected child, NOT a timeout —
    callers must report the two differently). The CALLER owns the error
    message and the kill."""
    box: dict = {}

    def read():
        for raw in proc.stdout:
            if match is None or match in raw:
                box["line"] = raw
                return
        box["eof"] = True

    reader = make_thread(target=read, name=name, daemon=True)
    reader.start()
    reader.join(timeout=timeout_s)
    return box.get("line"), bool(box.get("eof"))


def spawn_serving_process(artifact: str, *, port: int = 0,
                          n_devices: Optional[int] = None,
                          extra_args: Sequence[str] = (),
                          startup_timeout_s: float = 120.0):
    """Spawn one `pva-tpu-serve` process for `artifact` and return
    `(subprocess.Popen, HttpReplica)` once it reports its bound address.

    `n_devices` forces a CPU slice via `utils/forcehost.py` — the CI path
    for exercising process replicas on one host. The CALLER owns the
    process (terminate/kill + reap); the pool only routes around it."""
    import os

    from pytorchvideo_accelerate_tpu.utils.forcehost import forced_host_env

    env = (forced_host_env(n_devices) if n_devices
           else {**os.environ, "JAX_PLATFORMS":
                 os.environ.get("JAX_PLATFORMS", "cpu")})
    cmd = [sys.executable, "-m", "pytorchvideo_accelerate_tpu.serving.server",
           "--serve.checkpoint", artifact, "--serve.port", str(port),
           *extra_args]
    proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.DEVNULL, text=True)
    line, eof = read_line_with_deadline(proc, startup_timeout_s,
                                        match="pva-tpu-serve: http://",
                                        name="pva-fleet-spawn-read")
    if line is None:
        code = proc.poll()
        proc.kill()
        raise RuntimeError(
            f"serving process exited {code} before binding"
            if eof or code is not None
            else f"serving process did not bind within {startup_timeout_s}s")
    url = line.split()[1]
    return proc, HttpReplica(f"proc-{proc.pid}", url, pid=proc.pid)
