"""Open-loop load harness + the `pva-tpu-loadgen` CLI.

The honesty this module exists for: a CLOSED-loop client (submit, wait,
submit) measures the server at whatever rate the server allows — when the
server slows down, the client politely slows down with it, and the p99 you
report is fiction ("coordinated omission"). Production traffic does not
wait: arrivals keep coming at the offered rate while the queue grows. So
this harness is OPEN-loop by construction:

- arrivals are a seeded **Poisson process** at ``rate_rps`` (exponential
  inter-arrival gaps), scheduled against the wall clock — a submission is
  never delayed because an earlier request is still in flight;
- if the generator itself falls behind the schedule (a submit blocked, the
  host stalled), it says so: ``max_arrival_lag_ms`` reports how late the
  worst arrival fired and ``open_loop_ok`` is False past a tolerance —
  numbers from a degraded-to-closed-loop run must not read as open-loop;
- request sizes follow a **heavy-tailed clip mix**: mostly single-view
  clips with a tail of multi-view requests (the serving tier's free
  geometry axis), weights configurable — fleets die on their tail
  geometry, not their median.

Completion futures resolve off the arrival thread; the report classifies
every request exactly once: completed (latency percentiles computed over
these), **shed** (the 503 family — `QueueFullError`/`ShedError`, admission
or deadline sheds doing their job), or **failed** (everything else — the
number a healthy fleet keeps at zero). SLO verdicts (`slo_p99_ms`) are
asserted over completions.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from pytorchvideo_accelerate_tpu.config import FleetConfig
from pytorchvideo_accelerate_tpu.obs import trace
from pytorchvideo_accelerate_tpu.serving.batcher import QueueFullError
from pytorchvideo_accelerate_tpu.serving.stats import _percentile
from pytorchvideo_accelerate_tpu.utils.logging import get_logger
from pytorchvideo_accelerate_tpu.utils.sync import make_lock, shared_state

logger = get_logger("pva_tpu")

# the heavy-tail view mix: (views, weight) — most requests are one clip,
# a tail re-runs the multi-view eval protocol per request
DEFAULT_VIEW_MIX = ((1, 0.85), (2, 0.12), (4, 0.03))

# arrivals later than this against their schedule mean the generator
# degraded toward closed-loop; the report flags it instead of hiding it
OPEN_LOOP_LAG_TOLERANCE_MS = 250.0


# --- piecewise traffic profiles (step / ramp / spike) -----------------------
#
# A profile is a sequence of segments, each (duration_s, rate) for a
# constant-rate step or (duration_s, rate_start, rate_end) for a linear
# ramp. Arrivals inside every segment are still a seeded Poisson process
# (ramps via thinning against the segment's max rate), so the open-loop
# honesty fields — max_arrival_lag_ms / open_loop_ok, measured against the
# composite schedule — mean exactly what they mean for a constant rate.
# This is what lets the autoscaler convergence proof drive a real traffic
# STEP instead of a constant offered rate.

def step_profile(*segments) -> List[tuple]:
    """Validate/normalize a piecewise profile: each segment is
    (duration_s, rate) or (duration_s, rate_start, rate_end)."""
    out = []
    for seg in segments:
        seg = tuple(float(x) for x in seg)
        if len(seg) == 2:
            seg = (seg[0], seg[1], seg[1])
        if len(seg) != 3:
            raise ValueError(f"profile segment {seg!r}: want "
                             "(duration_s, rate) or (duration_s, r0, r1)")
        if seg[0] <= 0 or seg[1] < 0 or seg[2] < 0:
            raise ValueError(f"profile segment {seg!r}: duration must be "
                             "positive and rates non-negative")
        out.append(seg)
    if not out:
        raise ValueError("profile needs at least one segment")
    return out


def ramp_profile(duration_s: float, start_rps: float,
                 end_rps: float) -> List[tuple]:
    """One linear ramp from start_rps to end_rps over duration_s."""
    return step_profile((duration_s, start_rps, end_rps))


def spike_profile(base_rps: float, spike_rps: float, *, duration_s: float,
                  spike_at_s: float, spike_s: float) -> List[tuple]:
    """Constant base rate with one rectangular spike riding on top."""
    if not 0.0 < spike_at_s < spike_at_s + spike_s < duration_s:
        raise ValueError("spike must fit strictly inside the run window")
    return step_profile(
        (spike_at_s, base_rps), (spike_s, spike_rps),
        (duration_s - spike_at_s - spike_s, base_rps))


def _segment_arrivals(rng: np.random.Generator, duration_s: float,
                      r0: float, r1: float) -> np.ndarray:
    """Seeded Poisson arrivals on [0, duration_s) at a rate moving
    linearly r0 -> r1 (constant when equal); ramps by thinning a
    homogeneous process at the segment's max rate."""
    rmax = max(r0, r1)
    if rmax <= 0 or duration_s <= 0:
        return np.empty(0, np.float64)
    draw = max(int(rmax * duration_s * 2), 16)
    t = np.cumsum(rng.exponential(1.0 / rmax, size=draw))
    while t.size and t[-1] < duration_s:  # extend until past the window
        t = np.concatenate(
            [t, t[-1] + np.cumsum(rng.exponential(1.0 / rmax, size=draw))])
    t = t[t < duration_s]
    if r0 != r1 and t.size:
        keep = rng.random(t.size) < (r0 + (r1 - r0) * t / duration_s) / rmax
        t = t[keep]
    return t


def piecewise_arrivals(rng: np.random.Generator,
                       profile: Sequence[tuple]) -> np.ndarray:
    """Concatenated arrival times for a normalized profile, sorted,
    offset per segment start."""
    chunks, t_off = [], 0.0
    for dur, r0, r1 in profile:
        chunks.append(_segment_arrivals(rng, dur, r0, r1) + t_off)
        t_off += dur
    return np.concatenate(chunks) if chunks else np.empty(0, np.float64)


def profile_duration_s(profile: Sequence[tuple]) -> float:
    return float(sum(seg[0] for seg in profile))


def profile_mean_rps(profile: Sequence[tuple]) -> float:
    total = profile_duration_s(profile)
    if total <= 0:
        return 0.0
    return float(sum(dur * (r0 + r1) / 2.0
                     for dur, r0, r1 in profile) / total)


def _classify_outcome(err) -> str:
    """One rule for both generators: completed / shed (the 503 family) /
    failed — every request classified exactly once."""
    if err is None:
        return "ok"
    if isinstance(err, QueueFullError):
        return "shed"
    return "failed"


def _await_stragglers(gen, offered: int) -> list:
    """Shared grace-wait: the open loop ends at the schedule; stragglers
    get a bounded grace, then whatever never resolved counts as lost
    (-> failed) in the caller's accounting. `gen` carries `_lock`,
    `_done`, and `grace_s` (both generator classes)."""
    deadline = time.monotonic() + gen.grace_s
    while time.monotonic() < deadline:
        with gen._lock:
            if len(gen._done) >= offered:
                break
        time.sleep(0.01)
    with gen._lock:
        return list(gen._done)


def heavy_tail_clip_factory(base_clip: Dict[str, np.ndarray],
                            view_mix: Sequence = DEFAULT_VIEW_MIX
                            ) -> Callable:
    """Clip factory over one base geometry: returns `factory(rng) -> clip`
    drawing a view count from the heavy-tailed mix (views=1 keeps the bare
    rank-4 clip; V>1 stacks the clip into a (V, T, H, W, C) request)."""
    views = np.asarray([v for v, _ in view_mix], np.int64)
    weights = np.asarray([w for _, w in view_mix], np.float64)
    weights = weights / weights.sum()

    def factory(rng: np.random.Generator) -> Dict[str, np.ndarray]:
        v = int(rng.choice(views, p=weights))
        if v <= 1:
            return dict(base_clip)
        return {k: np.stack([arr] * v) for k, arr in base_clip.items()}

    return factory


@shared_state("_done")
class LoadGen:
    """One open-loop run against any `submit(clip, **kw) -> Future` front
    (a `Scheduler`, a `Router`, an `HttpReplica`)."""

    def __init__(self, submit, *, rate_rps: float = 0.0,
                 duration_s: float = 0.0,
                 clip_factory: Callable, seed: int = 0,
                 priority: Optional[str] = None,
                 deadline_ms: Optional[float] = None,
                 profile: Optional[Sequence[tuple]] = None,
                 grace_s: float = 15.0):
        # a piecewise profile REPLACES the constant (rate_rps, duration_s)
        # pair; rate/duration then derive from the profile for reporting
        self.profile = step_profile(*profile) if profile is not None else None
        if self.profile is not None:
            rate_rps = profile_mean_rps(self.profile)
            duration_s = profile_duration_s(self.profile)
        if rate_rps <= 0 or duration_s <= 0:
            raise ValueError("rate_rps and duration_s must be positive")
        self.submit = submit
        self.rate_rps = float(rate_rps)
        self.duration_s = float(duration_s)
        self.clip_factory = clip_factory
        self.seed = int(seed)
        self.priority = priority
        self.deadline_ms = deadline_ms
        self.grace_s = float(grace_s)
        self._lock = make_lock("LoadGen._lock")
        # (outcome, latency_s) per finished request; outcomes:
        # "ok" | "shed" | "failed"
        self._done: List = []

    def _record(self, outcome: str, latency_s: float) -> None:
        with self._lock:
            self._done.append((outcome, latency_s))

    def _on_done(self, t_submit: float, future, handle=None) -> None:
        latency = time.monotonic() - t_submit
        try:
            err = future.exception()
        except Exception as e:  # cancelled
            err = e
        outcome = _classify_outcome(err)
        self._record(outcome, latency)
        if handle is not None:
            # close the request's root trace span with its verdict — the
            # client-side end of the merged timeline
            handle.finish(outcome=outcome)

    def run(self) -> Dict[str, float]:
        """Blocking: generate the arrival schedule, fire it, wait out the
        stragglers (bounded by `grace_s`), return the report dict."""
        rng = np.random.default_rng(self.seed)
        if self.profile is not None:
            arrivals = piecewise_arrivals(rng, self.profile)
        else:
            gaps = rng.exponential(1.0 / self.rate_rps,
                                   size=max(int(self.rate_rps
                                                * self.duration_s * 2), 16))
            arrivals = np.cumsum(gaps)
            arrivals = arrivals[arrivals < self.duration_s]
        kwargs: dict = {}
        if self.priority is not None:
            kwargs["priority"] = self.priority
        if self.deadline_ms is not None:
            kwargs["deadline_ms"] = self.deadline_ms
        offered = 0
        max_lag = 0.0
        # distributed tracing: each arrival is a trace HEAD — the sampled
        # ones get a root span finished with the request's outcome when
        # its future resolves (disarmed: one global read before the loop)
        tracer = trace.get_tracer()
        t0 = time.monotonic()
        for t_arr in arrivals:
            now = time.monotonic() - t0
            if now < t_arr:
                time.sleep(t_arr - now)
            lag = (time.monotonic() - t0) - t_arr
            max_lag = max(max_lag, lag)
            clip = self.clip_factory(rng)
            offered += 1
            handle = (tracer.start("request", seq=offered)
                      if tracer is not None else None)
            t_submit = time.monotonic()
            try:
                with trace.attach(handle.ctx if handle is not None
                                  else None):
                    fut = self.submit(clip, **kwargs)
            except QueueFullError:
                self._record("shed", 0.0)
                if handle is not None:
                    handle.finish(outcome="shed")
                continue
            except Exception:  # noqa: BLE001 - a dead front is a failure
                self._record("failed", 0.0)
                if handle is not None:
                    handle.finish(outcome="failed")
                continue
            fut.add_done_callback(
                lambda f, t=t_submit, h=handle: self._on_done(t, f, h))
        wall = time.monotonic() - t0
        done = _await_stragglers(self, offered)
        lat_ok = sorted(lat for oc, lat in done if oc == "ok")
        completed = len(lat_ok)
        shed = sum(1 for oc, _ in done if oc == "shed")
        failed = sum(1 for oc, _ in done if oc == "failed")
        lost = offered - len(done)  # never resolved within grace
        report = {
            "offered": float(offered),
            "offered_rps": round(offered / wall, 3) if wall > 0 else 0.0,
            "completed": float(completed),
            "achieved_rps": round(completed / wall, 3) if wall > 0 else 0.0,
            "shed": float(shed),
            "failed": float(failed + lost),
            "shed_frac": round(shed / offered, 4) if offered else 0.0,
            "p50_ms": round(_percentile(lat_ok, 50) * 1e3, 3),
            "p95_ms": round(_percentile(lat_ok, 95) * 1e3, 3),
            "p99_ms": round(_percentile(lat_ok, 99) * 1e3, 3),
            "max_arrival_lag_ms": round(max_lag * 1e3, 3),
            "open_loop_ok": bool(max_lag * 1e3
                                 <= OPEN_LOOP_LAG_TOLERANCE_MS),
            "duration_s": round(wall, 3),
        }
        return report


def assert_slo(report: Dict[str, float], *, slo_p99_ms: float,
               max_shed_frac: float = 1.0) -> List[str]:
    """SLO verdicts as a list of violations (empty = pass): p99 under the
    SLO, zero non-shed failures, the run genuinely open-loop, and (when
    bounded) the shed fraction under its budget."""
    violations = []
    if report["completed"] <= 0:
        violations.append("no requests completed")
    if report["p99_ms"] > slo_p99_ms:
        violations.append(
            f"p99 {report['p99_ms']} ms > SLO {slo_p99_ms} ms")
    if report["failed"] > 0:
        violations.append(f"{int(report['failed'])} non-shed failures")
    if not report["open_loop_ok"]:
        violations.append(
            f"arrival schedule slipped {report['max_arrival_lag_ms']} ms "
            "(degraded toward closed-loop; numbers untrustworthy)")
    if report["shed_frac"] > max_shed_frac:
        violations.append(
            f"shed_frac {report['shed_frac']} > budget {max_shed_frac}")
    return violations


@shared_state("_done")
class StreamLoadGen:
    """Open-loop STREAM arrivals (docs/SERVING.md § streaming): streams —
    not independent requests — arrive as a seeded Poisson process at
    ``stream_rate_sps``; each stream lives for a HEAVY-TAILED number of
    advances (bounded Pareto — fleets die on the long-running tail
    streams that pin session slots, not on the median), and emits one
    label per ``advance_interval_s``.

    Honesty rules, inherited from `LoadGen` and tightened for labels:

    - the whole (stream x advance) event schedule is precomputed and
      fired against the wall clock — an advance is never delayed because
      an earlier one is in flight, and ``max_arrival_lag_ms`` /
      ``open_loop_ok`` flag a generator that degraded toward closed-loop;
    - **per-session label-latency honesty**: a label's latency is
      measured from its SCHEDULED advance time, not from whenever the
      generator got around to submitting it — a backed-up session cannot
      hide queueing delay the way a submit-anchored clock would
      (coordinated omission, applied per session);
    - every advance is classified exactly once: completed / shed
      (`QueueFullError` family) / failed.

    Each stream's first advance carries the establish window + stride;
    subsequent advances ship only the ``stride`` new frames, with the
    client-maintained resendable window attached when ``attach_window``
    (the re-establish-anywhere contract replica death recovery needs);
    the last advance carries ``end=True``."""

    def __init__(self, submit, *, stream_rate_sps: float = 0.0,
                 duration_s: float = 0.0,
                 window: int, stride: int, frame_shape: tuple,
                 advance_interval_s: float, seed: int = 0,
                 mean_advances: float = 8.0, max_advances: int = 64,
                 attach_window: bool = True, dtype: str = "float32",
                 priority: Optional[str] = None,
                 profile: Optional[Sequence[tuple]] = None,
                 grace_s: float = 15.0):
        # piecewise profile over STREAM arrivals (advances still pace at
        # advance_interval_s per stream) — same semantics as LoadGen
        self.profile = step_profile(*profile) if profile is not None else None
        if self.profile is not None:
            stream_rate_sps = profile_mean_rps(self.profile)
            duration_s = profile_duration_s(self.profile)
        if stream_rate_sps <= 0 or duration_s <= 0:
            raise ValueError("stream_rate_sps and duration_s must be "
                             "positive")
        if window % stride:
            raise ValueError("stride must divide the window")
        self.submit = submit
        self.stream_rate_sps = float(stream_rate_sps)
        self.duration_s = float(duration_s)
        self.window = int(window)
        self.stride = int(stride)
        self.frame_shape = tuple(frame_shape)  # (H, W, C)
        self.advance_interval_s = float(advance_interval_s)
        self.seed = int(seed)
        self.mean_advances = float(mean_advances)
        self.max_advances = int(max_advances)
        self.attach_window = bool(attach_window)
        self.dtype = dtype
        self.priority = priority
        self.grace_s = float(grace_s)
        self._lock = make_lock("StreamLoadGen._lock")
        self._done: List = []  # (outcome, label_latency_s)

    def _schedule(self, rng) -> List[tuple]:
        """-> [(t, stream_idx, k, n_stream)] sorted by time: Poisson
        stream arrivals x heavy-tail per-stream advance counts."""
        if self.profile is not None:
            arrivals = piecewise_arrivals(rng, self.profile)
        else:
            gaps = rng.exponential(
                1.0 / self.stream_rate_sps,
                size=max(int(self.stream_rate_sps * self.duration_s * 2), 8))
            arrivals = np.cumsum(gaps)
            arrivals = arrivals[arrivals < self.duration_s]
        events = []
        for i, t_arr in enumerate(arrivals):
            # bounded Pareto (alpha 1.5): mostly short streams, a heavy
            # tail of long ones; +1 so every stream emits >= 1 label
            n = int(min(1 + rng.pareto(1.5) * self.mean_advances / 3.0,
                        self.max_advances))
            for k in range(n):
                events.append((t_arr + k * self.advance_interval_s, i, k, n))
        events.sort()
        return events

    def _record(self, outcome: str, latency_s: float) -> None:
        with self._lock:
            self._done.append((outcome, latency_s))

    def _on_done(self, t_sched: float, t0: float, future) -> None:
        # label latency anchored to the SCHEDULED time (see class docs)
        latency = time.monotonic() - (t0 + t_sched)
        try:
            err = future.exception()
        except Exception as e:  # cancelled
            err = e
        self._record(_classify_outcome(err), latency)

    def run(self) -> Dict[str, float]:
        rng = np.random.default_rng(self.seed)
        events = self._schedule(rng)
        windows: Dict[int, np.ndarray] = {}
        shape = (self.window,) + self.frame_shape
        offered = 0
        max_lag = 0.0
        t0 = time.monotonic()
        for t_evt, i, k, n in events:
            now = time.monotonic() - t0
            if now < t_evt:
                time.sleep(t_evt - now)
            max_lag = max(max_lag, (time.monotonic() - t0) - t_evt)
            if k == 0:
                windows[i] = rng.standard_normal(shape).astype(self.dtype)
            frames = rng.standard_normal(
                (self.stride,) + self.frame_shape).astype(self.dtype)
            windows[i] = np.concatenate([windows[i][self.stride:], frames],
                                        axis=0)
            session: dict = {"sid": f"lg-{self.seed}-{i}",
                             "stride": self.stride,
                             "end": k == n - 1}
            if k == 0 or self.attach_window:
                session["window"] = windows[i]
            kwargs: dict = {"session": session}
            if self.priority is not None:
                kwargs["priority"] = self.priority
            offered += 1
            try:
                fut = self.submit({"video": frames}, **kwargs)
            except QueueFullError:
                self._record("shed", 0.0)
                continue
            except Exception:  # noqa: BLE001 - a dead front is a failure
                self._record("failed", 0.0)
                continue
            fut.add_done_callback(
                lambda f, t=t_evt: self._on_done(t, t0, f))
            if k == n - 1:
                windows.pop(i, None)
        wall = time.monotonic() - t0
        done = _await_stragglers(self, offered)
        lat_ok = sorted(lat for oc, lat in done if oc == "ok")
        completed = len(lat_ok)
        shed = sum(1 for oc, _ in done if oc == "shed")
        failed = sum(1 for oc, _ in done if oc == "failed")
        lost = offered - len(done)
        n_streams = len({i for _, i, _, _ in events})
        return {
            "streams": float(n_streams),
            "advances_offered": float(offered),
            "completed": float(completed),
            "achieved_lps": round(completed / wall, 3) if wall > 0 else 0.0,
            "shed": float(shed),
            "failed": float(failed + lost),
            "shed_frac": round(shed / offered, 4) if offered else 0.0,
            "label_p50_ms": round(_percentile(lat_ok, 50) * 1e3, 3),
            "label_p99_ms": round(_percentile(lat_ok, 99) * 1e3, 3),
            "max_arrival_lag_ms": round(max_lag * 1e3, 3),
            "open_loop_ok": bool(max_lag * 1e3
                                 <= OPEN_LOOP_LAG_TOLERANCE_MS),
            "duration_s": round(wall, 3),
        }


def _http_clip_factory(url: str) -> Callable:
    """Build the clip factory from a live server's /healthz clip spec."""
    import urllib.request

    with urllib.request.urlopen(url.rstrip("/") + "/healthz",
                                timeout=10) as r:
        health = json.loads(r.read())
    spec = health.get("clip_spec")
    if not spec:
        raise SystemExit(
            "server reports no clip_spec on /healthz; pass explicit "
            "--frames/--crop geometry")
    dtype = health.get("input_dtype", "float32")
    base = {k: np.zeros(tuple(shape), dtype) for k, shape in spec.items()}
    return heavy_tail_clip_factory(base)


def main(argv: Optional[Sequence[str]] = None) -> int:
    """`pva-tpu-loadgen --url http://host:port --rps 100 --duration 10`.

    Drives a serving endpoint (single replica or a fleet router front)
    with the open-loop harness and prints ONE JSON report line; exit 0
    iff the SLO held (p99 under --slo_p99_ms, zero non-shed failures,
    schedule kept)."""
    ap = argparse.ArgumentParser(
        prog="pva-tpu-loadgen",
        description="open-loop (Poisson) load harness for the serving "
                    "tier; see docs/SERVING.md § load harness")
    ap.add_argument("--url", default="",
                    help="endpoint base URL (e.g. http://127.0.0.1:8100)")
    # harness defaults are the fleet.* config block's (single source of
    # truth; the block documents itself as "load harness defaults")
    fleet_defaults = FleetConfig()
    ap.add_argument("--rps", type=float, default=fleet_defaults.loadgen_rps,
                    help="offered arrival rate (Poisson)")
    ap.add_argument("--duration", type=float,
                    default=fleet_defaults.loadgen_duration_s,
                    help="arrival window seconds")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--priority", choices=("realtime", "batch"),
                    default=None,
                    help="priority class (schedulers that support it)")
    ap.add_argument("--deadline_ms", type=float, default=None)
    ap.add_argument("--slo_p99_ms", type=float, default=1000.0)
    ap.add_argument("--max_shed_frac", type=float, default=1.0)
    ap.add_argument("--selftest", action="store_true",
                    help="drive an in-process stub fleet instead of --url "
                         "(harness plumbing check, no jax model)")
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code not in (0, None) else 0

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if args.selftest:
        report = _selftest(args)
    else:
        if not args.url:
            print("pva-tpu-loadgen: --url required (or --selftest)",
                  file=sys.stderr)
            return 2
        from pytorchvideo_accelerate_tpu.fleet.pool import HttpReplica

        # worker pool sized for the OFFERED concurrency (Little's law at
        # the SLO latency, with headroom): the default 8 workers would
        # silently cap in-flight requests and degrade the harness to
        # closed-loop-of-8 without ever tripping the arrival-lag check
        workers = max(16, min(int(args.rps * max(args.slo_p99_ms, 1000.0)
                                  / 1e3 * 2), 256))
        replica = HttpReplica("target", args.url, workers=workers)
        gen = LoadGen(replica.submit, rate_rps=args.rps,
                      duration_s=args.duration,
                      clip_factory=_http_clip_factory(args.url),
                      seed=args.seed, priority=args.priority,
                      deadline_ms=args.deadline_ms)
        report = gen.run()
        replica.close()
    violations = assert_slo(report, slo_p99_ms=args.slo_p99_ms,
                            max_shed_frac=args.max_shed_frac)
    report["slo_ok"] = not violations
    for v in violations:
        print(f"SLO VIOLATION: {v}", file=sys.stderr)
    print(json.dumps(report))
    return 0 if not violations else 1


def _selftest(args) -> Dict[str, float]:
    """In-process harness check: 2 stub replicas behind a router."""
    from pytorchvideo_accelerate_tpu.fleet.pool import (
        LocalReplica,
        ReplicaPool,
    )
    from pytorchvideo_accelerate_tpu.fleet.router import Router
    from pytorchvideo_accelerate_tpu.fleet.scheduler import Scheduler
    from pytorchvideo_accelerate_tpu.serving.stats import ServingStats
    from pytorchvideo_accelerate_tpu.serving.stub import StubEngine

    replicas = []
    for i in range(2):
        stats = ServingStats(window=512)
        sched = Scheduler(StubEngine(forward_s=0.002), stats=stats,
                          max_queue=256, name=f"selftest-{i}")
        replicas.append(LocalReplica(f"selftest-{i}", sched))
    pool = ReplicaPool(replicas, health_interval_s=0.2)
    router = Router(pool)
    base = {"video": np.zeros((2, 4, 4, 3), np.float32)}
    try:
        gen = LoadGen(router.submit, rate_rps=args.rps,
                      duration_s=min(args.duration, 5.0),
                      clip_factory=heavy_tail_clip_factory(base),
                      seed=args.seed, priority=args.priority,
                      deadline_ms=args.deadline_ms)
        return gen.run()
    finally:
        router.close()


if __name__ == "__main__":
    sys.exit(main())
