"""Continuous-batching scheduler: deadlines, priority classes, EDF launches.

Replaces the `MicroBatcher`'s fixed launch-on-max-or-timeout policy on the
serving hot path (docs/SERVING.md § fleet). The batcher's policy is the
right shape for one steady traffic class; a production tier serves a MIX —
interactive requests that want the next launch and bulk scoring that wants
full buckets — and the two must not price each other's latency. The
scheduler's policy, per launch:

- every pending request carries an absolute **deadline** (from its
  priority class's default or an explicit ``deadline_ms``) and a
  **priority class**: ``realtime`` (launch-now, work-conserving) or
  ``batch`` (coalesce toward full buckets until ``batch_max_wait_ms`` or
  deadline pressure);
- the next launch is chosen **earliest-deadline-first** (realtime class
  strictly before batch class), then filled with same-geometry pending
  requests in EDF order up to the largest compiled bucket — continuous
  batching: the engine never idles while compatible work is queued, and
  arrivals keep joining the next launch while the current one runs;
- **shed-before-deadline-miss**: a request whose deadline has passed — or
  whose remaining slack is provably smaller than the measured service time
  (per-bucket EWMA) — fails *immediately* with `ShedError`, a
  `QueueFullError` subclass, so the HTTP front answers PR 6's
  ``503 + Retry-After`` instead of burning a launch on an answer the
  client will 504 before reading.

Same interface as `MicroBatcher` (`submit`/`queue_depth`/`drain`/`close`),
so `InferenceServer` and the fleet `Router` front either one; padding and
the masked-row convention are identical (padded rows never resolve into a
response). A single flush thread serializes launches per engine — the
accelerator executes one batch at a time anyway — and `swap_engine` slots
a pre-warmed replacement engine in BETWEEN launches (the hot-swap cutover,
fleet/hotswap.py): an in-flight launch always runs start-to-finish on one
engine, so no future can ever see mixed weights.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from pytorchvideo_accelerate_tpu import obs
from pytorchvideo_accelerate_tpu.obs import trace
from pytorchvideo_accelerate_tpu.reliability.faults import fault_point
from pytorchvideo_accelerate_tpu.serving.batcher import QueueFullError
from pytorchvideo_accelerate_tpu.serving.engine import CLIP_KEYS, clip_key
from pytorchvideo_accelerate_tpu.utils.logging import get_logger
from pytorchvideo_accelerate_tpu.utils.sync import (
    make_condition,
    make_lock,
    make_thread,
    shared_state,
)

logger = get_logger("pva_tpu")

REALTIME, BATCH = "realtime", "batch"
PRIORITIES = (REALTIME, BATCH)

# EWMA smoothing for the per-bucket service-time estimate the shed decision
# reads: heavy enough to ride out one slow launch, light enough to track a
# hot-swap's warm-up transient within a few launches
_SVC_ALPHA = 0.3


class ShedError(QueueFullError):
    """Request shed by the scheduler before a guaranteed deadline miss.

    Subclasses `QueueFullError` so every existing 503-mapping site
    (serving/server.py, the chaos serve leg, client retry loops) treats a
    deadline shed exactly like an admission shed: ``503 + Retry-After``,
    never a 504 after the budget burned."""


@dataclass
class _SchedRequest:
    clip: Dict[str, np.ndarray]
    future: Future
    t_enqueue: float
    deadline: float  # absolute time.monotonic()
    priority: str
    key: tuple  # clip geometry: only same-shaped requests share a launch
    seq: int = 0
    # submitter's trace context, carried with the payload across the
    # pending-queue hop (None = disarmed tracing or untraced caller)
    ctx: Optional[object] = None
    # streaming session envelope (docs/SERVING.md § streaming): {"sid",
    # optional "window"/"stride"/"end"}. Session requests launch through
    # the engine's `advance_batch` instead of `predict`, and their key
    # carries a "stream" marker so stateful and stateless traffic of a
    # coincidentally equal geometry never share a launch.
    session: Optional[dict] = None

    def rank(self) -> Tuple[int, float, int]:
        """EDF order, realtime class strictly first; seq breaks ties FIFO."""
        return (0 if self.priority == REALTIME else 1, self.deadline, self.seq)


@shared_state("_pending", "_svc", "engine")
class Scheduler:
    """Continuous-batching EDF scheduler over one `InferenceEngine`.

    Thread-safety: `_pending` and `_svc` live under `_lock` (the condition's
    mutex); `engine` lives under `_launch_lock`, which is held for exactly
    one launch at a time — `swap_engine` blocks on it, which IS the
    drain-then-swap sequencing (and its hold time is the measured swap
    blackout). The two locks are never nested, so no ordering can invert.
    """

    # the HTTP front forwards per-request priority/deadline only to fronts
    # that declare support (a plain MicroBatcher ignores both by design)
    supports_priority = True

    @property
    def supports_sessions(self) -> bool:
        """Streaming-session capability: true iff the CURRENT engine can
        run incremental advances (`StreamingEngine.advance_batch`)."""
        return bool(getattr(self.engine, "supports_sessions", False))

    def __init__(self, engine, *, max_queue: int = 256, stats=None,
                 heartbeat=None, realtime_deadline_ms: float = 500.0,
                 batch_deadline_ms: float = 5000.0,
                 batch_max_wait_ms: float = 20.0,
                 shed_safety: float = 1.2, retry_after_s: float = 1.0,
                 name: str = "scheduler"):
        self.engine = engine
        self.name = name
        self.stats = stats
        self.max_queue = max(int(max_queue), 1)
        self.retry_after_s = float(retry_after_s)
        self.batch_max_wait_s = max(batch_max_wait_ms, 0.0) / 1e3
        self.shed_safety = max(float(shed_safety), 1.0)
        self._default_deadline_s = {
            REALTIME: max(realtime_deadline_ms, 1.0) / 1e3,
            BATCH: max(batch_deadline_ms, 1.0) / 1e3,
        }
        # bucket geometry is cached as immutables: the launch loop must
        # never reach through `engine` while holding `_lock` (that would
        # nest the two locks), and `swap_engine` asserts the replacement
        # keeps the identical buckets — so these can never go stale
        self._buckets: Tuple[int, ...] = tuple(engine.buckets)
        self._cap = self._buckets[-1]
        self._heartbeat = heartbeat
        self._lock = make_lock("Scheduler._lock")
        self._cond = make_condition("Scheduler._cond", lock=self._lock)
        self._launch_lock = make_lock("Scheduler._launch_lock")
        self._pending: List[_SchedRequest] = []
        self._svc: Dict[int, float] = {}  # bucket -> EWMA service seconds
        self._seq = 0
        self._closed = threading.Event()
        self._thread = make_thread(
            target=self._loop, name=f"pva-fleet-{name}", daemon=True)
        self._thread.start()

    # --- client side ------------------------------------------------------

    def submit(self, clip: Dict[str, np.ndarray], *,
               priority: str = REALTIME,
               deadline_ms: Optional[float] = None,
               session: Optional[dict] = None) -> Future:
        """Enqueue ONE clip — leaves (T, H, W, C) or (V, T, H, W, C) — and
        get a Future resolving to its fp32 logits (num_classes,). A missed
        queue bound or an unmeetable deadline resolves the future (or
        raises here) with a `QueueFullError`/`ShedError` → 503.

        `session` (docs/SERVING.md § streaming) marks a streaming-session
        advance: ``{"sid": str, "window": optional resendable (T,H,W,C),
        "stride": optional int, "end": bool}`` with the *s* new frames as
        the "video" clip. Session requests ride the same queue/deadline/
        shed machinery but launch through the engine's incremental
        `advance_batch`; they require a session-capable engine
        (`streaming/engine.StreamingEngine`)."""
        if priority not in PRIORITIES:
            raise ValueError(
                f"priority must be one of {PRIORITIES}, got {priority!r}")
        clips = {k: np.asarray(v) for k, v in clip.items() if k in CLIP_KEYS}
        if session is not None:
            if not getattr(self.engine, "supports_sessions", False):
                raise ValueError(
                    "this replica serves no streaming sessions "
                    "(engine lacks advance_batch; serve.streaming off?)")
            if not session.get("sid"):
                raise ValueError("session request carries no 'sid'")
            win = session.get("window")
            if not clips and win is None:
                raise ValueError(
                    "session request needs new frames ('video') or a "
                    "resendable 'window'")
            geo = (clip_key(clips) if clips
                   else (("window", tuple(np.shape(win))),))
            key = ("stream",) + geo
        elif not clips:
            raise ValueError("request has neither 'video' nor 'slow'/'fast'")
        else:
            for k, v in clips.items():
                if v.ndim not in (4, 5):
                    raise ValueError(
                        f"clip {k!r} must be (T,H,W,C) or (V,T,H,W,C), "
                        f"got shape {v.shape}")
            key = clip_key(clips)
        if self._closed.is_set():
            raise RuntimeError("scheduler is closed")
        now = time.monotonic()
        ttl = (self._default_deadline_s[priority]
               if deadline_ms is None else max(float(deadline_ms), 1.0) / 1e3)
        req = _SchedRequest(clip=clips, future=Future(), t_enqueue=now,
                            deadline=now + ttl, priority=priority,
                            key=key, ctx=trace.capture(), session=session)
        with self._lock:
            if self._closed.is_set():
                raise RuntimeError("scheduler is closed")
            if len(self._pending) >= self.max_queue:
                if self.stats is not None:
                    self.stats.observe_rejected("503")
                raise QueueFullError(
                    f"scheduler queue full ({self.max_queue}); retry later",
                    retry_after_s=self.retry_after_s)
            self._seq += 1
            req.seq = self._seq
            self._pending.append(req)
            self._cond.notify()
        return req.future

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._pending)

    def drain(self, timeout_s: float = 10.0) -> bool:
        """Wait for the pending queue to flush (the drain-on-SIGTERM path:
        stop ADMITTING upstream first, then let in-flight futures resolve)."""
        deadline = time.monotonic() + max(timeout_s, 0.0)
        while time.monotonic() < deadline:
            if self.queue_depth() == 0:
                return True
            time.sleep(0.01)
        return self.queue_depth() == 0

    def close(self) -> None:
        """Stop the flush thread; pending requests are failed, not dropped
        silently."""
        if self._closed.is_set():
            return
        self._closed.set()
        with self._lock:
            self._cond.notify_all()
        self._thread.join(timeout=30.0)
        with self._lock:
            leftovers, self._pending = self._pending, []
        for req in leftovers:
            if not req.future.done():
                try:
                    req.future.set_exception(
                        RuntimeError("scheduler closed"))
                except Exception:  # lost the race to the flush thread
                    pass

    # --- hot-swap cutover -------------------------------------------------

    def current_engine(self):
        """The engine the NEXT launch will use (hot-swap tooling reads the
        blue engine's mesh/geometry through here, never via a bare attr)."""
        with self._launch_lock:
            return self.engine

    def swap_engine(self, new_engine) -> float:
        """Blue/green cutover: install `new_engine` between launches and
        return the blackout in SECONDS (time this replica could not launch:
        waiting out the in-flight launch + the pointer swap).

        The caller pre-warms `new_engine` (compiles every bucket) BEFORE
        calling — see fleet/hotswap.py; a cold engine would turn the first
        post-swap launches into compile stalls. Bucket geometry must match:
        the scheduler's cached bucket ladder (and every queued request's
        padding plan) is built against it."""
        if tuple(new_engine.buckets) != self._buckets:
            raise ValueError(
                f"hot-swap changes the bucket ladder {self._buckets} -> "
                f"{tuple(new_engine.buckets)}; restart the replica instead "
                "(in-flight padding plans assume stable buckets)")
        t0 = time.perf_counter()
        with self._launch_lock:
            # streaming-session state carry happens HERE, with the old
            # engine quiesced by the launch lock: any earlier (prewarm
            # time) and the old engine's still-flowing stream launches
            # would donate away the very ring buffers the green engine
            # adopted (deleted-array crashes after cutover), and
            # sessions established mid-prewarm would be silently lost.
            # prepare_carry_from pre-compiled everything, so this is
            # bounded EXECUTION — honestly measured in the blackout.
            old = self.engine
            if (hasattr(new_engine, "carry_state_from")
                    and hasattr(old, "table") and old is not new_engine):
                new_engine.carry_state_from(old)
            self.engine = new_engine
        blackout = time.perf_counter() - t0
        obs.get_recorder().record("fleet", "hot-swap", scheduler=self.name,
                                  blackout_ms=round(blackout * 1e3, 3))
        return blackout

    # --- flush thread -----------------------------------------------------

    def _bucket_for(self, n: int) -> int:
        for b in self._buckets:
            if b >= n:
                return b
        return self._buckets[-1]

    def _loop(self) -> None:
        while True:
            if self._heartbeat is not None:
                self._heartbeat()
            with self._lock:
                if self._closed.is_set():
                    break
                now = time.monotonic()
                shed = self._reap(now)
                group = self._collect(now)
                if group is None and not shed:
                    self._cond.wait(timeout=self._wait_s(now))
                    continue
            # futures resolve OUTSIDE _lock (sheds here, results in
            # _launch): a done-callback may legally re-enter submit()/
            # queue_depth(), which would deadlock on the non-reentrant
            # lock — and launches run unlocked so arrivals keep queueing
            # into the next launch (the continuous-batching property)
            for req, err in shed:
                try:
                    req.future.set_exception(err)
                except Exception:
                    pass
                if self.stats is not None:
                    self.stats.observe_shed("deadline")
            if group is not None:
                with obs.span("serve_flush"):
                    self._launch(group)

    def _estimate_s(self, bucket: int) -> float:
        """Measured service time for `bucket`, falling back to the nearest
        known bucket (0.0 until the first launch lands — no shedding on
        guesses)."""
        if bucket in self._svc:
            return self._svc[bucket]
        known = sorted(self._svc)
        for b in known:
            if b >= bucket:
                return self._svc[b]
        return self._svc[known[-1]] if known else 0.0

    def _reap(self, now: float) -> List[tuple]:
        """Caller holds `_lock`. Drop cancelled/done requests and pull out
        every request that can no longer meet its deadline; returns the
        [(request, ShedError)] list for the CALLER to resolve after
        releasing the lock — resolving here would run arbitrary
        done-callbacks under the scheduler's own mutex."""
        keep: List[_SchedRequest] = []
        shed: List[tuple] = []
        for req in self._pending:
            if req.future.done():
                continue  # cancelled by the HTTP front's timeout path
            est = self._estimate_s(self._bucket_for(1)) * self.shed_safety
            if req.deadline - now <= est:
                # shed-before-deadline-miss: a late answer is wasted work
                # AND wasted engine time that delays every request behind it
                shed.append((req, ShedError(
                    f"deadline unmeetable (slack "
                    f"{max(req.deadline - now, 0) * 1e3:.1f} ms < "
                    f"est service {est * 1e3:.1f} ms); retry later",
                    retry_after_s=self.retry_after_s)))
                continue
            keep.append(req)
        self._pending = keep  # pva: disable=lock-discipline -- _reap is called only from _loop's `with self._lock` block (caller-holds-lock contract in the docstring)
        return shed

    def _collect(self, now: float) -> Optional[List[_SchedRequest]]:
        """Caller holds `_lock`. Pick the next launch (EDF head + its
        same-geometry cohort) or None when coalescing should continue."""
        if not self._pending:
            return None
        head = min(self._pending, key=_SchedRequest.rank)
        group = sorted((r for r in self._pending if r.key == head.key),
                       key=_SchedRequest.rank)[:self._cap]
        est = self._estimate_s(self._bucket_for(len(group)))
        launch_now = (
            head.priority == REALTIME            # work-conserving class
            or len(group) >= self._cap           # a full largest bucket
            or now - head.t_enqueue >= self.batch_max_wait_s
            or head.deadline - now <= est * self.shed_safety * 2.0)
        if not launch_now:
            return None
        launched = set(id(r) for r in group)
        self._pending = [r for r in self._pending if id(r) not in launched]  # pva: disable=lock-discipline -- _collect is called only from _loop's `with self._lock` block (caller-holds-lock contract in the docstring)
        return group

    def _wait_s(self, now: float) -> float:
        """Caller holds `_lock`: sleep until the earliest trigger (batch
        coalescing deadline or request deadline), bounded for heartbeats."""
        w = 0.1
        for req in self._pending:
            w = min(w, req.t_enqueue + self.batch_max_wait_s - now,
                    max(req.deadline - now, 0.0))
        return max(w, 0.001)

    def _launch(self, reqs: List[_SchedRequest]) -> None:
        # chaos hook: same fault point as the MicroBatcher flush — the
        # scheduler replaces it on the hot path, and an injected raise must
        # fail THIS launch's futures (the 500 path), never the thread
        try:
            fault_point("serve.flush")
            reqs = [r for r in reqs
                    if r.future.set_running_or_notify_cancel()]
            if not reqs:
                return
            if reqs[0].session is not None:
                self._launch_stream(reqs)
                return
            n = len(reqs)
            bucket = self._bucket_for(n)
            stacked: Dict[str, np.ndarray] = {}
            for k in reqs[0].clip:
                rows = np.stack([r.clip[k] for r in reqs])
                if bucket > n:  # zero rows, masked out below
                    pad = np.zeros((bucket - n,) + rows.shape[1:],
                                   rows.dtype)
                    rows = np.concatenate([rows, pad], axis=0)
                stacked[k] = rows
            stacked["mask"] = np.asarray(
                [1.0] * n + [0.0] * (bucket - n), np.float32)
            # tracing: traced requests record their scheduler wait, and
            # the launch runs under the head context so engine-side spans
            # join its trace (disarmed: one global read + shared no-ops)
            rt = trace.get_tracer()
            head_ctx = None
            if rt is not None:
                now_w, now_m = time.time(), time.monotonic()
                for req in reqs:
                    if req.ctx is not None:
                        if head_ctx is None:
                            head_ctx = req.ctx
                        waited = now_m - req.t_enqueue
                        rt.event(req.ctx, "sched_wait", now_w - waited,
                                 waited, priority=req.priority)
            t0 = time.perf_counter()
            # one engine for the WHOLE launch: swap_engine blocks on this
            # lock, so a cutover can never interleave with a launch
            with trace.attach(head_ctx):
                with trace.span("device_dispatch", batch=n, bucket=bucket):
                    with self._launch_lock:
                        logits = self.engine.predict(stacked)
            svc = time.perf_counter() - t0
            done = time.monotonic()
            latencies = []
            for i, req in enumerate(reqs):
                latencies.append(done - req.t_enqueue)
                # padded rows sliced away here — a response only ever
                # carries logits[i] for the request that submitted row i
                try:
                    req.future.set_result(logits[i])
                except Exception:
                    pass  # cancelled between claim and resolve
            if self.stats is not None:
                self.stats.observe_batch(
                    n, bucket, latencies,
                    trace_ids=[getattr(r.ctx, "trace_id", None)
                               for r in reqs])
            with self._lock:
                prev = self._svc.get(bucket)
                self._svc[bucket] = (svc if prev is None else
                                     (1 - _SVC_ALPHA) * prev
                                     + _SVC_ALPHA * svc)
        except Exception as e:  # noqa: BLE001 - fail the requests, not the thread
            logger.exception("fleet launch failed")
            for req in reqs:
                if not req.future.done():
                    try:
                        req.future.set_exception(e)
                    except Exception:
                        pass

    def _launch_stream(self, reqs: List[_SchedRequest]) -> None:
        """One continuous-batching launch of streaming-session advances:
        the engine owns slot resolution, bucket padding, and the
        incremental compiled step (`StreamingEngine.advance_batch`); the
        scheduler's job here is the same claim/trace/stats discipline as
        the stateless launch. Per-item failures resolve per-item — one
        malformed session must not fail its co-batched neighbours."""
        n = len(reqs)
        items = []
        for req in reqs:
            s = req.session or {}
            items.append({
                "sid": s.get("sid"),
                "frames": req.clip.get("video"),
                "window": s.get("window"),
                "stride": s.get("stride"),
                "end": bool(s.get("end")),
            })
        rt = trace.get_tracer()
        head_ctx = None
        if rt is not None:
            now_w, now_m = time.time(), time.monotonic()
            for req in reqs:
                if req.ctx is not None:
                    if head_ctx is None:
                        head_ctx = req.ctx
                    waited = now_m - req.t_enqueue
                    rt.event(req.ctx, "sched_wait", now_w - waited,
                             waited, priority=req.priority)
        bucket = self._bucket_for(n)
        t0 = time.perf_counter()
        with trace.attach(head_ctx):
            with trace.span("stream_dispatch", batch=n, bucket=bucket):
                with self._launch_lock:
                    outs = self.engine.advance_batch(items)
        svc = time.perf_counter() - t0
        done = time.monotonic()
        latencies = []
        for req, out in zip(reqs, outs):
            latencies.append(done - req.t_enqueue)
            try:
                if isinstance(out, BaseException):
                    req.future.set_exception(out)
                else:
                    req.future.set_result(out)
            except Exception:
                pass  # cancelled between claim and resolve
        if self.stats is not None:
            self.stats.observe_batch(
                n, bucket, latencies,
                trace_ids=[getattr(r.ctx, "trace_id", None)
                           for r in reqs])
        with self._lock:
            prev = self._svc.get(bucket)
            self._svc[bucket] = (svc if prev is None else
                                 (1 - _SVC_ALPHA) * prev
                                 + _SVC_ALPHA * svc)
