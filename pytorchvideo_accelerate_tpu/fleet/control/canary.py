"""Canary rollout: fractional hot-swap, direction-aware verdict,
escalation-ladder rollback.

The third control loop: a new artifact never cuts over the whole fleet
at once. `start_rollout` hot-swaps it onto `fraction` of the routable
replicas (blue/green per replica — `fleet/hotswap.swap_replica`, so
cutover blackout stays bounded and pre-warmed), keeping each victim's
BLUE engine for the rollback path. Traffic then splits naturally through
the router, and `evaluate()` compares canary-vs-baseline the only honest
way this repo knows:

- **pooled windows, post-rollout only**: raw latency samples from each
  side's `ServingStats.window()`, filtered to completions AFTER the
  cutover timestamp and pooled before taking percentiles (never
  percentiles-of-percentiles), plus counter DELTAS since cutover for
  errors/sheds (cumulative counters would charge pre-rollout history to
  the canary);
- **direction-aware deltas**: the comparison reuses
  `analysis/perfdiff.diff_rounds` — p99 up is bad, throughput down is
  bad, same thresholds and vocabulary as the cross-round perf gate — so
  a canary verdict and a bench perfdiff argue from one definition of
  "regressed";
- **exemplar-linked traces**: the verdict carries the canary side's
  `slowest_traces`, so a rollback isn't an anonymous number — it names
  the trace ids of the requests that condemned the artifact.

Regression handling follows TrainGuard's escalation-ladder discipline
(reliability/guard.py): a single bad window is a STRIKE (recorded,
observed again), `rollback_after` consecutive strikes trigger the
auto-rollback — every canary replica swaps back to its kept blue engine
— and a clean window resets the ladder. A clean verdict `promote()`s the
green artifact onto the remaining replicas, replica-by-replica, zero
downtime.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

from pytorchvideo_accelerate_tpu import obs
from pytorchvideo_accelerate_tpu.analysis.perfdiff import diff_rounds
from pytorchvideo_accelerate_tpu.fleet.hotswap import swap_replica
from pytorchvideo_accelerate_tpu.serving.stats import _percentile
from pytorchvideo_accelerate_tpu.utils.logging import get_logger
from pytorchvideo_accelerate_tpu.utils.sync import make_lock, shared_state

logger = get_logger("pva_tpu")


@shared_state("_strikes", "_blues", "_base_counts", "state", "history")
class CanaryController:
    """Fractional blue/green rollout with auto-rollback over a `Router`."""

    # counter keys whose DELTA since cutover feeds the verdict
    _DELTA_KEYS = ("requests", "errors", "shed", "rejected")

    def __init__(self, router, *, fraction: Optional[float] = None,
                 threshold: Optional[float] = None,
                 rollback_after: Optional[int] = None,
                 prewarm: bool = True, fleet=None):
        # dial defaults are the control.* config block's (single source
        # of truth for the canary discipline)
        from pytorchvideo_accelerate_tpu.config import ControlConfig

        dials = ControlConfig()
        if fraction is None:
            fraction = dials.canary_fraction
        if threshold is None:
            threshold = dials.canary_threshold
        if rollback_after is None:
            rollback_after = dials.canary_rollback_after
        if not 0.0 < fraction <= 1.0:
            raise ValueError(f"canary fraction must be in (0, 1], "
                             f"got {fraction}")
        self.router = router
        self.pool = router.pool
        # optional MultiModelFleet: families then come from fleet.models()
        # and each family's model_snapshot rides the verdict (replicas of
        # a family carry stats minted by fleet.stats_for, so each side's
        # windows pool on the family's own latency ladder)
        self.fleet = fleet
        self.fraction = float(fraction)
        self.threshold = float(threshold)
        self.rollback_after = max(int(rollback_after), 1)
        self.prewarm = bool(prewarm)
        self._lock = make_lock("CanaryController._lock")
        self.state = "idle"  # idle -> canary -> rolled_back | promoted
        self.history: List[dict] = []
        self._strikes = 0
        self._blues: Dict[str, object] = {}   # replica name -> kept engine
        self._base_counts: Dict[str, Dict[str, float]] = {}
        self._canaries: List = []
        self._green_factory: Optional[Callable] = None
        self._t_rollout = 0.0

    # --- rollout ----------------------------------------------------------

    def start_rollout(self, green_factory: Callable[[object], object],
                      label: str = "canary") -> dict:
        """Swap green onto `fraction` of the routable replicas.
        `green_factory(replica)` builds a fresh green engine for that
        replica (its own mesh/stats — the hot_swap contract)."""
        with self._lock:
            if self.state == "canary":
                raise RuntimeError("a canary rollout is already in flight")
            self.state = "canary"
            self._strikes = 0
        routable = [r for r in self.pool.routable()
                    if hasattr(r, "scheduler")]
        if not routable:
            with self._lock:
                self.state = "idle"
            raise RuntimeError("no routable in-process replicas to canary")
        n = max(1, int(len(routable) * self.fraction))
        # never canary the WHOLE fleet unless fraction says exactly that:
        # the baseline side must keep at least one replica to compare
        # against (and to serve, should the canary be a brick)
        if self.fraction < 1.0:
            n = min(n, len(routable) - 1) or 1
        self._canaries = routable[:n]
        self._green_factory = green_factory
        # counter baselines for BOTH sides, captured before the first swap:
        # deltas since this instant are what evaluate() compares
        with self._lock:
            for r in routable:
                self._base_counts[r.name] = self._counts(r)
        blackouts = {}
        for replica in self._canaries:
            blue = replica.scheduler.current_engine()
            with self._lock:
                self._blues[replica.name] = blue
            green = green_factory(replica)
            blackouts[replica.name] = round(
                swap_replica(replica, green, prewarm=self.prewarm) * 1e3, 3)
        self._t_rollout = time.monotonic()
        entry = {"t": self._t_rollout, "event": "rollout", "label": label,
                 "canaries": [r.name for r in self._canaries],
                 "blackout_ms": blackouts}
        with self._lock:
            self.history.append(entry)
        logger.info("canary: %s on %s (blackouts %s)", label,
                    entry["canaries"], blackouts)
        obs.get_recorder().record("fleet", "canary-rollout", label=label,
                                  replicas=",".join(entry["canaries"]))
        return entry

    # --- observation ------------------------------------------------------

    @staticmethod
    def _counts(replica) -> Dict[str, float]:
        snap = replica.stats.snapshot() if replica.stats is not None else {}
        return {k: float(snap.get(k, 0.0))
                for k in CanaryController._DELTA_KEYS}

    def _families(self, baseline_side) -> List[str]:
        """Model families present across both sides: the attached fleet's
        registration-stable enumeration when one was given, else the
        replicas' own `model` labels (unlabeled replicas -> no families ->
        pool-level comparison)."""
        if self.fleet is not None:
            return list(self.fleet.models())
        seen: List[str] = []
        for r in list(self._canaries) + list(baseline_side):
            m = getattr(r, "model", None)
            if m is not None and m not in seen:
                seen.append(m)
        return seen

    def _side_stats(self, replicas) -> Dict[str, float]:
        """Pooled post-rollout window + counter deltas for one side."""
        lat: List[float] = []
        out = {k: 0.0 for k in self._DELTA_KEYS}
        n_stats = 0
        for r in replicas:
            if r.stats is None:
                continue
            n_stats += 1
            w, _ = r.stats.window()
            lat.extend(v for ts, v in w if ts >= self._t_rollout)
            base = self._base_counts.get(r.name, {})
            for k, v in self._counts(r).items():
                out[k] += v - base.get(k, 0.0)
        vals = sorted(lat)
        out["completions"] = float(len(vals))
        out["serve_p50_ms"] = round(_percentile(vals, 50) * 1e3, 3)
        out["serve_p99_ms"] = round(_percentile(vals, 99) * 1e3, 3)
        span = time.monotonic() - self._t_rollout
        # PER-REPLICA completion rate: the two sides hold different
        # replica counts by construction (that's what a canary is), so a
        # side-absolute rps would read "canary is 1/N of the fleet" as a
        # throughput regression every single time
        out["serve_rps"] = (round(len(vals) / span / max(n_stats, 1), 3)
                            if span > 0 else 0.0)
        out["error_frac"] = (out["errors"] / out["requests"]
                             if out["requests"] > 0 else 0.0)
        return out

    def evaluate(self) -> dict:
        """One observation window -> a ladder verdict. Returns the verdict
        dict; `action` is "observe" (clean or a first strike), "rollback"
        (the ladder fired and the fleet was restored), and
        `rolled_back`/`strikes` carry the ladder state.

        Multi-model pools (pva-tpu-hbm, ROADMAP item 1): the comparison
        runs PER FAMILY — each side's windows pool only within one
        `replica.model` — because a pool-wide pooled window dilutes a
        regression that lives in one family (and a traffic-mix shift
        between a fast and a slow family reads as a phantom one). A
        regression in ANY family strikes the ladder, tagged
        ``<family>:<key>``. Single-family (or unlabeled) pools keep the
        original pool-level comparison and verdict shape exactly."""
        with self._lock:
            if self.state != "canary":
                raise RuntimeError(f"no canary in flight (state "
                                   f"{self.state!r})")
        baseline_side = [r for r in self.pool.replicas
                         if r not in self._canaries
                         and getattr(r, "stats", None) is not None]
        canary = self._side_stats(self._canaries)
        baseline = self._side_stats(baseline_side)
        # the cross-round perf gate's own direction-aware comparison:
        # baseline plays the "old" round, the canary the "new" one
        diff = diff_rounds(baseline, canary, threshold=self.threshold)
        families = self._families(baseline_side)
        per_family: Dict[str, dict] = {}
        if len(families) > 1:
            regressions = []
            for family in families:
                c_side = [r for r in self._canaries
                          if getattr(r, "model", None) == family]
                b_side = [r for r in baseline_side
                          if getattr(r, "model", None) == family]
                entry: dict = {"canaries": len(c_side),
                               "baselines": len(b_side)}
                if self.fleet is not None:
                    entry["snapshot"] = self.fleet.model_snapshot(family)
                if not c_side or not b_side:
                    # an uncompared family is a recorded fact, never a
                    # silent pass OR a phantom strike
                    entry["skipped"] = ("no canary replicas" if not c_side
                                        else "no baseline replicas")
                    per_family[family] = entry
                    continue
                fc = self._side_stats(c_side)
                fb = self._side_stats(b_side)
                fdiff = diff_rounds(fb, fc, threshold=self.threshold)
                fregs = list(fdiff["regressions"])
                if fc["error_frac"] > fb["error_frac"] and fc["errors"] > 0:
                    fregs.append("canary_error_frac")
                entry.update(canary=fc, baseline=fb,
                             regressions=sorted(fregs))
                per_family[family] = entry
                regressions.extend(f"{family}:{k}" for k in fregs)
        else:
            regressions = list(diff["regressions"])
            if (canary["error_frac"] > baseline["error_frac"]
                    and canary["errors"] > 0):
                regressions.append("canary_error_frac")
        slowest: List[dict] = []
        for r in self._canaries:
            if getattr(r, "stats", None) is not None:
                slowest.extend(r.stats.slowest_traces(k=3))
        slowest.sort(key=lambda d: -d.get("latency_ms", 0.0))
        verdict = {
            "t": time.monotonic(),
            "event": "evaluate",
            "regressions": sorted(regressions),
            "canary": canary,
            "baseline": baseline,
            "keys": diff["keys"],
            # exemplar-linked evidence: the traces that condemned (or
            # acquitted) the artifact, worst first
            "slowest_traces": slowest[:5],
        }
        if per_family:
            verdict["families"] = per_family
        if regressions:
            with self._lock:
                self._strikes += 1
                strikes = self._strikes
            verdict["strikes"] = strikes
            if strikes >= self.rollback_after:
                verdict["action"] = "rollback"
                verdict.update(self.rollback())
            else:
                # below the ladder threshold: recorded, observed again
                verdict["action"] = "observe"
                verdict["rolled_back"] = False
        else:
            with self._lock:
                self._strikes = 0  # a clean window resets the ladder
            verdict["strikes"] = 0
            verdict["action"] = "observe"
            verdict["rolled_back"] = False
        with self._lock:
            self.history.append(verdict)
        obs.get_recorder().record(
            "fleet", "canary-evaluate", action=verdict["action"],
            strikes=verdict["strikes"],
            regressions=",".join(verdict["regressions"]))
        return verdict

    # --- resolution -------------------------------------------------------

    def rollback(self) -> dict:
        """Swap every canary replica back to its kept blue engine."""
        blackouts = {}
        for replica in self._canaries:
            blue = self._blues.get(replica.name)
            if blue is None:
                continue
            # blue's compiled cache is intact — no prewarm needed
            blackouts[replica.name] = round(
                swap_replica(replica, blue, prewarm=False) * 1e3, 3)
        with self._lock:
            self.state = "rolled_back"
            self._blues.clear()
        logger.warning("canary: ROLLED BACK (blackouts %s)", blackouts)
        obs.get_recorder().record("fleet", "canary-rollback",
                                  replicas=",".join(blackouts))
        return {"rolled_back": True, "rollback_blackout_ms": blackouts}

    def promote(self) -> dict:
        """Clean canary -> cut the REST of the fleet over to green,
        replica-by-replica (the rest keep serving — zero downtime)."""
        with self._lock:
            if self.state != "canary":
                raise RuntimeError(f"nothing to promote (state "
                                   f"{self.state!r})")
            if self._strikes:
                raise RuntimeError(
                    f"refusing to promote with {self._strikes} strike(s) "
                    "on the ladder; evaluate() a clean window first")
        blackouts = {}
        canary_names = {r.name for r in self._canaries}
        for replica in self.pool.routable():
            if replica.name in canary_names or not hasattr(replica,
                                                           "scheduler"):
                continue
            green = self._green_factory(replica)
            blackouts[replica.name] = round(
                swap_replica(replica, green, prewarm=self.prewarm) * 1e3, 3)
        with self._lock:
            self.state = "promoted"
            self._blues.clear()
        logger.info("canary: promoted fleet-wide (blackouts %s)", blackouts)
        obs.get_recorder().record("fleet", "canary-promote",
                                  replicas=",".join(blackouts))
        return {"promoted": True, "promote_blackout_ms": blackouts}
