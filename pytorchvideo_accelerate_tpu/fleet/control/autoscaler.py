"""SLO-driven replica autoscaler: the damped resize loop.

Closes the loop ROADMAP item 1 left open: the fleet has sensors
(registry gauges, pooled percentiles) and actuators
(`spawn_serving_process`, pool membership, admission drain, session
re-home) but nothing that ACTS. The `Autoscaler` ticks on a fixed
interval; each tick reads one `ControlSignals` snapshot (signals.py) and
moves a TARGET replica count by at most one step, then reconciles
membership toward the target:

- **up** when smoothed backlog per routable replica crosses `queue_high`
  or smoothed p99 crosses the SLO — actuated by `spawn_fn` (default:
  `spawn_serving_process(artifact)`), the new member joining the pool as
  soon as its bind handshake lands;
- **down** when backlog falls under `queue_low` AND p99 sits under
  `downscale_frac * SLO` — the victim is first flipped to DRAINING via
  the admission state machine (`replica.drain()` — POST /drain for
  process replicas), its live streaming sessions are re-homed by
  forgetting their router affinity (each re-establishes elsewhere from
  its resendable window, deterministically — docs/SERVING.md
  § streaming), its in-flight requests are given `drain_grace_s` to
  settle, and only then is it removed and reaped;
- **replace** when a member stays dead for `dead_after_ticks`
  consecutive ticks: the corpse leaves membership and the ordinary
  reconcile spawns its successor — the dead replica is never counted
  against the target twice.

Damping is threefold — EWMA smoothing on both signals (`ewma_alpha`),
hysteresis between the up/down watermarks, and a `cooldown_s` dead time
after every action — because an undamped controller and an open-loop
load generator form a textbook oscillator. The last routable replica is
never drained, no matter what the signals say: a fleet that scales to
zero under a monitoring blip has no path back.

Every decision lands in `history` (monotonic timestamp, action, the
signal values that justified it) — the convergence evidence the
FLEET_AUTO bench lane asserts on — and in the obs flight recorder.
"""

from __future__ import annotations

import math
import time
from typing import Callable, List, Optional

from pytorchvideo_accelerate_tpu import obs
from pytorchvideo_accelerate_tpu.fleet.control.signals import SignalReader
from pytorchvideo_accelerate_tpu.utils.logging import get_logger
from pytorchvideo_accelerate_tpu.utils.sync import (
    make_lock,
    make_thread,
    shared_state,
)

logger = get_logger("pva_tpu")


@shared_state("target", "history", "_q_ewma", "_p99_ewma", "_last_action_t",
              "_down_streak", "_spawned",
              benign={"_closed": "monotonic shutdown latch; a torn bool "
                                 "read costs one extra control tick"})
class Autoscaler:
    """Damped closed-loop replica-count controller over a `Router`."""

    def __init__(self, router, *,
                 spawn_fn: Optional[Callable[[], object]] = None,
                 reap_fn: Optional[Callable[[object], None]] = None,
                 artifact: str = "",
                 min_replicas: int = 1, max_replicas: int = 8,
                 slo_p99_ms: float = 500.0,
                 queue_high: float = 4.0, queue_low: float = 0.5,
                 downscale_frac: float = 0.5,
                 cooldown_s: float = 2.0, interval_s: float = 0.25,
                 ewma_alpha: float = 0.5, drain_grace_s: float = 5.0,
                 dead_after_ticks: int = 3,
                 reader: Optional[SignalReader] = None,
                 model: Optional[str] = None):
        if min_replicas < 1:
            raise ValueError("min_replicas must be >= 1 (a fleet that can "
                             "scale to zero has no path back)")
        if max_replicas < min_replicas:
            raise ValueError(f"max_replicas {max_replicas} < min_replicas "
                             f"{min_replicas}")
        if queue_low >= queue_high:
            raise ValueError("queue_low must sit strictly under queue_high "
                             "(the hysteresis band IS the damping)")
        self.router = router
        self.pool = router.pool
        self.reader = reader if reader is not None else SignalReader(
            router, model=model)
        self.model = model
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.slo_p99_ms = float(slo_p99_ms)
        self.queue_high = float(queue_high)
        self.queue_low = float(queue_low)
        self.downscale_frac = float(downscale_frac)
        self.cooldown_s = float(cooldown_s)
        self.interval_s = max(float(interval_s), 0.01)
        self.ewma_alpha = min(max(float(ewma_alpha), 0.01), 1.0)
        self.drain_grace_s = float(drain_grace_s)
        self.dead_after_ticks = max(int(dead_after_ticks), 1)
        if spawn_fn is None:
            if not artifact:
                raise ValueError(
                    "Autoscaler needs spawn_fn or an artifact path for the "
                    "default spawn_serving_process actuator")
            spawn_fn = self._default_spawn(artifact)
        self.spawn_fn = spawn_fn
        self.reap_fn = reap_fn
        self._lock = make_lock("Autoscaler._lock")
        self.target = max(len(self.pool.routable()), self.min_replicas)
        self.history: List[dict] = []
        self._q_ewma = 0.0
        self._p99_ewma = 0.0
        self._last_action_t = 0.0   # 0 = no cooldown on the first action
        self._down_streak: dict = {}   # replica name -> consecutive down ticks
        self._spawned: dict = {}       # replica name -> spawn handle (reap arg)
        self._closed = False
        self._thread = None

    # --- actuators --------------------------------------------------------

    def _default_spawn(self, artifact: str):
        """Production actuator: one `pva-tpu-serve` process per scale-up,
        reaped (terminate -> kill) when its replica is scaled back down."""
        from pytorchvideo_accelerate_tpu.fleet.pool import (
            spawn_serving_process,
        )

        def spawn():
            proc, replica = spawn_serving_process(artifact)
            replica._proc = proc  # the reap handle rides on the replica
            return replica

        if self.reap_fn is None:
            def reap(replica):
                proc = getattr(replica, "_proc", None)
                if proc is None:
                    return
                proc.terminate()
                try:
                    proc.wait(timeout=5.0)
                except Exception:
                    proc.kill()
                    proc.wait()

            self.reap_fn = reap
        return spawn

    # --- the control loop -------------------------------------------------

    def start(self) -> "Autoscaler":
        self._thread = make_thread(target=self._loop,
                                   name="pva-fleet-autoscale", daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._closed:
            try:
                self.step()
            except Exception:
                # a broken tick must not kill the controller: the fleet
                # keeps its current size and the next tick retries
                logger.exception("autoscaler: control tick failed")
            time.sleep(self.interval_s)

    def close(self) -> None:
        self._closed = True
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def step(self) -> str:
        """One control tick: read -> smooth -> decide -> reconcile.
        Returns the action taken ("up" | "down" | "replace" | "hold")."""
        sig = self.reader.read()
        a = self.ewma_alpha
        with self._lock:
            self._q_ewma = a * sig.queue_per_replica() + (1 - a) * self._q_ewma
            self._p99_ewma = a * sig.p99_ms + (1 - a) * self._p99_ewma
            q, p99 = self._q_ewma, self._p99_ewma
        if getattr(self.reader, "history", None) is not None:
            # pva-tpu-hbm: smooth off the SHARED history ring when the
            # reader carries one — same time base the alert rules and
            # /history serve, instead of this controller's private
            # accumulators (which stay warm as the fallback). halflife is
            # ewma_alpha expressed per control interval.
            hl = (-self.interval_s * math.log(2.0) / math.log(1.0 - a)
                  if a < 1.0 else 0.0)
            q_h = self.reader.ewma("pva_fleet_queue_per_replica", hl)
            p99_h = self.reader.ewma("pva_fleet_p99_ms", hl)
            if q_h is not None:
                q = q_h
            if p99_h is not None:
                p99 = p99_h
        action = self._reap_confirmed_dead(sig)
        if action is None:
            action = self._decide(sig, q, p99)
        if action != "hold":
            self._record(action, sig, q, p99)
        return action

    def _decide(self, sig, q: float, p99: float) -> str:
        now = time.monotonic()
        with self._lock:
            cooling = now - self._last_action_t < self.cooldown_s
            target = self.target
        pressure = q > self.queue_high or p99 > self.slo_p99_ms
        idle = (q < self.queue_low
                and p99 < self.downscale_frac * self.slo_p99_ms)
        if pressure and target < self.max_replicas and not cooling:
            with self._lock:
                self.target = target + 1
        elif idle and target > self.min_replicas and not cooling:
            with self._lock:
                self.target = target - 1
        return self._reconcile()

    def _reconcile(self) -> str:
        """Drive membership toward the target, one replica per tick (the
        single-step move is part of the damping)."""
        members = list(self.pool.replicas)
        routable = self.pool.routable()
        with self._lock:
            target = self.target
        if len(members) < target:
            return "up" if self._spawn_one() else "hold"
        if len(routable) > target:
            return "down" if self._drain_one(routable) else "hold"
        return "hold"

    # --- scale-up ---------------------------------------------------------

    def _spawn_one(self) -> bool:
        try:
            replica = self.spawn_fn()
        except Exception:
            logger.exception("autoscaler: spawn failed; holding")
            return False
        try:
            self.pool.add_replica(replica)
        except ValueError:
            # name collision (a resurrection raced us): reap the orphan
            self._reap(replica)
            return False
        with self._lock:
            self._spawned[replica.name] = replica
        return True

    # --- scale-down: drain -> re-home -> settle -> reap -------------------

    def _drain_one(self, routable) -> bool:
        if len(routable) <= 1:
            return False  # never drain the last routable replica
        victim = self._pick_victim(routable)
        if victim is None:
            return False
        # 1. admission first: the replica stops admitting and /healthz goes
        # 503, so the poller pulls it within one interval — then mark it
        # down explicitly so the router routes around it NOW
        victim.drain()
        self.pool.mark_down(victim)
        # 2. re-home live streaming sessions: dropping the affinity pin
        # makes each session's next advance route to a surviving replica,
        # where the deterministic re-establish protocol rebuilds its ring
        # from the client's resendable window (raw and KV rings alike)
        moved = self.router.sessions_on(victim.name)
        for sid in moved:
            self.router.forget_session(sid)
        if moved:
            logger.info("autoscaler: re-homing %d session(s) off %s",
                        len(moved), victim.name)
        # 3. give in-flight requests the grace budget to settle
        deadline = time.monotonic() + self.drain_grace_s
        while time.monotonic() < deadline:
            with self.router._lock:
                left = self.router._outstanding.get(victim.name, 0)
            if left <= 0:
                break
            time.sleep(0.02)
        # 4. reap: out of membership for good, then the process (if ours)
        self.pool.remove_replica(victim, close=True)
        self._reap(victim)
        with self._lock:
            self._down_streak.pop(victim.name, None)
        obs.get_recorder().record("fleet", "scale-down", victim=victim.name,
                                  sessions_rehomed=len(moved))
        return True

    def _pick_victim(self, routable):
        """Fewest pinned sessions loses (cheapest re-home); self-spawned
        replicas break ties (we own their processes and can reap them)."""
        with self._lock:
            spawned = set(self._spawned)
        return min(
            routable,
            key=lambda r: (len(self.router.sessions_on(r.name)),
                           r.name not in spawned, r.name),
            default=None)

    def _reap(self, replica) -> None:
        with self._lock:
            self._spawned.pop(replica.name, None)
        if self.reap_fn is not None:
            try:
                self.reap_fn(replica)
            except Exception:
                logger.exception("autoscaler: reap of %s failed",
                                 replica.name)

    # --- dead-member replacement -----------------------------------------

    def _reap_confirmed_dead(self, sig) -> Optional[str]:
        """A member that stays unroutable for `dead_after_ticks` ticks is a
        corpse: remove it so the reconcile pass spawns its replacement —
        membership reflects reality, the target is never double-counted
        against a dead name. Never removes the last member (min_replicas
        floors the target; a fully-dead fleet keeps one name for the
        poller to watch for resurrection)."""
        members = list(self.pool.replicas)
        routable_names = {r.name for r in self.pool.routable()}
        victim = None
        with self._lock:
            for r in members:
                if r.name in routable_names:
                    self._down_streak.pop(r.name, None)
                    continue
                streak = self._down_streak.get(r.name, 0) + 1
                self._down_streak[r.name] = streak
                if (streak >= self.dead_after_ticks and victim is None
                        and len(members) > 1):
                    victim = r
        if victim is None:
            return None
        try:
            state = victim.health()
        except Exception:
            state = "dead"
        if state != "dead":  # draining/degraded members are not corpses
            return None
        logger.warning("autoscaler: %s confirmed dead after %d ticks; "
                       "replacing", victim.name, self.dead_after_ticks)
        for sid in self.router.sessions_on(victim.name):
            self.router.forget_session(sid)  # survivors re-establish
        self.pool.remove_replica(victim, close=True)
        self._reap(victim)
        with self._lock:
            self._down_streak.pop(victim.name, None)
        self._spawn_one()  # reconcile immediately: replace, don't wait
        return "replace"

    # --- evidence ---------------------------------------------------------

    def _record(self, action: str, sig, q: float, p99: float) -> None:
        entry = {
            "t": time.monotonic(), "action": action,
            "target": self.target,
            "routable": len(self.pool.routable()),
            "members": len(self.pool.replicas),
            "queue_per_replica": round(q, 3),
            "p99_ms": round(p99, 3),
            "shed_total": sig.shed_total,
        }
        with self._lock:
            self._last_action_t = entry["t"]
            self.history.append(entry)
        logger.info("autoscaler: %s -> target %d (q/replica %.2f, "
                    "p99 %.0f ms)", action, entry["target"], q, p99)
        obs.get_recorder().record("fleet", "autoscale", **entry)

    def actions_since(self, t: float) -> List[dict]:
        with self._lock:
            return [e for e in self.history if e["t"] >= t]
