"""Multi-model serving on one pool under a shared device budget.

One fleet, several model families (`x3d_s` + `videomae_t` in the bench
lane): each replica declares the family it serves (`replica.model`), the
router narrows candidates per request (`submit(..., model=)`) and labels
traffic per family (`pva_fleet_model_*{pool=,model=}`), and THIS module
adds the two things routing alone cannot give:

- **a shared budget** (`ModelBudget`): compiled-cache + HBM footprint is
  a per-chip resource the families compete for. Each family registers
  its declared footprint; when the sum crosses the budget, the
  LOWEST-PRIORITY over-budget family — registration order is priority
  order, latest-registered evicts first — is marked over-budget and its
  NEW work is shed at the fleet door (503 + Retry-After, labeled
  `pva_fleet_budget_shed_total{model=}`). The POOL never degrades: the
  in-budget families keep serving untouched, which is the whole point —
  budget pressure from model B must read as "B sheds", never "everyone's
  p99 doubles".
- **per-family observability** (`MultiModelFleet.model_snapshot`):
  the per-model `fleet_snapshot` slice plus the family's declared
  footprint/ladder, and `snapshot_labels`-style flattening for trackers.

Per-model bucket ladders: each family registers its own latency bucket
boundaries (`latency_buckets_ms`) — `stats_for()` mints a `ServingStats`
carrying that ladder for the family's replicas, so a sub-second x3d tier
and a multi-second videomae tier each get histogram resolution where
their traffic actually lands (the `set_family_buckets` lesson,
obs/registry.py, applied per model family).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from pytorchvideo_accelerate_tpu import obs
from pytorchvideo_accelerate_tpu.obs import memory as obs_memory
from pytorchvideo_accelerate_tpu.serving.batcher import QueueFullError
from pytorchvideo_accelerate_tpu.serving.stats import ServingStats
from pytorchvideo_accelerate_tpu.utils.logging import get_logger
from pytorchvideo_accelerate_tpu.utils.sync import make_lock, shared_state

logger = get_logger("pva_tpu")


@shared_state("_footprints")
class ModelBudget:
    """Shared compiled-cache/HBM budget across model families (MB).

    pva-tpu-hbm: on a device whose backend exposes `memory_stats()`, each
    family's footprint is the MEASURED MemoryLedger bytes of its
    ``model_weights:<model>`` + ``engine_compiled:<model>`` components —
    the declared `footprint_mb` then only sets the priority slot and is
    the documented CPU/test fallback. A family that under-declares
    cannot lie its way under the budget where the ledger can see it.
    """

    # ledger components that make up one family's device footprint
    _COMPONENTS = ("model_weights:{m}", "engine_compiled:{m}")

    def __init__(self, budget_mb: float):
        self.budget_mb = float(budget_mb)
        self._lock = make_lock("ModelBudget._lock")
        self._footprints: Dict[str, float] = {}  # insertion order = priority

    def register(self, model: str, footprint_mb: float) -> None:
        """Declare (or update) a family's footprint; re-registration keeps
        the original priority slot."""
        with self._lock:
            self._footprints[str(model)] = float(footprint_mb)

    def release(self, model: str) -> None:
        with self._lock:
            self._footprints.pop(str(model), None)

    def footprint_mb(self, model: str) -> float:
        """One family's effective footprint: measured ledger bytes where
        the device exposes them, the declared estimate elsewhere."""
        with self._lock:
            declared = self._footprints.get(str(model), 0.0)
        led = obs_memory.get_ledger()
        if led is not None:
            measured = [led.measured_bytes(c.format(m=model))
                        for c in self._COMPONENTS]
            # nonzero: a zero-byte "measurement" means the family never
            # registered an engine here — that's the declared fallback,
            # not a free admission
            if any(measured):
                return sum(b or 0 for b in measured) / 1e6
        return declared

    def footprint_source(self, model: str) -> str:
        """"measured" when `footprint_mb` reads the ledger, "declared"
        otherwise (CPU hosts / disarmed ledger / unregistered family)."""
        led = obs_memory.get_ledger()
        if led is not None and any(
                led.measured_bytes(c.format(m=model))
                for c in self._COMPONENTS):
            return "measured"
        return "declared"

    def usage_mb(self) -> float:
        with self._lock:
            models = list(self._footprints)
        return sum(self.footprint_mb(m) for m in models)

    def over_budget(self) -> List[str]:
        """Families whose admission must shed, lowest priority first.
        Walking registration order, the first families that FIT keep
        serving; everything past the point the budget is exhausted sheds.
        The earliest-registered family always fits (a budget smaller than
        every family would otherwise shed the whole pool — the exact
        failure mode this module exists to prevent)."""
        with self._lock:
            models = list(self._footprints)
        used = 0.0
        shed: List[str] = []
        for i, model in enumerate(models):
            used += self.footprint_mb(model)
            if i > 0 and used > self.budget_mb:
                shed.append(model)
        return shed


class MultiModelFleet:
    """Budget-aware per-family front over a `Router`.

    Speaks the router's `submit` surface with a REQUIRED model key; the
    over-budget check runs before dispatch, so a shed family's request
    never consumes router retries or replica queue slots."""

    def __init__(self, router, budget: ModelBudget,
                 retry_after_s: float = 1.0):
        self.router = router
        self.budget = budget
        self.retry_after_s = float(retry_after_s)
        self._ladders: Dict[str, Optional[tuple]] = {}
        self._c_budget_shed = router.registry.counter(
            "pva_fleet_budget_shed_total",
            "requests shed because the model family is over the shared "
            "compiled-cache/HBM budget, by pool and model",
            labelnames=("pool", "model"))

    def register_model(self, model: str, footprint_mb: float,
                       latency_buckets_ms: Optional[Sequence[float]] = None,
                       ) -> None:
        self.budget.register(model, footprint_mb)
        self._ladders[str(model)] = (
            tuple(float(b) for b in latency_buckets_ms)
            if latency_buckets_ms else None)
        over = self.budget.over_budget()
        logger.info("fleet: model %s registered (%.0f MB; budget %.0f/%.0f "
                    "MB used%s)", model, footprint_mb,
                    self.budget.usage_mb(), self.budget.budget_mb,
                    f"; shedding {over}" if over else "")
        obs.get_recorder().record(
            "fleet", "model-registered", model=str(model),
            footprint_mb=float(footprint_mb),
            over_budget=",".join(over))

    def stats_for(self, model: str) -> ServingStats:
        """A `ServingStats` carrying the family's own latency ladder (ms
        boundaries -> seconds), for this family's replicas."""
        ladder = self._ladders.get(str(model))
        return ServingStats(
            latency_buckets=[b / 1e3 for b in ladder] if ladder else None)

    def models(self) -> List[str]:
        """Families with at least one pooled replica, registration-stable."""
        seen: List[str] = []
        for r in list(self.router.pool.replicas):
            m = getattr(r, "model", None)
            if m is not None and m not in seen:
                seen.append(m)
        return seen

    def submit(self, clip, *, model: str, **kwargs):
        if model in self.budget.over_budget():
            # the budget-aware shed: THIS family yields, the pool doesn't
            self._c_budget_shed.inc(pool=self.router.pool.name,
                                    model=str(model))
            raise QueueFullError(
                f"model {model!r} over the shared budget "
                f"({self.budget.usage_mb():.0f}/"
                f"{self.budget.budget_mb:.0f} MB); retry later",
                retry_after_s=self.retry_after_s)
        return self.router.submit(clip, model=model, **kwargs)

    def model_snapshot(self, model: str) -> Dict[str, float]:
        snap = self.router.fleet_snapshot(model=model)
        snap["budget_shed"] = self._c_budget_shed.value(
            pool=self.router.pool.name, model=str(model))
        snap["footprint_mb"] = self.budget.footprint_mb(str(model))
        return snap

    def snapshot_labels(self) -> Dict[str, float]:
        """Flat tracker-facing view: every family's snapshot, keys
        prefixed ``<model>/`` (the ServingStats.snapshot_labels idiom)."""
        out: Dict[str, float] = {
            "budget_mb": self.budget.budget_mb,
            "budget_used_mb": self.budget.usage_mb(),
            "models_served": float(len(self.models())),
        }
        for model in self.models():
            for k, v in self.model_snapshot(model).items():
                out[f"{model}/{k}"] = v
        return out
