"""Fleet intelligence: the control loops over the serving tier.

fleet/ gave the serving tier sensors (per-replica registry labels,
pooled percentiles, traces) and actuators (`spawn_serving_process`,
blue/green hot-swap, admission drain, session re-home); this package
closes the loops (ROADMAP item 1 — docs/SERVING.md § fleet
intelligence):

- `signals.SignalReader` — one registry-fed `ControlSignals` snapshot
  per control tick (the same numbers `/metrics` serves);
- `autoscaler.Autoscaler` — damped SLO-driven pool resizing: spawn on
  backlog/p99 pressure, drain -> re-home -> reap on idle, dead-member
  replacement without double-counting;
- `multimodel.ModelBudget` / `multimodel.MultiModelFleet` — several
  model families on one pool under a shared compiled-cache/HBM budget;
  the over-budget family sheds, the pool never degrades;
- `canary.CanaryController` — fractional blue/green rollout with
  pooled-window direction-aware comparison (perfdiff vocabulary),
  exemplar-linked evidence, and escalation-ladder auto-rollback.
"""

from pytorchvideo_accelerate_tpu.fleet.control.autoscaler import (  # noqa: F401,E501
    Autoscaler,
)
from pytorchvideo_accelerate_tpu.fleet.control.canary import (  # noqa: F401
    CanaryController,
)
from pytorchvideo_accelerate_tpu.fleet.control.multimodel import (  # noqa: F401,E501
    ModelBudget,
    MultiModelFleet,
)
from pytorchvideo_accelerate_tpu.fleet.control.signals import (  # noqa: F401
    ControlSignals,
    SignalReader,
)
