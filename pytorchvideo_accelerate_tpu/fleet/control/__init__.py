"""Fleet intelligence: the control loops over the serving tier.

fleet/ gave the serving tier sensors (per-replica registry labels,
pooled percentiles, traces) and actuators (`spawn_serving_process`,
blue/green hot-swap, admission drain, session re-home); this package
closes the loops (ROADMAP item 1 — docs/SERVING.md § fleet
intelligence):

- `signals.SignalReader` — one registry-fed `ControlSignals` snapshot
  per control tick (the same numbers `/metrics` serves); with a shared
  `obs.history.MetricsHistory` attached it also serves the smoothed
  (EWMA) series off the retained ring;
- `autoscaler.Autoscaler` — damped SLO-driven pool resizing: spawn on
  backlog/p99 pressure, drain -> re-home -> reap on idle, dead-member
  replacement without double-counting;
- `multimodel.ModelBudget` / `multimodel.MultiModelFleet` — several
  model families on one pool under a shared compiled-cache/HBM budget;
  the over-budget family sheds, the pool never degrades (budgets consume
  MEASURED MemoryLedger bytes on device, declared footprints elsewhere);
- `canary.CanaryController` — fractional blue/green rollout with
  direction-aware comparison (perfdiff vocabulary) evaluated PER model
  family on multi-model pools (a regression in one family strikes that
  family instead of diluting into a pool average), exemplar-linked
  evidence, and escalation-ladder auto-rollback.
"""

from pytorchvideo_accelerate_tpu.fleet.control.autoscaler import (  # noqa: F401,E501
    Autoscaler,
)
from pytorchvideo_accelerate_tpu.fleet.control.canary import (  # noqa: F401
    CanaryController,
)
from pytorchvideo_accelerate_tpu.fleet.control.multimodel import (  # noqa: F401,E501
    ModelBudget,
    MultiModelFleet,
)
from pytorchvideo_accelerate_tpu.fleet.control.signals import (  # noqa: F401
    ControlSignals,
    SignalReader,
)
