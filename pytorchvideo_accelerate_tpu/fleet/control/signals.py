"""Registry-fed control signals: what the fleet controller acts on.

The autoscaler/canary loops must argue from the SAME numbers a human
watching `/metrics` sees — not from a private side-channel that can
drift. `SignalReader.read()` therefore sources every scalar it can from
the router's obs registry (`Registry.scrape()` — the exact series the
Prometheus endpoint renders: `pva_fleet_healthy_replicas{pool=}`,
`pva_fleet_outstanding{pool=,replica=}`, `pva_fleet_shed_total{pool=}`)
and takes only the pooled latency percentiles from
`Router.fleet_snapshot()` — raw latency windows never cross the metrics
wire (cumulative histograms lose the rolling-window property), and
percentiles-of-percentiles would lie (stats.py `merge`).

`ControlSignals` is deliberately a plain frozen snapshot: one read per
control tick, decisions on the snapshot, never live reads mid-decision —
a controller that reads twice can see two different fleets and flap.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, Optional

from pytorchvideo_accelerate_tpu.utils.logging import get_logger

logger = get_logger("pva_tpu")


@dataclass(frozen=True)
class ControlSignals:
    """One control tick's view of the fleet (all floats; -1 = unknown)."""

    t: float                 # monotonic read time
    routable: float          # replicas in the routable set (registry gauge)
    members: float           # total pool membership (routable + down)
    outstanding: float       # in-flight requests, summed over replicas
    queue_depth: float       # router-tracked dispatched-not-settled depth
    p99_ms: float            # pooled-window p99 (0.0 = no windowed samples)
    throughput_rps: float    # pooled-window completion rate
    shed_total: float        # cumulative router sheds (counter, monotonic)
    per_replica_outstanding: Dict[str, float] = field(default_factory=dict)

    def queue_per_replica(self) -> float:
        """Backlog pressure normalized by serving capacity."""
        return self.queue_depth / max(self.routable, 1.0)


class SignalReader:
    """Reads `ControlSignals` off a router's registry + pooled windows.

    With a shared `MetricsHistory` attached (pva-tpu-hbm), each `read()`
    also appends a scrape tick to the ring, and `ewma()` serves smoothed
    series straight from that shared history — so the autoscaler, the
    alert rules, and `/history` all argue from ONE retained time base
    instead of per-consumer private smoothing state.
    """

    # prefix of every router/pool series in the registry scrape
    _FLEET_PREFIX = "pva_fleet_"

    def __init__(self, router, *, model: Optional[str] = None,
                 history=None):
        self.router = router
        self.model = model
        self._pool_label = router.pool.name
        self.history = history  # obs.history.MetricsHistory or None
        self._last: Optional[ControlSignals] = None
        if history is not None:
            # the two control-loop series exist nowhere else in the
            # registry (p99 comes from pooled windows, queue-per-replica
            # is a derived ratio): publish them as live gauges off the
            # last read snapshot so the history ring retains them
            pool = self._pool_label
            router.registry.gauge(
                "pva_fleet_queue_per_replica",
                "router backlog normalized by routable capacity",
                labelnames=("pool",),
            ).set_function(
                lambda: (self._last.queue_per_replica()
                         if self._last is not None else 0.0), pool=pool)
            router.registry.gauge(
                "pva_fleet_p99_ms",
                "pooled-window p99 latency as read by the control loop",
                labelnames=("pool",),
            ).set_function(
                lambda: (self._last.p99_ms
                         if self._last is not None else 0.0), pool=pool)

    def ewma(self, name: str, halflife_s: float) -> Optional[float]:
        """EWMA of a pool-labeled fleet series from the shared history
        ring; None when no history is attached or the series is empty."""
        if self.history is None:
            return None
        return self.history.ewma(
            f'{name}{{pool="{self._pool_label}"}}', halflife_s)

    def _series(self, scrape: Dict[str, float], name: str,
                **labels: str) -> float:
        """One sample from a scrape dict, keyed the way render() keys it."""
        if not labels:
            return float(scrape.get(name, 0.0))
        inner = ",".join(f'{k}="{v}"' for k, v in labels.items())
        return float(scrape.get(f"{name}{{{inner}}}", 0.0))

    def read(self, model: Optional[str] = None) -> ControlSignals:
        model = model if model is not None else self.model
        scrape = self.router.registry.scrape(self._FLEET_PREFIX)
        routable = self._series(scrape, "pva_fleet_healthy_replicas",
                                pool=self._pool_label)
        shed = self._series(scrape, "pva_fleet_shed_total",
                            pool=self._pool_label)
        per_replica = {}
        prefix = 'pva_fleet_outstanding{pool="%s",replica="' % self._pool_label
        for key, v in scrape.items():
            if key.startswith(prefix):
                per_replica[key[len(prefix):-2]] = float(v)
        # pooled-window percentiles: the one signal the registry cannot
        # carry (see module docstring); same snapshot call /stats serves
        snap = self.router.fleet_snapshot(model=model)
        sig = ControlSignals(
            t=time.monotonic(),
            routable=routable,
            members=float(snap.get("replicas_total",
                                   snap.get("replicas", 0.0))),
            outstanding=sum(per_replica.values()),
            queue_depth=float(self.router.queue_depth()),
            p99_ms=float(snap.get("p99_ms", 0.0)),
            throughput_rps=float(snap.get("throughput_rps", 0.0)),
            shed_total=shed,
            per_replica_outstanding=per_replica,
        )
        self._last = sig
        if self.history is not None:
            # one scrape tick per control read: the shared ring's cadence
            # IS the control cadence (the gauges above read self._last)
            self.history.tick()
        return sig
