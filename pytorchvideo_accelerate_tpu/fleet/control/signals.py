"""Registry-fed control signals: what the fleet controller acts on.

The autoscaler/canary loops must argue from the SAME numbers a human
watching `/metrics` sees — not from a private side-channel that can
drift. `SignalReader.read()` therefore sources every scalar it can from
the router's obs registry (`Registry.scrape()` — the exact series the
Prometheus endpoint renders: `pva_fleet_healthy_replicas{pool=}`,
`pva_fleet_outstanding{pool=,replica=}`, `pva_fleet_shed_total{pool=}`)
and takes only the pooled latency percentiles from
`Router.fleet_snapshot()` — raw latency windows never cross the metrics
wire (cumulative histograms lose the rolling-window property), and
percentiles-of-percentiles would lie (stats.py `merge`).

`ControlSignals` is deliberately a plain frozen snapshot: one read per
control tick, decisions on the snapshot, never live reads mid-decision —
a controller that reads twice can see two different fleets and flap.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, Optional

from pytorchvideo_accelerate_tpu.utils.logging import get_logger

logger = get_logger("pva_tpu")


@dataclass(frozen=True)
class ControlSignals:
    """One control tick's view of the fleet (all floats; -1 = unknown)."""

    t: float                 # monotonic read time
    routable: float          # replicas in the routable set (registry gauge)
    members: float           # total pool membership (routable + down)
    outstanding: float       # in-flight requests, summed over replicas
    queue_depth: float       # router-tracked dispatched-not-settled depth
    p99_ms: float            # pooled-window p99 (0.0 = no windowed samples)
    throughput_rps: float    # pooled-window completion rate
    shed_total: float        # cumulative router sheds (counter, monotonic)
    per_replica_outstanding: Dict[str, float] = field(default_factory=dict)

    def queue_per_replica(self) -> float:
        """Backlog pressure normalized by serving capacity."""
        return self.queue_depth / max(self.routable, 1.0)


class SignalReader:
    """Reads `ControlSignals` off a router's registry + pooled windows."""

    # prefix of every router/pool series in the registry scrape
    _FLEET_PREFIX = "pva_fleet_"

    def __init__(self, router, *, model: Optional[str] = None):
        self.router = router
        self.model = model
        self._pool_label = router.pool.name

    def _series(self, scrape: Dict[str, float], name: str,
                **labels: str) -> float:
        """One sample from a scrape dict, keyed the way render() keys it."""
        if not labels:
            return float(scrape.get(name, 0.0))
        inner = ",".join(f'{k}="{v}"' for k, v in labels.items())
        return float(scrape.get(f"{name}{{{inner}}}", 0.0))

    def read(self, model: Optional[str] = None) -> ControlSignals:
        model = model if model is not None else self.model
        scrape = self.router.registry.scrape(self._FLEET_PREFIX)
        routable = self._series(scrape, "pva_fleet_healthy_replicas",
                                pool=self._pool_label)
        shed = self._series(scrape, "pva_fleet_shed_total",
                            pool=self._pool_label)
        per_replica = {}
        prefix = 'pva_fleet_outstanding{pool="%s",replica="' % self._pool_label
        for key, v in scrape.items():
            if key.startswith(prefix):
                per_replica[key[len(prefix):-2]] = float(v)
        # pooled-window percentiles: the one signal the registry cannot
        # carry (see module docstring); same snapshot call /stats serves
        snap = self.router.fleet_snapshot(model=model)
        return ControlSignals(
            t=time.monotonic(),
            routable=routable,
            members=float(snap.get("replicas_total",
                                   snap.get("replicas", 0.0))),
            outstanding=sum(per_replica.values()),
            queue_depth=float(self.router.queue_depth()),
            p99_ms=float(snap.get("p99_ms", 0.0)),
            throughput_rps=float(snap.get("throughput_rps", 0.0)),
            shed_total=shed,
            per_replica_outstanding=per_replica,
        )
