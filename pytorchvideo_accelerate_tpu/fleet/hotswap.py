"""Zero-downtime checkpoint hot-swap: blue/green per replica.

The production question this answers: a new checkpoint lands (a nightly
fine-tune, a rollback) — how do the serving replicas pick it up without
dropping requests or serving a single mixed-weights answer?

The sequence, per replica (fleet-wide it runs replica-by-replica, so the
router keeps the rest of the fleet serving — zero downtime end to end):

1. **green build**: restore the artifact into a FRESH `InferenceEngine` on
   the replica's own mesh (`InferenceEngine.from_artifact`), sharing the
   replica's `ServingStats` so the latency window survives the swap;
2. **pre-warm**: compile the green engine for EVERY (bucket, geometry) the
   blue engine has ever served (`compiled_keys` is exactly that set) —
   cutover must never turn first requests into multi-second compile
   stalls;
3. **drain-then-swap**: `Scheduler.swap_engine` installs green BETWEEN
   launches — it blocks on the launch lock until the in-flight launch
   finishes, so no launch (and therefore no future) ever sees mixed
   weights. The time it blocks is the measured `swap_blackout_ms`: with
   pre-warm done, it is bounded by one launch's service time.

Bucket ladders must match (same config → same `serve.max_batch_size` and
shard count); a swap that would change them is refused — queued padding
plans assume stable buckets, and that shape change is a restart, not a
swap. See docs/SERVING.md § hot-swap runbook.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import numpy as np

from pytorchvideo_accelerate_tpu import obs
from pytorchvideo_accelerate_tpu.obs import trace
from pytorchvideo_accelerate_tpu.utils.logging import get_logger

logger = get_logger("pva_tpu")


def prewarm_like(green, blue) -> int:
    """Compile `green` for every geometry `blue` has served; returns the
    number of compiled keys. Runs while blue is still serving — compiles
    happen on the caller's thread, launches keep flowing. Streaming
    engines also pre-compile every (bucket, stride, geometry) stream
    step blue serves plus the whole-pool re-embed (`prepare_carry_from`)
    — the session-state carry ITSELF happens later, inside
    `Scheduler.swap_engine` under the launch lock, because blue keeps
    launching (and donating its ring buffers) all through prewarm
    (docs/SERVING.md § streaming)."""
    n = 0
    for key in blue.compiled_keys:
        batch = {name: np.zeros(shape, green.input_dtype)
                 for name, shape in key}
        green.predict(batch)
        n += 1
    if hasattr(green, "prepare_carry_from") and hasattr(blue, "table"):
        # traced: the prewarm half of the session-state handoff — the
        # swap hop the trace-propagation rule guards (obs/trace.py);
        # the carry itself runs at cutover, blue quiesced
        with trace.span("session_carry_prewarm", sessions=len(
                blue.table.sessions())):
            n += green.prepare_carry_from(blue)
        logger.info("hot-swap: pre-compiled the stream carry; live "
                    "sessions move at cutover under the launch lock")
    return n


def swap_replica(replica, green, *, prewarm: bool = True) -> float:
    """Blue/green cutover for ONE replica; returns blackout in seconds."""
    blue = replica.scheduler.current_engine()
    if prewarm:
        n = prewarm_like(green, blue)
        logger.info("hot-swap %s: pre-warmed %d compiled keys",
                    replica.name, n)
    return replica.scheduler.swap_engine(green)


def hot_swap(replicas: List, artifact: str, *,
             max_batch_size: Optional[int] = None,
             prewarm: bool = True) -> Dict[str, float]:
    """Swap every replica in `replicas` onto the checkpoint at `artifact`,
    one at a time (the rest of the fleet keeps serving). Returns
    ``{"swap_blackout_ms": worst-case, "swap_total_s": wall,
    "per_replica_ms": {...}}``."""
    from pytorchvideo_accelerate_tpu.serving.engine import InferenceEngine

    t0 = time.perf_counter()
    per: Dict[str, float] = {}
    for replica in replicas:
        blue = replica.scheduler.current_engine()
        # the serving tier's quantization choice survives the swap: a blue
        # int8 replica pre-warms and cuts over to an int8 green even when
        # the new artifact ships fp weights (on-the-fly quantization), and
        # an fp fleet never silently picks up int8 from an embedded config
        streaming_blue = getattr(blue, "supports_sessions", False)
        inner_blue = blue.engine if streaming_blue else blue
        green = InferenceEngine.from_artifact(
            artifact, mesh=inner_blue.mesh,
            max_batch_size=(max_batch_size if max_batch_size is not None
                            else blue.buckets[-1]),
            stats=replica.stats,
            quantization=getattr(inner_blue, "quantization", None))
        if streaming_blue:
            # a streaming replica cuts over to a streaming green: the
            # session surface (budget/TTL) AND the trunk mode carry over,
            # and prewarm_like performs the state carry (raw/slow rings
            # adopt; token/stem rings re-embed and KV rings recompute
            # their masked trunk under the GREEN weights so no cached
            # activation ever outlives the weights that produced it)
            from pytorchvideo_accelerate_tpu.streaming import (
                StreamingEngine,
            )

            green = StreamingEngine(
                green,
                session_budget_mb=blue.session_budget_bytes / 1e6,
                session_ttl_s=blue.table.ttl_s,
                retry_after_s=blue.table.retry_after_s,
                name=blue.name,
                trunk=getattr(blue, "trunk", "full"),
                attn_window=getattr(blue, "attn_window", 0))
        blackout = swap_replica(replica, green, prewarm=prewarm)
        per[replica.name] = round(blackout * 1e3, 3)
        logger.info("hot-swap %s: cutover blackout %.2f ms",
                    replica.name, blackout * 1e3)
    out = {
        "swap_blackout_ms": max(per.values()) if per else 0.0,
        "swap_total_s": round(time.perf_counter() - t0, 3),
        "per_replica_ms": per,
    }
    obs.get_recorder().record("fleet", "hot-swap-complete", **{
        k: v for k, v in out.items() if k != "per_replica_ms"})
    return out
