"""Fleet serving tier: replica pool + router over the serving subsystem.

serving/ turns ONE checkpoint into ONE endpoint; fleet/ turns N of those
into a production tier (ROADMAP item 2; the decoupled-workers-behind-
queues shape of the Podracer architecture, PAPERS.md):

- `scheduler.Scheduler` — continuous-batching replacement for the
  `MicroBatcher` flush policy: per-request deadlines, `realtime`/`batch`
  priority classes, earliest-deadline-first launches into the engine's
  compiled buckets, shed-before-deadline-miss (`ShedError` → PR 6's
  503 + Retry-After);
- `pool.ReplicaPool` — N replicas (in-process `LocalReplica` engines on
  disjoint meshes, or `HttpReplica` processes) with health-gated
  membership driven by each replica's `/healthz` admission state;
- `router.Router` — least-outstanding-requests routing, replica-shed
  failover, route-around on replica death (mid-flight requests
  re-dispatched), per-replica registry labels, fleet-merged stats;
- `hotswap.hot_swap` — zero-downtime blue/green checkpoint swap with
  compiled-cache pre-warm and a measured `swap_blackout_ms`;
- `loadgen.LoadGen` + `pva-tpu-loadgen` — open-loop Poisson load harness
  with a heavy-tailed clip-size mix and SLO verdicts;
- `loadgen.StreamLoadGen` — open-loop arrivals of STREAMS (heavy-tail
  durations, per-session label-latency honesty) driving the stateful
  streaming mode (streaming/; router affinity, /stream);
- `control/` — the fleet-intelligence loops over all of the above
  (ROADMAP item 1): SLO-driven `Autoscaler`, multi-model serving under
  a shared budget (`ModelBudget`/`MultiModelFleet`), and canary rollout
  with escalation-ladder auto-rollback (`CanaryController`).

The router speaks the `MicroBatcher` interface, so `InferenceServer` (and
the whole admission/drain/Retry-After vocabulary) fronts a fleet
unchanged. See docs/SERVING.md § fleet.
"""

from pytorchvideo_accelerate_tpu.fleet.control import (  # noqa: F401
    Autoscaler,
    CanaryController,
    ControlSignals,
    ModelBudget,
    MultiModelFleet,
    SignalReader,
)
from pytorchvideo_accelerate_tpu.fleet.hotswap import (  # noqa: F401
    hot_swap,
    swap_replica,
)
from pytorchvideo_accelerate_tpu.fleet.loadgen import (  # noqa: F401
    LoadGen,
    StreamLoadGen,
    heavy_tail_clip_factory,
)
from pytorchvideo_accelerate_tpu.fleet.pool import (  # noqa: F401
    HttpReplica,
    LocalReplica,
    ReplicaDeadError,
    ReplicaPool,
    spawn_serving_process,
)
from pytorchvideo_accelerate_tpu.fleet.router import Router  # noqa: F401
from pytorchvideo_accelerate_tpu.fleet.scheduler import (  # noqa: F401
    BATCH,
    REALTIME,
    Scheduler,
    ShedError,
)
