"""CLI entrypoint.

Usage (reference-compatible flags, SURVEY §2.1-R1):

    python -m pytorchvideo_accelerate_tpu.run --data_dir /data/kinetics \\
        --is_slowfast --num_frames 32 --sampling_rate 2 --batch_size 8 \\
        --gradient_accumulation_steps 4 --with_tracking \\
        --checkpointing_steps epoch

or with dotted flags (--optim.lr 0.1, --mesh.fsdp 2, ...). Replaces
`accelerate launch run.py <flags>` (run_slowfast_r50.sh): no separate
launcher is needed on TPU — single-host runs start directly; pod runs start
one process per host (the pod scheduler's job) and self-configure via
`jax.distributed` (parallel/distributed.py).
"""

from __future__ import annotations

from typing import Optional, Sequence

from pytorchvideo_accelerate_tpu.config import parse_cli
from pytorchvideo_accelerate_tpu.trainer.loop import Trainer


def main(argv: Optional[Sequence[str]] = None) -> dict:
    cfg = parse_cli(argv)
    trainer = Trainer(cfg)
    return trainer.fit()


if __name__ == "__main__":
    main()
