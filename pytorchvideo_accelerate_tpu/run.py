"""CLI entrypoint.

Usage (reference-compatible flags, SURVEY §2.1-R1):

    python -m pytorchvideo_accelerate_tpu.run --data_dir /data/kinetics \\
        --is_slowfast --num_frames 32 --sampling_rate 2 --batch_size 8 \\
        --gradient_accumulation_steps 4 --with_tracking \\
        --checkpointing_steps epoch

or with dotted flags (--optim.lr 0.1, --mesh.fsdp 2, ...). Replaces
`accelerate launch run.py <flags>` (run_slowfast_r50.sh): no separate
launcher is needed on TPU — single-host runs start directly; pod runs start
one process per host (the pod scheduler's job) and self-configure via
`jax.distributed` (parallel/distributed.py).

`--write_config out.json` resolves all flags/config files into one JSON and
exits — the `accelerate config` workflow (persist once, reuse via
`--config out.json`, override per run with flags).
"""

from __future__ import annotations

from typing import Optional, Sequence

from pytorchvideo_accelerate_tpu.config import parse_cli
from pytorchvideo_accelerate_tpu.trainer.loop import Trainer


def main(argv: Optional[Sequence[str]] = None) -> dict:
    import sys

    argv = list(sys.argv[1:] if argv is None else argv)
    write_to = None
    rest = []
    i = 0
    while i < len(argv):  # both --write_config PATH and --write_config=PATH
        tok = argv[i]
        key = tok[2:].split("=", 1)[0].replace("-", "_") if tok.startswith("--") else ""
        if key == "write_config":
            if "=" in tok:
                write_to = tok.split("=", 1)[1]
                i += 1
            else:
                if i + 1 >= len(argv) or argv[i + 1].startswith("--"):
                    raise SystemExit(f"{tok} requires a file path")
                write_to = argv[i + 1]
                i += 2
            if not write_to:
                raise SystemExit(f"{tok} requires a file path")
        else:
            rest.append(tok)
            i += 1
    argv = rest

    cfg = parse_cli(argv)
    if write_to is not None:
        with open(write_to, "w") as f:
            f.write(cfg.to_json() + "\n")
        print(f"wrote resolved config to {write_to} "
              f"(reuse with --config {write_to})")
        return {"config_written": write_to}
    trainer = Trainer(cfg)
    if cfg.export_inference:
        # checkpoint -> serving artifact, no training: resume (when
        # configured) then write the params-only EMA-resolved export
        try:
            trainer._maybe_resume()
            out = trainer.export_inference(cfg.export_inference)
        finally:
            trainer.close()
        print(f"wrote inference artifact to {out} "
              f"(serve with pva-tpu-serve --serve.checkpoint {out})")
        return {"exported": out}
    if cfg.eval_only:
        return trainer.evaluate()
    return trainer.fit()


if __name__ == "__main__":
    main()
