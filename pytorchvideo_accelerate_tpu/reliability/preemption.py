"""Preemption grace path: SIGTERM/SIGINT → finish the step, save, exit 0.

Preemptible workers are the NORMAL case at pod scale (Podracer
architectures; the tier-1 harness itself kills with `timeout -k`). PR 3's
flight-recorder handler made a kill leave evidence; this makes it leave a
*resumable run*: the trainer polls `guard.requested` once per step (a
thread-safe Event read — no syncs, no locks on the hot path), and on a
request it finishes the in-flight step, writes an emergency checkpoint
(kind "preempt", consumed loader position), dumps the flight record, and
returns normally — exit 0, `resume=auto` lands on the exact step.

Semantics change vs PR 3 (documented in docs/RELIABILITY.md): while a
guard is installed, the FIRST SIGTERM/SIGINT no longer chains into the
flight recorder's re-raise-death path — it requests graceful shutdown
instead (and records/dumps itself). A SECOND signal restores the previous
disposition and re-delivers: stuck drains stay killable. `uninstall()`
(the fit() finally) restores the previous handlers exactly.
"""

from __future__ import annotations

import os
import signal
import threading
import time
from typing import Dict, Optional

from pytorchvideo_accelerate_tpu.reliability.atomic import atomic_write_json

EMERGENCY_RECORD = "emergency_checkpoint.json"

_SIGNALS = (signal.SIGTERM, signal.SIGINT)


class PreemptionGuard:
    """Signal-to-Event adapter with a two-strikes escalation."""

    def __init__(self):
        self._requested = threading.Event()
        self.reason: str = ""
        self._prev: Dict[int, object] = {}
        self._installed = False

    @property
    def requested(self) -> bool:
        return self._requested.is_set()

    def request(self, reason: str = "api") -> None:
        """Programmatic preemption (tests, embedding runtimes)."""
        if not self._requested.is_set():
            self.reason = reason
            self._requested.set()
            self._record(reason)

    def _record(self, reason: str) -> None:
        try:
            from pytorchvideo_accelerate_tpu.obs import get_recorder

            get_recorder().record("preempt", reason)
        except Exception:  # pragma: no cover - obs stays optional
            pass

    # --- signal plumbing (main thread only) -------------------------------

    def _handler(self, signum, frame) -> None:
        if self._requested.is_set():
            # second strike: the grace path is stuck or the operator means
            # it — restore the previous disposition and re-deliver
            self._restore(signum)
            os.kill(os.getpid(), signum)
            return
        name = signal.Signals(signum).name
        self.reason = name
        self._requested.set()
        self._record(name)
        try:
            from pytorchvideo_accelerate_tpu.obs import get_recorder

            get_recorder().dump()  # evidence even if the drain then wedges
        except Exception:  # pragma: no cover
            pass

    def install(self) -> bool:
        """Take over SIGTERM/SIGINT; returns False off the main thread
        (signal.signal raises there — the guard then only serves
        `request()`/`requested`, which is what threaded tests need)."""
        if self._installed:
            return True
        self._requested.clear()
        self.reason = ""
        try:
            for sig in _SIGNALS:
                self._prev[sig] = signal.getsignal(sig)
                signal.signal(sig, self._handler)
        except (ValueError, OSError):  # not the main thread
            self._prev.clear()
            return False
        self._installed = True
        return True

    def _restore(self, signum) -> None:
        prev = self._prev.get(signum)
        if prev is not None:
            try:
                signal.signal(signum, prev)
            except (ValueError, OSError):  # pragma: no cover
                pass

    def uninstall(self) -> None:
        if not self._installed:
            self._requested.clear()
            return
        for sig in _SIGNALS:
            self._restore(sig)
        self._prev.clear()
        self._installed = False
        self._requested.clear()


_DEFAULT = PreemptionGuard()


def get_guard() -> PreemptionGuard:
    """Process-default guard (the trainer installs/uninstalls it around
    fit(); chaos/tests reach the same instance to request or observe)."""
    return _DEFAULT


# --- emergency-checkpoint record --------------------------------------------

def record_emergency(output_dir: str, *, step: int, epoch: int,
                     checkpoint_dir: str, reason: str = "") -> Optional[str]:
    """Atomically drop `<output_dir>/emergency_checkpoint.json` — the
    breadcrumb `pva-tpu-doctor`'s reliability snapshot and operators read
    to find where a preempted run stopped. Best-effort: a failing record
    write must not turn a successful emergency save into a crash."""
    try:
        return atomic_write_json(
            os.path.join(output_dir, EMERGENCY_RECORD),
            {"step": int(step), "epoch": int(epoch),
             "checkpoint_dir": checkpoint_dir, "reason": reason,
             "pid": os.getpid(), "ts": round(time.time(), 6)})
    except OSError:
        return None


def read_emergency_record(output_dir: str) -> Optional[dict]:
    import json

    path = os.path.join(output_dir, EMERGENCY_RECORD)
    try:
        with open(path) as f:
            out = json.load(f)
        out["path"] = path
        return out
    except (OSError, ValueError):
        return None
