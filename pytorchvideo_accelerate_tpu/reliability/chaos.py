"""pva-tpu-chaos: the bundled chaos scenario + console script.

The proving harness for the resilience substrate (docs/RELIABILITY.md),
same contract as `pva-tpu-lint`/`pva-tpu-tsan`: a seeded scenario runs
every recovery path the way production fails, asserts recovery, and
`chaos_findings == 0` gates `bench.py --smoke` and `scripts/analyze.sh`.

Legs (all seeded via one `--seed`, CPU-only, replayable):

- **replay**: the same FaultPlan seed must fire the identical hit
  sequence twice — the property every other leg's determinism rests on;
- **decode**: injected decode failures on a real (tiny, generated) video
  tree; the retry + substitution machinery must deliver every batch;
- **ckpt**: a partial-write fault inside the atomic artifact writer; the
  retry must land a complete artifact and the destination must never
  hold a truncated file — not even transiently;
- **tracker**: a transient tracker outage recovers via retry (no metric
  loss); a permanent one disables the tracker without killing anything;
- **preempt**: a real SIGTERM mid-epoch (slow-worker + slow-dispatch
  faults armed) takes the grace path — emergency checkpoint, flight
  dump, exit 0 — and `resume=auto` lands on the exact step and finishes
  the run;
- **preempt_mesh**: the same grace path across a MESH RESHAPE — a
  forced-host subprocess trains on a (2, 2) (data, model) train mesh,
  SIGTERMs itself mid-run, and a second subprocess resumes with
  `resume=auto` on a (4, 1) mesh: it must land on the exact emergency
  step and finish (the mesh-portable-checkpoint contract,
  docs/PARALLELISM.md runbook);
- **quarantine**: a deterministically-corrupt clip (seeded garbage bytes)
  exhausts its persisted failure budget across runs, lands in the
  quarantine sidecar, and the NEXT run's sampler excludes it with zero
  decode attempts — every epoch still delivers full batches;
- **guard_nan**: seeded NaN poisoning of two consecutive dispatches — the
  in-graph skip absorbs the first, the TrainGuard ladder rolls the second
  back to the last-known-good step with the loader fast-forwarded past
  the poisoned span, the run finishes with finite loss, and the replay
  bundle is loadable + byte-deterministic;
- **collective_hang**: a wedged mesh `psum` (injected delay inside the
  watched section, forced-host child) trips the watchdog DURING the
  wedge with per-host, per-op attribution — evidence before the external
  kill;
- **dataplane_kill**: two real decode-worker processes feed a
  RemoteClipFeed; one is SIGKILLed mid-epoch with leases outstanding —
  the unacked span re-leases to the survivor, the batch stream stays
  byte-identical to the local loader's (zero duplicate/missing), and the
  dead worker's quarantine verdicts survive in the persisted sidecar;
- **serve**: synthetic overload against a micro-batcher + admission
  controller — load sheds with 503/Retry-After semantics before latency
  collapses, an injected flush fault fails one batch (not the thread),
  and the service recovers to `healthy`, then drains clean;
- **replica_kill**: two real serving processes (stub engine behind the
  REAL `InferenceServer` + fleet `Scheduler`) behind the fleet router
  under open-loop load; one replica is SIGKILLed mid-load — the router
  must route around it (mid-flight requests re-dispatched, ZERO non-shed
  failures), pool membership must drop it within the health-check
  interval, and the surviving replica's p99 must return under the SLO;
- **stream_replica_kill**: two real serving processes (session-capable
  stub stream engine behind the REAL `InferenceServer` /stream +
  `Scheduler` session launches) behind the affinity router, holding
  LIVE streaming sessions; the replica holding sessions is SIGKILLed
  mid-stream — affinity re-routes to the survivor, every affected
  session re-establishes DETERMINISTICALLY from its client's resendable
  window (each label's logits must equal the window-content expectation
  recomputed client-side, i.e. the stream resumes at the correct window
  position), with zero non-shed client-visible failures.

Exit codes: 0 clean, 1 findings, 2 usage.
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import sys
import tempfile
import threading
import time
from typing import Callable, List, Optional

from pytorchvideo_accelerate_tpu.reliability import faults
from pytorchvideo_accelerate_tpu.reliability.faults import FaultPlan, FaultSpec
from pytorchvideo_accelerate_tpu.reliability.preemption import (
    get_guard,
    read_emergency_record,
)
from pytorchvideo_accelerate_tpu.utils.sync import make_thread

Log = Callable[[str], None]


def _leg(report: dict, name: str) -> dict:
    out = report["legs"].setdefault(name, {})
    return out


def _finding(report: dict, leg: str, msg: str) -> None:
    report["findings"].append(f"{leg}: {msg}")


# --- legs -------------------------------------------------------------------

def leg_replay(report: dict, seed: int, log: Log) -> None:
    """Same seed → byte-identical fault sequence (the determinism every
    chaos assertion rests on)."""
    leg = _leg(report, "replay")

    def one_run() -> List[tuple]:
        faults.arm(FaultPlan(seed, [
            FaultSpec("decode.read", kind="raise", p=0.4),
            FaultSpec("prefetch.h2d", kind="delay", p=0.3, delay_s=0.0),
        ]))
        try:
            for _ in range(64):
                try:
                    faults.fault_point("decode.read")
                except faults.InjectedFault:
                    pass
                faults.fault_point("prefetch.h2d")
        finally:
            faults.disarm()
        return [(e["point"], e["hit"], e["kind"])
                for e in faults.fault_history()]

    a, b = one_run(), one_run()
    leg["fires"] = len(a)
    if not a:
        _finding(report, "replay", "seeded plan fired nothing in 64 hits")
    if a != b:
        _finding(report, "replay",
                 f"fault sequence not replayable: {a[:5]} != {b[:5]}")
    log(f"[chaos] replay: {len(a)} fires, sequences identical={a == b}")


def _write_video_tree(root: str, n_per_class: int = 2) -> bool:
    """Tiny real mp4 tree (2 classes); False when the codec is missing."""
    try:
        import cv2
        import numpy as np
    except Exception:
        return False
    rng = np.random.default_rng(0)
    for c in range(2):
        d = os.path.join(root, f"class{c}")
        os.makedirs(d, exist_ok=True)
        for v in range(n_per_class):
            wr = cv2.VideoWriter(os.path.join(d, f"v{v}.mp4"),
                                 cv2.VideoWriter_fourcc(*"mp4v"),
                                 30.0, (48, 32))
            if not wr.isOpened():
                return False
            for _ in range(12):
                wr.write(rng.integers(0, 255, (32, 48, 3), np.uint8))
            wr.release()
    return True


def leg_decode(report: dict, tmpdir: str, seed: int, log: Log) -> None:
    """Injected decode failures must never cost a batch: transient ones
    recover via retry, persistent ones via the substitution path."""
    from pytorchvideo_accelerate_tpu.data.manifest import scan_directory
    from pytorchvideo_accelerate_tpu.data.pipeline import (
        ClipLoader,
        VideoClipSource,
    )
    from pytorchvideo_accelerate_tpu.data.transforms import make_transform

    leg = _leg(report, "decode")
    root = os.path.join(tmpdir, "videos")
    if not _write_video_tree(root, n_per_class=3):
        leg["skipped"] = "no mp4 codec on this host"
        log("[chaos] decode: skipped (no codec)")
        return
    tf = make_transform(training=True, num_frames=4, crop_size=24,
                        min_short_side_scale=26, max_short_side_scale=30)
    src = VideoClipSource(scan_directory(root), tf, clip_duration=0.2,
                          training=True, seed=seed, decode_retries=2,
                          retry_base_delay_s=0.001)
    # num_workers=1: the per-point hit SEQUENCE is seeded either way, but
    # a single decode thread makes the whole leg's timeline replayable
    loader = ClipLoader(src, global_batch_size=2, shuffle=True,
                        num_workers=1, seed=seed)
    faults.arm(FaultPlan(seed, [FaultSpec("decode.read", kind="raise",
                                          p=0.35)]))
    try:
        batches = sum(1 for _ in loader.epoch(0))
    finally:
        faults.disarm()
        loader.close()
    fires = len(faults.fault_history())
    leg.update(batches=batches, fires=fires)
    want = loader.batches_per_epoch()
    if batches != want:
        _finding(report, "decode",
                 f"epoch yielded {batches}/{want} batches under faults")
    if fires == 0:
        _finding(report, "decode", "no decode faults fired (vacuous leg)")
    log(f"[chaos] decode: {batches}/{want} batches with {fires} injected "
        "failures")


def leg_ckpt(report: dict, tmpdir: str, seed: int, log: Log) -> None:
    """A write that dies mid-file must retry into a COMPLETE artifact and
    never leave a truncated file at the destination."""
    import jax.numpy as jnp
    import optax

    from pytorchvideo_accelerate_tpu.reliability.atomic import (
        atomic_write_bytes,
    )
    from pytorchvideo_accelerate_tpu.trainer.checkpoint import (
        export_inference,
        load_inference,
    )
    from pytorchvideo_accelerate_tpu.trainer.train_state import TrainState

    leg = _leg(report, "ckpt")
    state = TrainState.create(
        {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}, {}, optax.sgd(0.1))
    art = os.path.join(tmpdir, "artifact")
    # one partial-write on the FIRST write of the export: the tmp file is
    # truncated and the write raises; the retry must produce a complete
    # artifact, and the destination must never have held the prefix
    faults.arm(FaultPlan(seed, [FaultSpec("ckpt.write",
                                          kind="partial_write",
                                          at_hits=(0,), max_fires=1)]))
    try:
        export_inference(art, state, meta={"num_classes": 4,
                                           "model": "tiny"})
    finally:
        faults.disarm()
    fires = len(faults.fault_history())
    try:
        params, _stats, meta = load_inference(art)
        complete = "w" in params and meta.get("num_classes") == 4
    except Exception as e:  # noqa: BLE001 - the artifact IS the assertion
        complete = False
        leg["load_error"] = f"{type(e).__name__}: {e}"
    leftovers = [f for f in os.listdir(art) if ".tmp" in f]
    leg.update(fires=fires, complete=complete, tmp_leftovers=leftovers)
    if fires != 1:
        _finding(report, "ckpt", f"expected 1 injected write fault, {fires}")
    if not complete:
        _finding(report, "ckpt", "artifact incomplete after retried write")
    if leftovers:
        _finding(report, "ckpt", f"tmp files left behind: {leftovers}")
    # and when every attempt dies, the destination must simply not exist
    dead = os.path.join(tmpdir, "dead.json")
    faults.arm(FaultPlan(seed, [FaultSpec("ckpt.write", kind="raise")]))
    try:
        atomic_write_bytes(dead, b"{}")
    except faults.InjectedFault:
        pass
    else:
        _finding(report, "ckpt", "always-failing write did not raise")
    finally:
        faults.disarm()
    if os.path.exists(dead):
        _finding(report, "ckpt", "failed write left a destination file")
    log(f"[chaos] ckpt: retried partial write -> complete={complete}, "
        f"no tmp leftovers={not leftovers}")


def leg_tracker(report: dict, tmpdir: str, seed: int, log: Log) -> None:
    """Transient tracker outage: retry recovers, zero metric loss.
    Permanent outage: disabled after the budget, run unharmed."""
    from pytorchvideo_accelerate_tpu.trainer.tracking import TrackerHub

    leg = _leg(report, "tracker")
    logdir = os.path.join(tmpdir, "logs")
    hub = TrackerHub("jsonl", logdir, retries=3)
    hub.start("chaosrun", {})
    # hit 0 was start(); fail the first attempt of the first two log()
    # fan-outs — each retries into the next hit, which succeeds
    faults.arm(FaultPlan(seed, [FaultSpec("tracker.log", kind="raise",
                                          at_hits=(1, 3), max_fires=2)]))
    try:
        for i in range(5):
            hub.log({"x": float(i)}, step=i)
    finally:
        faults.disarm()
    fires = len(faults.fault_history())
    hub.finish()
    path = os.path.join(logdir, "chaosrun.jsonl")
    with open(path) as f:
        steps = [json.loads(ln).get("step") for ln in f
                 if "step" in ln]
    leg.update(fires=fires, survivors=len(hub.trackers), logged=len(steps))
    if len(hub.trackers) != 1:
        _finding(report, "tracker",
                 "transient outage disabled the tracker despite retries")
    if sorted(s for s in steps if s is not None) != [0, 1, 2, 3, 4]:
        _finding(report, "tracker", f"metric loss under outage: {steps}")
    # permanent outage: every attempt fails -> disabled, nothing raises
    hub2 = TrackerHub("jsonl", logdir, retries=2)
    hub2.start("chaosrun2", {})
    faults.arm(FaultPlan(seed, [FaultSpec("tracker.log", kind="raise")]))
    try:
        hub2.log({"x": 1.0}, step=0)
    finally:
        faults.disarm()
    if hub2.trackers:
        _finding(report, "tracker",
                 "permanently failing tracker was not disabled")
    hub2.finish()
    log(f"[chaos] tracker: {fires} injected failures, "
        f"{len(steps)} steps logged, survivors={len(hub.trackers)}")


def leg_preempt(report: dict, tmpdir: str, seed: int, log: Log) -> None:
    """Mid-epoch SIGTERM under slow-worker faults: grace path saves at the
    consumed step, exits clean; resume=auto lands exactly there and
    finishes the run.

    This leg is also the live regression test for the resume
    re-materialization in `Checkpointer.restore`: resuming mid-epoch and
    TRAINING on the restored state with jax's persistent compilation
    cache enabled (bench configures one) heap-corrupted the pinned
    jaxlib until restore started copying every leaf into an XLA-owned
    buffer."""
    from pytorchvideo_accelerate_tpu.config import (
        CheckpointConfig, DataConfig, ModelConfig, OptimConfig, TrainConfig,
    )
    from pytorchvideo_accelerate_tpu.trainer.loop import Trainer

    leg = _leg(report, "preempt")
    outdir = os.path.join(tmpdir, "run")

    def cfg(resume: str = "") -> TrainConfig:
        return TrainConfig(
            model=ModelConfig(name="tiny3d", num_classes=4,
                              dropout_rate=0.0),
            data=DataConfig(synthetic=True, synthetic_num_videos=16,
                            num_frames=4, crop_size=24, batch_size=2,
                            num_workers=1, limit_val_batches=1),
            optim=OptimConfig(num_epochs=2, lr=0.01),
            checkpoint=CheckpointConfig(output_dir=outdir,
                                        resume_from_checkpoint=resume),
            seed=seed,
        )

    tr = Trainer(cfg())
    total = tr.total_steps
    # slow worker + slow dispatch: every step pays an injected delay, so
    # the SIGTERM below always lands mid-epoch, never after the run
    faults.arm(FaultPlan(seed, [
        FaultSpec("step.dispatch", kind="delay", p=1.0, delay_s=0.05),
        FaultSpec("prefetch.h2d", kind="delay", p=0.5, delay_s=0.01),
    ]))
    # pre-install the guard so the kill can never race the dump-only
    # handler during Trainer warmup; fit()'s own install is then a no-op
    get_guard().install()
    in_fit = threading.Event()
    in_fit.set()

    def killer():
        time.sleep(0.4)
        if in_fit.is_set():  # a real signal, mid-epoch, main thread
            os.kill(os.getpid(), __import__("signal").SIGTERM)

    kt = make_thread(target=killer, name="chaos-sigterm", daemon=True)
    kt.start()
    try:
        res = tr.fit()
    finally:
        in_fit.clear()
        kt.join(timeout=5.0)
        faults.disarm()
        get_guard().uninstall()
    rec = read_emergency_record(outdir)
    leg.update(preempted=bool(res.get("preempted")),
               stopped_at=res.get("steps"),
               emergency=rec and {"step": rec["step"],
                                  "reason": rec.get("reason")},
               total_steps=total)
    if not res.get("preempted"):
        _finding(report, "preempt", "SIGTERM did not take the grace path")
        return
    if rec is None:
        _finding(report, "preempt", "no emergency_checkpoint.json record")
        return
    if not 0 < rec["step"] < total:
        _finding(report, "preempt",
                 f"emergency step {rec['step']} not mid-run (total {total})")
    # recovery: resume=auto must land on the EXACT saved step and finish
    tr2 = Trainer(cfg(resume="auto"))
    latest = tr2.checkpointer.latest_step()
    if latest != rec["step"]:
        _finding(report, "preempt",
                 f"resume=auto found step {latest}, emergency saved "
                 f"{rec['step']}")
    res2 = tr2.fit()
    leg["resumed_to"] = res2.get("steps")
    if res2.get("preempted") or res2.get("steps") != total:
        _finding(report, "preempt",
                 f"resumed run did not complete: {res2.get('steps')}/"
                 f"{total} steps")
    log(f"[chaos] preempt: SIGTERM at step {rec['step']}/{total}, "
        f"resumed and finished at {res2.get('steps')}")


# the (data, model) shapes the mesh-reshape preemption leg crosses, and the
# forced-host device count both subprocesses run under
_MESH_LEG_DEVICES = 4
_MESH_LEG_TRAIN = (2, 2)
_MESH_LEG_RESUME = (4, 1)

# subprocess body for both phases of leg_preempt_mesh: train tiny3d on a
# (data, model) train mesh; in "kill" mode, self-SIGTERM shortly after fit
# starts (the preemption guard is pre-installed, so the signal takes the
# grace path wherever it lands — compile or step loop) and report the
# emergency record; in resume mode, resume=auto on the reshaped mesh and
# report the step it landed on. One JSON line to stdout (forcehost
# contract).
_MESH_LEG_CODE = """
import json, os, sys, threading, time
import jax
jax.config.update("jax_platforms", "cpu")
from pytorchvideo_accelerate_tpu.config import (
    CheckpointConfig, DataConfig, MeshConfig, ModelConfig, OptimConfig,
    TrainConfig)
from pytorchvideo_accelerate_tpu.reliability.preemption import (
    get_guard, read_emergency_record)
from pytorchvideo_accelerate_tpu.trainer.loop import Trainer

outdir, kill, data_ax, model_ax, seed = (
    {outdir!r}, {kill!r} == "kill", {data_ax}, {model_ax}, {seed})
cfg = TrainConfig(
    mesh=MeshConfig(data=data_ax, model=model_ax),
    model=ModelConfig(name="tiny3d", num_classes=4, dropout_rate=0.0),
    data=DataConfig(synthetic=True, synthetic_num_videos=16, num_frames=4,
                    crop_size=24, batch_size=2, num_workers=1,
                    limit_val_batches=1),
    optim=OptimConfig(num_epochs=2, lr=0.01),
    checkpoint=CheckpointConfig(output_dir=outdir,
                                resume_from_checkpoint="" if kill
                                else "auto"),
    seed=seed,
)
tr = Trainer(cfg)
found = (tr.checkpointer.latest_step()
         if (not kill and tr.checkpointer) else None)
if kill:
    get_guard().install()  # never race the dump-only default handler
    t = threading.Thread(
        target=lambda: (time.sleep(0.5),
                        os.kill(os.getpid(), __import__("signal").SIGTERM)),
        daemon=True)
    t.start()
res = tr.fit()
rec = read_emergency_record(outdir)
out = {{"mesh": [data_ax, model_ax], "preempted": bool(res.get("preempted")),
        "steps": res.get("steps"), "total": tr.total_steps,
        "emergency_step": rec and rec.get("step"), "found": found}}
print("\\n" + json.dumps(out))
"""


def leg_preempt_mesh(report: dict, tmpdir: str, seed: int, log: Log) -> None:
    """Preemption grace across a mesh reshape: SIGTERM on a (2, 2) train
    mesh, emergency save, `resume=auto` on (4, 1) lands on the same step
    and finishes. Both halves run in forced-host subprocesses (this
    process's device count latched at backend init and cannot change), so
    the leg exercises the REAL cross-shape restore path — orbax resharding
    into the new mesh's layouts — not an in-process approximation."""
    from pytorchvideo_accelerate_tpu.utils.forcehost import run_forced_host

    leg = _leg(report, "preempt_mesh")
    outdir = os.path.join(tmpdir, "mesh_run")

    def phase(kill: str, shape) -> dict:
        return run_forced_host(
            _MESH_LEG_CODE.format(outdir=outdir, kill=kill,
                                  data_ax=shape[0], model_ax=shape[1],
                                  seed=seed),
            _MESH_LEG_DEVICES, timeout=420.0)

    a = phase("kill", _MESH_LEG_TRAIN)
    leg["train"] = a
    if not a.get("preempted"):
        _finding(report, "preempt_mesh",
                 "SIGTERM did not take the grace path on the (2,2) mesh")
        return
    if not a.get("emergency_step"):
        _finding(report, "preempt_mesh", "no emergency checkpoint record")
        return
    b = phase("resume", _MESH_LEG_RESUME)
    leg["resume"] = b
    # resume=auto must FIND the exact emergency step (the reshaped restore
    # re-places every leaf under the new mesh's shardings; a step drift
    # means it read stale or partial state), then run to completion
    if b.get("found") != a["emergency_step"]:
        _finding(report, "preempt_mesh",
                 f"resume=auto on the reshaped mesh found step "
                 f"{b.get('found')}, emergency saved {a['emergency_step']}")
    if b.get("preempted") or (b.get("steps") or 0) < a["emergency_step"]:
        _finding(report, "preempt_mesh",
                 f"resume on the reshaped mesh did not complete: {b}")
        return
    log(f"[chaos] preempt_mesh: SIGTERM at step {a['emergency_step']} on "
        f"mesh {a['mesh']}, resume=auto on {b['mesh']} landed on the same "
        f"step and finished at {b['steps']}")


# subprocess body for both phases of leg_preempt_pipeline: VideoMAE-tiny
# PRETRAIN on a (data, model) train mesh, pipelined over the model axis in
# the kill phase (parallel/pipeline.py) and UNPIPELINED on the reshaped
# resume mesh — the checkpoint-interchange contract the pipeline's
# param-tree identity exists to keep. Same forcehost one-JSON-line shape
# as _MESH_LEG_CODE.
_PIPELINE_LEG_CODE = """
import json, os, sys, threading, time
import jax
jax.config.update("jax_platforms", "cpu")
from pytorchvideo_accelerate_tpu.config import (
    CheckpointConfig, DataConfig, MeshConfig, ModelConfig, OptimConfig,
    ParallelConfig, TrainConfig)
from pytorchvideo_accelerate_tpu.reliability.preemption import (
    get_guard, read_emergency_record)
from pytorchvideo_accelerate_tpu.trainer.loop import Trainer

outdir, kill, data_ax, model_ax, stages, bsz, seed = (
    {outdir!r}, {kill!r} == "kill", {data_ax}, {model_ax}, {stages},
    {bsz}, {seed})
cfg = TrainConfig(
    mesh=MeshConfig(data=data_ax, model=model_ax),
    parallel=ParallelConfig(pipeline_stages=stages,
                            pipeline_microbatches=2 if stages > 1 else 0),
    model=ModelConfig(name="videomae_t_pretrain", num_classes=4,
                      dropout_rate=0.0),
    data=DataConfig(synthetic=True, synthetic_num_videos=16, num_frames=4,
                    crop_size=32, batch_size=bsz, num_workers=1,
                    limit_val_batches=1),
    optim=OptimConfig(num_epochs=2, lr=0.01),
    checkpoint=CheckpointConfig(output_dir=outdir,
                                resume_from_checkpoint="" if kill
                                else "auto"),
    seed=seed,
)
tr = Trainer(cfg)
found = (tr.checkpointer.latest_step()
         if (not kill and tr.checkpointer) else None)
if kill:
    get_guard().install()  # never race the dump-only default handler
    t = threading.Thread(
        target=lambda: (time.sleep(0.5),
                        os.kill(os.getpid(), __import__("signal").SIGTERM)),
        daemon=True)
    t.start()
res = tr.fit()
rec = read_emergency_record(outdir)
out = {{"mesh": [data_ax, model_ax], "stages": stages,
        "preempted": bool(res.get("preempted")),
        "steps": res.get("steps"), "total": tr.total_steps,
        "emergency_step": rec and rec.get("step"), "found": found,
        "bubble_frac": res.get("pipeline_bubble_frac_analytic")}}
print("\\n" + json.dumps(out))
"""


def leg_preempt_pipeline(report: dict, tmpdir: str, seed: int,
                         log: Log) -> None:
    """Leg 14 — preemption grace across a PIPELINE layout change: SIGTERM
    mid-pipelined-epoch on a (2, 2) mesh running the VideoMAE pretrain
    trunk as a 2-stage pipeline, emergency save, `resume=auto` on (4, 1)
    UNPIPELINED lands on the exact step and finishes. Extends
    leg_preempt_mesh to the pipelined layout: the stage pipeline keeps
    its param tree identical to the plain model (parallel/pipeline.py),
    so the reshaped restore needs no conversion — this leg is the
    runtime proof. Both phases keep the same GLOBAL batch (per-shard
    batch_size compensates for the data-axis change), so steps/epoch and
    the resume arithmetic line up across layouts."""
    from pytorchvideo_accelerate_tpu.utils.forcehost import run_forced_host

    leg = _leg(report, "preempt_pipeline")
    outdir = os.path.join(tmpdir, "pipeline_run")

    def phase(kill: str, shape, stages: int, bsz: int) -> dict:
        return run_forced_host(
            _PIPELINE_LEG_CODE.format(outdir=outdir, kill=kill,
                                      data_ax=shape[0], model_ax=shape[1],
                                      stages=stages, bsz=bsz, seed=seed),
            _MESH_LEG_DEVICES, timeout=420.0)

    # global batch 8 in both phases: (2,2) pipelined at 4/shard,
    # (4,1) unpipelined at 2/shard
    a = phase("kill", _MESH_LEG_TRAIN, stages=2, bsz=4)
    leg["train"] = a
    if not a.get("preempted"):
        _finding(report, "preempt_pipeline",
                 "SIGTERM did not take the grace path on the pipelined "
                 "(2,2) mesh")
        return
    if not a.get("emergency_step"):
        _finding(report, "preempt_pipeline",
                 "no emergency checkpoint record from the pipelined run")
        return
    b = phase("resume", _MESH_LEG_RESUME, stages=1, bsz=2)
    leg["resume"] = b
    if b.get("found") != a["emergency_step"]:
        _finding(report, "preempt_pipeline",
                 f"resume=auto unpipelined on the reshaped mesh found step "
                 f"{b.get('found')}, emergency saved {a['emergency_step']}")
    if b.get("preempted") or (b.get("steps") or 0) < a["emergency_step"]:
        _finding(report, "preempt_pipeline",
                 f"unpipelined resume did not complete: {b}")
        return
    log(f"[chaos] preempt_pipeline: SIGTERM at step {a['emergency_step']} "
        f"on pipelined mesh {a['mesh']} (P={a['stages']}), resume=auto "
        f"unpipelined on {b['mesh']} landed on the same step and finished "
        f"at {b['steps']}")


# serving-control-plane engine double (bucket geometry + a host-side
# forward slow enough to build a queue; no jax — the serving legs measure
# the control plane, not the chip) — the shared serving/stub.py double
from pytorchvideo_accelerate_tpu.serving.stub import StubEngine as _StubEngine  # noqa: E402


def leg_serve(report: dict, seed: int, log: Log) -> None:
    """Synthetic overload: shed with Retry-After semantics before the
    queue saturates, survive an injected flush fault, recover to healthy,
    then drain clean."""
    import numpy as np

    from pytorchvideo_accelerate_tpu.serving.admission import (
        AdmissionController,
    )
    from pytorchvideo_accelerate_tpu.serving.batcher import (
        MicroBatcher,
        QueueFullError,
    )
    from pytorchvideo_accelerate_tpu.serving.stats import ServingStats

    leg = _leg(report, "serve")
    stats = ServingStats(window=256)
    mb = MicroBatcher(_StubEngine(forward_s=0.02), max_wait_ms=1.0,
                      max_queue=16, stats=stats, retry_after_s=0.25)
    stats.queue_depth_fn = mb.queue_depth
    ac = AdmissionController(max_queue=16, shed_frac=0.5, recover_frac=0.2,
                             retry_after_s=0.25)
    clip = {"video": np.zeros((2, 4, 4, 3), np.float32)}
    served, shed, errors = [], [], []
    # one injected flush failure partway through the flood: the batch's
    # futures must fail (the 500 path) without taking the flush thread
    faults.arm(FaultPlan(seed, [FaultSpec("serve.flush", kind="raise",
                                          at_hits=(3,), max_fires=1)]))

    def client(k: int):
        # open-loop arrival: submit the whole burst without waiting
        # (overload means arrivals outrun the drain), collect at the end;
        # the admit-then-submit sequence mirrors server.py's do_POST
        futs = []
        for _ in range(40):
            ok, retry_after = ac.admit(mb.queue_depth())
            if not ok:
                stats.observe_shed(ac.state())
                shed.append(retry_after)
                continue
            try:
                futs.append(mb.submit(clip))
            except QueueFullError as e:
                shed.append(e.retry_after_s)
        for fut in futs:
            try:
                served.append(fut.result(timeout=30.0))
            except Exception as e:  # noqa: BLE001 - injected flush fault
                errors.append(type(e).__name__)

    try:
        ts = [make_thread(target=client, args=(k,), name=f"chaos-client-{k}",
                          daemon=True) for k in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30.0)
        # the flood is over: depth drains, the state machine must recover
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and mb.queue_depth() > 0:
            time.sleep(0.01)
        ac.admit(mb.queue_depth())  # drives degraded -> healthy
        recovered = ac.state()
        # drain: stop admitting, flush in-flight, close
        ac.start_draining()
        drained_admit = ac.admit(0)
        drained = mb.drain(timeout_s=5.0)
    finally:
        faults.disarm()
        mb.close()
    fires = len(faults.fault_history())
    snap = stats.snapshot()
    leg.update(served=len(served), shed=len(shed), errors=len(errors),
               flush_fires=fires, recovered_state=recovered,
               stats_shed=snap["shed"], drained=drained)
    if not shed:
        _finding(report, "serve", "overload never shed (vacuous leg)")
    if shed and min(shed) <= 0:
        _finding(report, "serve", "shed without a Retry-After hint")
    if not served:
        _finding(report, "serve", "overload starved every request")
    if fires and not errors:
        _finding(report, "serve",
                 "injected flush fault did not surface to any future")
    if recovered != "healthy":
        _finding(report, "serve",
                 f"state stuck at {recovered!r} after the flood drained")
    if snap["shed"] <= 0:
        _finding(report, "serve", "shed counter not visible on /stats")
    if drained_admit[0]:
        _finding(report, "serve", "draining state admitted a request")
    if not drained:
        _finding(report, "serve", "drain left requests queued")
    log(f"[chaos] serve: {len(served)} served, {len(shed)} shed, "
        f"{len(errors)} failed by injected flush fault, "
        f"recovered={recovered!r}, drained={drained}")


# subprocess body for leg_replica_kill: the shared stub engine (host-side
# forward, no model compile) behind the REAL fleet Scheduler +
# InferenceServer, so the leg's HTTP surface, shed mapping, and /healthz
# state are production code. One JSON line {{"url": ...}} to stdout once
# bound, then serve.
_REPLICA_SRV_CODE = """
import json
from pytorchvideo_accelerate_tpu.fleet.scheduler import Scheduler
from pytorchvideo_accelerate_tpu.serving.server import InferenceServer
from pytorchvideo_accelerate_tpu.serving.stats import ServingStats
from pytorchvideo_accelerate_tpu.serving.stub import StubEngine

engine = StubEngine(forward_s={forward_s})
engine.model_name = "chaos-stub"
stats = ServingStats(window=512)
sched = Scheduler(engine, stats=stats, max_queue=128,
                  realtime_deadline_ms=10000.0)
srv = InferenceServer(engine, sched, stats, host="127.0.0.1", port=0,
                      request_timeout_s=30.0)
host, port = srv.address
print(json.dumps({{"url": "http://%s:%d" % (host, port)}}), flush=True)
srv.serve_forever(drain_on_sigterm=False)
"""

# SLO the surviving replica's post-kill probe burst must hold: the stub
# forward is ~5 ms, so 500 ms p99 means "recovered", not "fast hardware"
_KILL_RECOVERY_SLO_MS = 500.0


def _read_url_line(proc, timeout_s: float = 90.0) -> str:
    """First stdout line of a replica subprocess, with a deadline (a
    replica that never binds must fail the leg, not hang the scenario);
    the wedge-safe reader lives in fleet/pool.py, shared with every other
    spawn site."""
    from pytorchvideo_accelerate_tpu.fleet.pool import read_line_with_deadline

    line, eof = read_line_with_deadline(proc, timeout_s,
                                        name="chaos-replica-read")
    if not (line or "").strip():
        raise RuntimeError(
            f"replica subprocess {'closed stdout' if eof else 'produced no URL'}"
            f" within {timeout_s}s (exit={proc.poll()})")
    return json.loads(line)["url"]


def leg_replica_kill(report: dict, seed: int, log: Log) -> None:
    """SIGKILL one of two serving processes mid-load: the fleet router
    routes around it with zero non-shed failures, membership drops it
    within the health interval, and recovered p99 holds the SLO."""
    import signal as _signal
    import subprocess

    import numpy as np

    from pytorchvideo_accelerate_tpu.fleet.loadgen import (
        LoadGen,
        heavy_tail_clip_factory,
    )
    from pytorchvideo_accelerate_tpu.fleet.pool import (
        HttpReplica,
        ReplicaPool,
    )
    from pytorchvideo_accelerate_tpu.fleet.router import Router

    leg = _leg(report, "replica_kill")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    procs: List[subprocess.Popen] = []
    router = None
    health_interval_s = 0.25
    try:
        for _ in range(2):
            procs.append(subprocess.Popen(
                [sys.executable, "-c",
                 _REPLICA_SRV_CODE.format(forward_s=0.005)],
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL, text=True))
        replicas = [HttpReplica(f"kill-{i}", _read_url_line(p),
                                pid=p.pid, timeout_s=20.0)
                    for i, p in enumerate(procs)]
        pool = ReplicaPool(replicas, health_interval_s=health_interval_s)
        router = Router(pool, retries=3)

        clip = {"video": np.zeros((2, 4, 4, 3), np.float32)}
        kill_at: dict = {}

        def killer():
            time.sleep(0.8)  # mid-load, by construction
            kill_at["t"] = time.monotonic()
            os.kill(procs[0].pid, _signal.SIGKILL)

        kt = make_thread(target=killer, name="chaos-replica-kill",
                         daemon=True)
        kt.start()
        load = LoadGen(router.submit, rate_rps=40.0, duration_s=2.5,
                       clip_factory=heavy_tail_clip_factory(clip),
                       seed=seed).run()
        kt.join(timeout=5.0)
        # membership: the dead replica must leave the routable set within
        # (about) one health interval of the kill — route-around on the
        # request path is immediate, this checks the poller's verdict too
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline and len(pool.routable()) != 1:
            time.sleep(0.02)
        detected_s = time.monotonic() - kill_at.get("t", time.monotonic())
        routable = len(pool.routable())
        # recovery: a fresh probe burst rides the survivor only; its p99
        # must be back under the SLO (no lingering dead-replica timeouts)
        probe = LoadGen(router.submit, rate_rps=40.0, duration_s=1.0,
                        clip_factory=heavy_tail_clip_factory(clip),
                        seed=seed + 1).run()
        snap = router.fleet_snapshot()
        leg.update(load={k: load[k] for k in
                         ("offered", "completed", "failed", "shed",
                          "p99_ms", "open_loop_ok")},
                   probe={k: probe[k] for k in
                          ("completed", "failed", "p99_ms")},
                   routable=routable,
                   detected_s=round(detected_s, 3),
                   router_retries=snap.get("router_retries"))
        if load["failed"] > 0:
            _finding(report, "replica_kill",
                     f"{int(load['failed'])} non-shed failures under the "
                     "kill (route-around must re-dispatch)")
        if routable != 1:
            _finding(report, "replica_kill",
                     f"dead replica still routable ({routable} routable "
                     f"after {detected_s:.2f}s; interval "
                     f"{health_interval_s}s)")
        if probe["failed"] > 0 or probe["completed"] <= 0:
            _finding(report, "replica_kill",
                     f"post-kill probe burst unhealthy: {probe}")
        if probe["p99_ms"] > _KILL_RECOVERY_SLO_MS:
            _finding(report, "replica_kill",
                     f"recovered p99 {probe['p99_ms']} ms > "
                     f"{_KILL_RECOVERY_SLO_MS} ms SLO")
        log(f"[chaos] replica_kill: {int(load['completed'])} served "
            f"through the kill ({int(load['failed'])} failed, "
            f"{snap.get('router_retries')} re-dispatched), dead replica "
            f"out in {detected_s:.2f}s, recovered p99 "
            f"{probe['p99_ms']} ms")
    finally:
        if router is not None:
            router.close()
        for p in procs:
            try:
                p.kill()
                p.wait(timeout=10.0)
            except Exception:
                pass


# subprocess body for leg_stream_replica_kill: the session-capable stub
# stream engine behind the REAL fleet Scheduler (session launches) +
# InferenceServer (/stream endpoint, 409 resend protocol). One JSON line
# {{"url": ...}} once bound, then serve.
_STREAM_SRV_CODE = """
import json
from pytorchvideo_accelerate_tpu.fleet.scheduler import Scheduler
from pytorchvideo_accelerate_tpu.serving.server import InferenceServer
from pytorchvideo_accelerate_tpu.serving.stats import ServingStats
from pytorchvideo_accelerate_tpu.serving.stub import StubStreamEngine

engine = StubStreamEngine(forward_s={forward_s})
engine.model_name = "chaos-stream-stub"
stats = ServingStats(window=512)
sched = Scheduler(engine, stats=stats, max_queue=128,
                  realtime_deadline_ms=10000.0)
srv = InferenceServer(engine, sched, stats, host="127.0.0.1", port=0,
                      request_timeout_s=30.0)
host, port = srv.address
print(json.dumps({{"url": "http://%s:%d" % (host, port)}}), flush=True)
srv.serve_forever(drain_on_sigterm=False)
"""


def leg_stream_replica_kill(report: dict, seed: int, log: Log) -> None:
    """SIGKILL the replica holding live streaming sessions mid-stream:
    affinity re-routes every session to the survivor, re-establish from
    the client's resendable window is deterministic (label logits equal
    the window-content expectation recomputed client-side — the stream
    resumes at the correct window position, not merely 'somewhere'),
    and nothing fails non-shed."""
    import signal as _signal
    import subprocess

    import numpy as np

    from pytorchvideo_accelerate_tpu.fleet.pool import (
        HttpReplica,
        ReplicaPool,
    )
    from pytorchvideo_accelerate_tpu.fleet.router import Router
    from pytorchvideo_accelerate_tpu.serving.stub import stub_stream_logits

    leg = _leg(report, "stream_replica_kill")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    T, S, HW, NCLS = 8, 2, 4, 4
    n_sessions, n_advances, kill_after = 4, 10, 4
    procs: List[subprocess.Popen] = []
    router = None
    rng = np.random.default_rng(seed)
    try:
        for _ in range(2):
            procs.append(subprocess.Popen(
                [sys.executable, "-c",
                 _STREAM_SRV_CODE.format(forward_s=0.002)],
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL, text=True))
        replicas = [HttpReplica(f"skill-{i}", _read_url_line(p),
                                pid=p.pid, timeout_s=20.0)
                    for i, p in enumerate(procs)]
        pool = ReplicaPool(replicas, health_interval_s=0.25)
        router = Router(pool, retries=3)

        windows = {f"st-{i}": rng.standard_normal(
            (T, HW, HW, 3)).astype(np.float32) for i in range(n_sessions)}
        failures, mismatches, sheds = 0, 0, 0
        # establish every session (spreads over both replicas: idle ties
        # rotate round-robin)
        for sid, win in windows.items():
            fut = router.submit({}, session={"sid": sid, "window": win,
                                             "stride": S})
            out = np.asarray(fut.result(timeout=30))
            want = stub_stream_logits(win, NCLS)
            if abs(out[0] - want[0]) > 1e-4:
                mismatches += 1
        holders = {sid: router._affinity.get(sid) for sid in windows}
        victim_name = replicas[0].name
        victim_sessions = [s for s, h in holders.items()
                           if h == victim_name]
        leg["victim_sessions"] = len(victim_sessions)
        killed = {"done": False}
        for k in range(n_advances):
            if k == kill_after and not killed["done"]:
                os.kill(procs[0].pid, _signal.SIGKILL)
                killed["done"] = True
                log(f"[chaos] stream_replica_kill: killed {victim_name} "
                    f"holding {len(victim_sessions)} live session(s)")
            futs = {}
            for sid in windows:
                frames = rng.standard_normal(
                    (S, HW, HW, 3)).astype(np.float32)
                windows[sid] = np.concatenate(
                    [windows[sid][S:], frames], axis=0)
                # the resendable window rides every advance — the
                # re-establish-anywhere contract replica death needs
                futs[sid] = router.submit(
                    {"video": frames},
                    session={"sid": sid, "window": windows[sid],
                             "stride": S})
            for sid, fut in futs.items():
                try:
                    out = np.asarray(fut.result(timeout=30))
                except Exception as e:  # noqa: BLE001 - verdict, not crash
                    from pytorchvideo_accelerate_tpu.serving.batcher import (
                        QueueFullError,
                    )

                    if isinstance(e, QueueFullError):
                        sheds += 1
                    else:
                        failures += 1
                    continue
                want = stub_stream_logits(windows[sid], NCLS)
                if abs(out[0] - want[0]) > 1e-4:
                    mismatches += 1
        moved = [s for s in victim_sessions
                 if router._affinity.get(s) not in (None, victim_name)]
        leg.update(advances=n_advances * n_sessions, failed=failures,
                   shed=sheds, mismatches=mismatches,
                   moved=len(moved))
        if failures:
            _finding(report, "stream_replica_kill",
                     f"{failures} non-shed client-visible failure(s) "
                     "across the kill (affinity re-route + re-establish "
                     "must absorb replica death)")
        if mismatches:
            _finding(report, "stream_replica_kill",
                     f"{mismatches} label(s) diverged from the client-"
                     "window expectation (session did not resume at the "
                     "correct window position)")
        if victim_sessions and not moved:
            _finding(report, "stream_replica_kill",
                     "no victim session re-routed off the killed replica")
        log(f"[chaos] stream_replica_kill: {n_advances * n_sessions} "
            f"advances over {n_sessions} sessions through the kill "
            f"({failures} failed, {sheds} shed, {mismatches} position "
            f"mismatches, {len(moved)}/{len(victim_sessions)} victim "
            "sessions re-homed)")
    finally:
        if router is not None:
            router.close()
        for p in procs:
            try:
                p.kill()
                p.wait(timeout=10.0)
            except Exception:
                pass


# subprocess body for leg_stream_kv_kill: a REAL causal-masked
# videomae_t served through KV-ring streaming (trunk="causal") behind
# the real fleet Scheduler + InferenceServer. Deterministic by
# construction (jax.random.key(0) init, CPU) so a same-seed local
# engine reproduces every state the fleet can reach. One JSON line
# {{"url": ...}} once warmed + bound, then serve.
_KV_SRV_CODE = """
import json

import numpy as np
import jax

from pytorchvideo_accelerate_tpu.config import ModelConfig
from pytorchvideo_accelerate_tpu.fleet.scheduler import Scheduler
from pytorchvideo_accelerate_tpu.models import create_model
from pytorchvideo_accelerate_tpu.serving.engine import InferenceEngine
from pytorchvideo_accelerate_tpu.serving.server import InferenceServer
from pytorchvideo_accelerate_tpu.serving.stats import ServingStats
from pytorchvideo_accelerate_tpu.streaming import StreamingEngine

cfg = ModelConfig(name="videomae_t", num_classes={ncls},
                  dropout_rate=0.0, attn_mask="causal")
model = create_model(cfg, "fp32")
variables = model.init(jax.random.key(0),
                       np.zeros((1, {t}, {hw}, {hw}, 3), np.float32))
engine = InferenceEngine(model, variables["params"],
                         variables.get("batch_stats", {{}}),
                         num_classes={ncls}, max_batch_size=2,
                         model_name="videomae_t")
stream = StreamingEngine(engine, session_budget_mb=32.0,
                         session_ttl_s=60.0, name="chaos-kv",
                         trunk="causal")
stream.warmup_stream({t}, {hw}, {hw}, 3, {s})
stats = ServingStats(window=512)
sched = Scheduler(stream, stats=stats, max_queue=128,
                  realtime_deadline_ms=30000.0)
srv = InferenceServer(stream, sched, stats, host="127.0.0.1", port=0,
                      request_timeout_s=60.0)
host, port = srv.address
print(json.dumps({{"url": "http://%s:%d" % (host, port)}}), flush=True)
srv.serve_forever(drain_on_sigterm=False)
"""


def leg_stream_kv_kill(report: dict, seed: int, log: Log) -> None:
    """SIGKILL the replica holding KV-BACKED streaming sessions (a real
    causal-trunk videomae_t, per-layer KV rings) mid-stream: affinity
    re-routes every session to the survivor, which re-establishes from
    the client's resendable window — rebuilding the KV ring state
    deterministically. The verdict is exact, not shape-level: every
    label through the kill must match a same-seed local engine to the
    serving tolerance, where the expectation legitimately FORKS at the
    re-establish (a fresh KV establish carries window-only context and
    window-order positions — the carry-semantics twin of hot-swap), so
    the local mirror forks exactly where the fleet did, and at least
    one victim session must take the fork."""
    import signal as _signal
    import subprocess

    import jax
    import numpy as np

    from pytorchvideo_accelerate_tpu.config import ModelConfig
    from pytorchvideo_accelerate_tpu.fleet.pool import (
        HttpReplica,
        ReplicaPool,
    )
    from pytorchvideo_accelerate_tpu.fleet.router import Router
    from pytorchvideo_accelerate_tpu.models import create_model
    from pytorchvideo_accelerate_tpu.serving.engine import InferenceEngine
    from pytorchvideo_accelerate_tpu.streaming import StreamingEngine

    leg = _leg(report, "stream_kv_kill")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    T, S, HW, NCLS = 8, 2, 16, 4
    TOL = 2e-4
    n_sessions, n_advances, kill_after = 4, 6, 3
    procs: List[subprocess.Popen] = []
    router = None
    rng = np.random.default_rng(seed)
    try:
        for _ in range(2):
            procs.append(subprocess.Popen(
                [sys.executable, "-c",
                 _KV_SRV_CODE.format(t=T, s=S, hw=HW, ncls=NCLS)],
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL, text=True))
        # the same-seed oracle, built while the replicas warm: same init
        # key + trunk mode -> bit-for-bit the weights they serve; its
        # sessions mirror the remote session state branch by branch
        cfg = ModelConfig(name="videomae_t", num_classes=NCLS,
                          dropout_rate=0.0, attn_mask="causal")
        model = create_model(cfg, "fp32")
        variables = model.init(
            jax.random.key(0), np.zeros((1, T, HW, HW, 3), np.float32))
        oracle = StreamingEngine(
            InferenceEngine(model, variables["params"],
                            variables.get("batch_stats", {}),
                            num_classes=NCLS, max_batch_size=2,
                            model_name="videomae_t"),
            session_budget_mb=32.0, session_ttl_s=600.0,
            name="chaos-kv-oracle", trunk="causal")

        def want(item):
            out = oracle.advance_batch([dict(item)])[0]
            if isinstance(out, Exception):
                raise out
            return np.asarray(out, np.float32)

        replicas = [HttpReplica(f"kvr-{i}", _read_url_line(p),
                                pid=p.pid, timeout_s=60.0)
                    for i, p in enumerate(procs)]
        pool = ReplicaPool(replicas, health_interval_s=0.25)
        router = Router(pool, retries=3)

        windows = {f"st-{i}": rng.standard_normal(
            (T, HW, HW, 3)).astype(np.float32) for i in range(n_sessions)}
        failures, sheds, mismatches, forked = 0, 0, 0, 0
        for sid, win in windows.items():
            out = np.asarray(router.submit(
                {}, session={"sid": sid, "window": win,
                             "stride": S}).result(timeout=60), np.float32)
            if float(np.max(np.abs(out - want(
                    {"sid": sid, "window": win, "stride": S})))) > TOL:
                mismatches += 1
        holders = {sid: router._affinity.get(sid) for sid in windows}
        victim_name = replicas[0].name
        victim_sessions = [s for s, h in holders.items()
                           if h == victim_name]
        leg["victim_sessions"] = len(victim_sessions)
        for k in range(n_advances):
            if k == kill_after:
                os.kill(procs[0].pid, _signal.SIGKILL)
                log(f"[chaos] stream_kv_kill: killed {victim_name} "
                    f"holding {len(victim_sessions)} KV session(s)")
            futs, sent = {}, {}
            for sid in windows:
                frames = rng.standard_normal(
                    (S, HW, HW, 3)).astype(np.float32)
                sent[sid] = frames
                windows[sid] = np.concatenate(
                    [windows[sid][S:], frames], axis=0)
                futs[sid] = router.submit(
                    {"video": frames},
                    session={"sid": sid, "window": windows[sid],
                             "stride": S})
            for sid, fut in futs.items():
                try:
                    out = np.asarray(fut.result(timeout=60), np.float32)
                except Exception as e:  # noqa: BLE001 - verdict, not crash
                    from pytorchvideo_accelerate_tpu.serving.batcher import (
                        QueueFullError,
                    )

                    if isinstance(e, QueueFullError):
                        sheds += 1
                    else:
                        failures += 1
                    continue
                adv = want({"sid": sid, "frames": sent[sid]})
                if float(np.max(np.abs(out - adv))) <= TOL:
                    continue
                # the advance expectation missed: the fleet may have
                # re-established this session from the resendable window
                # (replica death). Fork the mirror the same way and
                # re-judge — the fresh-establish KV rebuild is itself
                # deterministic, so this branch is exact too.
                oracle.end_session(sid)
                est = want({"sid": sid, "window": windows[sid],
                            "stride": S})
                if float(np.max(np.abs(out - est))) <= TOL:
                    forked += 1
                    continue
                mismatches += 1
        moved = [s for s in victim_sessions
                 if router._affinity.get(s) not in (None, victim_name)]
        leg.update(advances=n_advances * n_sessions, failed=failures,
                   shed=sheds, mismatches=mismatches, moved=len(moved),
                   kv_reestablished=forked)
        if failures:
            _finding(report, "stream_kv_kill",
                     f"{failures} non-shed client-visible failure(s) "
                     "across the kill (affinity re-route + KV "
                     "re-establish must absorb replica death)")
        if mismatches:
            _finding(report, "stream_kv_kill",
                     f"{mismatches} label(s) matched NEITHER the "
                     "continuous-KV expectation nor the deterministic "
                     "window-rebuild (KV state did not survive or "
                     "rebuild correctly)")
        if victim_sessions and not forked:
            _finding(report, "stream_kv_kill",
                     "no session took the re-establish fork: the kill "
                     "never exercised the KV rebuild-from-window path")
        if victim_sessions and not moved:
            _finding(report, "stream_kv_kill",
                     "no victim session re-routed off the killed replica")
        log(f"[chaos] stream_kv_kill: {n_advances * n_sessions} advances "
            f"over {n_sessions} KV sessions through the kill "
            f"({failures} failed, {sheds} shed, {mismatches} mismatches, "
            f"{forked} deterministic KV re-establishes, "
            f"{len(moved)}/{len(victim_sessions)} victims re-homed)")
    finally:
        if router is not None:
            router.close()
        for p in procs:
            try:
                p.kill()
                p.wait(timeout=10.0)
            except Exception:
                pass


def leg_autoscale_kill(report: dict, seed: int, log: Log) -> None:
    """SIGKILL a pooled replica UNDER AUTOSCALER CONTROL
    (fleet/control/autoscaler.py): the controller must confirm the corpse
    (`dead_after_ticks` consecutive unroutable ticks + a dead health
    verdict), replace it with EXACTLY ONE spawn (a dead name is never
    double-counted against the target), re-home its sessions onto
    survivors with zero non-shed failures and position-correct labels —
    and still refuse to drain the last routable replica no matter what
    the signals say (the never-scale-to-zero floor is structural)."""
    import signal as _signal
    import subprocess

    import numpy as np

    from pytorchvideo_accelerate_tpu.fleet.control import Autoscaler
    from pytorchvideo_accelerate_tpu.fleet.pool import (
        HttpReplica,
        ReplicaPool,
    )
    from pytorchvideo_accelerate_tpu.fleet.router import Router
    from pytorchvideo_accelerate_tpu.serving.stub import stub_stream_logits

    leg = _leg(report, "autoscale_kill")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    T, S, HW, NCLS = 8, 2, 4, 4
    n_sessions, n_advances, kill_after = 4, 6, 2
    procs: List[subprocess.Popen] = []
    router = None
    asc = None
    spawn_n = {"n": 0}
    rng = np.random.default_rng(seed)
    try:
        for _ in range(2):
            procs.append(subprocess.Popen(
                [sys.executable, "-c",
                 _STREAM_SRV_CODE.format(forward_s=0.002)],
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL, text=True))
        replicas = [HttpReplica(f"akill-{i}", _read_url_line(p),
                                pid=p.pid, timeout_s=20.0)
                    for i, p in enumerate(procs)]
        pool = ReplicaPool(replicas, health_interval_s=0.25)
        router = Router(pool, retries=3)

        def spawn():
            p = subprocess.Popen(
                [sys.executable, "-c",
                 _STREAM_SRV_CODE.format(forward_s=0.002)],
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL, text=True)
            procs.append(p)  # cleanup owns every child, spawned or seed
            spawn_n["n"] += 1
            r = HttpReplica(f"akill-sp-{spawn_n['n']}", _read_url_line(p),
                            pid=p.pid, timeout_s=20.0)
            r._proc = p
            return r

        def reap(replica):
            p = getattr(replica, "_proc", None)
            if p is not None:
                try:
                    p.kill()
                    p.wait(timeout=10.0)
                except Exception:
                    pass

        # replacement is the only control loop under test: the watermarks
        # park at +/-inf so no pressure/idle decision can fire, and the
        # controller is stepped MANUALLY (never start()ed) so the tick
        # count the corpse confirmation needs is deterministic
        asc = Autoscaler(router, spawn_fn=spawn, reap_fn=reap,
                         min_replicas=2, max_replicas=4,
                         slo_p99_ms=1e9, queue_high=1e9, queue_low=0.0,
                         cooldown_s=0.05, interval_s=0.05, ewma_alpha=1.0,
                         drain_grace_s=1.0, dead_after_ticks=2)

        windows = {f"as-{i}": rng.standard_normal(
            (T, HW, HW, 3)).astype(np.float32) for i in range(n_sessions)}
        failures, mismatches, sheds = 0, 0, 0

        def advance_all():
            nonlocal failures, mismatches, sheds
            futs = {}
            for sid in windows:
                frames = rng.standard_normal(
                    (S, HW, HW, 3)).astype(np.float32)
                windows[sid] = np.concatenate(
                    [windows[sid][S:], frames], axis=0)
                # the resendable window rides every advance — the
                # re-establish-anywhere contract replacement needs
                futs[sid] = router.submit(
                    {"video": frames},
                    session={"sid": sid, "window": windows[sid],
                             "stride": S})
            for sid, fut in futs.items():
                try:
                    out = np.asarray(fut.result(timeout=30))
                except Exception as e:  # noqa: BLE001 - verdict, not crash
                    from pytorchvideo_accelerate_tpu.serving.batcher import (
                        QueueFullError,
                    )

                    if isinstance(e, QueueFullError):
                        sheds += 1
                    else:
                        failures += 1
                    continue
                want = stub_stream_logits(windows[sid], NCLS)
                if abs(out[0] - want[0]) > 1e-4:
                    mismatches += 1

        for sid, win in windows.items():
            out = np.asarray(router.submit(
                {}, session={"sid": sid, "window": win,
                             "stride": S}).result(timeout=30))
            if abs(out[0] - stub_stream_logits(win, NCLS)[0]) > 1e-4:
                mismatches += 1
        holders = {sid: router._affinity.get(sid) for sid in windows}
        victim_name = replicas[0].name
        victim_sessions = [s for s, h in holders.items()
                           if h == victim_name]
        leg["victim_sessions"] = len(victim_sessions)
        for _ in range(kill_after):
            advance_all()
        os.kill(procs[0].pid, _signal.SIGKILL)
        log(f"[chaos] autoscale_kill: killed {victim_name} holding "
            f"{len(victim_sessions)} live session(s)")
        time.sleep(0.6)  # > one poller interval: the corpse leaves routable
        replaced = False
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if asc.step() == "replace":
                replaced = True
                break
            time.sleep(0.15)
        names = sorted(r.name for r in pool.replicas)
        leg.update(replaced=replaced, spawns=spawn_n["n"], members=names)
        if not replaced:
            _finding(report, "autoscale_kill",
                     "controller never replaced the killed replica "
                     f"(membership {names})")
        if victim_name in names:
            _finding(report, "autoscale_kill",
                     f"corpse {victim_name} still in membership after the "
                     "replace (a dead name the target keeps paying for)")
        if spawn_n["n"] != 1:
            _finding(report, "autoscale_kill",
                     f"{spawn_n['n']} spawn(s) for ONE dead replica (the "
                     "corpse was double-counted against the target)")
        for _ in range(kill_after, n_advances):
            advance_all()
        moved = [s for s in victim_sessions
                 if router._affinity.get(s) not in (None, victim_name)]
        leg.update(advances=n_advances * n_sessions, failed=failures,
                   shed=sheds, mismatches=mismatches, moved=len(moved))
        if failures:
            _finding(report, "autoscale_kill",
                     f"{failures} non-shed client-visible failure(s) "
                     "across kill + replace (re-route, re-establish and "
                     "the replacement must absorb replica death)")
        if mismatches:
            _finding(report, "autoscale_kill",
                     f"{mismatches} label(s) diverged from the client-"
                     "window expectation through the replacement")
        if victim_sessions and not moved:
            _finding(report, "autoscale_kill",
                     "no victim session re-routed off the killed replica")
        # last-healthy probe (white-box): drain down to ONE routable
        # replica, then demand the controller refuses to drain IT
        first = asc._drain_one(pool.routable())
        last = asc._drain_one(pool.routable())
        n_routable = len(pool.routable())
        leg.update(drain_first=bool(first), drain_last_refused=not last,
                   routable_after=n_routable)
        if not first:
            _finding(report, "autoscale_kill",
                     "drain of a redundant replica refused with 2 routable")
        if last or n_routable != 1:
            _finding(report, "autoscale_kill",
                     "controller drained (or lost) the LAST routable "
                     f"replica ({n_routable} left) — the fleet can scale "
                     "to zero")
        log(f"[chaos] autoscale_kill: replace={replaced} "
            f"(spawns {spawn_n['n']}), {n_advances * n_sessions} advances "
            f"({failures} failed, {sheds} shed, {mismatches} mismatches, "
            f"{len(moved)}/{len(victim_sessions)} victims re-homed), "
            f"last-healthy drain refused={not last}")
    finally:
        if asc is not None:
            asc.close()
        if router is not None:
            router.close()
        for p in procs:
            try:
                p.kill()
                p.wait(timeout=10.0)
            except Exception:
                pass


def leg_guard_nan(report: dict, tmpdir: str, seed: int, log: Log) -> None:
    """NaN spike mid-epoch (seeded ``nan`` faults at `step.dispatch`): the
    in-graph skip absorbs the first poisoned step, the second crosses the
    ladder → auto-rollback to the last-known-good step with the loader
    fast-forwarded PAST the poisoned span; the run completes with finite
    loss and leaves a loadable, byte-stable replay bundle
    (reliability/guard.py; docs/RELIABILITY.md § divergence runbook)."""
    import numpy as np

    from pytorchvideo_accelerate_tpu.config import (
        CheckpointConfig, DataConfig, GuardConfig, ModelConfig, OptimConfig,
        TrainConfig,
    )
    from pytorchvideo_accelerate_tpu.reliability.guard import (
        dump_replay_bundle,
        load_replay_bundle,
    )
    from pytorchvideo_accelerate_tpu.trainer.loop import Trainer

    leg = _leg(report, "guard_nan")
    outdir = os.path.join(tmpdir, "guard_run")
    cfg = TrainConfig(
        model=ModelConfig(name="tiny3d", num_classes=4, dropout_rate=0.0),
        data=DataConfig(synthetic=True, synthetic_num_videos=16,
                        num_frames=4, crop_size=24, batch_size=2,
                        num_workers=1, limit_val_batches=1),
        optim=OptimConfig(num_epochs=2, lr=0.01),
        checkpoint=CheckpointConfig(output_dir=outdir),
        # LKG every 3 steps so one exists before the injected anomaly;
        # warmup high = the spike detector stays quiet and the NONFINITE
        # path alone drives the ladder (deterministic leg)
        guard=GuardConfig(enabled=True, lkg_every_steps=3, lkg_keep=2,
                          rollback_after=2, max_rollbacks=2,
                          warmup_steps=1000),
        seed=seed,
    )
    tr = Trainer(cfg)
    guard = tr.train_guard
    # poison the 6th and 7th dispatches: consecutive nonfinite steps —
    # skip at streak 1, rollback at streak 2 (guard.rollback_after)
    faults.arm(FaultPlan(seed, [FaultSpec("step.dispatch", kind="nan",
                                          at_hits=(5, 6), max_fires=2)]))
    try:
        res = tr.fit()
    finally:
        faults.disarm()
    fires = [e for e in faults.fault_history()
             if e["point"] == "step.dispatch"]
    leg.update(fires=len(fires), rollbacks=res.get("guard_rollbacks"),
               skips=guard.skips, steps=res.get("steps"),
               train_loss=res.get("train_loss"))
    if len(fires) != 2:
        _finding(report, "guard_nan",
                 f"expected 2 injected nan dispatches, got {len(fires)}")
    if res.get("guard_rollbacks") != 1:
        _finding(report, "guard_nan",
                 f"expected exactly 1 rollback, got "
                 f"{res.get('guard_rollbacks')}")
        return
    rb = guard.last_rollback
    leg["rollback"] = {k: rb[k] for k in ("lkg_step", "anomaly_step",
                                          "resume_position")}
    if rb["lkg_step"] >= rb["anomaly_step"]:
        _finding(report, "guard_nan",
                 f"rollback target {rb['lkg_step']} not before the "
                 f"anomaly step {rb['anomaly_step']}")
    if rb["lkg_step"] not in (3, 4, 5):
        _finding(report, "guard_nan",
                 f"LKG step {rb['lkg_step']} outside the healthy window "
                 "before the injected anomalies (steps 6-7)")
    # loader position intact: the resume position is the anomalous
    # batch's CONSUMED position (post-batch == step index in epoch 0),
    # i.e. the poisoned span is skipped, nothing else is
    if rb["resume_position"] != {"epoch": 0,
                                 "position": rb["anomaly_step"]}:
        _finding(report, "guard_nan",
                 f"loader not fast-forwarded past the poisoned span: "
                 f"{rb['resume_position']} (anomaly step "
                 f"{rb['anomaly_step']})")
    if res.get("preempted") or not np.isfinite(res.get("train_loss",
                                                       float("nan"))):
        _finding(report, "guard_nan",
                 f"run did not recover to a finite loss: {res}")
    # the replay bundle is the repro artifact: loadable, carries the
    # poison, and the writer is byte-deterministic (same input → same
    # bytes, the property that makes a bundle replayable evidence)
    meta, arrs = load_replay_bundle(rb["bundle"])
    if np.isfinite(arrs["video"]).all():
        _finding(report, "guard_nan",
                 "replay bundle batch does not carry the injected NaNs")
    if meta["verdict"]["kind"] != "nonfinite":
        _finding(report, "guard_nan",
                 f"bundle verdict {meta['verdict']} is not nonfinite")
    a = dump_replay_bundle(os.path.join(tmpdir, "redump_a"), arrs,
                           {"step": meta["step"]})
    b = dump_replay_bundle(os.path.join(tmpdir, "redump_b"), arrs,
                           {"step": meta["step"]})
    for fname in sorted(os.listdir(a)):
        with open(os.path.join(a, fname), "rb") as fa, \
                open(os.path.join(b, fname), "rb") as fb:
            if fa.read() != fb.read():
                _finding(report, "guard_nan",
                         f"replay bundle not byte-deterministic: {fname}")
    log(f"[chaos] guard_nan: {len(fires)} poisoned steps -> "
        f"{guard.skips} skip(s) + rollback to step {rb['lkg_step']}, "
        f"resumed past position {rb['resume_position']['position']}, "
        f"finished at step {res.get('steps')} with finite loss")


def leg_quarantine(report: dict, tmpdir: str, seed: int, log: Log) -> None:
    """A deterministically-corrupt clip (seeded garbage bytes — the
    decode.read failure that kills the same index every epoch): the
    failure budget fills across runs sharing the persisted sidecar, the
    clip is quarantined, every epoch still delivers full batches, and the
    next run's sampler excludes the clip without a single decode
    attempt."""
    import numpy as np

    from pytorchvideo_accelerate_tpu.data.manifest import (
        Quarantine,
        scan_directory,
    )
    from pytorchvideo_accelerate_tpu.data.pipeline import (
        ClipLoader,
        VideoClipSource,
    )
    from pytorchvideo_accelerate_tpu.data.transforms import make_transform
    from pytorchvideo_accelerate_tpu.obs import get_registry

    leg = _leg(report, "quarantine")
    root = os.path.join(tmpdir, "qvideos")
    if not _write_video_tree(root, n_per_class=3):
        leg["skipped"] = "no mp4 codec on this host"
        log("[chaos] quarantine: skipped (no codec)")
        return
    bad_path = os.path.join(root, "class0", "v0.mp4")
    rng = np.random.default_rng(seed)
    with open(bad_path, "wb") as f:  # corrupt-bytes, seeded
        f.write(rng.integers(0, 256, 4096, dtype=np.uint8).tobytes())
    sidecar = os.path.join(tmpdir, "quarantine.json")
    tf = make_transform(training=True, num_frames=4, crop_size=24,
                        min_short_side_scale=26, max_short_side_scale=30)
    counter = get_registry().counter(
        "pva_data_quarantined_total", "", labelnames=("site",))
    before = counter.value(site="decode")

    def run(epoch: int):
        """One fresh 'run' over the tree: new source + loader, shared
        persisted sidecar (budget counts at most one failure per run)."""
        q = Quarantine(sidecar, budget=2)
        src = VideoClipSource(scan_directory(root), tf, clip_duration=0.2,
                              training=True, seed=seed, decode_retries=1,
                              retry_base_delay_s=0.001, quarantine=q)
        loader = ClipLoader(src, global_batch_size=2, shuffle=True,
                            num_workers=1, seed=seed)
        try:
            batches = sum(1 for _ in loader.epoch(epoch))
        finally:
            loader.close()
        return q, src, loader, batches

    q1, src1, loader1, b1 = run(0)   # failure 1 of 2: under budget
    q2, _src2, _l2, b2 = run(1)      # failure 2: quarantined + persisted
    q3, src3, loader3, b3 = run(2)   # excluded: zero decode attempts
    want = loader1.batches_per_epoch()
    leg.update(batches=[b1, b2, b3], want=want,
               quarantined=sorted(q3.paths()),
               counter_delta=counter.value(site="decode") - before)
    if not (b1 == b2 == b3 == want):
        _finding(report, "quarantine",
                 f"epochs under a corrupt clip yielded {[b1, b2, b3]} "
                 f"batches, want {want} each")
    if q1.contains(bad_path):
        _finding(report, "quarantine",
                 "clip quarantined on its FIRST failure (budget=2 should "
                 "absorb one transient)")
    if not q2.contains(bad_path):
        _finding(report, "quarantine",
                 "second failing run did not quarantine the clip")
    if not q3.contains(bad_path):
        _finding(report, "quarantine",
                 "sidecar did not persist across runs (round-trip lost)")
    bad_idx = next(i for i, e in enumerate(src3.manifest.entries)
                   if e.path == bad_path)
    plan = loader3._epoch_indices(3)
    if bad_idx in plan:
        _finding(report, "quarantine",
                 "sampler still schedules the quarantined clip")
    if len(plan) != len(src3.manifest):
        _finding(report, "quarantine",
                 "exclusion changed epoch geometry (batch count drift)")
    if counter.value(site="decode") - before != 1:
        _finding(report, "quarantine",
                 f"pva_data_quarantined_total moved by "
                 f"{counter.value(site='decode') - before}, want 1")
    log(f"[chaos] quarantine: corrupt clip sidelined after 2 failing "
        f"runs, {b1}/{b2}/{b3} of {want} batches delivered, sampler "
        f"excludes index {bad_idx}")


def leg_dataplane_kill(report: dict, tmpdir: str, seed: int,
                       log: Log) -> None:
    """Leg 13: decode-worker SIGKILL mid-epoch (docs/INPUT_PIPELINE.md §
    disaggregated data plane). Two worker PROCESSES feed a RemoteClipFeed;
    one is SIGKILLed with leases outstanding — the feed must re-lease the
    unacked span to the survivor and the full batch stream must be
    byte-identical to the local loader's (zero duplicate, zero missing →
    identical training loss by construction). With a codec present, the
    tree carries a deterministically-corrupt clip: the remote worker's
    quarantine report must land in the trainer's persisted sidecar and
    SURVIVE the reporting worker's death."""
    import signal as signal_mod

    import numpy as np

    from pytorchvideo_accelerate_tpu.data.manifest import (
        Quarantine,
        scan_directory,
    )
    from pytorchvideo_accelerate_tpu.data.pipeline import ClipLoader
    from pytorchvideo_accelerate_tpu.dataplane import spec as dpspec
    from pytorchvideo_accelerate_tpu.dataplane.bench import batch_digest
    from pytorchvideo_accelerate_tpu.dataplane.feed import RemoteClipFeed

    leg = _leg(report, "dataplane_kill")
    tspec = dict(num_frames=4, training=True, crop_size=24,
                 min_short_side_scale=26, max_short_side_scale=30)
    root = os.path.join(tmpdir, "dpvideos")
    bad_path = None
    if _write_video_tree(root, n_per_class=6):
        bad_path = os.path.join(root, "class0", "v0.mp4")
        rng = np.random.default_rng(seed)
        with open(bad_path, "wb") as f:  # seeded corrupt bytes
            f.write(rng.integers(0, 256, 4096, dtype=np.uint8).tobytes())
        spec = dpspec.video_spec(
            scan_directory(root), tspec, clip_duration=0.2, training=True,
            seed=seed, decode_retries=1, retry_base_delay_s=0.001)
    else:
        leg["codec"] = "unavailable (synthetic source, quarantine half skipped)"
        spec = dpspec.synthetic_spec(tspec, num_videos=12, num_classes=4,
                                     seed=seed)

    def make_loader() -> ClipLoader:
        # shuffle=False pins the corrupt clip (sorted index 0) into the
        # FIRST batch, so the quarantine report deterministically precedes
        # the kill; byte parity is shuffle-independent anyway
        return ClipLoader(dpspec.build_source(spec), global_batch_size=4,
                          shuffle=False, num_workers=1, seed=seed)

    loader = make_loader()
    try:
        # batch_digest is THE byte-identity definition (shared with the
        # DATA_PLANE bench lane — the two gates must agree on it)
        local = [batch_digest(b) for b, _ in
                 loader.epoch_items(0, from_start=True) if b is not None]
    finally:
        loader.close()

    sidecar = os.path.join(tmpdir, "dp_quarantine.json")
    quarantine = Quarantine(sidecar, budget=1, site="dataplane")
    loader = make_loader()
    feed = RemoteClipFeed(loader, spec, spawn=2, credits=2,
                          quarantine=quarantine, batch_timeout_s=120.0)
    remote: List[str] = []
    victim = None
    try:
        for i, (batch, _state) in enumerate(
                feed.epoch_items(0, from_start=True)):
            if batch is None:
                continue
            remote.append(batch_digest(batch))
            if victim is None and i == 0:
                # wait for the quarantine verdict (codec runs), then kill
                # the REPORTING worker — its completed verdicts must
                # outlive it; prefer a moment when it holds leases so the
                # re-lease path is exercised, not just membership
                deadline = time.monotonic() + 20.0
                while (bad_path is not None
                       and not quarantine.contains(bad_path)
                       and time.monotonic() < deadline):
                    time.sleep(0.01)
                stats = feed.stats()
                reporters = {q["pid"] for q in stats["qreports"]}
                deadline = time.monotonic() + 5.0
                while time.monotonic() < deadline:
                    stats = feed.stats()
                    busy = [w for w in stats["workers"].values()
                            if w["outstanding"] > 0
                            and (not reporters or w["pid"] in reporters)]
                    if busy:
                        break
                    time.sleep(0.005)
                cand = [w["pid"] for w in stats["workers"].values()
                        if w["outstanding"] > 0] or \
                       [w["pid"] for w in stats["workers"].values()]
                victim = (next((p for p in cand if p in reporters), None)
                          or cand[0])
                os.kill(victim, signal_mod.SIGKILL)
        stats = feed.stats()
    finally:
        feed.close()
        loader.close()
    leg.update(batches=len(remote), want=len(local), victim_pid=victim,
               releases=stats.get("releases"),
               workers_lost=stats.get("workers_lost"),
               qreports=len(stats.get("qreports", [])))
    if remote != local:
        _finding(report, "dataplane_kill",
                 f"remote stream diverged after the kill: {len(remote)} "
                 f"batches vs {len(local)} local (dup/missing/reordered)")
    if stats.get("workers_lost") != 1:
        _finding(report, "dataplane_kill",
                 f"feed lost {stats.get('workers_lost')} workers, want "
                 "exactly the SIGKILLed one")
    if bad_path is not None:
        if not quarantine.contains(bad_path):
            _finding(report, "dataplane_kill",
                     "remote decode failure never reached the trainer's "
                     "quarantine sidecar")
        elif not Quarantine(sidecar, budget=1).contains(bad_path):
            _finding(report, "dataplane_kill",
                     "quarantine verdict did not persist to the sidecar "
                     "(lost with the dead worker)")
    log(f"[chaos] dataplane_kill: worker {victim} SIGKILLed mid-epoch; "
        f"{stats.get('releases')} span(s) re-leased, {len(remote)}/"
        f"{len(local)} batches byte-identical, quarantine "
        f"{'persisted' if bad_path else 'n/a (no codec)'}")


# forced-host child for leg_collective_hang: a REAL mesh psum wedged by an
# injected delay inside the watched section; the watchdog (tiny timeout)
# must fire DURING the wedge with per-host attribution. One JSON line to
# stdout (forcehost contract).
_HANG_LEG_CODE = """
import json, time
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
from jax.sharding import PartitionSpec as P
from pytorchvideo_accelerate_tpu.config import MeshConfig
from pytorchvideo_accelerate_tpu.obs.watchdog import Watchdog
from pytorchvideo_accelerate_tpu.parallel import collectives, hangcheck
from pytorchvideo_accelerate_tpu.parallel.mesh import make_train_mesh
from pytorchvideo_accelerate_tpu.reliability import faults

evidence = {{}}
wd = Watchdog({timeout}, on_stall=lambda names: evidence.update(
    stalled=list(names),
    attribution={{n: list(v)
                  for n, v in dict(wd.last_attribution or {{}}).items()}}))
wd.start()
hangcheck.install_collective_watch(wd)
mesh = make_train_mesh(MeshConfig(data={devices}, model=1))
f = jax.jit(collectives.shard_map(
    lambda x: collectives.psum(x, "data"), mesh=mesh,
    in_specs=P("data"), out_specs=P()))
x = np.ones(({devices},), np.float32)
warm = float(np.asarray(f(x)).ravel()[0])  # compile outside the wedge
faults.arm(faults.FaultPlan({seed}, [faults.FaultSpec(
    "collective.sync", kind="delay", delay_s={wedge},
    at_hits=(0,), max_fires=1)]))
t0 = time.monotonic()
try:
    with hangcheck.collective_section("psum", step=1):
        out = float(np.asarray(f(x)).ravel()[0])
finally:
    faults.disarm()
elapsed = time.monotonic() - t0
wd.stop()
# second diagnostic on the SAME op: the schedule recorder
# (parallel/schedule_recorder.py) must name the psum one emulated host
# skips — the watchdog says WHERE the pod wedged, the recorder says WHY
from pytorchvideo_accelerate_tpu.parallel import schedule_recorder as sr
rec = sr.CollectiveScheduleRecorder()
sr.install_schedule_recorder(rec)
try:
    for h in range(2):
        with rec.as_host(f"host={{h}}/2"):
            with hangcheck.collective_section("step_dispatch", step=1):
                pass
            if h == 0:  # host 1 SKIPS the psum — the deadlock shape
                with hangcheck.collective_section("psum", step=1):
                    float(np.asarray(f(x)).ravel()[0])
            with hangcheck.collective_section("epoch_sync"):
                pass
    div = sr.diff_schedules(rec.schedules())
finally:
    sr.uninstall_schedule_recorder()
first = div.get("first_divergence") or {{}}
print("\\n" + json.dumps({{
    "stalled": evidence.get("stalled"),
    "attribution": evidence.get("attribution"),
    "elapsed_s": round(elapsed, 3),
    "fires": len(faults.fault_history()), "psum": out, "warm": warm,
    "sched_diverged": div.get("diverged"),
    "sched_tick": first.get("tick"),
    "sched_ops": {{h: (e[1] if e else None)
                   for h, e in (first.get("hosts") or {{}}).items()}}}}))
"""

_HANG_LEG_DEVICES = 4
_HANG_LEG_TIMEOUT_S = 0.3
_HANG_LEG_WEDGE_S = 1.5


def leg_collective_hang(report: dict, seed: int, log: Log) -> None:
    """Wedged mesh collective in a forced-host child: an injected delay
    inside the watched `psum` section must trip the watchdog DURING the
    wedge (section exit clears the component, so evidence means it fired
    while stuck) with attribution naming the op and the host — the
    evidence an external kill would otherwise destroy."""
    from pytorchvideo_accelerate_tpu.utils.forcehost import run_forced_host

    leg = _leg(report, "collective_hang")
    out = run_forced_host(
        _HANG_LEG_CODE.format(timeout=_HANG_LEG_TIMEOUT_S,
                              devices=_HANG_LEG_DEVICES,
                              wedge=_HANG_LEG_WEDGE_S, seed=seed),
        _HANG_LEG_DEVICES, timeout=300.0)
    leg.update(out)
    if out.get("fires") != 1:
        _finding(report, "collective_hang",
                 f"expected 1 injected collective wedge, got "
                 f"{out.get('fires')}")
    if out.get("stalled") != ["collective"]:
        _finding(report, "collective_hang",
                 f"watchdog did not fire on the wedged collective: "
                 f"stalled={out.get('stalled')}")
        return
    detail = (out.get("attribution") or {}).get("collective", ["", 0])[0]
    if "psum" not in detail or "host=" not in detail:
        _finding(report, "collective_hang",
                 f"stall not attributed to the collective per host: "
                 f"{detail!r}")
    if out.get("elapsed_s", 0) < _HANG_LEG_WEDGE_S:
        _finding(report, "collective_hang",
                 f"wedge did not actually hold the section "
                 f"({out.get('elapsed_s')}s < {_HANG_LEG_WEDGE_S}s)")
    if out.get("psum") != float(_HANG_LEG_DEVICES):
        _finding(report, "collective_hang",
                 f"psum returned {out.get('psum')} after the wedge")
    # the recorder's first-divergence must name the SAME op the watchdog
    # attributed, on the host that skipped it — the two diagnostics are
    # pinned to each other so they can't drift apart
    ops = out.get("sched_ops") or {}
    if not (out.get("sched_diverged") is True
            and ops.get("host=0/2") == "psum"
            and ops.get("host=1/2") != "psum"
            and "host=1/2" in ops):
        _finding(report, "collective_hang",
                 f"schedule recorder did not name the skipped psum per "
                 f"host: diverged={out.get('sched_diverged')} ops={ops}")
    log(f"[chaos] collective_hang: watchdog attributed the wedge to "
        f"{detail!r} before the {_HANG_LEG_WEDGE_S}s delay released; "
        f"recorder first-divergence at tick {out.get('sched_tick')} "
        f"names {ops}")


def leg_sigterm_plumbing(report: dict, log: Log) -> None:
    """The raw signal path: a real SIGTERM to the installed guard sets the
    request (and does NOT kill), outside any trainer."""
    from pytorchvideo_accelerate_tpu.reliability.preemption import (
        PreemptionGuard,
    )
    import signal as _signal

    leg = _leg(report, "sigterm")
    g = PreemptionGuard()
    if not g.install():  # not the main thread (embedded runs): skip
        leg["skipped"] = "not the main thread"
        return
    try:
        os.kill(os.getpid(), _signal.SIGTERM)
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline and not g.requested:
            time.sleep(0.005)
        leg.update(requested=g.requested, reason=g.reason)
        if not g.requested:
            _finding(report, "sigterm",
                     "SIGTERM did not set the preemption request")
    finally:
        g.uninstall()
    log(f"[chaos] sigterm: guard caught the signal "
        f"(reason={leg.get('reason')!r})")


# --- scenario ---------------------------------------------------------------

def run_scenario(seed: int = 42, smoke: bool = True,
                 log: Optional[Log] = None) -> dict:
    """Run every leg; returns the report dict. `smoke` is accepted for
    CLI-symmetry with pva-tpu-tsan — the scenario is already sized for CI
    (tiny shapes, two short tiny3d fits); full mode is identical today."""
    from pytorchvideo_accelerate_tpu.obs import trace as obstrace

    log = log or (lambda msg: None)
    t0 = time.perf_counter()
    report: dict = {"seed": int(seed), "smoke": bool(smoke),
                    "findings": [], "legs": {}}
    # distributed tracing ARMED across every leg: the recovery machinery
    # (retries, sheds, drains, rollbacks) must behave identically with the
    # tracer live — the "chaos gate stays clean with tracing armed"
    # obligation. Seeded, so the sampling decisions replay with the run.
    obstrace.configure_tracing(1.0, seed=seed, capacity=2048)
    try:
        with tempfile.TemporaryDirectory(prefix="pva_chaos_") as tmpdir:
            for fn, args in (
                    (leg_replay, (report, seed, log)),
                    (leg_sigterm_plumbing, (report, log)),
                    (leg_decode, (report, tmpdir, seed, log)),
                    (leg_quarantine, (report, tmpdir, seed, log)),
                    (leg_dataplane_kill, (report, tmpdir, seed, log)),
                    (leg_ckpt, (report, tmpdir, seed, log)),
                    (leg_tracker, (report, tmpdir, seed, log)),
                    (leg_serve, (report, seed, log)),
                    (leg_replica_kill, (report, seed, log)),
                    (leg_stream_replica_kill, (report, seed, log)),
                    (leg_stream_kv_kill, (report, seed, log)),
                    (leg_autoscale_kill, (report, seed, log)),
                    (leg_collective_hang, (report, seed, log)),
                    (leg_guard_nan, (report, tmpdir, seed, log)),
                    (leg_preempt, (report, tmpdir, seed, log)),
                    (leg_preempt_mesh, (report, tmpdir, seed, log)),
                    (leg_preempt_pipeline, (report, tmpdir, seed, log)),
            ):
                try:
                    fn(*args)
                except Exception as e:  # noqa: BLE001 - a crashed leg IS a finding
                    faults.disarm()  # never leak an armed plan into later legs
                    _finding(report, fn.__name__,
                             f"leg crashed: {type(e).__name__}: {e}")
    finally:
        obstrace.disable_tracing()
    report["elapsed_s"] = round(time.perf_counter() - t0, 3)
    log(f"[chaos] scenario done in {report['elapsed_s']}s: "
        f"{len(report['findings'])} finding(s)")
    return report


def finding_count(report: dict) -> int:
    return len(report.get("findings", ()))


def publish(report: dict) -> None:
    """Mirror the verdict into the obs spine (gauge + flight ring), the
    lint/tsan pattern: crash dumps carry the chaos verdict too."""
    from pytorchvideo_accelerate_tpu import obs

    obs.get_registry().gauge(
        "pva_chaos_findings",
        "findings from the last pva-tpu-chaos scenario").set(
            finding_count(report))
    for f in report.get("findings", ()):
        obs.get_recorder().record("chaos", "finding", detail=f[:200])


def format_report(report: dict) -> str:
    lines: List[str] = []
    for name, leg in report.get("legs", {}).items():
        lines.append(f"leg {name}: " + json.dumps(leg, default=str))
    for f in report.get("findings", ()):
        lines.append(f"FINDING {f}")
    lines.append(
        f"pva-tpu-chaos: {finding_count(report)} finding(s) over "
        f"{len(report.get('legs', {}))} legs in "
        f"{report.get('elapsed_s', 0)}s (seed {report.get('seed')})")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="pva-tpu-chaos",
        description="deterministic fault-injection scenario over the "
                    "data/train/serve resilience layer; see "
                    "docs/RELIABILITY.md")
    ap.add_argument("--smoke", action="store_true",
                    help="the CI lane (the scenario is CI-sized either way)")
    ap.add_argument("--seed", type=int, default=42,
                    help="fault-plan seed: same seed, same fault sequence")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code not in (0, None) else 0

    def log(msg: str) -> None:
        print(msg, file=sys.stderr, flush=True)

    # the trainer legs must not wedge a CLI run on a half-attached
    # accelerator: CPU unless the caller overrides (the tsan CLI pattern)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    report = run_scenario(seed=args.seed, smoke=args.smoke, log=log)
    publish(report)
    if args.format == "json":
        print(json.dumps(report, indent=1, default=str))
    else:
        print(format_report(report))
    return 1 if finding_count(report) else 0


if __name__ == "__main__":
    sys.exit(main())
