"""TrainGuard: self-healing training — anomaly detection, last-known-good
rollback, replay bundles (docs/RELIABILITY.md § divergence runbook).

PR 6 made the stack survive *process-level* failures; this closes the loop
on *semantic* ones: a nonfinite loss, a grad spike that poisons the run, a
divergence that a multi-day Kinetics job would otherwise ride to a dead
checkpoint. The obs spine already computes the signals (loss, grad_norm,
the in-graph nonfinite flag); the guard turns them into recovery:

1. **In-graph skip-batch** (`trainer/steps.py guard_skip`): with the guard
   armed, a step whose loss or grad norm is nonfinite DISCARDS its own
   update inside the compiled step (`jnp.where` on every state leaf — no
   recompile, no host round-trip), so a single NaN batch can never poison
   params/EMA/optimizer state. Host-side detection is one step late by
   design (the deferred-fetch discipline); the in-graph skip is why that
   latency is safe.
2. **EWMA spike detection** (`SpikeDetector`): per-metric exponential
   moving mean/variance over loss and grad_norm; an UPWARD z-score
   excursion past `guard.spike_zscore` is an anomaly. Downward cliffs
   (warmup, an LR drop) are improvements and never fire; a warmup
   observation budget keeps the young-variance phase quiet.
3. **Last-known-good ring**: an own orbax `Checkpointer` under
   `<output_dir>/guard_lkg`, saved every `guard.lkg_every_steps` and
   ADVANCED ONLY WHEN THE WINDOW IS HEALTHY (no anomaly observed within
   the cadence window); `guard.lkg_keep` bounds the ring (orbax
   max_to_keep pruning).
4. **Escalation ladder**: anomaly streak < `guard.rollback_after` →
   *skip* (recorded; the in-graph skip already protected the state).
   Streak at the threshold → *rollback*: restore the LKG through the
   mesh-portable restore path and fast-forward the loader PAST the
   offending span (the consumed-position `LoaderState` recorded with the
   anomaly), so a deterministic bad span is not replayed into the same
   divergence. More than `guard.max_rollbacks` rollbacks → *halt*
   (`GuardHalt`), because a rollback loop means the problem is the data
   or the optimizer, not transient luck — see the runbook.
5. **Replay bundle**: the first anomalous step of every streak dumps
   `<output_dir>/replay/step_<N>/` — the batch tensors (`.npy`, bf16
   widened to f32), RNG seed/step, loader position, config, and metric
   evidence, all timestamp-free so the same anomaly dumps byte-identical
   bundles — a repro artifact, not a mystery.

Disarmed (`guard.enabled=false`, the default) nothing here is
constructed, the compiled step carries no skip branch, and the step loop
does one `is None` check — the `faults.py`/tsan structural-zero-overhead
discipline. Armed, the per-step cost is one deferred scalar fetch of an
already-retired step (the `DeferredStepLogger` pattern: never blocks the
step just dispatched) and one held batch reference.
"""

from __future__ import annotations

import json
import math
import os
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import numpy as np

from pytorchvideo_accelerate_tpu.utils.logging import get_logger

logger = get_logger("pva_tpu")

REPLAY_DIRNAME = "replay"


class GuardHalt(RuntimeError):
    """The escalation ladder's top: rollbacks exhausted (or impossible).
    Carries the replay-bundle path in its message — the repro artifact the
    divergence runbook starts from."""


class SpikeDetector:
    """EWMA mean/variance z-score detector over one scalar stream.

    `update(value)` returns `None` (healthy), `"nonfinite"`, or
    `"spike"`. Design points, each locked by tests/test_zguard.py:

    - UPWARD excursions only: a loss cliff downward (warmup progress, an
      LR-schedule drop) is an improvement, never an anomaly.
    - `warmup` observations pass freely while still feeding the EWMA —
      early-training statistics are too young to judge against.
    - An anomalous value is NOT absorbed into the EWMA: a divergence must
      not drag the baseline up after itself and mask its own tail.
    - Nonfinite values short-circuit (and are never absorbed).
    """

    def __init__(self, alpha: float = 0.05, zscore: float = 6.0,
                 warmup: int = 20):
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        self.alpha = float(alpha)
        self.zscore = float(zscore)
        self.warmup = int(warmup)
        self.n = 0
        self.mean = 0.0
        self.var = 0.0

    def update(self, value: float) -> Optional[str]:
        v = float(value)
        if not math.isfinite(v):
            return "nonfinite"
        if self.n >= self.warmup:
            std = math.sqrt(self.var) if self.var > 0 else 0.0
            if std > 0 and (v - self.mean) / std > self.zscore:
                return "spike"
        d = v - self.mean
        self.mean += self.alpha * d
        # EW variance (West): blends the squared innovation
        self.var = (1.0 - self.alpha) * (self.var + self.alpha * d * d)
        self.n += 1
        return None


# --- replay bundles ---------------------------------------------------------

def _np_host(value) -> np.ndarray:
    """Host numpy view of a (possibly device, possibly bf16) array. Non-f32
    floats widen to float32 — numpy's format has no bf16, and the widening
    is value-exact — with the original dtype recorded by the caller."""
    arr = np.asarray(value)
    if arr.dtype.kind == "f" and arr.dtype.itemsize < 4:
        arr = arr.astype(np.float32)
    elif arr.dtype not in (np.dtype(np.float32), np.dtype(np.float64),
                           np.dtype(np.int32), np.dtype(np.int64),
                           np.dtype(np.uint8), np.dtype(np.bool_)):
        arr = arr.astype(np.float32)
    return arr


def dump_replay_bundle(path: str, batch: Dict[str, Any],
                       meta: Dict[str, Any]) -> str:
    """Write a deterministic replay bundle directory: one `<key>.npy` per
    batch leaf + a sorted-keys `meta.json`, staged in a tmp dir and
    `os.rename`d into place (a kill mid-dump leaves no half bundle).
    Deliberately timestamp-free: the same anomaly dumps byte-identical
    bundles, which is what makes one a REPRO artifact."""
    import jax

    host = {str(k): _np_host(v)
            for k, v in jax.device_get(dict(batch)).items()}
    meta = dict(meta)
    meta["arrays"] = {
        k: {"shape": list(v.shape), "dtype": str(v.dtype),
            "source_dtype": str(np.asarray(batch[k]).dtype)}
        for k, v in host.items()}
    tmp = f"{path}.tmp-{os.getpid()}"
    os.makedirs(tmp, exist_ok=True)
    try:
        for k, v in host.items():
            np.save(os.path.join(tmp, f"{k}.npy"), v)
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump(meta, f, indent=1, sort_keys=True, default=str)
        if os.path.isdir(path):  # re-dump of the same step: replace whole
            import shutil

            shutil.rmtree(path)
        os.rename(tmp, path)
    except OSError:
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
        raise
    return path


def load_replay_bundle(path: str):
    """Read a bundle back -> `(meta, {key: np.ndarray})`."""
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    arrays = {k: np.load(os.path.join(path, f"{k}.npy"))
              for k in meta.get("arrays", {})}
    return meta, arrays


# --- the guard --------------------------------------------------------------

@dataclass
class GuardAction:
    """A verdict the step loop must act on (skips are absorbed inside the
    guard; only rollback crosses this boundary — halt raises)."""

    kind: str  # "rollback"
    lkg_step: int
    resume_position: Dict[str, int]  # LoaderState dict PAST the bad span
    bundle_path: str = ""
    reason: str = ""


@dataclass
class _Stash:
    step: int
    metrics: Dict[str, Any]  # device scalars — fetched one step later
    batch: Any               # device batch reference (one batch of HBM)
    position: Dict[str, int]  # post-consumption LoaderState dict


class TrainGuard:
    """The trainer-side state machine. One instance per Trainer when
    `guard.enabled`; `pva-tpu-doctor` reads the last one constructed via
    `guard_snapshot()`."""

    def __init__(self, cfg, output_dir: str, mesh=None, tp: bool = True,
                 config_dict: Optional[dict] = None, seed: int = 0):
        self.cfg = cfg
        self.output_dir = output_dir
        self.mesh = mesh
        self.tp = tp
        self.config_dict = config_dict or {}
        self.seed = int(seed)
        policy = getattr(cfg, "policy", "both")
        if policy not in ("nonfinite", "spike", "both"):
            raise ValueError(
                f"guard.policy must be nonfinite|spike|both, got {policy!r}")
        self.policy = policy
        self.detectors: Dict[str, SpikeDetector] = {
            name: SpikeDetector(alpha=cfg.ewma_alpha,
                                zscore=cfg.spike_zscore,
                                warmup=cfg.warmup_steps)
            for name in ("loss", "grad_norm")}
        self._pending: Optional[_Stash] = None
        self._streak = 0
        self._streak_bundle = ""
        self._last_anomaly_step: Optional[int] = None
        self.skips = 0
        self.rollbacks = 0
        self.lkg_step: Optional[int] = None
        self.last_verdict: Optional[dict] = None
        self.last_rollback: Optional[dict] = None
        self.events: List[dict] = []  # bounded evidence trail (snapshot)
        self.quarantine = None  # attached by the trainer when one exists
        self._ckpt = None  # lazy: only a run that ever saves pays orbax
        global _last_guard
        _last_guard = self

    # --- LKG ring ---------------------------------------------------------

    @property
    def lkg_dir(self) -> str:
        return os.path.join(self.output_dir, "guard_lkg")

    def _checkpointer(self):
        if self._ckpt is None:
            from pytorchvideo_accelerate_tpu.trainer.checkpoint import (
                Checkpointer,
            )

            self._ckpt = Checkpointer(self.lkg_dir,
                                      max_to_keep=max(self.cfg.lkg_keep, 1),
                                      use_async=True)
        return self._ckpt

    def ring_steps(self) -> List[int]:
        if self._ckpt is not None:
            return list(self._ckpt.all_steps())
        try:  # closed/never-opened ring: read the directory (doctor view)
            return sorted(int(d) for d in os.listdir(self.lkg_dir)
                          if d.isdigit())
        except OSError:
            return []

    def _maybe_save_lkg(self, gstep: int, live_state, loader_state) -> None:
        """Advance the LKG ring iff due AND the window is healthy: no
        anomaly observed within the last cadence window. (The state saved
        is the live one — its most recent step's metrics are one deferred
        fetch away from observation; the in-graph skip guarantees nothing
        nonfinite can be inside it regardless.)"""
        every = max(int(self.cfg.lkg_every_steps), 1)
        due = self.lkg_step is None or gstep - self.lkg_step >= every
        healthy = (self._streak == 0
                   and (self._last_anomaly_step is None
                        or gstep - self._last_anomaly_step >= every))
        if not (due and healthy and gstep > 0):
            return
        ckpt = self._checkpointer()
        if gstep in (ckpt.all_steps() or ()):
            # a post-rollback trajectory can revisit a step index the ring
            # already holds; replace it so the ring tracks THIS trajectory
            ckpt.delete(gstep)
        ckpt.save(gstep, live_state,
                  {"kind": "lkg", "data_state": loader_state.to_dict()})
        self.lkg_step = gstep
        self._event("lkg_save", step=gstep)
        self._publish("lkg_save")
        try:
            from pytorchvideo_accelerate_tpu.obs import get_registry

            get_registry().gauge(
                "pva_guard_lkg_step",
                "last-known-good checkpoint step the guard would roll back "
                "to").set(gstep)
        except Exception:  # pragma: no cover - telemetry stays optional
            pass

    # --- per-step hook -----------------------------------------------------

    def step(self, gstep: int, metrics: Dict[str, Any], batch,
             loader_state, live_state) -> Optional[GuardAction]:
        """Called right after dispatching step `gstep` (metrics are its
        device scalars). Observes the PREVIOUS step's stash — whose step
        has retired behind the one just dispatched, so the scalar fetch
        never stalls the pipeline — then stashes this one. Returns a
        `GuardAction` on rollback, raises `GuardHalt` at the ladder top."""
        prev, self._pending = self._pending, _Stash(
            gstep, metrics, batch, loader_state.to_dict())
        if prev is None:
            return None
        return self._observe(prev, gstep, live_state, loader_state)

    def flush(self, live_state, loader_state) -> Optional[GuardAction]:
        """Epoch-end: observe the final pending step (its result has been
        synced by the epoch-end fetch already)."""
        prev, self._pending = self._pending, None
        if prev is None:
            return None
        return self._observe(prev, prev.step, live_state, loader_state)

    def _verdict(self, loss: float, grad_norm: float) -> Optional[dict]:
        nonfinite = not (math.isfinite(loss) and math.isfinite(grad_norm))
        if nonfinite:
            if self.policy == "spike":
                # even spike-only policy must not FEED nonfinite values
                # into the EWMAs; it just doesn't escalate on them
                return None
            return {"kind": "nonfinite"}
        if self.policy == "nonfinite":
            for name, v in (("loss", loss), ("grad_norm", grad_norm)):
                self.detectors[name].update(v)  # keep baselines warm
            return None
        for name, v in (("loss", loss), ("grad_norm", grad_norm)):
            kind = self.detectors[name].update(v)
            if kind == "spike":
                return {"kind": "spike", "metric": name}
        return None

    def _observe(self, stash: _Stash, live_gstep: int, live_state,
                 live_loader_state) -> Optional[GuardAction]:
        loss = float(stash.metrics["loss"])
        grad_norm = float(stash.metrics["grad_norm"])
        verdict = self._verdict(loss, grad_norm)
        if verdict is None:
            self._streak = 0
            self._streak_bundle = ""
            self._maybe_save_lkg(live_gstep, live_state, live_loader_state)
            return None

        self._streak += 1
        self._last_anomaly_step = stash.step
        verdict.update(step=stash.step, loss=loss, grad_norm=grad_norm,
                       streak=self._streak, position=dict(stash.position))
        self.last_verdict = verdict
        if self._streak == 1:
            verdict["bundle"] = self._dump_bundle(stash, verdict)
            self._streak_bundle = verdict["bundle"]

        if self._streak < max(int(self.cfg.rollback_after), 1):
            self.skips += 1
            self._event("skip", **{k: v for k, v in verdict.items()
                                   if k != "position"})
            self._publish("skip")
            self._warn("guard: anomalous step skipped", verdict)
            return None

        # ladder: rollback — or halt when rollbacks are exhausted or there
        # is nothing to roll back to
        if self.rollbacks >= int(self.cfg.max_rollbacks):
            self._halt(verdict,
                       f"{self.rollbacks} rollback(s) already spent "
                       f"(guard.max_rollbacks={self.cfg.max_rollbacks}) — "
                       "a rollback loop means the data or the optimizer, "
                       "not luck")
        if self.lkg_step is None:
            self._halt(verdict,
                       "no last-known-good checkpoint exists yet "
                       "(anomaly inside the first guard.lkg_every_steps "
                       "window)")
        self.rollbacks += 1
        self._streak = 0
        self._pending = None  # the just-dispatched step is abandoned too
        action = GuardAction(
            kind="rollback", lkg_step=int(self.lkg_step),
            resume_position=dict(stash.position),
            bundle_path=self._streak_bundle,
            reason=f"{verdict['kind']} at step {stash.step} "
                   f"(loss={loss:g}, grad_norm={grad_norm:g})")
        self.last_rollback = {
            "lkg_step": action.lkg_step,
            "anomaly_step": stash.step,
            "resume_position": action.resume_position,
            "bundle": action.bundle_path, "reason": action.reason}
        self._event("rollback", **self.last_rollback)
        self._publish("rollback")
        self._warn("guard: rolling back to last-known-good",
                   self.last_rollback)
        return action

    def _halt(self, verdict: dict, why: str) -> None:
        self._event("halt", step=verdict.get("step"), why=why)
        self._publish("halt")
        raise GuardHalt(
            f"TrainGuard halt: {verdict['kind']} anomaly at step "
            f"{verdict.get('step')} — {why}. Replay bundle: "
            f"{self._streak_bundle or verdict.get('bundle') or 'none'} "
            "(docs/RELIABILITY.md § divergence runbook)")

    # --- recovery ----------------------------------------------------------

    def restore(self, state_template, action: GuardAction):
        """Mesh-portable LKG restore (`trainer/checkpoint.Checkpointer`),
        fenced behind the ring's async saves. Returns the restored state;
        the caller fast-forwards the loader to `action.resume_position`."""
        ckpt = self._checkpointer()
        ckpt.wait()
        state, _extra, step = ckpt.restore(
            state_template, step=action.lkg_step, mesh=self.mesh,
            tp=self.tp)
        return state, step

    # --- evidence ----------------------------------------------------------

    def _dump_bundle(self, stash: _Stash, verdict: dict) -> str:
        path = os.path.join(self.output_dir, REPLAY_DIRNAME,
                            f"step_{stash.step}")
        meta = {
            "step": stash.step,
            "seed": self.seed,
            "position": dict(stash.position),
            "verdict": {k: v for k, v in verdict.items()
                        if k not in ("position", "bundle")},
            "config": self.config_dict,
            "note": "rng = RngManager(seed).step_key(step); batch leaves "
                    "below (bf16 widened to f32; see arrays.*.source_dtype)",
        }
        try:
            return dump_replay_bundle(path, stash.batch, meta)
        except Exception as e:  # noqa: BLE001 - evidence must not kill recovery
            logger.warning("guard: replay bundle dump failed (%s: %s)",
                           type(e).__name__, e)
            return ""

    def _event(self, action: str, **info) -> None:
        self.events.append({"action": action, **info})
        del self.events[:-64]

    def _publish(self, action: str) -> None:
        try:
            from pytorchvideo_accelerate_tpu.obs import get_registry

            get_registry().counter(
                "pva_guard_events_total",
                "TrainGuard ladder events (skip/rollback/halt/lkg_save), "
                "by action", labelnames=("action",)).inc(action=action)
        except Exception:  # pragma: no cover - telemetry stays optional
            pass

    def _warn(self, msg: str, info: dict) -> None:
        try:
            from pytorchvideo_accelerate_tpu.obs import get_recorder

            get_recorder().warn(msg, **{k: str(v)[:200]
                                        for k, v in info.items()})
        except Exception:  # pragma: no cover
            pass

    def perf_keys(self) -> Dict[str, int]:
        """fit()'s perf-dict contribution (bench headline: asserted 0 on a
        clean smoke run)."""
        return {"guard_rollbacks": int(self.rollbacks),
                "quarantined_clips": (len(self.quarantine)
                                      if self.quarantine is not None else 0)}

    def close(self) -> None:
        if self._ckpt is not None:
            self._ckpt.close()
            self._ckpt = None


def poison_batch(batch):
    """Chaos helper for the ``nan`` kind at `step.dispatch`
    (trainer/loop.py): NaN-poison the float clip leaves of a dispatched
    batch — the deterministic stand-in for a numerically-diverged input.
    Non-float leaves (u8 clips, labels, masks) pass through untouched."""
    import jax.numpy as jnp

    out = dict(batch)
    for k in ("video", "slow", "fast"):
        v = out.get(k)
        if v is not None and jnp.issubdtype(v.dtype, jnp.floating):
            out[k] = v * jnp.asarray(float("nan"), v.dtype)
    return out


_last_guard: Optional[TrainGuard] = None


def guard_snapshot(output_dir: str = "") -> dict:
    """Doctor view (`pva-tpu-doctor diagnose()`): LKG step + ring contents,
    rollback/skip counts, last anomaly verdict, quarantine list, and —
    given an output_dir — the on-disk replay bundles and quarantine
    sidecar a SECOND shell can read while the run is wedged or dead."""
    out: dict = {}
    g = _last_guard
    out["armed"] = g is not None
    if g is not None:
        out.update(lkg_step=g.lkg_step, lkg_ring=g.ring_steps(),
                   rollbacks=g.rollbacks, skips=g.skips,
                   last_verdict=g.last_verdict,
                   last_rollback=g.last_rollback,
                   events=list(g.events[-10:]))
        if g.quarantine is not None:
            out["quarantine"] = g.quarantine.snapshot()
    if output_dir:
        rdir = os.path.join(output_dir, REPLAY_DIRNAME)
        try:
            out["replay_bundles"] = sorted(
                d for d in os.listdir(rdir) if d.startswith("step_"))
        except OSError:
            out["replay_bundles"] = []
        sidecar = os.path.join(output_dir, "quarantine.json")
        if os.path.exists(sidecar):
            try:
                with open(sidecar) as f:
                    out["quarantine_sidecar"] = json.load(f)
            except (OSError, ValueError) as e:
                out["quarantine_sidecar"] = {
                    "error": f"{type(e).__name__}: {e}"}
    return out
