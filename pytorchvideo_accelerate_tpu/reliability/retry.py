"""One retry primitive for the whole package: jittered exponential backoff
with a hard deadline.

Replaces the ad-hoc fail-or-disable behavior at the three transient-failure
sites (decode reads, checkpoint/artifact writes, tracker fan-out) with one
policy whose evidence trail is shared: every RE-attempt increments
`pva_retry_attempts_total{op=...}` in the obs registry and lands in the
flight-recorder ring; exhaustion increments `pva_retry_giveups_total`,
recovery after at least one failure `pva_retry_recoveries_total`. The first
attempt is free — a hot path that never fails never pays a counter.

Determinism under chaos: when a fault plan is armed
(`reliability/faults.py`), the backoff jitter derives from the plan's seed
and the op name, so a chaos run's retry timing replays with its fault
sequence. Without a plan, jitter is ordinary `random`.
"""

from __future__ import annotations

import random
import time
import zlib
from typing import Callable, Optional, Tuple, Type

from pytorchvideo_accelerate_tpu.reliability import faults


class RetryGiveUp(Exception):
    """Marker mixin — retry_call never raises this directly; it re-raises
    the last underlying error. Exists for callers that want to wrap."""


def _jitter(name: str, attempt: int) -> float:
    """Uniform in [0.5, 1.5): seeded from the armed fault plan (replayable
    chaos runs) or the process RNG (production)."""
    plan = faults.current_plan()
    if plan is None:
        return 0.5 + random.random()
    h = zlib.crc32(f"{plan.seed}:retry:{name}:{attempt}".encode())
    return 0.5 + (h & 0xFFFFFFFF) / 2**32


def _publish(kind: str, name: str, attempt: int, error: str = "") -> None:
    try:
        from pytorchvideo_accelerate_tpu.obs import get_recorder, get_registry

        reg = get_registry()
        counter = {
            "attempt": ("pva_retry_attempts_total",
                        "re-attempts after a retryable failure, by op"),
            "giveup": ("pva_retry_giveups_total",
                       "retry budgets exhausted (error re-raised), by op"),
            "recovery": ("pva_retry_recoveries_total",
                         "calls that succeeded after >=1 failure, by op"),
        }[kind]
        reg.counter(counter[0], counter[1], labelnames=("op",)).inc(op=name)
        get_recorder().record("retry", name, event=kind, attempt=attempt,
                              **({"error": error[:200]} if error else {}))
    except Exception:  # pragma: no cover - telemetry must not break retries
        pass


def retry_call(
    fn: Callable,
    *,
    name: str,
    attempts: int = 3,
    retry_on: Tuple[Type[BaseException], ...] = (OSError,),
    base_delay_s: float = 0.05,
    max_delay_s: float = 2.0,
    deadline_s: float = 30.0,
    sleep: Callable[[float], None] = time.sleep,
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
):
    """Call `fn()`; on a `retry_on` failure, back off and retry.

    - `attempts` is the TOTAL call budget (1 = no retries).
    - Backoff: `base_delay_s * 2^(attempt-1) * jitter(0.5..1.5)`, capped at
      `max_delay_s`.
    - `deadline_s` bounds elapsed wall time across the whole call: when the
      next sleep would cross it, the last error re-raises immediately —
      a retry loop must never outlive its caller's budget (the in-flight
      step a preemption grace period is waiting on, a serving request's
      504 budget).
    - Non-retryable exceptions propagate untouched on the first throw.
    """
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    t0 = time.monotonic()
    failures = 0
    for attempt in range(1, attempts + 1):
        try:
            result = fn()
        except retry_on as e:
            failures += 1
            last = e
            if attempt >= attempts:
                _publish("giveup", name, attempt, error=str(e))
                raise
            # jitter INSIDE the cap: max_delay_s is the documented hard
            # per-try bound, a 1.5x jitter must not overshoot it
            delay = min(base_delay_s * 2 ** (attempt - 1)
                        * _jitter(name, attempt), max_delay_s)
            if time.monotonic() - t0 + delay > deadline_s:
                _publish("giveup", name, attempt,
                         error=f"deadline {deadline_s}s: {e}")
                raise
            _publish("attempt", name, attempt, error=str(e))
            if on_retry is not None:
                on_retry(attempt, e)
            sleep(delay)
        else:
            if failures:
                _publish("recovery", name, attempt)
            return result
    raise last  # pragma: no cover - loop always raises or returns
