"""Resilience substrate: deterministic fault injection, retries, atomic
writes, preemption handling (docs/RELIABILITY.md).

The failure-handling counterpart of the analysis/ packages: PR 4/5 made the
concurrency *provable* (lint + tsan); this package makes failure handling
provable the same way — every recovery path has a seeded fault that
exercises it (`pva-tpu-chaos --smoke` is the CI gate, next to lint/tsan).

Stdlib-only on purpose: data/decode.py and the serving worker paths import
`faults`/`retry`, and they must stay importable without jax.
"""

from __future__ import annotations

from pytorchvideo_accelerate_tpu.reliability.atomic import (  # noqa: F401
    atomic_write,
    atomic_write_bytes,
    atomic_write_json,
)
from pytorchvideo_accelerate_tpu.reliability.faults import (  # noqa: F401
    FaultPlan,
    FaultSpec,
    InjectedFault,
    InjectedThreadKill,
    arm,
    current_plan,
    disarm,
    fault_history,
    fault_point,
)
from pytorchvideo_accelerate_tpu.reliability.preemption import (  # noqa: F401
    PreemptionGuard,
    get_guard,
    read_emergency_record,
    record_emergency,
)
from pytorchvideo_accelerate_tpu.reliability.retry import (  # noqa: F401
    RetryGiveUp,
    retry_call,
)
