"""Atomic file writes: tmp + fsync + os.replace.

Every artifact this package writes itself (inference-export weights/meta,
the emergency-checkpoint record, chaos/doctor reports) goes through here,
so a kill mid-save can never leave a truncated file at the destination
path — readers see the old complete content or the new complete content,
never a prefix. (Orbax training checkpoints bring their own atomic commit
protocol; this covers the plain-file writers around it.)

The `ckpt.write` fault point sits between the tmp write and the rename:
`pva-tpu-chaos` proves the property by truncating/raising there and
asserting the destination never changes.
"""

from __future__ import annotations

import json
import os
from typing import Callable

from pytorchvideo_accelerate_tpu.reliability.faults import fault_point


def _fsync_dir(path: str) -> None:
    """Durably commit the rename itself (POSIX: the directory entry)."""
    try:
        fd = os.open(path or ".", os.O_RDONLY)
    except OSError:  # pragma: no cover - non-POSIX / odd filesystems
        return
    try:
        os.fsync(fd)
    except OSError:  # pragma: no cover
        pass
    finally:
        os.close(fd)


def atomic_write(path: str, write_fn: Callable[[str], None],
                 fsync: bool = True) -> str:
    """Call `write_fn(tmp_path)` to produce the content, then fsync and
    `os.replace` onto `path`. The tmp file lives in the destination
    directory (same filesystem — replace must be a rename, not a copy) and
    keeps the destination's extension (writers like np.savez key behavior
    on it). Any failure removes the tmp and leaves `path` untouched."""
    d, base = os.path.split(path)
    root, ext = os.path.splitext(base)
    tmp = os.path.join(d, f".{root}.tmp-{os.getpid()}{ext}")
    try:
        write_fn(tmp)
        fault_point("ckpt.write", write_path=tmp)
        if fsync:
            fd = os.open(tmp, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        os.replace(tmp, path)
        if fsync:
            _fsync_dir(d)
    finally:
        try:
            if os.path.exists(tmp):
                os.remove(tmp)
        except OSError:  # pragma: no cover - cleanup is best-effort
            pass
    return path


def atomic_write_bytes(path: str, data: bytes, fsync: bool = True) -> str:
    def write(tmp: str) -> None:
        with open(tmp, "wb") as f:
            f.write(data)

    return atomic_write(path, write, fsync=fsync)


def atomic_write_json(path: str, obj, fsync: bool = True, **dump_kw) -> str:
    dump_kw.setdefault("indent", 1)
    dump_kw.setdefault("default", str)
    return atomic_write_bytes(
        path, json.dumps(obj, **dump_kw).encode(), fsync=fsync)
