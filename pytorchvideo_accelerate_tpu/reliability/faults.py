"""Seeded, deterministic fault-injection registry (pva-tpu-chaos hook).

The resilience twin of `utils/sync.py`'s primitive factory: named fault
points are planted at the real hazard sites of the data→train→serve path —

    decode.read      data/decode.py        unreadable/corrupt video
    prefetch.h2d     data/device_prefetch  slow or failing host→HBM copy
    ckpt.write       reliability/atomic.py checkpoint/artifact write dies
    tracker.log      trainer/tracking.py   tracker backend outage
    serve.flush      serving/batcher.py    inference batch failure
    step.dispatch    trainer/loop.py       slow/failing/NaN step dispatch
    collective.sync  parallel/hangcheck.py wedged/straggler mesh collective

— and cost ONE module-global read when disarmed (the default, always in
production): `fault_point()` loads `_plan`, sees None, returns. Armed (a
chaos run), each hit of a point is numbered, and every fire decision is a
pure function of `(plan.seed, point, hit_index)` — so the same plan replays
the same fault sequence, byte for byte, every run. Concurrent callers make
the *interleaving* nondeterministic, never the fired hit set.

Fault kinds:

- ``raise``: raise `InjectedFault` (an OSError, so decode/IO handlers treat
  it exactly like the real failure it stands in for);
- ``delay``: sleep `delay_s` (slow worker / slow link);
- ``partial_write``: truncate the in-flight file (the `path=` the call site
  passed) to half, then raise — the mid-write kill that must never produce
  a truncated artifact through the atomic writer;
- ``kill_thread``: raise `InjectedThreadKill` (a BaseException, so it
  escapes ordinary `except Exception` recovery and takes the worker down
  the way a real thread death would);
- ``nan``: non-raising — `fault_point` RETURNS ``"nan"`` and the call site
  interprets it (trainer/loop.py poisons the dispatched batch with NaNs:
  the numeric-divergence scenario the TrainGuard's skip/rollback ladder
  recovers from; see reliability/guard.py).

`fault_point()` returns the fired kind for non-raising kinds (``"delay"``
after sleeping, ``"nan"`` immediately) and `None` when nothing fired, so
value-interpreting sites stay one `==` away from the disarmed fast path.
A spec with `path_substr` set fires only at hits whose call-site `path`
contains it — how a chaos leg pins a deterministic per-clip failure (the
corrupt video that dies at the same clip every epoch) without touching
the other files.

Every fire increments `pva_fault_injected_total{point=...}` in the obs
registry and lands in the flight-recorder ring, so a chaos run's crash
evidence says which faults were live. See docs/RELIABILITY.md.
"""

from __future__ import annotations

import time
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from pytorchvideo_accelerate_tpu.utils.sync import make_lock


class InjectedFault(OSError):
    """An injected failure. OSError on purpose: the call sites' real
    failure handlers (DECODE_ERRORS, checkpoint-write retries) must treat
    it like the outage it simulates — a fault the product code needs
    special-casing for would prove nothing."""


class InjectedThreadKill(BaseException):
    """An injected worker death. BaseException on purpose: it must escape
    `except Exception` recovery the way a real SIGKILLed/dying thread's
    disappearance does."""


@dataclass
class FaultSpec:
    """One fault point's behavior under a plan.

    `at_hits` (exact 0-based hit indices) wins over `p` (per-hit fire
    probability, decided by a deterministic per-hit RNG). `max_fires`
    bounds total fires (0 = unlimited). `path_substr` (when set) gates the
    spec to hits whose call-site `path` contains it — a per-file fault."""

    point: str
    kind: str = "raise"  # raise | delay | partial_write | kill_thread | nan
    p: float = 1.0
    at_hits: Tuple[int, ...] = ()
    max_fires: int = 0
    delay_s: float = 0.01
    message: str = ""
    path_substr: str = ""

    _KINDS = ("raise", "delay", "partial_write", "kill_thread", "nan")

    def __post_init__(self):
        if self.kind not in self._KINDS:
            raise ValueError(
                f"fault kind must be one of {self._KINDS}, got {self.kind!r}")
        self.at_hits = tuple(int(h) for h in self.at_hits)

    def to_dict(self) -> dict:
        return {"point": self.point, "kind": self.kind, "p": self.p,
                "at_hits": list(self.at_hits), "max_fires": self.max_fires,
                "delay_s": self.delay_s, "path_substr": self.path_substr}


def _hit_roll(seed: int, point: str, hit: int) -> float:
    """Deterministic per-hit uniform in [0, 1): stable across processes
    (crc32, not `hash()` — tuple hashing is salted per interpreter)."""
    h = zlib.crc32(f"{seed}:{point}:{hit}".encode())
    return (h & 0xFFFFFFFF) / 2**32


class FaultPlan:
    """A seeded set of FaultSpecs; `arm(plan)` makes it live process-wide.

    Thread-safe: hit numbering and the fired-history append happen under
    one lock (the decode pool and serving threads hit points concurrently).
    """

    def __init__(self, seed: int, specs: List[FaultSpec]):
        self.seed = int(seed)
        self.specs: Dict[str, List[FaultSpec]] = {}
        for s in specs:
            self.specs.setdefault(s.point, []).append(s)
        self._lock = make_lock("FaultPlan._lock")
        self._hits: Dict[str, int] = {}
        self._fires: Dict[int, int] = {}  # id(spec) -> fires so far
        self.history: List[dict] = []

    def to_dict(self) -> dict:
        return {"seed": self.seed,
                "specs": [s.to_dict() for ss in self.specs.values()
                          for s in ss]}

    @classmethod
    def from_dict(cls, d: dict) -> "FaultPlan":
        return cls(d.get("seed", 0),
                   [FaultSpec(**s) for s in d.get("specs", [])])

    def points(self) -> Tuple[str, ...]:
        return tuple(sorted(self.specs))

    # --- the armed hit path -------------------------------------------------

    def _decide(self, point: str,
                path: Optional[str] = None) -> Optional[Tuple[FaultSpec, int]]:
        """Number this hit and pick the firing spec (if any) — pure
        bookkeeping under the lock; the action happens outside it. Hit
        numbering is per point regardless of `path`, so a `path_substr`
        spec never perturbs the sequence other specs see."""
        with self._lock:
            hit = self._hits.get(point, 0)
            self._hits[point] = hit + 1
            for spec in self.specs.get(point, ()):
                fires = self._fires.get(id(spec), 0)
                if spec.max_fires and fires >= spec.max_fires:
                    continue
                if spec.path_substr and spec.path_substr not in (path or ""):
                    continue
                if spec.at_hits:
                    fire = hit in spec.at_hits
                else:
                    fire = _hit_roll(self.seed, point, hit) < spec.p
                if fire:
                    self._fires[id(spec)] = fires + 1
                    self.history.append(
                        {"point": point, "hit": hit, "kind": spec.kind,
                         "ts": round(time.time(), 6)})
                    return spec, hit
            return None

    def hit(self, point: str, path: Optional[str] = None,
            write_path: Optional[str] = None) -> Optional[str]:
        decision = self._decide(point, path)
        if decision is None:
            return None
        spec, hit = decision
        _publish_fire(point, spec.kind, hit)
        msg = spec.message or f"injected {spec.kind} at {point} (hit {hit})"
        if spec.kind == "delay":
            time.sleep(spec.delay_s)
            return "delay"
        if spec.kind == "nan":
            # non-raising: the CALL SITE interprets it (e.g. trainer/loop.py
            # poisons the dispatched batch) — the registry only decides
            return "nan"
        if spec.kind == "partial_write":
            # truncation ONLY on a write_path the call site declared as
            # in-flight scratch (atomic.py's tmp file). `path` is evidence
            # (e.g. decode.read's SOURCE video) — a mis-authored
            # partial_write spec at a read point must degrade to a plain
            # raise, never corrupt real data the harness only reads.
            if write_path:
                try:
                    with open(write_path, "r+b") as f:
                        f.truncate(max(f.seek(0, 2) // 2, 0))
                except OSError:
                    pass  # nothing written yet: the raise alone suffices
            raise InjectedFault(msg)
        if spec.kind == "kill_thread":
            raise InjectedThreadKill(msg)
        raise InjectedFault(msg)


def _publish_fire(point: str, kind: str, hit: int) -> None:
    """Fire evidence: counter + flight-ring event. Best-effort — telemetry
    must never turn an injected fault into a different failure."""
    try:
        from pytorchvideo_accelerate_tpu.obs import get_recorder, get_registry

        get_registry().counter(
            "pva_fault_injected_total",
            "faults fired by the armed pva-tpu-chaos plan, by point",
            labelnames=("point",)).inc(point=point)
        get_recorder().record("fault", point, kind=kind, hit=hit)
    except Exception:  # pragma: no cover - obs stays optional here
        pass


# The armed plan or None. Module-global by design (the `utils/sync.py`
# pattern): the disarmed check must be one load, and arming is a
# whole-process decision exactly like the sanitizer runtime.
_plan: Optional[FaultPlan] = None
# survives disarm so chaos legs can read the fired sequence afterwards
_last_plan: Optional[FaultPlan] = None


def arm(plan: FaultPlan) -> FaultPlan:
    """Install `plan` process-wide. Called only by chaos harnesses/tests —
    never by application code."""
    global _plan, _last_plan
    _plan = _last_plan = plan
    return plan


def disarm() -> None:
    global _plan
    _plan = None


def current_plan() -> Optional[FaultPlan]:
    return _plan


def fault_history() -> List[dict]:
    """Fired-fault sequence of the current (or last armed) plan."""
    plan = _plan or _last_plan
    if plan is None:
        return []
    with plan._lock:
        return list(plan.history)


def fault_point(name: str, path: Optional[str] = None,
                write_path: Optional[str] = None) -> Optional[str]:
    """A named hazard site. Disarmed: one global read, immediate return
    (of None). Armed: number the hit and maybe fire (see module
    docstring); non-raising kinds return the fired kind string so
    value-interpreting sites (``nan`` poisoning) can act on it.

    `write_path` is the in-flight SCRATCH file at a write site
    (`partial_write` truncates it before raising); `path` is evidence AND
    the `path_substr` match target at read sites — the source file is
    never mutated."""
    plan = _plan
    if plan is None:
        return None
    return plan.hit(name, path, write_path)
