"""Hardware peak numbers for utilization reporting (bench.py + trainer MFU).

bf16 peak TFLOP/s per chip by `device_kind` substring; None for platforms
without a published peak (CPU, unknown accelerators) — callers then skip the
MFU line rather than report nonsense.

`resolve_peak` adds the measured fallback: on platforms with no datasheet
number (the CPU smoke lanes where `mfu` has been null on every round) it
calibrates an achievable matmul rate once per process and reports MFU
against THAT, labeled `measured` so a reader can never mistake it for a
fraction of a datasheet peak. A measured denominator is a proxy — "fraction
of this host's best matmul rate" — but an honest, labeled proxy beats a
permanent null (ROADMAP item 1).
"""

from __future__ import annotations

import time
from typing import Optional, Tuple

_PEAK_TFLOPS = [
    ("v6", 918.0),      # Trillium / v6e
    ("v5p", 459.0),
    ("v5", 197.0),      # v5e / "TPU v5 lite"
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 45.0),
]

_MEASURED: dict = {}  # device_kind -> measured peak (once per process)


def peak_tflops(device) -> Optional[float]:
    """bf16 peak TFLOP/s for one chip, or None if unknown/non-TPU."""
    if device.platform != "tpu":
        return None
    kind = device.device_kind.lower()
    for key, tf in _PEAK_TFLOPS:
        if key in kind:
            return tf
    return None


def measured_peak_tflops(device, n: int = 512, reps: int = 3,
                         min_probe_s: float = 0.01, max_n: int = 4096,
                         ) -> Optional[float]:
    """Best-of-`reps` f32 `n`x`n` matmul rate on `device`, TFLOP/s —
    the measured stand-in for a missing datasheet peak. Cached per
    device kind (one short calibration per process). None when the
    probe itself fails (no backend, OOM) — callers fall back to a null
    MFU exactly as before.

    The probe size ADAPTS: on an accelerator fast enough that the
    matmul finishes inside dispatch/transfer latency, a fixed 512^3
    probe would calibrate latency, not throughput — a "peak" of a few
    TFLOP/s on silicon with hundreds, inflating every MFU proxy built
    on it. `n` doubles (to `max_n`) until one timed run takes at least
    `min_probe_s`, so the measurement is compute-bound wherever the
    hardware allows."""
    key = (device.platform, device.device_kind)
    if key in _MEASURED:
        return _MEASURED[key]
    try:
        import jax
        import jax.numpy as jnp
        import numpy as np

        mm = jax.jit(lambda x, y: x @ y)

        def one_run(size: int, i: int) -> float:
            a = jax.device_put(jnp.ones((size, size), jnp.float32), device)
            np.asarray(mm(a, a))  # compile + warm for this size
            b = a * float(i + 1)  # fresh operand: no result caching
            t0 = time.perf_counter()
            np.asarray(mm(b, b))  # value-fetch sync (bench discipline)
            return time.perf_counter() - t0

        dt = one_run(n, 0)
        while dt < min_probe_s and n < max_n:
            n *= 2
            dt = one_run(n, 0)
        best = 2.0 * n * n * n / max(dt, 1e-9) / 1e12
        for i in range(1, reps):
            dt = one_run(n, i)
            tf = 2.0 * n * n * n / max(dt, 1e-9) / 1e12
            best = max(best, tf)
        _MEASURED[key] = best
    except Exception:
        _MEASURED[key] = None
    return _MEASURED[key]


def resolve_peak(device) -> Tuple[Optional[float], str]:
    """(peak TFLOP/s, source): the datasheet number when one exists
    ("datasheet"), else a per-process measured matmul calibration
    ("measured"), else (None, "none"). MFU consumers must carry the
    source label — a measured-peak MFU is a utilization proxy, not a
    fraction of silicon peak, and must never be compared against one."""
    peak = peak_tflops(device)
    if peak:
        return peak, "datasheet"
    peak = measured_peak_tflops(device)
    if peak:
        return peak, "measured"
    return None, "none"
