"""Hardware peak numbers for utilization reporting (bench.py + trainer MFU).

bf16 peak TFLOP/s per chip by `device_kind` substring; None for platforms
without a published peak (CPU, unknown accelerators) — callers then skip the
MFU line rather than report nonsense.
"""

from __future__ import annotations

from typing import Optional

_PEAK_TFLOPS = [
    ("v6", 918.0),      # Trillium / v6e
    ("v5p", 459.0),
    ("v5", 197.0),      # v5e / "TPU v5 lite"
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 45.0),
]


def peak_tflops(device) -> Optional[float]:
    """bf16 peak TFLOP/s for one chip, or None if unknown/non-TPU."""
    if device.platform != "tpu":
        return None
    kind = device.device_kind.lower()
    for key, tf in _PEAK_TFLOPS:
        if key in kind:
            return tf
    return None
