"""Compile-time batch-size fitting from XLA's own memory accounting.

On a fixed-HBM chip (v5e: 16 GiB) the largest per-chip batch is a hard
deployment parameter, and discovering it by OOM-crashing training jobs is
the GPU-era workflow. XLA knows the answer at compile time:
`compiled.memory_analysis()` reports argument/output/temp/alias bytes for
the exact train-step executable — no step needs to run, and (unlike an OOM
probe) a wedge-prone device tunnel is never touched for the compile-only
estimate on the CPU backend.

Estimate = arguments + outputs + temps − aliased (donated state buffers
are reused in-place). CPU-backend compiles approximate the TPU numbers
(same logical buffers; TPU tile padding adds a few percent — `margin`
covers it). `find_max_batch` bisects to the largest batch whose estimate
fits the budget.

CLI: python -m pytorchvideo_accelerate_tpu.utils.memfit --model x3d_s \
         --frames 13 --crop 160 [--hbm_gib 16] [--accum 1]
"""

from __future__ import annotations

import json
import sys
from typing import Callable, Optional, Tuple


def step_memory_bytes(model_name: str, batch: int, frames: int, crop: int,
                      num_classes: int = 700, accum: int = 1,
                      overrides: Optional[dict] = None,
                      input_u8: bool = False) -> dict:
    """Compile the train step at `batch` (per chip) and return XLA's
    memory accounting in bytes. Compile-only: nothing executes.
    Pretrain models (videomae_b_pretrain) are handled via the shared
    setup's pretrain branch. `input_u8=False` (default) sizes the fp32
    clip layout — conservative vs the u8-ingest path (whose inputs are
    4x smaller); pass True to fit the `--data.host_cast u8` config."""
    import jax

    from pytorchvideo_accelerate_tpu.utils.bench_setup import build_step_setup

    setup = build_step_setup(
        model_name, frames=frames, crop=crop, batch_per_chip=batch,
        num_classes=num_classes, accum=accum, overrides=overrides,
        devices=jax.devices()[:1], fill="zeros",  # compile-only: no RNG cost
        input_u8=input_u8,
    )
    compiled = setup.step.lower(
        setup.state, setup.device_batch(0), jax.random.key(0)).compile()
    ma = compiled.memory_analysis()
    out = {
        "batch_per_chip": batch,
        "argument_bytes": int(ma.argument_size_in_bytes),
        "output_bytes": int(ma.output_size_in_bytes),
        "temp_bytes": int(ma.temp_size_in_bytes),
        "alias_bytes": int(ma.alias_size_in_bytes),
    }
    out["estimate_bytes"] = (out["argument_bytes"] + out["output_bytes"]
                             + out["temp_bytes"] - out["alias_bytes"])
    # peak_memory_in_bytes is absent from the pinned jax 0.4.37
    # CompiledMemoryStats (same vintage as the collectives shims); the
    # sizing logic keys on estimate_bytes, so fall back to it
    peak = getattr(ma, "peak_memory_in_bytes", None)
    out["peak_bytes"] = int(peak) if peak is not None else out["estimate_bytes"]
    return out


def find_max_batch(measure: Callable[[int], int], budget_bytes: int,
                   max_batch: int = 1024) -> Tuple[int, list]:
    """Largest b in [1, max_batch] with measure(b) <= budget_bytes.

    Doubles until overflow, then bisects; `measure` is called O(log n)
    times (each call is a compile). Returns (best, probes) where probes is
    [(batch, bytes)]; best == 0 when even batch 1 overflows."""
    probes = []

    def fits(b):
        n = measure(b)
        probes.append((b, n))
        return n <= budget_bytes

    if not fits(1):
        return 0, probes
    lo = 1  # largest known-fitting
    hi = None  # smallest known-overflowing
    b = 2
    while hi is None and b <= max_batch:
        if fits(b):
            lo = b
            b *= 2
        else:
            hi = b
    if hi is None:
        # doubling passed the cap without overflowing: the answer may be
        # anywhere in (lo, max_batch] — probe the cap itself, bisect only
        # on failure (a power-of-two-only answer would understate up to 2x)
        if lo == max_batch:
            return lo, probes
        if fits(max_batch):
            return max_batch, probes
        hi = max_batch
    while hi - lo > 1:
        mid = (lo + hi) // 2
        if fits(mid):
            lo = mid
        else:
            hi = mid
    return lo, probes


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", default="slowfast_r50")
    ap.add_argument("--frames", type=int, default=32)
    ap.add_argument("--crop", type=int, default=256)
    ap.add_argument("--accum", type=int, default=1)
    ap.add_argument("--num_classes", type=int, default=700)
    ap.add_argument("--hbm_gib", type=float, default=16.0,
                    help="per-chip HBM budget (v5e: 16)")
    ap.add_argument("--margin", type=float, default=0.9,
                    help="use margin*hbm as the budget (tile padding, "
                         "runtime reserves, CPU-compile underestimate)")
    ap.add_argument("--max_batch", type=int, default=512)
    ap.add_argument("--cpu", action="store_true",
                    help="force CPU-backend compile (safe when the device "
                         "tunnel is wedged; estimates are approximate)")
    ap.add_argument("--inputs", choices=("f32", "u8"), default="f32",
                    help="clip staging to size: f32 (conservative default) "
                         "or the --data.host_cast u8 ingest layout")
    args = ap.parse_args(argv)

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    if args.accum > args.max_batch:
        ap.error(f"--accum {args.accum} exceeds --max_batch {args.max_batch}: "
                 "even one sample per micro-step would overshoot the cap")

    budget = int(args.hbm_gib * args.margin * (1 << 30))

    # with grad accumulation the effective batch must divide into accum
    # micro-steps: bisect over the MICRO batch k, measure k*accum
    def measure(k):
        r = step_memory_bytes(args.model, k * args.accum, args.frames,
                              args.crop, args.num_classes, args.accum,
                              input_u8=args.inputs == "u8")
        print(json.dumps(r), file=sys.stderr, flush=True)
        return r["estimate_bytes"]

    best_micro, probes = find_max_batch(
        measure, budget, max(args.max_batch // args.accum, 1))
    print(json.dumps({
        "model": args.model, "frames": args.frames, "crop": args.crop,
        "accum": args.accum, "hbm_gib": args.hbm_gib, "margin": args.margin,
        "budget_bytes": budget,
        "max_batch_per_chip": best_micro * args.accum,
        "micro_batch_per_chip": best_micro,
        "probes": [{"batch": k * args.accum, "bytes": n} for k, n in probes],
        "backend": jax.devices()[0].platform,
    }))


if __name__ == "__main__":
    main()
