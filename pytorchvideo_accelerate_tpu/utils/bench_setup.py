"""Shared train-step scaffolding for the measurement tools.

bench.py (throughput), scripts/perf_sweep.py (variant A/B), and
utils/memfit.py (compile-time batch fitting) all need the same setup:
model from the registry, synthetic host batch of the right family shape
(slowfast dual-pathway vs single clip; label unless pretraining; optional
micro-batch axis), init, optimizer state, compiled step. One builder keeps
the three tools measuring the same thing — family/batch-layout changes
land here once.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Any, Callable, Optional

logger = logging.getLogger(__name__)


def is_pretrain_model(model_name: str) -> bool:
    return model_name.endswith("_pretrain")


@dataclass
class StepSetup:
    model: Any
    mesh: Any
    state: Any
    step: Callable  # jitted (state, batch, rng) -> (state, metrics)
    n_chips: int
    global_batch: int
    host_batch: Callable[[int], dict]  # seed -> host numpy batch
    device_batch: Callable[[int], Any]  # seed -> mesh-sharded batch
    pretrain: bool
    input_u8: bool = False  # effective (clamped off for pretrain)
    tx: Any = None  # optimizer, for callers that rebuild step variants
    #               (graphcheck's guard-armed donation probe)


def build_step_setup(
    model_name: str,
    *,
    frames: int,
    crop: int,
    batch_per_chip: int,
    num_classes: int = 700,
    alpha: int = 4,
    accum: int = 1,
    pretrain: Optional[bool] = None,  # None = infer from the name
    overrides: Optional[dict] = None,
    devices=None,
    total_steps: int = 30,
    fill: str = "random",  # random | zeros (compile-only callers: zeros
    #                        pages are calloc'd, no RNG cost at big batches)
    input_u8: bool = False,  # raw-u8 batches + in-graph normalize (the
    #                          host_cast=u8 production path; supervised only)
    mesh_cfg=None,  # MeshConfig for a non-default layout (e.g. the 2-D
    #                 (data, model) shapes the multichip bench sweeps)
    mixed_precision: str = "bf16",  # "fp32" for numerics probes (the
    #                 multichip parity lane: bf16 summation-order noise
    #                 compounds across update steps)
    global_batch: Optional[int] = None,  # fixed TOTAL batch instead of
    #                 batch_per_chip * n_chips — the mesh-parity lane needs
    #                 the identical batch on every mesh shape
    pipeline_stages: int = 1,  # >1: run the transformer trunk as a P-stage
    #                 SPMD pipeline over the mesh's model axis
    #                 (parallel/pipeline.py; stages must equal that axis's
    #                 size — pass a matching mesh_cfg). Params stay
    #                 replicated over the stage axis (no TP).
    pipeline_microbatches: int = 0,  # 0 = auto (accum when >1, else 2P)
) -> StepSetup:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pytorchvideo_accelerate_tpu.config import (
        DataConfig, MeshConfig, ModelConfig, OptimConfig,
    )
    from pytorchvideo_accelerate_tpu.models import create_model
    from pytorchvideo_accelerate_tpu.parallel.mesh import (
        data_shard_count,
        make_train_mesh,
    )
    from pytorchvideo_accelerate_tpu.parallel.sharding import (
        family_uses_tp,
        shard_batch,
        shard_state,
    )
    from pytorchvideo_accelerate_tpu.trainer import (
        TrainState, build_optimizer, make_pretrain_step, make_train_step,
    )

    if pretrain is None:
        pretrain = is_pretrain_model(model_name)
    input_u8 = input_u8 and not pretrain  # MAE target needs the f32 clip
    cfg = ModelConfig(name=model_name, num_classes=num_classes,
                      slowfast_alpha=alpha, **(overrides or {}))
    if devices is None:
        devices = jax.devices()
    n_chips = len(devices)
    # the trainer's backbone layout (2-D (data, model) train mesh); a
    # legacy MeshConfig still resolves to the 4-axis library mesh
    mesh = make_train_mesh(mesh_cfg or MeshConfig(), devices=devices)
    plan = None
    if pipeline_stages > 1:
        from pytorchvideo_accelerate_tpu.parallel.pipeline import (
            make_plan as make_pipeline_plan,
        )

        plan = make_pipeline_plan(mesh, pipeline_stages,
                                  microbatches=pipeline_microbatches,
                                  accum_steps=accum)
    model = create_model(cfg, mixed_precision, mesh=None, pipeline=plan)
    B = global_batch if global_batch is not None else batch_per_chip * n_chips
    if B % data_shard_count(mesh):
        raise ValueError(
            f"global batch {B} must divide the mesh's "
            f"{data_shard_count(mesh)} data shards")

    if accum > 1 and B % accum:
        raise ValueError(
            f"global batch {B} ({batch_per_chip}/chip x {n_chips}) must be "
            f"divisible by accum={accum}")

    def host_batch(seed: int) -> dict:
        r = np.random.default_rng(seed)

        def clips(shape):
            if input_u8:
                # raw-u8 batches (the --data.host_cast u8 production path):
                # 4x fewer bytes over the host->device link — which is the
                # bench's single most wedge-exposed phase on the tunnel —
                # with the normalize affine applied in-graph by the step
                if fill == "zeros":
                    return np.zeros(shape, np.uint8)
                return r.integers(0, 256, shape, np.uint8)
            if fill == "zeros":
                return np.zeros(shape, np.float32)
            return r.standard_normal(shape, dtype=np.float32)

        if model_name.startswith("slowfast"):
            b = {
                "slow": clips((B, frames // alpha, crop, crop, 3)),
                "fast": clips((B, frames, crop, crop, 3)),
            }
        else:
            b = {"video": clips((B, frames, crop, crop, 3))}
        if not pretrain:
            b["label"] = r.integers(0, num_classes, B).astype(np.int32)
        if accum > 1:
            b = {k: v.reshape(accum, B // accum, *v.shape[1:])
                 for k, v in b.items()}
        return b

    def device_batch(seed: int):
        return shard_batch(mesh, host_batch(seed), micro_dim=accum > 1)

    # model init sample: shapes are arithmetic — no need to materialize a
    # full batch just to read them
    if model_name.startswith("slowfast"):
        sample = (jnp.zeros((1, frames // alpha, crop, crop, 3)),
                  jnp.zeros((1, frames, crop, crop, 3)))
    else:
        sample = jnp.zeros((1, frames, crop, crop, 3))
    variables = model.init(jax.random.key(0), sample)
    tx = build_optimizer(OptimConfig(), total_steps=total_steps)
    # shard_state, not raw create: uncommitted single-device leaves would
    # make the measured step's SECOND call recompile (layout settling),
    # corrupting the warmup accounting — same fix as Trainer's. The tp
    # flag mirrors the trainer's per-family model-axis decision.
    state = shard_state(mesh, TrainState.create(
        variables["params"], variables.get("batch_stats", {}), tx),
        tp=family_uses_tp(model_name) and plan is None)
    if pretrain:
        step = make_pretrain_step(model, tx, mesh, accum_steps=accum,
                                  pipeline=plan)
    else:
        d = DataConfig()  # canonical mean/std — the stats the u8
        #                   production path normalizes with
        step = make_train_step(
            model, tx, mesh, accum_steps=accum,
            device_normalize=(d.mean, d.std) if input_u8 else None,
            pipeline=plan,
        )
    return StepSetup(model=model, mesh=mesh, state=state, step=step,
                     n_chips=n_chips, global_batch=B, host_batch=host_batch,
                     device_batch=device_batch, pretrain=pretrain,
                     input_u8=input_u8, tx=tx)


def xla_flops(compiled) -> Optional[float]:
    """Per-step FLOPs from XLA's cost model; None when unavailable (varies
    by backend — the reason is logged, not swallowed)."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        return float(ca.get("flops", 0.0)) or None
    except Exception as e:
        logger.warning("cost_analysis unavailable: %s: %s",
                       type(e).__name__, e)
        return None


def fetch_loss(metrics) -> float:
    """Value-fetch sync for timed loops: `jax.block_until_ready` is acked
    EARLY by the axon forwarding backend (the r3/r5 430%+ "MFU" readings —
    physically impossible, so the call returned before execution). A
    device->host transfer of the loss scalar's bytes cannot complete
    early, and the step-state chain means the last loss implies every
    prior step executed. Fetched values are cached per-array, so callers
    must pass a FRESH array each time (each step's metrics are)."""
    import numpy as np

    return float(np.asarray(metrics["loss"]))
