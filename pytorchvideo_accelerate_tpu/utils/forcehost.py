"""Forced-host multi-device subprocess helper.

One CPU host can impersonate an N-chip slice: XLA's
``--xla_force_host_platform_device_count=N`` flag gives a fresh process N
fake CPU devices, which is how mesh semantics (sharded steps, collective
layouts, mesh-reshape restores) are tested without a TPU — tier-1's
conftest does it in-process, but the flag latches at backend init, so any
ALREADY-INITIALIZED process (the bench parent, a chaos scenario, a user
REPL) can only get a differently-sized device set by spawning a fresh
interpreter. This module is that spawn, packaged:

- `forced_host_env(n)` — the env block (JAX_PLATFORMS=cpu + XLA_FLAGS)
  for a subprocess that should see `n` CPU devices;
- `run_forced_host(code, n)` — run a python snippet under that env and
  parse its LAST stdout line as JSON (the bench child convention: logs to
  stderr, one machine-readable line to stdout).

Used by tests/test_zmesh.py, the bench MULTICHIP lane, and the
pva-tpu-chaos mesh-reshape preemption leg. Stdlib-only on purpose: the
caller never needs jax imported (and must not let its own device count
leak into the child).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from typing import Optional

_FORCE_FLAG = "--xla_force_host_platform_device_count"


def last_json_line(stdout: str) -> Optional[dict]:
    """The bench child-output protocol, in one place: logs go to stderr and
    exactly one machine-readable JSON object is the final stdout line —
    scan lines in reverse, return the first that parses, None if none do.
    Shared by bench.py `run_child` and `run_forced_host`."""
    for line in reversed((stdout or "").strip().splitlines()):
        try:
            return json.loads(line)
        except ValueError:
            continue
    return None


def forced_host_env(n_devices: int, extra_env: Optional[dict] = None) -> dict:
    """Environment for a fresh process that sees `n_devices` CPU devices.

    Any inherited force-count flag is REPLACED, not appended — XLA honors
    the first occurrence, so tier-1's ambient 8-device flag would otherwise
    silently win over the requested count."""
    env = dict(os.environ)
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if not f.startswith(_FORCE_FLAG)]
    flags.append(f"{_FORCE_FLAG}={int(n_devices)}")
    env["XLA_FLAGS"] = " ".join(flags)
    env["JAX_PLATFORMS"] = "cpu"
    env.update(extra_env or {})
    return env


def run_forced_host(code: str, n_devices: int, timeout: float = 600.0,
                    extra_env: Optional[dict] = None) -> dict:
    """Run `code` (a python source string) in a subprocess with `n_devices`
    forced CPU devices; returns the last stdout line parsed as JSON.

    The snippet's contract: print exactly one JSON object as its final
    stdout line (everything else goes to stderr). Raises RuntimeError with
    the stderr tail on a nonzero exit, a timeout, or unparseable output —
    a mesh test must fail loudly, never return half a result."""
    env = forced_host_env(n_devices, extra_env)
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], env=env, timeout=timeout,
            capture_output=True, text=True)
    except subprocess.TimeoutExpired as e:
        tail = ((e.stderr or b"").decode() if isinstance(e.stderr, bytes)
                else (e.stderr or ""))[-2000:]
        raise RuntimeError(
            f"forced-host({n_devices}) subprocess timed out after "
            f"{timeout}s; stderr tail:\n{tail}") from e
    if proc.returncode != 0:
        raise RuntimeError(
            f"forced-host({n_devices}) subprocess exited "
            f"{proc.returncode}; stderr tail:\n{proc.stderr[-2000:]}")
    out = last_json_line(proc.stdout)
    if out is None:
        raise RuntimeError(
            f"forced-host({n_devices}) subprocess produced no JSON line; "
            f"stdout tail:\n{(proc.stdout or '')[-500:]}")
    return out
