"""Single creation point for threading primitives (pva-tpu-tsan hook).

Every lock, condition, worker thread, and cross-thread handoff queue in the
package is constructed HERE instead of via `threading.*` directly (the
`thread-factory` lint rule enforces it). Reason: the dynamic sanitizer
(`analysis/tsan.py`) can only track locksets, lock-acquisition order, and
happens-before edges for primitives it can see being created — a factory
gives it one interception point instead of a monkeypatching whack-a-mole.

Disarmed (the default, always in production): each `make_*` is one module
global read + a `None` check at CREATION time, then returns the raw stdlib
primitive — the returned object is indistinguishable from `threading.Lock()`
et al., so steady-state lock traffic pays zero overhead. Armed (inside a
`pva-tpu-tsan` run): the registered runtime wraps each primitive with its
tracking twin.

`shared_state(...)` is the companion registry: the known cross-thread
classes declare which instance attributes are shared mutable state, and the
sanitizer instruments exactly those attribute accesses while armed (a class
decorator is import-time metadata only — nothing happens until `arm()`).
Registered today: DevicePrefetcher, MicroBatcher, ServingStats,
AdmissionController, Watchdog, SpanCollector, FlightRecorder, TrackerHub,
the distributed tracer (obs/trace.Tracer), the fleet tier's
Scheduler / ReplicaPool / Router / LoadGen (fleet/*.py), the fleet
control loops' Autoscaler / CanaryController / ModelBudget
(fleet/control/*.py), the data plane's RemoteClipFeed / DecodeWorker
(dataplane/*.py — the credit/ack machinery), and the pva-tpu-hbm layer's
MemoryLedger / MetricsHistory / AlertEngine / ProfilerCapture
(obs/memory.py, obs/history.py, obs/alerts.py, obs/profiler.py — ledger
churn and alert flaps race scrape ticks) — new threaded classes MUST
declare here so the pva-tpu-tsan stress scenario gates their concurrency
like everything else's.

Stdlib-only on purpose: obs/ and serving worker paths import this module,
and they must stay importable without jax (this file must never grow a
heavyweight import).
"""

from __future__ import annotations

import queue
import threading
from typing import Dict, List, Optional

# The armed sanitizer runtime (analysis/tsan.Tsan) or None. Module-global by
# design: the check must be one load, and arming is a whole-process decision
# exactly like logging configuration.
_runtime = None


def set_runtime(runtime) -> None:
    """Install (or clear, with None) the sanitizer runtime. Called only by
    `analysis.tsan` arm/disarm — never by application code."""
    global _runtime
    _runtime = runtime


def get_runtime():
    return _runtime


# --- creation points --------------------------------------------------------

def make_lock(name: str = "lock"):
    """A mutex; `name` is the lock's CLASS for the sanitizer (lockdep-style:
    every instance created at one call site shares the name, so the
    acquisition-order graph generalizes across instances)."""
    rt = _runtime
    if rt is None:
        return threading.Lock()
    return rt.wrap_lock(name, reentrant=False)


def make_rlock(name: str = "rlock"):
    rt = _runtime
    if rt is None:
        return threading.RLock()
    return rt.wrap_lock(name, reentrant=True)


def make_condition(name: str = "cond", lock=None):
    """A condition over a factory-made lock (created here when not given,
    so the sanitizer sees the condition's mutex too)."""
    if lock is None:
        lock = make_rlock(name + ".lock")
    return threading.Condition(lock)


def make_thread(target=None, name: Optional[str] = None, args: tuple = (),
                kwargs: Optional[dict] = None,
                daemon: Optional[bool] = None) -> threading.Thread:
    """A worker thread. Armed, start()/join() publish happens-before edges
    (parent's writes before start() are visible to the child; the child's
    writes are visible to a joiner) so ordinary lifecycle handoffs never
    read as races."""
    rt = _runtime
    if rt is None:
        return threading.Thread(target=target, name=name, args=args,
                                kwargs=kwargs or {}, daemon=daemon)
    return rt.wrap_thread(target=target, name=name, args=args,
                          kwargs=kwargs or {}, daemon=daemon)


def make_queue(maxsize: int = 0) -> "queue.Queue":
    """A cross-thread handoff queue. Armed, every put→get carries a
    happens-before edge (the producer's writes to an object published
    through the queue are ordered before the consumer's reads — the
    standard ownership-transfer pattern must not false-alarm)."""
    rt = _runtime
    if rt is None:
        return queue.Queue(maxsize=maxsize)
    return rt.wrap_queue(maxsize=maxsize)


# --- shared-state registry --------------------------------------------------

# classes that declared shared fields, in registration order
_SHARED_CLASSES: List[type] = []


def shared_state(*fields: str, benign: Optional[Dict[str, str]] = None):
    """Class decorator: declare which instance attributes are cross-thread
    shared mutable state. Import-time cost: two class attributes and a
    registry append — instrumentation is installed only while the sanitizer
    is armed (and removed on disarm).

    `benign` maps field -> reason for races that are understood and
    accepted; armed findings on those fields are reported as suppressed
    (auditable, never fatal) — the dynamic twin of the linter's
    `# pva: disable=... -- reason`.
    """

    def deco(cls: type) -> type:
        cls.__pva_shared_fields__ = frozenset(fields)
        cls.__pva_benign_fields__ = dict(benign or {})
        _SHARED_CLASSES.append(cls)
        rt = _runtime
        if rt is not None:  # module imported while a sanitizer is armed
            rt.instrument_class(cls)
        return cls

    return deco


def shared_classes() -> List[type]:
    return list(_SHARED_CLASSES)
