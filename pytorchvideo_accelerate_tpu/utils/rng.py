"""Seeded randomness.

Replaces accelerate's `set_seed` (python/numpy/torch/cuda/xla RNG, SURVEY
§2.2-A11) and its per-iteration cross-rank RNG synchronization
(`synchronize_rng_states` at data_loader.py:577-578). In JAX the second half
is free: `jax.random` keys are values, identical on every process by
construction, and per-step keys are derived with `fold_in` — so "RNG sync
across ranks" is a non-problem, and per-step determinism survives resume by
re-deriving from (seed, step).
"""

from __future__ import annotations

import random as _pyrandom
from dataclasses import dataclass

import jax
import numpy as np


def set_seed(seed: int) -> jax.Array:
    """Seed host-side RNGs (python, numpy) and return the root JAX key.

    Reference semantics: `set_seed(42)` at run.py:138. Host RNGs matter for
    data-pipeline shuffling; device randomness flows from the returned key.
    """
    _pyrandom.seed(seed)
    np.random.seed(seed % (2**32))
    return jax.random.key(seed)


@dataclass
class RngManager:
    """Derives all randomness from one seed.

    - `step_key(step)`: dropout/drop-path key for a train step, identical on
      every host (keys are deterministic values), distinct per step.
    - `data_key(epoch)`: shuffle key for the data pipeline epoch.
    - `host_key(step)`: additionally folded with process_index, for the rare
      host-local use (e.g. per-host augmentation workers).
    """

    seed: int

    def __post_init__(self):
        # Key derivation only — global python/numpy seeding is an explicit
        # startup action (call set_seed once in the app), not a constructor
        # side effect, so building a second manager mid-run can't silently
        # rewind host-side augmentation streams.
        self.root = jax.random.key(self.seed)

    def step_key(self, step) -> jax.Array:
        return jax.random.fold_in(self.root, step)

    def init_key(self) -> jax.Array:
        """Model parameter-init key, in its own fold_in subtree so it can
        never collide with step_key(n) for any step n."""
        return jax.random.fold_in(jax.random.fold_in(self.root, 0x1A171), 0)

    def data_key(self, epoch: int) -> jax.Array:
        return jax.random.fold_in(jax.random.fold_in(self.root, 0x9E3779B9), epoch)

    def host_key(self, step: int) -> jax.Array:
        return jax.random.fold_in(self.step_key(step), jax.process_index())

    def numpy_epoch_seed(self, epoch: int) -> int:
        """Deterministic numpy seed for host-side augmentation at `epoch`."""
        return int(jax.random.randint(self.data_key(epoch), (), 0, 2**31 - 1))
