"""Device reachability doctor: probe, diagnose, and guard TPU backend init
(SURVEY §5 failure detection — the device-attachment analogue of
`data/verify.py`'s dataset doctor).

Motivation (observed on the build image, PROBES_r05.md): a PJRT plugin's
client-create can hang FOREVER rather than fail, and plugin registration
machinery may force the device platform at jax-config level so even
`JAX_PLATFORMS=cpu` jobs wedge. The reference stack (torch + NCCL) fails
loudly on a bad device; a jax job just sits there. This module gives the
framework the same loud-failure property:

- `quick_probe(timeout)`: can a DISPOSABLE subprocess enumerate devices
  and run one op within the deadline? (The parent never touches devices —
  a wedged init in the main process is unrecoverable.)
- `assert_device_reachable(timeout)`: Trainer guard (config
  `device_init_timeout`) — raises RuntimeError with the diagnosis recipe
  instead of letting the training job hang in backend init.
- `diagnose(...)`: full evidence capture — plugin env/file facts, loopback
  relay liveness, a verbose init attempt whose stderr tail survives the
  kill, and alternative init paths (cpu-via-config control, cpu-via-env,
  tpu-direct) that localize WHICH layer is stuck.
- `main()`: the `pva-tpu-doctor` CLI.

Every subprocess redirects stderr to a file first: a hung child gets
SIGKILLed, and a pipe would discard exactly the init logs the diagnosis
needs.
"""

from __future__ import annotations

import datetime
import json
import os
import signal
import socket
import subprocess
import sys
import time
from typing import Dict, Optional

ENV_PREFIXES = ("TPU", "PJRT", "JAX", "XLA", "AXON", "PALLAS", "LIBTPU")

PROBE_CODE = ("import jax, numpy as np\n"
              "d = jax.devices()[0]\n"
              "x = jax.device_put(np.ones((128, 128), np.float32), d)\n"
              "jax.jit(lambda a: a @ a)(x).block_until_ready()\n"
              "print(d.platform, d.device_kind)\n")
DEVICES_CODE = ("import jax\n"
                "ds = jax.devices()\n"
                "print('DEVICES:', [(d.platform, d.device_kind) "
                "for d in ds])\n")
CPU_CONFIG_CODE = ("import jax\n"
                   "jax.config.update('jax_platforms', 'cpu')\n"
                   "ds = jax.devices()\n"
                   "print('DEVICES:', [(d.platform, d.device_kind) "
                   "for d in ds])\n")


def _utcnow() -> str:
    return datetime.datetime.now(datetime.timezone.utc).strftime("%FT%TZ")


def env_snapshot() -> Dict[str, str]:
    return {k: v for k, v in sorted(os.environ.items())
            if any(k.upper().startswith(p) or f"_{p}" in k.upper()
                   for p in ENV_PREFIXES)}


def file_facts() -> dict:
    out: dict = {}
    for label, path in (
            ("pjrt_plugin", os.environ.get("PJRT_LIBRARY_PATH", "")),
            ("libtpu", os.environ.get("TPU_LIBRARY_PATH", ""))):
        if not path:
            out[label] = "env var unset"
        elif os.path.exists(path):
            st = os.stat(path)
            out[label] = {"path": path, "bytes": st.st_size,
                          "mtime": datetime.datetime.fromtimestamp(
                              st.st_mtime).strftime("%FT%T")}
        else:
            out[label] = {"path": path, "missing": True}
    return out


def loopback_listeners() -> list:
    """Every loopback LISTEN socket + a connect attempt to each — a relay
    that refuses is a different failure than a relay that accepts while
    the handshake behind it never completes."""
    ports = set()
    try:
        for row in open("/proc/net/tcp").read().splitlines()[1:]:
            f = row.split()
            ip, port = f[1].split(":")
            if f[3] == "0A" and ip == "0100007F":  # LISTEN on 127.0.0.1
                ports.add(int(port, 16))
    except OSError as e:
        return [{"error": f"/proc/net/tcp unreadable: {e}"}]
    out = []
    for port in sorted(ports):
        rec: dict = {"port": port}
        t0 = time.perf_counter()
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=2.0):
                rec["connect"] = "ok"
        except OSError as e:
            rec["connect"] = f"{type(e).__name__}: {e}"
        rec["connect_ms"] = round((time.perf_counter() - t0) * 1e3, 1)
        out.append(rec)
    return out


def _attempt(code: str, env: dict, timeout_s: int,
             err_path: Optional[str] = None,
             tail_bytes: int = 4000) -> dict:
    """Run `code` in a disposable subprocess (own process group, killed
    wholesale on timeout) with stderr redirected to a FILE so the tail
    survives the kill. Default: a fresh mkstemp file, removed after
    reading — fixed shared names would collide across concurrent probes
    (two launch ranks both running the init guard) and across users of a
    shared /tmp."""
    import tempfile

    own_file = err_path is None
    if own_file:
        fd, err_path = tempfile.mkstemp(prefix="pva_doctor_", suffix=".txt")
        os.close(fd)
    rec: dict = {"timeout_s": timeout_s}
    t0 = time.time()
    try:
        with open(err_path, "wb") as errf:
            p = subprocess.Popen([sys.executable, "-c", code], env=env,
                                 stdout=subprocess.PIPE, stderr=errf,
                                 text=True, start_new_session=True)
            try:
                out, _ = p.communicate(timeout=timeout_s)
                rec.update(ok=p.returncode == 0, returncode=p.returncode,
                           stdout=(out or "").strip()[-300:])
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(p.pid, signal.SIGKILL)
                except OSError:
                    pass
                p.wait()
                rec.update(ok=False, error="timeout (killed)")
        rec["elapsed_s"] = round(time.time() - t0, 1)
        try:
            with open(err_path, "rb") as f:
                data = f.read()
            rec["stderr_bytes"] = len(data)
            rec["stderr_tail"] = data[-tail_bytes:].decode("utf-8", "replace")
        except OSError:
            pass
    finally:
        if own_file:
            try:
                os.unlink(err_path)
            except OSError:
                pass
    return rec


def quick_probe(timeout_s: int = 240) -> dict:
    """Enumerate devices + run one op in a disposable subprocess, default
    init path (whatever the job itself would get). Returns the attempt
    record; `ok` means the main process can safely init its backend."""
    env = dict(os.environ)
    env["PYTHONUNBUFFERED"] = "1"
    rec = _attempt(PROBE_CODE, env, timeout_s, tail_bytes=1000)
    rec["ts"] = _utcnow()
    return rec


def assert_device_reachable(timeout_s: int, log=None) -> dict:
    """Trainer guard: fail LOUDLY (RuntimeError) when backend init would
    hang, instead of wedging the training job in jax.devices().

    A probe child that lands on the CPU backend counts as reachable — the
    job will get the same backend, and CPU init doesn't hang."""
    log = log or (lambda msg: print(msg, file=sys.stderr))
    log(f"[device_doctor] probing device init ({timeout_s}s cap) ...")
    rec = quick_probe(timeout_s)
    if rec.get("ok"):
        log(f"[device_doctor] device ok in {rec['elapsed_s']}s: "
            f"{rec.get('stdout', '')}")
        return rec
    raise RuntimeError(
        "device backend init did not complete within "
        f"{timeout_s}s (probe: {rec.get('error') or rec.get('stderr_tail', '')[-200:]}). "
        "Refusing to start a training job that would hang in "
        "jax.devices(). Diagnose with `pva-tpu-doctor --variants` "
        "(plugin env, relay liveness, verbose init attempt, init-path "
        "variants), run on CPU with --cpu, or raise/disable the guard "
        "via --device_init_timeout (0 disables)."
    )


def init_variant(name: str, env_overrides: dict, timeout_s: int,
                 code: str = DEVICES_CODE) -> dict:
    """One `jax.devices()` attempt under an alternative init path:

    - `cpu_config`: platform forced by jax.config.update in code — the
      interpreter/jax health control, and the only override that beats a
      registration-time config force.
    - `cpu_env`: JAX_PLATFORMS=cpu env var only — diverges from
      cpu_config exactly when plugin registration overrides the env.
    - `tpu_direct`: JAX_PLATFORMS=tpu, bypassing any vendor plugin; a
      quick "no TPU found" failure vs a hang localizes the stuck layer.
    """
    env = dict(os.environ)
    env.update({k: str(v) for k, v in env_overrides.items()})
    env["PYTHONUNBUFFERED"] = "1"
    rec = _attempt(code, env, timeout_s, tail_bytes=1000)
    return {"variant": name, "env_overrides": env_overrides, **rec}


def verbose_init_attempt(timeout_s: int = 120,
                         tail_bytes: int = 4000) -> dict:
    """Default init path under maximum plugin verbosity — whatever the
    plugin logs before wedging is the diagnosis."""
    env = dict(os.environ)
    env.update(
        TPU_STDERR_LOG_LEVEL="0",   # INFO and up to stderr
        TPU_MIN_LOG_LEVEL="0",
        TPU_VMODULE="*=1",
        JAX_LOGGING_LEVEL="DEBUG",
        PYTHONUNBUFFERED="1",
    )
    return _attempt(DEVICES_CODE, env, timeout_s,
                    tail_bytes=tail_bytes)


def obs_snapshot(output_dir: str = "", last: int = 30) -> dict:
    """Telemetry snapshot for diagnosing a wedged run (obs/ spine):

    - in-process: every thread's OPEN span stack (who is inside what right
      now) + the last flight-recorder events — the live view when the
      doctor runs inside the stuck process (the Trainer init guard path);
    - cross-process: the tail of `<output_dir>/flight_record.json` — the
      file the watchdog / excepthook / SIGTERM handler dumps, i.e. the
      evidence a SECOND shell reads while (or after) the run is wedged:
      `pva-tpu-doctor --obs-dir <output_dir> --skip-init`.
    """
    out: dict = {"ts": _utcnow()}
    try:
        from pytorchvideo_accelerate_tpu import obs

        out["span_stacks"] = obs.current_stacks()
        out["recent_events"] = obs.get_recorder().snapshot(last)
    except Exception as e:  # the doctor must never die of its own probes
        out["error"] = f"{type(e).__name__}: {e}"
    if output_dir:
        path = os.path.join(output_dir, "flight_record.json")
        try:
            with open(path) as f:
                data = json.load(f)
            out["flight_record_file"] = {
                "path": path,
                "dumped_at": data.get("dumped_at"),
                "pid": data.get("pid"),
                "events": data.get("events", [])[-last:],
            }
        except (OSError, ValueError) as e:
            out["flight_record_file"] = {
                "path": path, "error": f"{type(e).__name__}: {e}"}
    return out


def lint_snapshot(root: str = "", max_items: int = 40) -> dict:
    """Static-analysis health of the installed package (analysis/ —
    docs/STATIC_ANALYSIS.md): a fresh `pva-tpu-lint` pass (finding count
    + heads) and every outstanding `# pva: disable=... -- reason`
    suppression with its file, rules, and reason. Suppressions are
    DEBT the linter is carrying on purpose; surfacing them here keeps
    them auditable instead of letting reasons rot in comments."""
    out: dict = {"ts": _utcnow()}
    try:
        from pytorchvideo_accelerate_tpu.analysis import (
            iter_suppressions,
            lint_source,
        )
        from pytorchvideo_accelerate_tpu.analysis.core import iter_py_files

        if not root:
            root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        # one read per file feeds BOTH the lint pass and the suppression
        # audit (run_lint would re-read the tree this loop already reads)
        findings, sups = [], []
        for fp in iter_py_files([root]):
            try:
                with open(fp, encoding="utf-8") as f:
                    source = f.read()
            except OSError:
                continue
            findings.extend(lint_source(source, path=fp))
            rel = os.path.relpath(fp, os.path.dirname(root))
            for s in iter_suppressions(source):
                sups.append({"file": rel, "line": s.line,
                             "rules": list(s.rules), "reason": s.reason})
        out["findings"] = len(findings)
        out["finding_heads"] = [f.format() for f in findings[:max_items]]
        out["suppressions"] = len(sups)
        out["suppression_list"] = sups[:max_items]
        # a suppression without a reason defeats the audit trail — count
        # them so the doctor's reader sees the debt explicitly
        out["suppressions_without_reason"] = sum(
            1 for s in sups if not s["reason"])
    except Exception as e:  # the doctor must never die of its own probes
        out["error"] = f"{type(e).__name__}: {e}"
    return out


def trace_snapshot() -> dict:
    """Distributed-tracing health (obs/trace.py — docs/OBSERVABILITY.md
    § distributed tracing): whether the tracer is armed, ring occupancy
    vs capacity, how many heads were sampled, the tracer's self-measured
    bookkeeping overhead, the slowest root spans still in the ring, and
    the last export path (`trace_ring.json`) a second shell can merge
    with `pva-tpu-trace`."""
    out: dict = {"ts": _utcnow()}
    try:
        from pytorchvideo_accelerate_tpu.obs import trace

        out.update(trace.snapshot())
    except Exception as e:  # the doctor must never die of its own probes
        out["error"] = f"{type(e).__name__}: {e}"
    return out


def tsan_snapshot() -> dict:
    """Dynamic-sanitizer health (analysis/tsan.py — docs/STATIC_ANALYSIS.md
    § dynamic sanitizer): whether a pva-tpu-tsan run happened in this
    process, the current lock-order graph, live held locks per thread, and
    recent finding counts. For a wedged ARMED process this is the "who
    holds what right now" view the stall dump can't always reach."""
    out: dict = {"ts": _utcnow()}
    try:
        from pytorchvideo_accelerate_tpu.analysis.tsan_report import (
            tsan_snapshot as _snap,
        )

        out.update(_snap())
    except Exception as e:  # the doctor must never die of its own probes
        out["error"] = f"{type(e).__name__}: {e}"
    return out


def reliability_snapshot(output_dir: str = "") -> dict:
    """Resilience health (reliability/ — docs/RELIABILITY.md): the armed
    fault plan if a chaos run is live (production must read `None`), the
    fired-fault history length, the retry/fault counters from the default
    registry, and — given the run's output_dir — the last emergency-
    checkpoint record (where a preempted run stopped, and the directory
    `resume=auto` will pick up)."""
    out: dict = {"ts": _utcnow()}
    try:
        from pytorchvideo_accelerate_tpu.obs import get_registry
        from pytorchvideo_accelerate_tpu.reliability import faults
        from pytorchvideo_accelerate_tpu.reliability.preemption import (
            read_emergency_record,
        )

        plan = faults.current_plan()
        out["fault_plan_armed"] = plan is not None
        if plan is not None:
            out["fault_plan"] = plan.to_dict()
        out["fault_fires"] = len(faults.fault_history())
        reg = get_registry()
        counters: dict = {}
        for name in ("pva_retry_attempts_total", "pva_retry_giveups_total",
                     "pva_retry_recoveries_total",
                     "pva_fault_injected_total"):
            m = reg.get(name)
            if m is None:
                continue
            counters[name] = {
                ",".join(f"{k}={v}" for k, v in labels.items()) or "total":
                value for labels, value in m.samples()}
        out["retry_counters"] = counters
        if output_dir:
            out["emergency_checkpoint"] = read_emergency_record(output_dir)
    except Exception as e:  # the doctor must never die of its own probes
        out["error"] = f"{type(e).__name__}: {e}"
    return out


def guard_snapshot(output_dir: str = "") -> dict:
    """Self-healing-guard health (reliability/guard.py TrainGuard —
    docs/RELIABILITY.md § divergence runbook): LKG step + ring contents,
    rollback/skip counts, the last anomaly verdict, the quarantine list,
    and — given the run's output_dir — the on-disk replay bundles and
    quarantine sidecar a second shell reads for a wedged/dead run."""
    out: dict = {"ts": _utcnow()}
    try:
        from pytorchvideo_accelerate_tpu.reliability.guard import (
            guard_snapshot as _snap,
        )

        out.update(_snap(output_dir))
    except Exception as e:  # the doctor must never die of its own probes
        out["error"] = f"{type(e).__name__}: {e}"
    return out


def graphcheck_snapshot() -> dict:
    """Compiled-graph analysis health (analysis/graphcheck.py —
    docs/STATIC_ANALYSIS.md § graphcheck): the last in-process
    pva-tpu-graphcheck run's per-pass finding counts and the
    donation-verified verdict. ran=False in a fresh process — the doctor
    reports the absence rather than paying a multi-second trace of the
    step functions on every diagnosis."""
    out: dict = {"ts": _utcnow()}
    try:
        from pytorchvideo_accelerate_tpu.analysis.graphcheck import (
            graphcheck_snapshot as _snap,
        )

        out.update(_snap())
    except Exception as e:  # the doctor must never die of its own probes
        out["error"] = f"{type(e).__name__}: {e}"
    return out


def spmd_snapshot() -> dict:
    """Collective-schedule divergence health (analysis/spmdcheck.py —
    docs/STATIC_ANALYSIS.md § spmdcheck): the last in-process static
    pass's finding counts by rule/kind plus the live schedule recorder's
    per-host record counts (recorder=None when disarmed). ran=False in a
    fresh process — the static pass is cheap but the doctor reports
    state, it doesn't mint it."""
    out: dict = {"ts": _utcnow()}
    try:
        from pytorchvideo_accelerate_tpu.analysis.spmdcheck import (
            spmd_snapshot as _snap,
        )

        out.update(_snap())
    except Exception as e:  # the doctor must never die of its own probes
        out["error"] = f"{type(e).__name__}: {e}"
    return out


def memory_snapshot() -> dict:
    """Device-memory ledger health (obs/memory.py — docs/OBSERVABILITY.md
    § memory ledger): per-component registered bytes, the unattributed
    residual against the backend's `bytes_in_use`, the declared-vs-
    measured drift per component, and the measurement source ("measured"
    on a backend with memory_stats, "estimate" elsewhere — a CPU doctor
    run must say so, never fake device bytes). armed=False when no run
    in this process configured the ledger."""
    out: dict = {"ts": _utcnow()}
    try:
        from pytorchvideo_accelerate_tpu.obs import memory as obs_memory

        led = obs_memory.get_ledger()
        out["armed"] = led is not None
        if led is not None:
            out.update(led.snapshot())
    except Exception as e:  # the doctor must never die of its own probes
        out["error"] = f"{type(e).__name__}: {e}"
    return out


def alerts_snapshot() -> dict:
    """Metrics-history / burn-rate alert health (obs/history.py +
    obs/alerts.py): history ring occupancy and span, per-rule state
    (active, fire count, last fast/slow burn factors, last clear) and
    the currently-firing set. armed=False when neither the history nor
    the alert engine was configured in this process."""
    out: dict = {"ts": _utcnow()}
    try:
        from pytorchvideo_accelerate_tpu.obs import alerts as obs_alerts
        from pytorchvideo_accelerate_tpu.obs import history as obs_history

        engine = obs_alerts.get_engine()
        hist = obs_history.get_history()
        out["armed"] = engine is not None or hist is not None
        if engine is not None:
            out.update(engine.snapshot())
        elif hist is not None:
            out["history"] = hist.snapshot()
    except Exception as e:  # the doctor must never die of its own probes
        out["error"] = f"{type(e).__name__}: {e}"
    return out


def diagnose(timeout_s: int = 120, skip_init: bool = False,
             variants: bool = False, obs_dir: str = "") -> dict:
    rec = {
        "probe": "diagnostics",
        "ts": _utcnow(),
        "env": env_snapshot(),
        "files": file_facts(),
        "loopback_listeners": loopback_listeners(),
        "obs": obs_snapshot(obs_dir),
        "trace": trace_snapshot(),
        "lint": lint_snapshot(),
        "graphcheck": graphcheck_snapshot(),
        "spmd": spmd_snapshot(),
        "tsan": tsan_snapshot(),
        "reliability": reliability_snapshot(obs_dir),
        "guard": guard_snapshot(obs_dir),
        "memory": memory_snapshot(),
        "alerts": alerts_snapshot(),
    }
    if not skip_init:
        rec["verbose_init"] = verbose_init_attempt(timeout_s)
        rec["ok"] = bool(rec["verbose_init"].get("ok"))
    if variants:
        rec["init_variants"] = [
            init_variant("cpu_config", {}, 120, code=CPU_CONFIG_CODE),
            init_variant("cpu_env", {"JAX_PLATFORMS": "cpu"}, 120),
            init_variant("tpu_direct", {"JAX_PLATFORMS": "tpu"},
                         min(timeout_s, 120)),
        ]
    return rec


def main(argv: Optional[list] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--timeout", type=int, default=120,
                    help="seconds for the verbose init attempt")
    ap.add_argument("--skip-init", action="store_true",
                    help="environment + relay checks only (no init attempt)")
    ap.add_argument("--variants", action="store_true",
                    help="also try alternative init paths (cpu-config "
                         "control, cpu-env, tpu-direct) to localize a hang")
    ap.add_argument("--obs-dir", default="",
                    help="training run's output_dir: include the tail of "
                         "its dumped flight_record.json (watchdog/"
                         "excepthook evidence) in the obs snapshot — the "
                         "second-shell diagnosis path for a wedged run")
    ap.add_argument("--log", default="",
                    help="append the JSON record to this jsonl file")
    args = ap.parse_args(argv)

    rec = diagnose(args.timeout, args.skip_init, args.variants,
                   obs_dir=args.obs_dir)
    print(json.dumps(rec, indent=1))
    if args.log:
        with open(args.log, "a") as f:
            f.write(json.dumps(rec) + "\n")
    ok = rec.get("ok")
    return 0 if (ok or args.skip_init) else 1


if __name__ == "__main__":
    sys.exit(main())
