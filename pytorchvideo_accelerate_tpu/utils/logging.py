"""Logging setup: one configured logger, main-process gating."""

from __future__ import annotations

import logging
import sys


def get_logger(name: str = "pva_tpu") -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
        )
        logger.addHandler(handler)
        logger.setLevel(logging.INFO)
    return logger
