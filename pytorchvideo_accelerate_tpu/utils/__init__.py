"""Utilities: RNG management, logging, profiling, debug modes."""

from pytorchvideo_accelerate_tpu.utils.rng import RngManager, set_seed  # noqa: F401
