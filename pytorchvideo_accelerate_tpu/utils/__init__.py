"""Utilities: RNG management, logging, profiling, sync factory, debug modes.

The RNG re-exports are lazy (PEP 562): `utils.rng` imports jax, and the
stdlib-only consumers of this package (obs/, utils/sync.py — imported from
worker threads and the analysis CLIs) must not pay a jax import for
touching `pytorchvideo_accelerate_tpu.utils.*`.
"""

_RNG_EXPORTS = ("RngManager", "set_seed")

__all__ = list(_RNG_EXPORTS)


def __getattr__(name):
    if name in _RNG_EXPORTS:
        from pytorchvideo_accelerate_tpu.utils import rng

        return getattr(rng, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
