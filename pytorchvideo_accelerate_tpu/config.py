"""Typed configuration + CLI for the framework.

Replaces the reference's two-tier config system (SURVEY.md §5 "Config / flag
system"): ``accelerate config`` YAML + env vars for infrastructure, and Python
Fire turning ``main()``'s 26 kwargs into flags (reference ``run.py:328-427``).
Here both tiers live in one typed dataclass tree with dotted CLI overrides
(``--optim.lr 0.1``) plus flat aliases for every reference flag name
(``--lr 0.1`` works too), so a reference user can bring their launch command
across unchanged.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any, Optional, Sequence


@dataclass
class MeshConfig:
    """Device-mesh shape. Product of explicit axes must divide device count.

    The trainer runs on the 2-D ``(data, model)`` train mesh
    (parallel/mesh.py make_train_mesh; docs/PARALLELISM.md): ``data`` = data
    parallel (batch sharding + implicit gradient psum), ``model`` = the
    model-parallel axis — transformer families (mvit/videomae) split
    attention heads and MLP widths over it, the context-parallel lane
    (``--model.attention ring|ulysses``) shards the token axis over it, and
    conv families replicate over it.  -1 on ``data`` means "use all
    remaining devices". Checkpoints are portable across train-mesh shapes.

    The legacy ``fsdp``/``tensor``/``context`` axes select the 4-axis
    library mesh instead (parallel/ research layout): ``fsdp`` =
    parameter/optimizer-state sharding (also shards the batch), ``tensor``
    = tensor parallelism, ``context`` = sequence/context parallelism.
    ``model`` cannot combine with them.
    """

    data: int = -1
    model: int = 1
    fsdp: int = 1
    tensor: int = 1
    context: int = 1


@dataclass
class ParallelConfig:
    """Pipeline parallelism over the mesh's model axis
    (parallel/pipeline.py; docs/PARALLELISM.md § pipeline).

    ``pipeline_stages`` > 1 partitions the transformer trunk's block
    stack into that many stages placed one per model-axis slice (the 2-D
    train mesh's ``model`` axis, or ``tensor`` on the library mesh — the
    stage count must equal that axis's size), and the train step streams
    microbatches through the stages (1F1B-style steady-state occupancy;
    plain autodiff replays the schedule backwards). Transformer families
    only (mvit/videomae); on the 2-D train mesh the stages SPEND the
    model axis, so they exclude Megatron TP and ring/ulysses CP there —
    compose pipeline x CP on the library mesh (tensor=P, context=C)
    instead. Checkpoints are layout-portable: the param tree is identical
    to the unpipelined model, so a run saved under (data, P) resumes
    unpipelined on (N, 1) or a single chip (trainer/checkpoint.py)."""

    pipeline_stages: int = 1
    # microbatches streamed through the stages per step; 0 = auto (reuse
    # optim.gradient_accumulation_steps when accumulation is on — the
    # batch already carries the micro axis — else 2 x stages). More
    # microbatches amortize the fill/drain bubble:
    # bubble = (P-1)/(M+P-1) per direction.
    pipeline_microbatches: int = 0


@dataclass
class DataConfig:
    """Data pipeline knobs (reference `run.py:140-183` + transform stack R6)."""

    data_dir: str = ""
    # alternative to the dir-per-class tree: pytorchvideo from_csv-style
    # `path label` list files (one video per line, space- or comma-
    # separated, integer labels; relative paths resolve against data_dir)
    train_list: str = ""
    val_list: str = ""
    # pre-decoded frame cache (data/cache.py, built offline with
    # `python -m pytorchvideo_accelerate_tpu.data.cache build`): when set,
    # clips come from memmap slices instead of per-clip video decode; expects
    # train/ and val/ sub-caches mirroring data_dir
    cache_dir: str = ""
    synthetic: bool = False  # synthetic clips (test/bench fixture; SURVEY §4.4)
    synthetic_num_videos: int = 64
    num_frames: int = 8  # run.py:374 default; 32 in run_slowfast_r50.sh
    sampling_rate: int = 8  # pva: disable=knob-read -- read via the clip_duration property below (the one derived config value)
    frames_per_second: int = 30  # pva: disable=knob-read -- read via the clip_duration property below (the one derived config value)
    batch_size: int = 8  # per data-parallel shard, matching per-rank semantics
    # auto | thread | process (native shm decode workers). auto = threads:
    # cv2/numpy release the GIL and threads won every measurement made
    # (bench transport_crossover). process is an explicit opt-in for
    # GIL-holding pure-Python transform stacks.
    transport: str = "auto"
    num_workers: int = 8
    # HOST-side prefetch: decoded numpy batches assembled ahead of
    # consumption inside ClipLoader (bounds decode-thread run-ahead). Raise
    # when decode latency is spiky (cold storage, long-GOP videos).
    prefetch_batches: int = 2
    # DEVICE-side prefetch: on-device batches held ahead of the step loop by
    # data/device_prefetch.DevicePrefetcher, overlapping the host->HBM copy
    # of batch N+1 with compute of batch N. Each unit costs one batch of
    # HBM; 0 = synchronous inline placement (the A/B baseline). Distinct
    # from prefetch_batches: that hides DECODE latency on the host, this
    # hides TRANSFER latency onto the chip.
    device_prefetch_depth: int = 2
    # DISAGGREGATED decode (dataplane/; docs/INPUT_PIPELINE.md): >0 spawns
    # that many decode-worker PROCESSES (pva-tpu-dataworker) and the train
    # loader's decode happens there — clip tensors stream back over a
    # zero-copy wire protocol into the device-prefetch ring, byte-identical
    # to local decode (epoch/shuffle/quarantine state stays trainer-owned;
    # checkpoints and mid-epoch resume are unchanged). 0 = local decode.
    # Additional workers (other hosts) may connect to dataplane_listen at
    # any time and join mid-epoch.
    dataplane_workers: int = 0
    # per-worker in-flight lease bound; the trainer-side reorder buffer is
    # bounded by credits x workers (credit-based back-pressure — a slow
    # trainer idles workers, never balloons their memory)
    dataplane_credits: int = 2
    # host:port the feed listens on for workers (port 0 = ephemeral,
    # logged at startup; bind a routable address for cross-host workers)
    dataplane_listen: str = "127.0.0.1:0"
    crop_size: int = 256
    min_short_side_scale: int = 256
    max_short_side_scale: int = 320
    mean: tuple = (0.45, 0.45, 0.45)
    std: tuple = (0.225, 0.225, 0.225)
    horizontal_flip_p: float = 0.5
    # cast clips to the compute dtype on the host (half the host->HBM bytes;
    # value-preserving for the supervised models, which cast inputs on
    # device anyway — NOT applied to VideoMAE pretraining, whose fp32
    # regression target would be quantized). "auto" follows
    # TrainConfig.mixed_precision; "fp32" keeps float32 clips.
    host_cast: str = "auto"  # auto (bf16 host cast) | fp32 | u8 (ship raw
    # uint8, normalize in-graph on device: 4x less host->HBM transfer)
    decode_audio: bool = False  # pva: disable=knob-read -- reference-API parity knob; the audio pathway is not implemented yet
    # multi-view val: views/video with view-averaged logits (the reference's
    # uniform clip-tiling eval, run.py:163); 1 = single center clip
    eval_num_clips: int = 1
    # spatial crops per temporal view (uniform_crop along the longer side);
    # the SlowFast/X3D papers' 30-view protocol = 10 clips x 3 crops
    eval_num_spatial_crops: int = 1
    limit_train_batches: int = -1  # run.py:385
    limit_val_batches: int = -1


@dataclass
class ModelConfig:
    """Model selection + finetuning controls (reference `run.py:105-118`)."""

    name: str = "slow_r50"  # models.available_models(): slow_r50|slowfast_r50|
    # slowfast_r101|c2d_r50|x3d_xs|x3d_s|x3d_m|x3d_l|r2plus1d_r50|csn_r101|
    # mvit_b|mvit_b_32x3|videomae_b|videomae_b_pretrain
    num_classes: int = 0  # 0 = infer from dataset labels (replaces run.py:185)
    pretrained: bool = False
    pretrained_path: str = ""  # converted torch-hub weights (models/convert.py)
    freeze_backbone: bool = False  # run.py:108,116 semantics via optax masking
    slowfast_alpha: int = 4
    dropout_rate: float = 0.5
    # Transformer-family extras (MViT/VideoMAE); ignored by CNNs.
    attention: str = "dense"  # dense (XLA-fused) | pallas (ops/pallas_attention)
    # | ring | ulysses (context-parallel, parallel/ring_attention.py + ulysses.py)
    mask_ratio: float = 0.9  # VideoMAE pretrain tube-mask ratio
    # depthwise-conv lowering for X3D / MViT pooling (ops/depthwise.py):
    # "conv" = XLA grouped convolution; "shift" = tap decomposition into
    # fused VPU multiply-adds; "pallas" = hand-tiled halo kernel (one
    # HBM->VMEM DMA per output tile; stride-1 blocks only, strided entries
    # fall back to conv). Same param tree in all cases; A/B on device with
    # scripts/perf_sweep.py
    depthwise_impl: str = "conv"
    # fused conv->norm->activation lowering for the slowfast/x3d/slow
    # residual-block hot paths (ops/pallas_fused.py; docs/KERNELS.md):
    # "off" = today's unfused graph, byte-for-byte; "auto" = hand-tiled
    # Pallas kernels on TPU and the scale-folded XLA formulation
    # elsewhere; "pallas"/"xla" force one lowering (parity tests,
    # graphcheck, pva-tpu-kbench A/Bs). Same param tree in every mode —
    # checkpoints and converted weights are interchangeable across the
    # knob. Strided sites and non-BN convs keep the unfused path.
    fused_kernels: str = "off"
    # per-block jax.checkpoint (rematerialization): only block-boundary
    # activations (plus one block's interior at a time) stay resident,
    # trading one extra forward of recompute for the activation HBM that
    # gates long clips / bigger batches on a fixed chip
    remat: bool = False
    # temporal attention band for the VideoMAE classifier trunk
    # (models/videomae.py; docs/SERVING.md § trunk-reuse): "none" =
    # bidirectional, byte-for-byte the pre-knob graph; "causal" = a
    # token attends only its own and earlier temporal slots; "windowed"
    # = only the trailing attn_window slots. The banded trunk is what
    # makes per-tubelet states KV-cacheable for streaming serving
    # (--serve.stream_trunk) — finetune with the mask on so serving
    # accuracy recovers (the recipe in docs/SERVING.md).
    attn_mask: str = "none"  # none | causal | windowed
    attn_window: int = 0     # temporal slots (= frames / tubelet_t)


@dataclass
class OptimConfig:
    """Optimizer/schedule (reference `run.py:192-195`)."""

    optimizer: str = "sgd"
    lr: float = 0.1
    momentum: float = 0.9
    weight_decay: float = 1e-4
    gradient_accumulation_steps: int = 1  # default 4 in reference launch recipe
    num_epochs: int = 4
    schedule: str = "cosine"  # cosine (CosineAnnealingLR semantics) | constant
    warmup_steps: int = 0
    label_smoothing: float = 0.0
    grad_clip_norm: float = 0.0  # 0 = off
    # in-graph mixup (Zhang 2018 arXiv:1710.09412): lambda ~ Beta(a, a)
    # per step, clips mixed with the FLIPPED batch on device (timm's
    # pairing — a static reversal GSPMD lowers to a one-hop collective
    # permute, not the cross-device gather a random permutation would
    # cost), loss = lam*CE(y) + (1-lam)*CE(y_flip). The MViT/SlowFast
    # K400 recipes train with it (alpha 0.8 typical); 0 = off.
    # Supervised steps only.
    mixup_alpha: float = 0.0
    # in-graph cutmix (Yun 2019 arXiv:1905.04899): a spatial box of the
    # flipped clip (shared across time), label weight = kept-area
    # fraction; when both alphas are on, a coin picks mixup OR cutmix per
    # forward — per MICRO-batch under grad accumulation (timm's
    # switching at micro granularity). 1.0 typical; 0 = off.
    cutmix_alpha: float = 0.0
    # exponential moving average of weights, updated in-graph each step;
    # when on, evaluation scores the EMA weights (the MViT/VideoMAE
    # fine-tune recipes' convention; 0.9999 typical). EMA rides the
    # checkpoint; toggling it across a resume changes the state tree and
    # fails loudly. 0 = off.
    ema_decay: float = 0.0


@dataclass
class CheckpointConfig:
    """Checkpoint/resume (reference `run.py:123-133, 203-224, 276-325`)."""

    output_dir: str = "."
    # "epoch" | integer string | "" (off) — exact reference parsing semantics.
    checkpointing_steps: str = ""
    # path | "auto" (scan output_dir for latest — fixes run.py:208-212 dead
    # code) | "" (off)
    resume_from_checkpoint: str = ""
    max_to_keep: int = 0  # 0 = keep all (ProjectConfiguration.total_limit)
    async_checkpoint: bool = True


@dataclass
class ServeConfig:
    """Inference serving (serving/: engine + micro-batcher + HTTP endpoint).

    No reference equivalent — the reference stack is training-only. The
    engine restores a params-only artifact written by `export_inference`
    (EMA-resolved), pins the weights to the mesh, and serves `/predict`
    behind an adaptive micro-batcher; see docs/SERVING.md."""

    # export_inference artifact directory (weights.npz + meta.json) —
    # produce one with `--export_inference PATH` after/with a resume
    checkpoint: str = ""
    host: str = "127.0.0.1"
    port: int = 8100
    # batcher flush policy: a batch launches when `max_batch_size` requests
    # are queued OR the oldest has waited `max_wait_ms` — the classic
    # latency/throughput knob pair. The batch is then padded UP to the
    # nearest compiled bucket (multiples of the mesh's data-shard count,
    # doubling up to max_batch_size) with masked rows, so every batch shape
    # hits a cached executable instead of recompiling.
    max_batch_size: int = 8
    max_wait_ms: float = 5.0
    # bound on queued-but-unbatched requests; submissions beyond it are
    # rejected (HTTP 503) instead of growing latency without limit
    max_queue: int = 256
    # rolling window (completed requests) for the latency percentiles and
    # throughput reported by /stats
    stats_window: int = 1024
    # per-request wall-clock budget inside the server before a 504
    request_timeout_s: float = 30.0
    # admission control (serving/admission.py): shed load with
    # 503 + Retry-After once queue depth crosses shed_queue_frac *
    # max_queue (the "degraded" state) — BEFORE latency collapses at the
    # hard queue bound; recover to "healthy" below recover_queue_frac.
    shed_queue_frac: float = 0.9
    recover_queue_frac: float = 0.5
    # the Retry-After seconds sent with every 503/504 rejection
    retry_after_s: float = 1.0
    # drain-on-SIGTERM budget: stop accepting, flush in-flight futures,
    # then shut down (0 = no drain handler; the PR-3 dump-only behavior)
    drain_grace_s: float = 10.0
    # batching front on the hot path (fleet/scheduler.py): "edf" (default)
    # is the continuous-batching scheduler — per-request deadlines,
    # realtime/batch priority classes, earliest-deadline-first launches,
    # shed-before-deadline-miss with 503 + Retry-After; "micro" restores
    # the fixed launch-on-max-or-timeout MicroBatcher policy
    scheduler: str = "edf"
    # default deadlines per priority class (explicit per-request
    # deadline_ms in the /predict body overrides); a request that provably
    # cannot meet its deadline is shed instead of riding to a 504
    realtime_deadline_ms: float = 2000.0
    batch_deadline_ms: float = 10000.0
    # quantized inference (serving/quantize.py; docs/SERVING.md §
    # quantization): "off" = full-precision weights, byte-identical to
    # the pre-quantization engine; "int8" = per-channel absmax int8
    # WEIGHTS dequantized to the compute dtype (bf16 activations)
    # inside the jitted forward — 4x smaller artifacts/HBM residency
    # and hot-swap transfers, quality-gated against full-precision
    # evaluate() top-1 (tests/test_zquant.py). Applies at
    # `export_inference` time (bakes a quantized artifact) AND at
    # engine load time (on-the-fly quantization of fp artifacts).
    quantization: str = "off"
    # per-deployment latency-histogram bucket bounds (comma-separated
    # MILLISECONDS, e.g. "5,10,25,50,100,250,1000"); "" keeps the shared
    # serving ladder. An interactive tier wants sub-ms resolution, a bulk
    # tier wants multi-second tails — one ladder fits neither
    # (obs/registry.py family buckets).
    latency_buckets_ms: str = ""
    # stateful streaming sessions (streaming/; docs/SERVING.md §
    # streaming): wrap the engine in a StreamingEngine so /stream serves
    # incremental rolling-window advances — each request ships only the
    # new frames, the window ring stays device-resident, and the
    # continuous-batching scheduler batches advances across sessions.
    # /predict keeps serving stateless one-shot requests either way.
    streaming: bool = False
    # HBM budget for device-resident session rings; admission refuses a
    # new session (503 + Retry-After) when every slot under the budget is
    # held by a live session
    stream_session_budget_mb: float = 256.0
    # idle sessions past this are evicted (their slot reclaimed); a
    # stream that stopped advancing is a leak, not a client
    stream_session_ttl_s: float = 120.0
    # strides to pre-compile at server build (comma-separated frames per
    # advance) for the artifact's clip geometry: the first advance at an
    # un-prewarmed (stride, bucket) pays a synchronous compile on the
    # flush thread, which both stalls the launch AND poisons the
    # service-time EWMA into transient deadline sheds — exactly the cold
    # start `InferenceEngine.warmup` prevents for /predict. Strides that
    # do not divide the window (or the model tubelet) are skipped.
    stream_strides: str = "2"
    # streaming trunk-compute reuse (streaming/engine.py KV rings;
    # docs/SERVING.md § trunk-reuse): "full" = today's graph
    # byte-for-byte (the trunk re-runs over the cached token ring each
    # advance); "causal"/"windowed" = the banded-attention trunk whose
    # per-tubelet K/V are cached in device-resident KV rings, so an
    # advance computes only the new tubelets' queries. Changes the math:
    # serve a backbone FINETUNED with the matching model.attn_mask (the
    # quality gate + recipe in docs/SERVING.md), or eat the top-1 delta
    # the bench STREAM lane reports. VideoMAE classifiers only —
    # MViT/conv/dual-rate families refuse loudly.
    stream_trunk: str = "full"  # full | causal | windowed


@dataclass
class FleetConfig:
    """Replica-pool serving tier (fleet/): router, health gating, hot-swap,
    load harness defaults (docs/SERVING.md § fleet). `serve.*` configures
    ONE replica; `fleet.*` configures the tier around N of them."""

    # replicas the bench fleet lane / CI harnesses stand up (production
    # fleets register real processes with the pool instead)
    replicas: int = 2
    # health-poll cadence for pool membership; route-around on an observed
    # death is immediate, this bounds how fast a DEAD-but-silent replica
    # leaves the rotation (and how fast a recovered one rejoins)
    health_interval_s: float = 0.5
    # per-request re-dispatch budget after a replica dies mid-flight
    route_retries: int = 2
    # open-loop load-harness defaults (fleet/loadgen.py, pva-tpu-loadgen)
    loadgen_rps: float = 50.0
    loadgen_duration_s: float = 5.0
    # the SLO the SERVE_FLEET bench lane asserts (p99 over completions)
    slo_p99_ms: float = 1500.0


@dataclass
class ControlConfig:
    """Fleet-intelligence loops (fleet/control/): SLO-driven autoscaling,
    multi-model budget, canary rollout (docs/SERVING.md § fleet
    intelligence). These dials shape the DAMPING — an undamped controller
    against an open-loop load generator is an oscillator."""

    # autoscaler pool bounds; min >= 1 (the last routable replica is
    # never drained, no matter what the signals say)
    min_replicas: int = 1
    max_replicas: int = 8
    # the p99 the controller defends; scale-up fires when the smoothed
    # pooled p99 crosses it (the FLEET_AUTO lane asserts convergence
    # back under it after a traffic step)
    slo_p99_ms: float = 500.0
    # hysteresis band on smoothed backlog per routable replica: above
    # `queue_high` grow, below `queue_low` (AND p99 under
    # downscale_frac * SLO) shrink; the gap between them is damping
    queue_high: float = 4.0
    queue_low: float = 0.5
    downscale_frac: float = 0.5
    # dead time after every action + control cadence + signal smoothing
    cooldown_s: float = 2.0
    interval_s: float = 0.25
    ewma_alpha: float = 0.5
    # scale-down grace for in-flight requests after the victim drains
    # and its sessions re-home
    drain_grace_s: float = 5.0
    # shared compiled-cache/HBM budget across model families (MB);
    # the lowest-priority over-budget family sheds, the pool never does
    budget_mb: float = 4096.0
    # canary: fraction of the fleet that takes the new artifact, the
    # direction-aware regression threshold (perfdiff semantics), and the
    # escalation-ladder strike count before auto-rollback
    canary_fraction: float = 0.25
    canary_threshold: float = 0.2
    canary_rollback_after: int = 2


@dataclass
class ObsConfig:
    """Telemetry spine (obs/): spans, flight recorder, watchdog, registry.

    `enabled` gates the whole layer: spans become no-ops, the compiled
    train step drops its health gauges, and the logged metric keys revert
    exactly to the pre-obs set. The watchdog is opt-in on top (a
    no-progress deadline only the operator can pick); see
    docs/OBSERVABILITY.md for the runbook."""

    enabled: bool = True
    # no-progress deadline (seconds) before the watchdog dumps all-thread
    # stacks + the flight record to stderr/output_dir — evidence BEFORE an
    # external timeout kills the process blind. 0 = watchdog off.
    watchdog_timeout_s: float = 0.0
    # bounded in-memory event ring (spans/metrics/warnings) dumped to
    # <output_dir>/flight_record.json on exception, SIGTERM, or stall
    flight_recorder_events: int = 512
    # distributed tracing (obs/trace.py): head-based sampling rate for new
    # trace roots (train steps, /predict requests, loadgen arrivals).
    # 0 = tracing disarmed, structurally zero overhead (the default);
    # incoming `traceparent` headers are always continued once armed —
    # the remote head already made the sampling decision.
    trace_sample_rate: float = 0.0
    # bounded per-process trace-event ring, exported as Chrome/Perfetto
    # JSON (<output_dir>/trace_ring.json; merge N of them with
    # `pva-tpu-trace`)
    trace_ring_events: int = 4096
    # pva-tpu-hbm (obs/memory.py): arm the device-memory ledger — real
    # allocation sites register bytes, cross-checked against the
    # backend's memory_stats() where available (docs/OBSERVABILITY.md
    # § memory ledger). Off = one global read at each site.
    memory_ledger: bool = True
    # on-demand profiler window, run-relative: "A..B" captures
    # jax.profiler from this run's step A until step B, written
    # atomically under <output_dir>/profile_<tag>/ (obs/profiler.py).
    # "" = disarmed.
    profile_steps: str = ""
    # metrics-history ring over Registry.scrape() ticks (obs/history.py):
    # the substrate for burn-rate alerting, /history, and the
    # autoscaler's shared EWMAs. 0 disables.
    history_ticks: int = 512


@dataclass
class ReliabilityConfig:
    """Resilience substrate (reliability/): retries, preemption grace,
    emergency checkpoints (docs/RELIABILITY.md). Fault injection has no
    config here on purpose — arming a FaultPlan is a chaos-harness act
    (`pva-tpu-chaos`), never a production knob."""

    # SIGTERM/SIGINT grace path in Trainer.fit(): finish the in-flight
    # step, write an emergency checkpoint (resume=auto round-trips to the
    # exact step), dump the flight record, exit 0. False restores PR 3's
    # dump-only signal behavior.
    graceful_shutdown: bool = True
    # total decode attempts per clip read before the substitution path
    # takes over (transient I/O — cold NFS, flaky storage — recovers here;
    # a truly corrupt file still substitutes after the budget)
    decode_retries: int = 2
    # total attempts for checkpoint/artifact writes (orbax save dispatch,
    # inference-export files, the emergency record)
    ckpt_retries: int = 3
    # total attempts per tracker call before the tracker is disabled
    # (PR 3 disabled on the FIRST failure; a tracker outage is usually
    # transient, losing the rest of the run's metrics is not)
    tracker_retries: int = 2
    # backoff shape for checkpoint/artifact writes: base * 2^attempt *
    # jitter, capped per try at retry_max_delay_s, whole-call wall time
    # capped at retry_deadline_s. retry_base_delay_s also seeds the decode
    # read backoff; the decode deadline (5s — the substitution path waits
    # behind it) and the tracker budget (2s — a logging outage must never
    # stall a training step longer) are fixed by design.
    retry_base_delay_s: float = 0.05
    retry_max_delay_s: float = 2.0
    retry_deadline_s: float = 30.0


@dataclass
class GuardConfig:
    """Self-healing training (reliability/guard.py TrainGuard —
    docs/RELIABILITY.md § divergence runbook): in-graph nonfinite
    skip-batch, EWMA anomaly detection on loss/grad_norm, a last-known-good
    checkpoint ring with automatic rollback past the offending data span,
    replay bundles, and bad-sample quarantine. Disarmed (the default) the
    step graph carries no skip branch and the step loop does one None
    check — structurally zero overhead."""

    enabled: bool = False
    # policy: which anomaly signals escalate. "nonfinite" (NaN/inf loss or
    # grad norm), "spike" (EWMA z-score excursion on loss/grad_norm), or
    # "both". The in-graph skip-batch always covers nonfinite updates when
    # the guard is enabled, regardless of policy.
    policy: str = "both"
    # LKG cadence/ring: an async orbax save to <output_dir>/guard_lkg every
    # `lkg_every_steps` healthy steps; the ring keeps `lkg_keep` entries
    # (orbax max_to_keep pruning). LKG only advances when no anomaly was
    # observed within the cadence window.
    lkg_every_steps: int = 50
    lkg_keep: int = 3
    # EWMA spike detector shape: upward z-score threshold, smoothing
    # factor, and the observation budget during which spikes never fire
    # (young statistics + warmup loss cliffs must not false-positive)
    spike_zscore: float = 6.0
    ewma_alpha: float = 0.05
    warmup_steps: int = 20
    # escalation ladder: anomalies below `rollback_after` consecutive
    # observations are skips (recorded; the in-graph skip already
    # protected the state); at the threshold the guard rolls back to the
    # LKG and fast-forwards the loader past the offending span; more than
    # `max_rollbacks` rollbacks raises GuardHalt (a rollback loop means
    # data or optimizer trouble — see the runbook)
    rollback_after: int = 2
    max_rollbacks: int = 2
    # bad-sample quarantine (data/manifest.Quarantine): decode failures
    # per clip before the path is quarantined to the persisted
    # <output_dir>/quarantine.json sidecar the sampler excludes. 0 = off.
    # Counts at most one failure per clip per run (the in-run substitution
    # memory), so budget > 1 means "failed in that many runs/sessions".
    quarantine_budget: int = 3


@dataclass
class TrackingConfig:
    """Metric logging (reference `run.py:227-231, 267-274, 306-315`)."""

    with_tracking: bool = False
    logging_dir: str = "pytorchvideo_accelerate_tpu_runs"
    log_every: int = 10
    # "all" resolves to every importable tracker, like accelerate
    # tracking.py:1260-1290; individual: "tensorboard", "wandb", "jsonl".
    trackers: str = "all"


@dataclass
class TrainConfig:
    mesh: MeshConfig = field(default_factory=MeshConfig)
    parallel: ParallelConfig = field(default_factory=ParallelConfig)
    data: DataConfig = field(default_factory=DataConfig)
    model: ModelConfig = field(default_factory=ModelConfig)
    optim: OptimConfig = field(default_factory=OptimConfig)
    checkpoint: CheckpointConfig = field(default_factory=CheckpointConfig)
    tracking: TrackingConfig = field(default_factory=TrackingConfig)
    serve: ServeConfig = field(default_factory=ServeConfig)
    fleet: FleetConfig = field(default_factory=FleetConfig)
    control: ControlConfig = field(default_factory=ControlConfig)  # pva: disable=knob-read -- control-plane dials ride TrainConfig for dotted-key CLI parsing; the fleet runner (ROADMAP 4/5) consumes the block
    obs: ObsConfig = field(default_factory=ObsConfig)
    reliability: ReliabilityConfig = field(default_factory=ReliabilityConfig)
    guard: GuardConfig = field(default_factory=GuardConfig)

    seed: int = 42  # run.py:138 set_seed(42); run.py:355 exposes --seed
    # write a params-only (EMA-resolved) serving artifact to this path and
    # exit without training — combine with --resume_from_checkpoint to
    # export a finished run; serve it with
    # `pva-tpu-serve --serve.checkpoint PATH` (trainer/checkpoint.py
    # export_inference; docs/SERVING.md)
    export_inference: str = ""
    # run the validation loop once and exit (score a resumed/converted
    # checkpoint); no reference equivalent — run.py always trains
    eval_only: bool = False
    # "bf16" = bf16 compute / fp32 params (TPU-native replacement for the
    # reference's fp16 GradScaler path, SURVEY §2.3-N7); "fp32" = full fp32.
    mixed_precision: str = "bf16"
    cpu: bool = False  # force CPU backend (reference --cpu)
    # >0: probe device init in a disposable subprocess with this deadline
    # (seconds) BEFORE the job touches jax.devices(), and fail loudly if it
    # can't complete — a wedged PJRT client-create otherwise hangs the job
    # forever with no error (utils/device_doctor.py; SURVEY §5). 0 = off.
    device_init_timeout: int = 0
    # persistent XLA compilation cache dir ("" = off): pays the 1-2 min
    # model compile once per config instead of once per restart
    compilation_cache_dir: str = ""
    profile: bool = False  # jax.profiler trace of a step window (SURVEY §5)
    profile_dir: str = "/tmp/pva_tpu_profile"
    debug_nans: bool = False  # jax.config debug_nans (SURVEY §5 sanitizers)
    # trace-time batch-contract chex asserts in the compiled steps
    debug_asserts: bool = False
    # per-epoch cross-host fingerprint comparison (multi-process runs)
    debug_desync: bool = False
    # Multi-host control plane (jax.distributed.initialize); empty = single
    # process or auto-detected TPU pod env.
    coordinator_address: str = ""
    num_processes: int = 0
    process_id: int = -1

    @property
    def clip_duration(self) -> float:
        """`(sampling_rate * num_frames) / fps` — reference run.py:140."""
        d = self.data
        return (d.sampling_rate * d.num_frames) / d.frames_per_second

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, default=str)


# --- CLI ------------------------------------------------------------------

# Flat reference-flag aliases -> dotted path, so the reference launch command
# (run_slowfast_r50.sh) maps 1:1 onto the new CLI.
_REFERENCE_ALIASES = {
    "cpu": "cpu",
    "mixed_precision": "mixed_precision",
    "seed": "seed",
    "checkpointing_steps": "checkpoint.checkpointing_steps",
    "resume_from_checkpoint": "checkpoint.resume_from_checkpoint",
    "output_dir": "checkpoint.output_dir",
    "with_tracking": "tracking.with_tracking",
    "logging_dir": "tracking.logging_dir",
    "log_every": "tracking.log_every",
    "data_dir": "data.data_dir",
    "num_frames": "data.num_frames",
    "sampling_rate": "data.sampling_rate",
    "frames_per_second": "data.frames_per_second",
    "num_workers": "data.num_workers",
    "batch_size": "data.batch_size",
    "limit_train_batches": "data.limit_train_batches",
    "limit_val_batches": "data.limit_val_batches",
    "num_epochs": "optim.num_epochs",
    "lr": "optim.lr",
    "momentum": "optim.momentum",
    "weight_decay": "optim.weight_decay",
    "gradient_accumulation_steps": "optim.gradient_accumulation_steps",
    "pretrained": "model.pretrained",
    "freeze_backbone": "model.freeze_backbone",
    "slowfast_alpha": "model.slowfast_alpha",
    "model_name": "model.name",
    "synthetic": "data.synthetic",
    "cache_dir": "data.cache_dir",
    "eval_num_clips": "data.eval_num_clips",
    "eval_num_spatial_crops": "data.eval_num_spatial_crops",
    "trackers": "tracking.trackers",
}


def _leaf_fields(cfg=None, prefix=""):
    """Yield (dotted_name, default_value) pairs for every leaf field."""
    obj = cfg if cfg is not None else TrainConfig()
    for f in dataclasses.fields(obj):
        v = getattr(obj, f.name)
        if dataclasses.is_dataclass(v):
            yield from _leaf_fields(v, prefix + f.name + ".")
        else:
            yield prefix + f.name, v


def _unknown_key_message(dotted: str, valid: set) -> str:
    """Diagnosis for an unknown dotted key. When the key's block prefix IS a
    known config block (`--serve.typo_key`), list that block's valid keys —
    a typo under a real block must fail loudly and helpfully, never be
    silently ignored or answered with a bare 'unknown'."""
    block = dotted.split(".", 1)[0]
    block_keys = sorted(k for k in valid if k.startswith(block + "."))
    if "." in dotted and block_keys:
        return (f"unknown key {dotted!r} under config block {block!r}; "
                f"valid keys: " + ", ".join(block_keys))
    return f"unknown key {dotted!r} (see --help for the full flag list)"


def _coerce(value: str, default: Any):
    if isinstance(default, bool):
        if isinstance(value, bool):
            return value
        if isinstance(value, (int, float)):  # config-file JSON 0/1
            return bool(value)
        return value.lower() in ("1", "true", "yes", "y", "t")
    if isinstance(default, int):
        return int(value)
    if isinstance(default, float):
        return float(value)
    if isinstance(default, tuple):
        if isinstance(value, (list, tuple)):  # config-file native lists
            return tuple(type(default[0])(p) for p in value)
        parts = [p for p in str(value).replace("(", "").replace(")", "").split(",") if p]
        return tuple(type(default[0])(p) for p in parts)
    return value


def _set_dotted(cfg: TrainConfig, dotted: str, value: Any) -> None:
    obj = cfg
    parts = dotted.split(".")
    for p in parts[:-1]:
        obj = getattr(obj, p)
    current = getattr(obj, parts[-1])
    if value is None:  # bare `--flag` with no value
        if not isinstance(current, bool):
            raise ValueError(f"flag requires a value ({type(current).__name__})")
        value = "true"
    setattr(obj, parts[-1], _coerce(value, current))


def config_from_dict(data: dict, base: Optional[TrainConfig] = None,
                     source: str = "<dict>") -> TrainConfig:
    """Apply a (flat or nested) config dict onto a TrainConfig.

    Accepts `{"optim": {"lr": 0.1}}` nesting, dotted keys ("optim.lr"), or
    the flat reference aliases ("lr"); `TrainConfig.to_dict()` output loads
    back unchanged. Shared by `--config file.json` and the serving engine's
    artifact-embedded config (trainer/checkpoint.py meta.json).
    """
    cfg = base or TrainConfig()
    valid = {name for name, _ in _leaf_fields()}

    def apply(tree: dict, prefix: str) -> None:
        for k, v in tree.items():
            dotted = prefix + str(k).replace("-", "_")
            if isinstance(v, dict) and dotted not in valid:
                apply(v, dotted + ".")
                continue
            dotted = _REFERENCE_ALIASES.get(dotted, dotted)
            if dotted not in valid:
                raise ValueError(
                    f"{_unknown_key_message(dotted, valid)} in {source}")
            _set_dotted(cfg, dotted, v)

    apply(data, "")
    return cfg


def load_config_file(path: str, base: Optional[TrainConfig] = None) -> TrainConfig:
    """Apply a JSON config file onto a TrainConfig (see `config_from_dict`).

    The `accelerate config` YAML tier's equivalent (SURVEY §5 "Config / flag
    system"): persistent settings in a file, per-run overrides as flags.
    """
    with open(path) as f:
        data = json.load(f)
    return config_from_dict(data, base=base, source=path)


def parse_cli(argv: Optional[Sequence[str]] = None, base: Optional[TrainConfig] = None) -> TrainConfig:
    """Parse ``--flag value`` / ``--flag=value`` / bare boolean ``--flag``.

    Accepts both dotted names (``--optim.lr``) and the reference's flat flag
    names (``--lr``), including ``--is_slowfast`` which maps onto
    ``model.name=slowfast_r50`` for drop-in launch-script compatibility.
    ``--config file.json`` loads a config file FIRST (flags override it) —
    the `accelerate config` two-tier equivalent.
    """
    import sys

    argv = list(sys.argv[1:] if argv is None else argv)
    cfg = base or TrainConfig()
    # config files apply before any flag, wherever --config appears
    def load_file(path):
        try:
            return load_config_file(path, base=cfg)
        except (OSError, ValueError) as e:  # ValueError covers bad JSON too
            raise SystemExit(f"--config {path}: {e}")

    remaining = []
    i = 0
    while i < len(argv):
        if argv[i] in ("--config", "--config_file"):
            if i + 1 >= len(argv):
                raise SystemExit("--config requires a file path")
            cfg = load_file(argv[i + 1])
            i += 2
        elif argv[i].startswith(("--config=", "--config_file=")):
            cfg = load_file(argv[i].split("=", 1)[1])
            i += 1
        else:
            remaining.append(argv[i])
            i += 1
    argv = remaining
    valid = {name for name, _ in _leaf_fields()}

    i = 0
    while i < len(argv):
        tok = argv[i]
        if not tok.startswith("--"):
            raise SystemExit(f"unexpected argument: {tok}")
        tok = tok[2:]
        if "=" in tok:
            key, value = tok.split("=", 1)
            i += 1
        else:
            key = tok
            if i + 1 < len(argv) and not argv[i + 1].startswith("--"):
                value = argv[i + 1]
                i += 2
            else:
                value = None  # bare flag: only valid for booleans
                i += 1
        key = key.replace("-", "_")
        if key == "is_slowfast":  # reference flag (run.py:351)
            if value is None or _coerce(value, True):
                cfg.model.name = "slowfast_r50"
            continue
        if key == "pin_memory":  # reference flag (run.py:354); no TPU meaning
            continue            # (host->HBM transfer is the runtime's job)
        if key == "help":
            print(usage())
            raise SystemExit(0)
        dotted = _REFERENCE_ALIASES.get(key, key)
        if dotted not in valid:
            raise SystemExit(
                f"unknown flag --{key}: {_unknown_key_message(dotted, valid)}")
        try:
            _set_dotted(cfg, dotted, value)
        except (TypeError, ValueError) as e:
            raise SystemExit(f"invalid value for --{key}: {e}")
    return cfg


def usage() -> str:
    lines = [
        "flags (dotted or reference-style):",
        "  --config FILE.json (JSON config applied before flags; nested,",
        "      dotted, or flat-alias keys — see load_config_file)",
        "  --write_config FILE.json (resolve all flags/config files into",
        "      one JSON and exit; reuse via --config)",
    ]
    for name, default in _leaf_fields():
        lines.append(f"  --{name} (default: {default!r})")
    return "\n".join(lines)
