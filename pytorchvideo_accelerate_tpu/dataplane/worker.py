"""Decode worker: the horizontally-scalable half of the data plane.

One worker process (console script ``pva-tpu-dataworker``) — or, for tests
and the tsan stress scenario, one worker THREAD — connects to a trainer's
`RemoteClipFeed` (dataplane/feed.py), receives the source spec in the
handshake, and then serves index-span leases: decode + transform every
leased clip through the exact `VideoClipSource.get()` path the local
loader runs, assemble with the shared `data.pipeline.assemble_batch`, and
stream the ready batch back over the length-prefixed wire protocol
(dataplane/wire.py). Byte parity with the local loader is a consequence of
sharing the sample function, the seed streams, and the assembly code — the
worker adds transport, never semantics.

Back-pressure is the FEED's job (credit-based leasing): a worker only ever
holds the spans the trainer granted it, so a slow trainer idles workers
instead of ballooning their memory. Epoch/shuffle determinism stays
centralized too — leases carry explicit manifest indices, the worker never
re-derives epoch geometry or consults a sampler.

Failure reporting generalizes the PR 6/9 machinery across the wire:

- transient decode failures retry + substitute INSIDE the worker's source
  (reliability/retry.py; the substitution streams are attempt-keyed, so
  every worker — and the local loader — substitutes identically);
- exhausted-retry failures report back as ``qreport`` frames and land in
  the TRAINER's persisted quarantine sidecar (data/manifest.Quarantine) —
  the same budget, the same sampler-level exclusion next epoch/run;
- a hard failure (``_MAX_CONSECUTIVE_FAILURES`` unreadable clips) becomes
  an ``error`` frame the feed re-raises in the consumer, exactly where the
  local loader would have raised.

Tracing: each lease carries the trainer's W3C ``traceparent``; the worker
continues that trace around the decode (obs/trace.py continue_trace) and
stamps the batch frame with it, so a merged timeline shows the remote hop.

Stdlib + numpy + cv2 only — a decode worker never imports jax.
"""

from __future__ import annotations

import argparse
import logging
import os
import socket
import sys
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional

from pytorchvideo_accelerate_tpu import obs
from pytorchvideo_accelerate_tpu.obs import trace
from pytorchvideo_accelerate_tpu.data.pipeline import assemble_batch
from pytorchvideo_accelerate_tpu.dataplane import spec as spec_mod
from pytorchvideo_accelerate_tpu.dataplane.wire import (
    WireError,
    parse_address,
    recv_frame,
    send_frame,
)
from pytorchvideo_accelerate_tpu.utils.sync import make_lock, shared_state

logger = logging.getLogger(__name__)

# two jobs: the HANDSHAKE recv deadline (a feed that accepts but never
# configures must not pin a worker forever), and the driver for the TCP
# keepalive schedule after it — a trainer host that vanishes without a FIN
# (power loss, partition) is detected within roughly this long and the
# worker exits instead of living as an hours-long orphan. An idle
# inter-epoch gap, by contrast, never kills a worker.
IDLE_TIMEOUT_S = 600.0


class _QuarantineReporter:
    """Worker-side quarantine shim: `contains` is always False (the trainer
    owns exclusion — it happens at the sampler, before a lease exists) and
    `record` ships the verdict home as a ``qreport`` frame, where the feed
    counts it against the clip's persisted budget."""

    def __init__(self, worker: "DecodeWorker"):
        self._worker = worker

    def __len__(self) -> int:  # sampler-exclusion surface: trainer-owned
        return 0

    def contains(self, path: str) -> bool:
        return False

    def record(self, path: str, error: Optional[BaseException] = None) -> bool:
        self._worker._send(
            "qreport",
            {"path": path,
             "error": f"{type(error).__name__}: {error}"[:200]
             if error else ""})
        return False


@shared_state("batches_done", "reports_sent")
class DecodeWorker:
    """One worker over one connected socket; `run()` serves until a
    ``stop`` frame, a closed connection, or the idle deadline."""

    def __init__(self, sock: socket.socket, decode_threads: int = 2,
                 idle_timeout_s: float = IDLE_TIMEOUT_S):
        self.sock = sock
        self.decode_threads = max(int(decode_threads), 1)
        self.idle_timeout_s = idle_timeout_s
        self.batches_done = 0
        self.reports_sent = 0
        # decode pool threads (qreport) and the lease loop (batch) share
        # the socket; frames must never interleave
        self._send_lock = make_lock("DecodeWorker._send_lock")
        self._pool: Optional[ThreadPoolExecutor] = None
        self._source = None
        self._spy = 0
        self._accum = 1
        self._local_batch = 0

    # --- wire ----------------------------------------------------------------

    def _send(self, kind: str, meta: Optional[dict] = None,
              arrays: Optional[dict] = None,
              traceparent: Optional[str] = None) -> None:
        with self._send_lock:
            send_frame(self.sock, kind, meta, arrays, traceparent)
            if kind == "qreport":
                self.reports_sent += 1

    # --- protocol ------------------------------------------------------------

    def _configure(self, meta: dict) -> None:
        geom = meta.get("batch", {})
        self._spy = int(geom["samples_per_yield"])
        self._accum = int(geom.get("accum_steps", 1))
        self._local_batch = int(geom.get("local_batch_size", self._spy))
        tr = meta.get("trace") or {}
        if tr.get("sample_rate") and trace.get_tracer() is None:
            # a spawned process arms its own tracer from the handshake; an
            # in-process worker thread rides the already-armed one
            trace.configure_tracing(float(tr["sample_rate"]),
                                    seed=int(tr.get("seed", 0)))
        self._source = spec_mod.build_source(
            meta["spec"], quarantine=_QuarantineReporter(self))
        self._pool = ThreadPoolExecutor(max_workers=self.decode_threads)

    def _decode_lease(self, fr) -> None:
        epoch = int(fr.meta["epoch"])
        index = int(fr.meta["index"])
        gen = fr.meta.get("gen")
        indices: List[int] = [int(i) for i in fr.meta["indices"]]
        tracer = trace.get_tracer()
        handle = (tracer.continue_trace(fr.traceparent, "remote_decode",
                                        epoch=epoch, batch=index)
                  if tracer is not None and fr.traceparent else None)
        try:
            with handle or trace.NOOP:
                def fetch_one(i):
                    with obs.span("decode"):
                        return self._source.get(int(i), epoch)

                samples = list(self._pool.map(fetch_one, indices))
                batch = assemble_batch(samples, self._spy,
                                       accum_steps=self._accum,
                                       local_batch_size=self._local_batch)
        except Exception as e:  # noqa: BLE001 - must cross the wire
            # the local loader would raise here — IOError after 10
            # consecutive unreadable clips, or a TRANSFORM bug propagating
            # on purpose (pipeline.py keeps those distinct from corrupt
            # files). Either way: report instead of dying, so the feed
            # raises it in the CONSUMER with the original type named and
            # the worker stays available — a deterministic poisoned span
            # must not serially kill every worker it gets re-leased to
            self._send("error", {"epoch": epoch, "index": index, "gen": gen,
                                 "message":
                                 f"{type(e).__name__}: {e}"[:500]})
            return
        self._send("batch", {"epoch": epoch, "index": index, "gen": gen},
                   arrays=batch, traceparent=fr.traceparent)
        self.batches_done += 1

    def run(self) -> None:
        """Serve the connection: hello → config → lease loop."""
        # handshake under a deadline (a feed that accepts but never
        # configures must not pin a worker forever); after it, reads
        # block — an idle inter-epoch gap is normal, and a dead trainer
        # (even SIGKILLed) closes the TCP stream, which reads as EOF.
        # The half-open corner (trainer host power loss / partition, no
        # FIN ever arrives) is covered by a keepalive schedule DERIVED
        # from idle_timeout_s — a plain read timeout can't distinguish
        # "idle between epochs" from "mid-frame corruption", but kernel
        # probes detect a dead peer within ~idle_timeout_s and the
        # blocked recv then errors out cleanly.
        self.sock.settimeout(self.idle_timeout_s)
        try:
            self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
            if hasattr(socket, "TCP_KEEPIDLE"):  # Linux schedule knobs
                idle = max(int(self.idle_timeout_s / 2), 10)
                intvl = max(int(self.idle_timeout_s / 10), 5)
                self.sock.setsockopt(socket.IPPROTO_TCP,
                                     socket.TCP_KEEPIDLE, idle)
                self.sock.setsockopt(socket.IPPROTO_TCP,
                                     socket.TCP_KEEPINTVL, intvl)
                self.sock.setsockopt(socket.IPPROTO_TCP,
                                     socket.TCP_KEEPCNT, 5)
        except OSError:  # pragma: no cover - platform-dependent
            pass
        try:
            self._send("hello", {"pid": os.getpid()})
            cfg = recv_frame(self.sock, allow_eof=True)
            if cfg is None:
                return
            if cfg.kind != "config":
                raise WireError(f"expected config frame, got {cfg.kind!r}")
            self._configure(cfg.meta)
            self.sock.settimeout(None)
            while True:
                fr = recv_frame(self.sock, allow_eof=True)
                if fr is None or fr.kind == "stop":
                    return
                if fr.kind == "lease":
                    self._decode_lease(fr)
                # unknown kinds are ignored: a newer feed may gossip
        except (WireError, OSError, KeyError, TypeError, ValueError) as e:
            # KeyError/TypeError/ValueError: a wire-valid frame with
            # malformed meta (a version-skewed feed) — same clean-exit
            # posture as protocol corruption, never a raw traceback death
            logger.warning("decode worker exiting: %s: %s",
                           type(e).__name__, e)
        finally:
            if self._pool is not None:
                self._pool.shutdown(wait=False)
            try:
                self.sock.close()
            except OSError:
                pass


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="pva-tpu-dataworker",
        description="disaggregated decode worker: connect to a trainer's "
                    "RemoteClipFeed and serve clip-decode leases "
                    "(docs/INPUT_PIPELINE.md § disaggregated data plane)")
    ap.add_argument("--connect", required=True, metavar="HOST:PORT",
                    help="the feed's listen address (trainer log / "
                         "--data.dataplane_listen)")
    ap.add_argument("--threads", type=int, default=2,
                    help="decode threads inside this worker (cv2 releases "
                         "the GIL; scale workers horizontally first)")
    ap.add_argument("--idle-timeout-s", type=float, default=IDLE_TIMEOUT_S)
    args = ap.parse_args(argv)
    try:
        host, port = parse_address(args.connect)
    except ValueError as e:
        ap.error(f"--connect: {e}")
    logging.basicConfig(level=logging.INFO, stream=sys.stderr)
    sock = socket.create_connection((host, port), timeout=30.0)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    DecodeWorker(sock, decode_threads=args.threads,
                 idle_timeout_s=args.idle_timeout_s).run()
    return 0


if __name__ == "__main__":
    sys.exit(main())
