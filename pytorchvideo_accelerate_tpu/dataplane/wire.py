"""Length-prefixed binary wire protocol for the disaggregated data plane.

One frame carries one protocol message between a decode worker and the
trainer-side `RemoteClipFeed` (dataplane/feed.py):

    MAGIC "PVDP" | u32 header_len | header JSON | raw array payloads

The header is small JSON — `kind` (hello/config/lease/batch/qreport/error/
stop), a `meta` dict, an ordered `arrays` manifest of `{key, dtype, shape}`
entries, and an optional W3C `traceparent` (the PR 10 tracer's HTTP hop
format reused verbatim, so a batch's decode spans on the worker join the
trainer's trace). The payload is the arrays' raw bytes, concatenated in
manifest order.

Zero-copy on purpose, both directions: `pack_frame` returns buffer views
(`sendall` ships the array memory without a serialization pass — a 32f/256²
bf16 batch is tens of MB, and a json/pickle hop would double the data
plane's CPU bill), and `recv_frame` reads the whole payload into ONE
bytearray and hands out `np.frombuffer` windows over it.

Failure posture (the fuzz contract, tests/test_zdataplane.py): a garbage
magic, an oversized or non-JSON header, a negative/overflowing shape, or a
connection closed mid-frame all raise `WireError` — a clean, attributable
error, never a hang and never a partially-built batch. `WireError` is a
`ConnectionError` so socket-level handlers (the feed's reader threads, the
worker loop) treat protocol corruption exactly like a dead peer: drop the
connection, re-lease the span.

Stdlib + numpy only: worker processes must import this without jax.
"""

from __future__ import annotations

import json
import socket
import struct
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

MAGIC = b"PVDP"
_LEN = struct.Struct("<I")
# a header is a few hundred bytes of JSON; anything near this bound is
# garbage or an attack, not a batch manifest
MAX_HEADER_BYTES = 1 << 20
# per-array and per-frame payload bound: rejects corrupt/hostile shape
# manifests before a multi-GB allocation, while leaving room far above any
# real clip batch (reference geometry is tens of MB)
MAX_PAYLOAD_BYTES = 1 << 31


class WireError(ConnectionError):
    """Protocol-level corruption (bad magic/header/shape) or a peer lost
    mid-frame. A ConnectionError on purpose: the recovery is the same as a
    dead socket — drop the worker, re-lease its spans."""


def parse_address(text: str) -> tuple:
    """`HOST:PORT` -> (host, int(port)) with an error that names the
    shape. ONE parser for both ends of the wire: the trainer's
    `--data.dataplane_listen` and the worker's `--connect` must agree on
    what an address looks like."""
    host, sep, port = str(text).rpartition(":")
    if not sep or not host or not port.isdigit():
        raise ValueError(
            f"address must be HOST:PORT (e.g. 127.0.0.1:0), got {text!r}")
    return host, int(port)


@dataclass
class Frame:
    """One decoded protocol message."""

    kind: str
    meta: dict = field(default_factory=dict)
    arrays: Dict[str, np.ndarray] = field(default_factory=dict)
    traceparent: Optional[str] = None


def _np_dtype(name: str) -> np.dtype:
    """dtype from its wire name; lazily registers ml_dtypes for the
    extended families (bfloat16 host-cast batches)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # noqa: F401 - registers bfloat16 et al.

        return np.dtype(name)


def pack_frame(kind: str, meta: Optional[dict] = None,
               arrays: Optional[Dict[str, np.ndarray]] = None,
               traceparent: Optional[str] = None) -> List[memoryview]:
    """Encode one frame as a list of buffers ready for sequential
    `sendall` — the array buffers are VIEWS of the caller's memory (the
    zero-copy half of the contract; don't mutate them mid-send)."""
    specs: List[dict] = []
    payloads: List[np.ndarray] = []
    for key, arr in (arrays or {}).items():
        a = np.ascontiguousarray(arr)
        specs.append({"key": str(key), "dtype": a.dtype.name,
                      "shape": list(a.shape)})
        payloads.append(a)
    header: dict = {"kind": kind, "meta": meta or {}, "arrays": specs}
    if traceparent:
        header["traceparent"] = traceparent
    hb = json.dumps(header, separators=(",", ":")).encode()
    if len(hb) > MAX_HEADER_BYTES:
        raise WireError(f"frame header too large ({len(hb)} bytes)")
    parts = [memoryview(MAGIC + _LEN.pack(len(hb)) + hb)]
    parts.extend(memoryview(a).cast("B") for a in payloads)
    return parts


def send_frame(sock: socket.socket, kind: str, meta: Optional[dict] = None,
               arrays: Optional[Dict[str, np.ndarray]] = None,
               traceparent: Optional[str] = None) -> None:
    for part in pack_frame(kind, meta, arrays, traceparent):
        sock.sendall(part)


def _recv_exact(sock: socket.socket, n: int,
                allow_eof: bool = False) -> Optional[bytearray]:
    """Read exactly n bytes into one bytearray. A clean EOF at a frame
    boundary (`allow_eof`) returns None; EOF mid-frame is corruption."""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        k = sock.recv_into(view[got:], n - got)
        if k == 0:
            if got == 0 and allow_eof:
                return None
            raise WireError(f"peer closed mid-frame ({got}/{n} bytes)")
        got += k
    return buf


def recv_frame(sock: socket.socket,
               allow_eof: bool = True) -> Optional[Frame]:
    """Read one frame; None on a clean EOF at a frame boundary. Raises
    `WireError` on any protocol corruption, `socket.timeout` past the
    socket's deadline (callers own the timeout policy — a truncated frame
    from a live-but-silent peer must not hang forever)."""
    head = _recv_exact(sock, len(MAGIC) + _LEN.size, allow_eof=allow_eof)
    if head is None:
        return None
    if bytes(head[:4]) != MAGIC:
        raise WireError(f"bad frame magic {bytes(head[:4])!r}")
    (hlen,) = _LEN.unpack(head[4:])
    if not 0 < hlen <= MAX_HEADER_BYTES:
        raise WireError(f"implausible header length {hlen}")
    try:
        header = json.loads(bytes(_recv_exact(sock, hlen)))
    except (ValueError, UnicodeDecodeError) as e:
        raise WireError(f"unparseable frame header: {e}") from e
    if not isinstance(header, dict) or not isinstance(header.get("kind"),
                                                      str):
        raise WireError("frame header missing 'kind'")
    specs = header.get("arrays", [])
    if not isinstance(specs, list):
        raise WireError("frame header 'arrays' is not a list")
    sizes: List[tuple] = []
    total = 0
    for spec in specs:
        try:
            dtype = _np_dtype(spec["dtype"])
            shape = tuple(int(d) for d in spec["shape"])
            key = str(spec["key"])
        except (KeyError, TypeError, ValueError, OverflowError) as e:
            raise WireError(f"bad array spec {spec!r}: {e}") from e
        if dtype.kind == "O" or dtype.itemsize == 0:
            # object/void dtypes can't carry raw wire bytes — and frombuffer
            # on them raises far uglier things than a protocol error
            raise WireError(f"non-plain dtype {dtype!r} on the wire")
        if any(d < 0 or d > MAX_PAYLOAD_BYTES for d in shape):
            # a huge dim beside a zero dim would pass the product bound
            # (0 elements) and then overflow numpy's intp in reshape
            raise WireError(f"implausible dimension in shape {shape}")
        # pure-Python product on purpose: np.prod(dtype=int64) silently
        # WRAPS on hostile dims like 2**32 x 2**32 (to 0, which would pass
        # the bound and then blow up in reshape instead of raising here)
        nbytes = dtype.itemsize
        for d in shape:
            nbytes *= d
            if nbytes > MAX_PAYLOAD_BYTES:
                raise WireError(
                    f"implausible array size for shape {shape}")
        sizes.append((key, dtype, shape, nbytes))
        total += nbytes
        if total > MAX_PAYLOAD_BYTES:
            raise WireError(f"implausible frame payload ({total} bytes)")
    payload = _recv_exact(sock, total) if total else bytearray()
    view = memoryview(payload)
    arrays: Dict[str, np.ndarray] = {}
    off = 0
    for key, dtype, shape, nbytes in sizes:
        arrays[key] = np.frombuffer(
            view[off:off + nbytes], dtype=dtype).reshape(shape)
        off += nbytes
    return Frame(kind=header["kind"], meta=dict(header.get("meta") or {}),
                 arrays=arrays, traceparent=header.get("traceparent"))
