"""DATA_PLANE bench lane: local loader vs N remote decode workers.

Same source, same seed, same epoch geometry — the ONLY variable is where
decode happens. The consumer simulates a trainer (a fixed busy-step per
batch) and the lane measures what an operator needs to compare:

- ``dataplane_cps`` / ``local_cps``: end-to-end clips/sec of each path;
- ``dataplane_input_wait_frac`` / ``local_input_wait_frac``: fraction of
  the consume loop blocked waiting for the next batch (the same reading
  `obs/input_wait_frac` gives the real trainer — → 0 means the decode
  plane outruns the consumer);
- ``parity``: the remote batch stream is byte-identical to the local one
  (hash-compared; the non-negotiable correctness gate).

The local loader runs ONE decode worker thread and the remote path runs N
worker PROCESSES, so the comparison shows the actual lever: horizontal
decode scale-out on a fixed trainer host. Host-CPU-real numbers in the
bench_data tradition — trustworthy on any box, never device claims.

Also the analyze.sh gate step (``python -m
pytorchvideo_accelerate_tpu.dataplane.bench --smoke``): exit 1 on a parity
break or on remote input-wait materially worse than local.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import sys
import time
from typing import Callable, List, Optional

from pytorchvideo_accelerate_tpu.data.pipeline import ClipLoader
from pytorchvideo_accelerate_tpu.dataplane import spec as spec_mod
from pytorchvideo_accelerate_tpu.dataplane.feed import RemoteClipFeed

# remote wait must not exceed local wait by more than this (timing noise
# allowance; with N>=2 workers vs 1 local decode thread the remote side is
# structurally ahead, and --smoke asserts it stays that way)
WAIT_FRAC_TOLERANCE = 0.05


def batch_digest(batch: dict) -> str:
    """sha1 over sorted keys + dtype + shape + raw bytes — THE definition
    of 'byte-identical batch stream' (this lane and chaos leg 13 must
    agree on it, so both import this one)."""
    h = hashlib.sha1()
    for key in sorted(batch):
        arr = batch[key]
        h.update(key.encode())
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(arr.tobytes())
    return h.hexdigest()


def _consume(items, step_s: float) -> dict:
    """Drain one epoch_items pass with a simulated train step per batch;
    returns digests + wait/throughput accounting."""
    digests: List[str] = []
    clips = 0
    wait_s = 0.0
    t_start = time.perf_counter()
    while True:
        t0 = time.perf_counter()
        batch, _state = next(items)
        wait_s += time.perf_counter() - t0
        if batch is None:
            break
        digests.append(batch_digest(batch))
        clips += int(next(iter(batch.values())).shape[0])
        if step_s:
            # sleep, not busy-wait: a real train step runs ON the
            # accelerator — the trainer host is idle while it computes, and
            # a spinning consumer would starve the very decode processes
            # this lane measures (observed 3x distortion on a 2-core host)
            time.sleep(step_s)
    wall = time.perf_counter() - t_start
    return {"digests": digests, "clips": clips, "wall_s": wall,
            "wait_s": wait_s,
            "wait_frac": min(wait_s / wall, 1.0) if wall > 0 else 0.0,
            "cps": round(clips / wall, 2) if wall > 0 else 0.0}


def run_dataplane_bench(smoke: bool = True, workers: int = 2,
                        step_s: Optional[float] = None, trials: int = 3,
                        deadline_s: Optional[float] = None,
                        log: Optional[Callable] = None) -> dict:
    """Run the local-vs-remote comparison; returns the lane dict.

    Shape/sizing rationale: decode is made the bottleneck of the LOCAL
    path (one decode thread, ~2x the simulated step's cost per batch)
    while the remote plane carries strictly more aggregate decode capacity
    (`workers` processes x 2 threads) than the step needs — so clean runs
    land local wait_frac solidly high and remote near zero, an ORDERING
    that survives uniform background-CPU noise because it comes from
    capacity, not from a timing knife-edge. Each side still runs `trials`
    interleaved passes and reports its best (min wait_frac) — the same
    min-of-runs stance the tracer's overhead calibration takes against
    preemption outliers on shared hosts."""
    log = log or (lambda *a: None)
    n_videos = 48 if smoke else 128
    crop = 48 if smoke else 64
    frames = 8
    # sized against this host: raw 48x(144,192) clips cost ~4x the 80 ms
    # simulated step per batch through ONE decode thread (local decode
    # CANNOT hide behind the step) while `workers` processes x 2 threads
    # bring effective decode under the step (remote decode CAN) — the
    # capacity ordering the smoke gate asserts
    step_s = step_s if step_s is not None else 0.08
    tspec = dict(num_frames=frames, training=True, crop_size=crop,
                 min_short_side_scale=crop + 2,
                 max_short_side_scale=crop + 8)
    spec = spec_mod.synthetic_spec(tspec, num_videos=n_videos,
                                   num_classes=4, seed=17, raw_frames=48,
                                   raw_size=[144, 192])

    def make_loader() -> ClipLoader:
        return ClipLoader(spec_mod.build_source(spec), global_batch_size=4,
                          shuffle=True, num_workers=1, prefetch_batches=2,
                          seed=17)

    out: dict = {"dataplane_workers": int(workers), "num_videos": n_videos,
                 "step_s": step_s, "trials": int(trials)}

    def run_local() -> dict:
        loader = make_loader()
        try:
            return _consume(loader.epoch_items(0, from_start=True), step_s)
        finally:
            loader.close()

    def run_remote() -> dict:
        loader = make_loader()
        feed = RemoteClipFeed(loader, spec, spawn=int(workers), credits=2,
                              decode_threads=2, batch_timeout_s=120.0)
        try:
            res = _consume(feed.epoch_items(0, from_start=True), step_s)
            res["stats"] = feed.stats()
            return res
        finally:
            feed.close()
            loader.close()

    locals_, remotes = [], []
    t_deadline = (time.monotonic() + deadline_s) if deadline_s else None
    for i in range(max(int(trials), 1)):  # interleaved: noise hits both
        if t_deadline and i and time.monotonic() > t_deadline:
            # cooperative self-bound: a caller that abandons this function
            # from outside (a harness Future timeout) cannot stop the
            # thread — it must stop ITSELF, or it keeps spawning worker
            # processes under whatever the harness measures next
            log(f"[dataplane] deadline hit after {i} trial(s); "
                "using what completed")
            break
        locals_.append(run_local())
        remotes.append(run_remote())
    local = min(locals_, key=lambda r: r["wait_frac"])
    remote = min(remotes, key=lambda r: r["wait_frac"])
    out["local_cps"] = local["cps"]
    out["local_input_wait_frac"] = round(local["wait_frac"], 4)
    out["dataplane_cps"] = remote["cps"]
    out["dataplane_input_wait_frac"] = round(remote["wait_frac"], 4)
    out["stats"] = remote["stats"]
    out["batches"] = len(local["digests"])
    # parity is checked on EVERY pass, not the best one — byte identity is
    # the correctness gate, noise-independent by construction
    out["parity"] = (len(local["digests"]) > 0
                     and all(r["digests"] == local["digests"]
                             for r in locals_ + remotes))
    log(f"[dataplane] {workers} remote workers: "
        f"{out['dataplane_cps']} clips/s (wait_frac "
        f"{out['dataplane_input_wait_frac']}) vs local "
        f"{out['local_cps']} clips/s (wait_frac "
        f"{out['local_input_wait_frac']}); parity={out['parity']}")
    return out


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m pytorchvideo_accelerate_tpu.dataplane.bench",
        description="DATA_PLANE lane: local loader vs N remote decode "
                    "workers on the same source/seed "
                    "(docs/INPUT_PIPELINE.md § disaggregated data plane)")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes + gate asserts (the analyze.sh/CI "
                         "lane): parity must hold and remote input-wait "
                         "must be no worse than local")
    ap.add_argument("--workers", type=int, default=2)
    args = ap.parse_args(argv)

    def log(msg):
        print(msg, file=sys.stderr, flush=True)

    # the CLI is the analyze.sh/CI gate: always the smoke shapes (the
    # full-size lane lives in bench.py); --smoke only toggles nothing yet
    # and is kept for flag symmetry with the other gate tools
    out = run_dataplane_bench(smoke=True, workers=args.workers, log=log)
    print(json.dumps({k: v for k, v in out.items() if k != "stats"}))
    if not out["parity"]:
        log("[dataplane] FAIL: remote batch stream diverged from local")
        return 1
    if (out["dataplane_input_wait_frac"]
            > out["local_input_wait_frac"] + WAIT_FRAC_TOLERANCE):
        log("[dataplane] FAIL: remote input-wait worse than local "
            f"({out['dataplane_input_wait_frac']} vs "
            f"{out['local_input_wait_frac']})")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
