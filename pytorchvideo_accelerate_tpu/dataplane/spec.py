"""Source specs: the JSON shape of "build my ClipSource over there".

A decode worker is a separate process (later: a separate host) that must
reconstruct the EXACT deterministic sample function the trainer's local
loader would have used — same transform, same seed, same substitution
streams — so a remote batch is byte-identical to a local one. The trainer
serializes that recipe once into a spec dict, ships it in the handshake
`config` frame (dataplane/wire.py), and `build_source` rebuilds it worker-
side.

Two source types today (the cache-backed source stays local — its memmap
slices are already cheaper than the wire):

- ``synthetic``: SyntheticClipSource kwargs verbatim (tests/bench/chaos);
- ``video``: the manifest shipped as explicit `(path, label, name)` entries
  — deterministic regardless of worker-side filesystem enumeration, and the
  SAMPLER-level quarantine exclusion stays trainer-owned (leases carry
  explicit indices; the worker never re-derives epoch geometry).

The transform spec is `make_transform`'s kwargs plus `training` — all
JSON-scalar, tuples tolerated as lists.
"""

from __future__ import annotations

from typing import Optional

from pytorchvideo_accelerate_tpu.data.manifest import Manifest, VideoEntry
from pytorchvideo_accelerate_tpu.data.pipeline import (
    ClipSource,
    SyntheticClipSource,
    VideoClipSource,
)
from pytorchvideo_accelerate_tpu.data.transforms import make_transform


def synthetic_spec(transform: dict, **source_kwargs) -> dict:
    """Spec for a SyntheticClipSource; `transform` = make_transform kwargs
    including `training`."""
    return {"source": {"type": "synthetic", **source_kwargs},
            "transform": dict(transform)}


def video_spec(manifest: Manifest, transform: dict, *, clip_duration: float,
               training: bool, seed: int, num_clips: int = 1,
               decode_retries: int = 2,
               retry_base_delay_s: float = 0.05) -> dict:
    """Spec for a VideoClipSource over an explicit manifest."""
    return {
        "source": {
            "type": "video",
            "entries": [[e.path, int(e.label), e.label_name]
                        for e in manifest.entries],
            "class_names": list(manifest.class_names),
            "clip_duration": float(clip_duration),
            "training": bool(training),
            "seed": int(seed),
            "num_clips": int(num_clips),
            "decode_retries": int(decode_retries),
            "retry_base_delay_s": float(retry_base_delay_s),
        },
        "transform": dict(transform),
    }


def build_transform(tspec: dict):
    kw = dict(tspec)
    # JSON round-trips tuples as lists; make_transform wants sequences, so
    # only mean/std need normalizing for equality-sensitive callers
    for key in ("mean", "std"):
        if key in kw:
            kw[key] = tuple(kw[key])
    return make_transform(**kw)


def build_source(spec: dict,
                 quarantine: Optional[object] = None) -> ClipSource:
    """Reconstruct the ClipSource a spec describes. `quarantine` (any
    object with `contains(path)`/`record(path, err)`) is threaded into the
    video source — worker-side it is the report-back shim that lands
    failures in the TRAINER's persisted sidecar (dataplane/worker.py)."""
    src = dict(spec["source"])
    kind = src.pop("type", None)
    transform = build_transform(spec.get("transform", {}))
    if kind == "synthetic":
        if "raw_size" in src:
            src["raw_size"] = tuple(src["raw_size"])
        return SyntheticClipSource(transform, **src)
    if kind == "video":
        entries = [VideoEntry(str(p), int(label), str(name))
                   for p, label, name in src.pop("entries")]
        manifest = Manifest(entries=entries,
                            class_names=list(src.pop("class_names")))
        return VideoClipSource(manifest, transform,
                               clip_duration=src.pop("clip_duration"),
                               quarantine=quarantine, **src)
    raise ValueError(f"unknown source spec type {kind!r}")
