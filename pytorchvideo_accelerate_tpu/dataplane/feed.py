"""RemoteClipFeed: the trainer-side half of the disaggregated data plane.

Slots in exactly where `ClipLoader`'s local iterator feeds the
`DevicePrefetcher` today: same `epoch_items()` contract ((batch,
LoaderState) pairs, post-CONSUMPTION state, a final (None, rollover)
marker), same `state` surface — but the decode+transform work happens in N
remote worker processes (dataplane/worker.py) instead of this host's
thread pool.

Determinism stays centralized (the Podracer split: decoupled actors, one
learner-owned curriculum): THIS side computes `_epoch_indices` (shuffle +
quarantine substitution) through the wrapped ClipLoader and leases each
batch's explicit index chunk to a worker; workers never sample. A batch is
therefore byte-identical no matter which worker decodes it — or whether
the local loader does — so checkpoints keep recording the consumed
position and mid-epoch resume works unchanged.

Credit-based back-pressure, two composed bounds (`_pump_locked`): each
worker holds at most `credits` UNRECEIVED leases (worker memory — a credit
frees when the batch lands back here), and leases are only granted inside
the window `[next_yield, next_yield + credits x workers)` (the trainer-side
reorder buffer — the window only advances when the trainer consumes). A
slow trainer therefore idles the whole plane at a hard `credits x workers`
bound and can never balloon worker memory (asserted non-vacuously in
tests/test_zdataplane.py); anchoring the window at the batch the consumer
is waiting for is also what makes worker death deadlock-free — the
re-leased head span is always grantable.

Worker death re-leases: a reader thread that loses its socket returns the
worker's un-received spans to the front of the lease queue and surviving
workers pick them up — zero duplicate, zero missing batches (chaos leg 13
SIGKILLs a worker mid-epoch and diffs the stream). Already-received
batches are kept, not re-decoded. ``qreport`` frames land in the trainer's
persisted `Quarantine` sidecar — a remote decode failure quarantines
exactly like a local one.

Tracing: the consumer's context is captured at epoch start and every lease
carries its W3C traceparent, so worker-side decode spans join the trainer's
trace across the process boundary (the PR 10 propagation contract; the
trace-propagation lint rule covers this module's send sites).
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import time
from collections import deque
from typing import Dict, Iterator, List, Optional, Tuple

from pytorchvideo_accelerate_tpu.obs import trace
from pytorchvideo_accelerate_tpu.data.pipeline import ClipLoader, LoaderState
from pytorchvideo_accelerate_tpu.dataplane.wire import (
    WireError,
    recv_frame,
    send_frame,
)
from pytorchvideo_accelerate_tpu.utils.sync import (
    make_condition,
    make_lock,
    make_thread,
    shared_state,
)


class RemoteDecodeFailure(IOError):
    """A quarantine verdict reported over the wire (sidecar evidence)."""


class NoWorkersError(ConnectionError):
    """Every decode worker disconnected while batches were outstanding."""


class _Worker:
    """Feed-side record of one connected worker. All mutable fields are
    guarded by the feed's condition; `send_lock` alone serializes frame
    writes (leases vs stop) on the socket."""

    __slots__ = ("wid", "sock", "pid", "outstanding", "send_lock", "alive",
                 "thread")

    def __init__(self, wid: int, sock: socket.socket, pid: int):
        self.wid = wid
        self.sock = sock
        self.pid = pid
        self.outstanding: set = set()  # (gen, batch_index) leased, unreceived
        self.send_lock = make_lock("RemoteClipFeed._Worker.send_lock")
        self.alive = True
        self.thread = None


# every spawned worker process, for emergency reaping: a harness that
# abandons a wedged feed (bench lane timeout) must be able to kill the
# orphans rather than let them burn CPU under later measured lanes
_SPAWNED: List[subprocess.Popen] = []
_SPAWNED_LOCK = make_lock("dataplane.feed._SPAWNED_LOCK")


def spawn_worker(address: Tuple[str, int],
                 decode_threads: int = 2) -> subprocess.Popen:
    """Launch one `pva-tpu-dataworker` process pointed at `address`.
    stderr inherits (worker warnings are operator evidence); the worker
    never touches jax, but JAX_PLATFORMS=cpu rides along so a transitive
    import in a future transform can't grab the accelerator."""
    host, port = address
    env = dict(os.environ)
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env["PYTHONPATH"] = pkg_root + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "pytorchvideo_accelerate_tpu.dataplane.worker",
         "--connect", f"{host}:{port}", "--threads", str(decode_threads)],
        env=env, stdin=subprocess.DEVNULL)
    with _SPAWNED_LOCK:
        _SPAWNED.append(proc)
    return proc


def reap_spawned_workers() -> int:
    """SIGKILL every still-running spawned worker process; returns how
    many were killed. For harnesses that gave up on a feed from OUTSIDE
    (a lane timeout) — a normal `RemoteClipFeed.close()` already waits
    for its own processes."""
    with _SPAWNED_LOCK:
        procs, _SPAWNED[:] = list(_SPAWNED), []
    killed = 0
    for p in procs:
        if p.poll() is None:
            p.kill()
            try:
                p.wait(timeout=5.0)
            except subprocess.TimeoutExpired:  # pragma: no cover
                pass
            killed += 1
    return killed


@shared_state("_workers", "_unleased", "_pending", "_done", "_next_yield",
              "_error", "_gen", "_epoch", "_indices", "_spy", "_closing",
              "_lease_traceparent", "consumed", "received",
              "releases", "workers_lost", "qreports")
class RemoteClipFeed:
    """Lease coordinator + reorder buffer over N remote decode workers.

    `loader` supplies geometry, epoch indices, and the checkpointable
    `LoaderState`; its thread pool never decodes while the feed is in
    charge. `spawn` launches that many local worker processes (the
    single-host CI shape); additional external `pva-tpu-dataworker`
    processes may connect to `address` at any time and join the rotation
    mid-epoch — that is the horizontal-scale path.
    """

    def __init__(self, loader: ClipLoader, source_spec: dict,
                 spawn: int = 0, listen: Tuple[str, int] = ("127.0.0.1", 0),
                 credits: int = 2, quarantine=None,
                 trace_config: Optional[dict] = None,
                 decode_threads: int = 2,
                 connect_timeout_s: float = 120.0,
                 batch_timeout_s: float = 300.0):
        if credits < 1:
            raise ValueError(f"credits must be >= 1, got {credits}")
        self.loader = loader
        self.source_spec = source_spec
        self.credits = int(credits)
        self.quarantine = quarantine
        self.trace_config = trace_config or {}
        self.decode_threads = int(decode_threads)
        self.batch_timeout_s = batch_timeout_s
        self.consumed = 0
        self.received = 0
        self.releases = 0     # spans re-leased after a worker death
        self.workers_lost = 0
        self.qreports: List[dict] = []
        self._cond = make_condition("RemoteClipFeed._cond")
        self._workers: Dict[int, _Worker] = {}
        self._next_wid = 0
        self._unleased: deque = deque()
        self._pending: Dict[int, _Worker] = {}   # batch index -> worker
        self._done: Dict[int, tuple] = {}        # batch index -> (batch, wid)
        self._next_yield = 0
        self._error: Optional[BaseException] = None
        self._gen = 0        # epoch-pass generation; stale frames dropped
        self._epoch = 0
        self._indices = None
        self._spy = 0
        self._lease_traceparent: Optional[str] = None
        self._closing = False

        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(listen)
        self._listener.listen(16)
        # accept() with a poll timeout: closing a listener's fd does NOT
        # wake a thread blocked in accept() on Linux, so a blocking accept
        # would pin close() for its full join timeout
        self._listener.settimeout(0.2)
        self.address: Tuple[str, int] = self._listener.getsockname()[:2]
        self._accept_thread = make_thread(
            target=self._accept_loop, name="dataplane-accept", daemon=True)
        self._accept_thread.start()
        self._procs: List[subprocess.Popen] = [
            spawn_worker(self.address, self.decode_threads)
            for _ in range(int(spawn))]
        if spawn:
            try:
                self.wait_for_workers(int(spawn), timeout=connect_timeout_s)
            except TimeoutError:
                self.close()  # no spawned orphans on a failed construction
                raise

    # --- ClipLoader surface (what DevicePrefetcher and the trainer use) ------

    @property
    def state(self) -> LoaderState:
        return self.loader.state

    @state.setter
    def state(self, value: LoaderState) -> None:
        self.loader.state = value

    @property
    def global_batch_size(self) -> int:
        return self.loader.global_batch_size

    @property
    def local_batch_size(self) -> int:
        return self.loader.local_batch_size

    @property
    def accum_steps(self) -> int:
        return self.loader.accum_steps

    @property
    def samples_per_yield(self) -> int:
        return self.loader.samples_per_yield

    def batches_per_epoch(self) -> int:
        return self.loader.batches_per_epoch()

    def steps_per_epoch(self) -> int:
        return self.loader.steps_per_epoch()

    # --- membership ----------------------------------------------------------

    def wait_for_workers(self, n: int, timeout: float = 120.0) -> None:
        deadline = time.monotonic() + timeout
        with self._cond:
            while len(self._workers) < n:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(
                        f"only {len(self._workers)}/{n} decode workers "
                        f"connected within {timeout}s")
                self._cond.wait(timeout=min(left, 0.2))

    def worker_count(self) -> int:
        with self._cond:
            return len(self._workers)

    def stats(self) -> dict:
        """Doctor/bench/chaos view of the credit machinery."""
        with self._cond:
            return {
                "workers": {w.wid: {"pid": w.pid,
                                    "outstanding": len(w.outstanding)}
                            for w in self._workers.values()},
                "consumed": self.consumed,
                "received": self.received,
                "releases": self.releases,
                "workers_lost": self.workers_lost,
                "unleased": len(self._unleased),
                "buffered": len(self._done),
                "credits": self.credits,
                "qreports": list(self.qreports),
            }

    def _accept_loop(self) -> None:
        while True:
            try:
                sock, _addr = self._listener.accept()
            except socket.timeout:
                with self._cond:
                    if self._closing:
                        return
                continue
            except OSError:
                return  # listener closed: feed shutting down
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                sock.settimeout(30.0)
                hello = recv_frame(sock)
                if hello is None or hello.kind != "hello":
                    sock.close()
                    continue
                send_frame(sock, "config", {
                    "spec": self.source_spec,
                    "batch": {
                        "samples_per_yield": self.loader.samples_per_yield,
                        "local_batch_size": self.loader.local_batch_size,
                        "accum_steps": self.loader.accum_steps,
                    },
                    "trace": self.trace_config,
                })
                # blocking reads from here on: an idle inter-epoch gap (or
                # a long eval) must not time a healthy worker out; close()
                # unblocks the reader by closing the socket
                sock.settimeout(None)
            except (WireError, OSError):
                try:
                    sock.close()
                except OSError:
                    pass
                continue
            with self._cond:
                if self._closing:
                    sock.close()
                    return
                wid = self._next_wid
                self._next_wid += 1
                w = _Worker(wid, sock, int(hello.meta.get("pid", -1)))
                self._workers[wid] = w
                to_send = self._pump_locked()  # a late joiner starts leasing
                self._cond.notify_all()
            w.thread = make_thread(target=self._reader, args=(w,),
                                   name=f"dataplane-reader-{wid}",
                                   daemon=True)
            w.thread.start()
            self._send_leases(to_send)

    # --- reader threads ------------------------------------------------------

    def _reader(self, w: _Worker) -> None:
        try:
            while True:
                fr = recv_frame(w.sock, allow_eof=True)
                if fr is None:
                    break
                if fr.kind == "batch":
                    self._on_batch(w, fr)
                elif fr.kind == "qreport":
                    self._on_qreport(w, fr)
                elif fr.kind == "error":
                    with self._cond:
                        if fr.meta.get("gen") == self._gen:
                            self._error = IOError(
                                fr.meta.get("message", "remote decode error"))
                            self._cond.notify_all()
                # hello/unknown: ignore
        except (WireError, OSError, socket.timeout):
            pass
        except (KeyError, TypeError, ValueError):
            # a wire-VALID frame with malformed meta (version skew, a
            # hostile worker): same posture as protocol corruption — drop
            # the peer cleanly, never die with an unhandled traceback
            pass
        finally:
            self._on_worker_gone(w)

    def _on_batch(self, w: _Worker, fr) -> None:
        b = int(fr.meta["index"])
        with self._cond:
            if fr.meta.get("gen") != self._gen or (self._gen, b) not in \
                    w.outstanding:
                # stale: an aborted pass's leftovers, or a span that was
                # re-leased away — drop it (the slot was already returned
                # when the span left `outstanding`)
                return
            w.outstanding.discard((self._gen, b))
            # the frame's backing bytearray is exclusively this batch's
            # (one recv buffer per frame), so the array views are safely
            # owned by the reorder buffer — no defensive copy
            self._done[b] = (fr.arrays, w.wid)
            self._pending.pop(b, None)
            self.received += 1
            to_send = self._pump_locked()  # receipt freed a worker slot
            self._cond.notify_all()
        # tracing: record the cross-process hop under the lease's context
        tracer = trace.get_tracer()
        if tracer is not None and fr.traceparent:
            handle = tracer.continue_trace(fr.traceparent, "remote_batch",
                                           epoch=fr.meta.get("epoch"),
                                           batch=b, worker=w.wid)
            if handle is not None:
                handle.finish()
        self._send_leases(to_send)

    def _on_qreport(self, w: _Worker, fr) -> None:
        path = str(fr.meta.get("path", ""))
        err = str(fr.meta.get("error", ""))
        with self._cond:
            self.qreports.append({"worker": w.wid, "pid": w.pid,
                                  "path": path, "error": err})
        if self.quarantine is not None and path:
            # same persisted sidecar, same budget, same counter as a local
            # decode failure (data/manifest.Quarantine)
            self.quarantine.record(path, RemoteDecodeFailure(err))

    def _on_worker_gone(self, w: _Worker) -> None:
        to_send: list = []
        with self._cond:
            if not w.alive:
                return
            w.alive = False
            self._workers.pop(w.wid, None)
            returned = sorted(b for gen, b in w.outstanding
                              if gen == self._gen)
            w.outstanding.clear()
            if not self._closing:
                for b in returned:
                    self._pending.pop(b, None)
                # MERGE, don't prepend: after two deaths in a row the
                # returned spans can interleave with previously-returned
                # ones (A held {2,5}, B held {3,4}), and the pump's
                # head-of-deque window check requires `_unleased` to stay
                # ascending — a bare appendleft could bury the span the
                # consumer is waiting for behind a larger head and stall
                # the pass until timeout
                self._unleased = deque(
                    sorted(set(returned).union(self._unleased)))
                self.releases += len(returned)
                self.workers_lost += 1
                if not self._workers and (self._unleased or self._pending):
                    self._error = NoWorkersError(
                        "all decode workers disconnected with "
                        f"{len(self._unleased)} span(s) outstanding")
                to_send = self._pump_locked()
            self._cond.notify_all()
        try:
            w.sock.close()
        except OSError:
            pass
        self._send_leases(to_send)

    # --- leasing -------------------------------------------------------------

    def _pump_locked(self) -> List[tuple]:
        """Assign unleased spans to workers with free credits (least-loaded
        first), within the lease WINDOW. Called under the condition; returns
        (worker, frame-kwargs) pairs for the caller to SEND outside the lock
        — a slow socket must never stall the reader threads.

        Two bounds compose here, and their split is what makes the design
        deadlock-free:

        - per-worker `credits` caps UNRECEIVED leases — worker memory.
          A credit frees at RECEIPT (a live worker always delivers, so a
          slot always comes back without the trainer's help);
        - the window `[next_yield, next_yield + credits x workers)` caps
          leased-but-unconsumed spans — the trainer-side reorder buffer.
          It only advances when the consumer consumes, so a stalled
          trainer idles the whole plane at a hard bound.

        Because the window is anchored at `next_yield`, the batch the
        consumer is waiting for is ALWAYS leasable — a worker death can
        never strand it behind survivors saturated with later spans (the
        head-of-line deadlock a consumption-released credit would allow).
        """
        to_send: List[tuple] = []
        if self._indices is None or not self._workers:
            return to_send
        window_end = self._next_yield + self.credits * len(self._workers)
        # `_unleased` stays ascending (built ascending; death MERGES spans
        # back in sorted order), so the head is always the smallest — one
        # check bounds the whole deque
        while self._unleased and self._unleased[0] < window_end:
            candidates = [w for w in self._workers.values()
                          if w.alive and len(w.outstanding) < self.credits]
            if not candidates:
                break
            w = min(candidates, key=lambda x: len(x.outstanding))
            b = self._unleased.popleft()  # pva: disable=lock-discipline -- _pump_locked is only ever called with self._cond held (the _locked suffix contract)
            w.outstanding.add((self._gen, b))
            self._pending[b] = w  # pva: disable=lock-discipline -- _pump_locked is only ever called with self._cond held (the _locked suffix contract)
            chunk = self._indices[b * self._spy:(b + 1) * self._spy]
            to_send.append((w, {
                "kind": "lease",
                "meta": {"epoch": self._epoch, "index": b, "gen": self._gen,
                         "indices": [int(i) for i in chunk]},
                "traceparent": self._lease_traceparent,
            }))
        return to_send

    def _send_leases(self, to_send: List[tuple]) -> None:
        for w, kw in to_send:
            try:
                with w.send_lock:
                    send_frame(w.sock, kw["kind"], kw["meta"],
                               traceparent=kw.get("traceparent"))
            except OSError:
                # the reader thread will notice the dead socket and
                # re-lease; double handling here would race it
                pass

    # --- iteration -----------------------------------------------------------

    def epoch(self, epoch: Optional[int] = None,
              from_start: bool = False) -> Iterator[dict]:
        """ClipLoader.epoch() twin (host batches, state honored/updated)."""
        for batch, state in self.epoch_items(epoch, from_start):
            self.loader.state = state
            if batch is not None:
                yield batch

    def epoch_items(self, epoch: Optional[int] = None,
                    from_start: bool = False) -> Iterator[tuple]:
        """The DevicePrefetcher contract, verbatim from ClipLoader: (batch,
        post-consumption LoaderState) pairs in exact batch order, a final
        (None, rollover) marker, `self.loader.state` never mutated here."""
        start_state = self.loader._start_state(epoch, from_start)
        epoch = start_state.epoch
        indices = self.loader._epoch_indices(epoch)
        n_batches = self.loader.batches_per_epoch()
        start = start_state.position
        # capture the consumer's trace context ONCE per pass: every lease
        # ships it as a traceparent so remote decode spans join the trace
        ctx = trace.capture()
        with self._cond:
            self._lease_traceparent = (
                trace.format_traceparent(ctx) if ctx is not None else None)
            self._gen += 1
            gen = self._gen
            self._epoch = epoch
            self._indices = indices
            self._spy = self.loader.samples_per_yield
            self._unleased = deque(range(start, n_batches))
            self._done.clear()
            self._pending.clear()
            self._next_yield = start
            self._error = None
            for w in self._workers.values():
                # an aborted previous pass's leases are stale: the readers
                # drop their frames by generation, and the slots free here
                w.outstanding = {o for o in w.outstanding if o[0] == gen}
            to_send = self._pump_locked()
        self._send_leases(to_send)
        try:
            b = start
            while b < n_batches:
                deadline = time.monotonic() + self.batch_timeout_s
                with self._cond:
                    while b not in self._done and self._error is None:
                        left = deadline - time.monotonic()
                        if left <= 0:
                            raise WireError(
                                f"no decode worker delivered batch {b} "
                                f"within {self.batch_timeout_s}s "
                                f"({len(self._workers)} worker(s) "
                                "connected)")
                        self._cond.wait(timeout=min(left, 0.2))
                    if self._error is not None:
                        raise self._error
                    batch, _wid = self._done.pop(b)
                    self._next_yield = b + 1
                    self.consumed += 1
                    to_send = self._pump_locked()  # the window advanced
                self._send_leases(to_send)
                yield batch, LoaderState(epoch=epoch, position=b + 1)
                b += 1
            yield None, LoaderState(epoch=epoch + 1, position=0)
        finally:
            # early exit (limit_train_batches break, an upstream
            # exception): invalidate the pass — readers drop stale batch
            # frames by generation, credits reset at the next pass start
            with self._cond:
                self._gen += 1
                self._unleased.clear()
                self._done.clear()
                self._pending.clear()

    # --- teardown ------------------------------------------------------------

    def close(self, timeout: float = 10.0) -> None:
        """Stop workers, close sockets, reap spawned processes. Idempotent;
        the wrapped loader stays usable (the trainer closes it itself)."""
        with self._cond:
            if self._closing:
                return
            self._closing = True
            if self._error is None:
                # release a consumer blocked mid-pass NOW: without this a
                # close() racing an active epoch (trainer crash teardown)
                # would leave the prefetcher thread waiting out the full
                # batch_timeout_s before noticing the world ended
                self._error = NoWorkersError("feed closed mid-pass")
            workers = list(self._workers.values())
            self._cond.notify_all()
        try:
            self._listener.close()
        except OSError:
            pass
        for w in workers:
            try:
                with w.send_lock:
                    send_frame(w.sock, "stop")
            except OSError:
                pass
            try:
                # shutdown (not just close): it is the call that actually
                # wakes a reader thread blocked in recv on this socket
                w.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                w.sock.close()
            except OSError:
                pass
        for w in workers:
            if w.thread is not None:
                w.thread.join(timeout=timeout)
        deadline = time.monotonic() + timeout
        for p in self._procs:
            try:
                p.wait(timeout=max(deadline - time.monotonic(), 0.1))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait(timeout=5.0)
        self._accept_thread.join(timeout=timeout)
