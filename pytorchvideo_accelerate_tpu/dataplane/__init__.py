"""Disaggregated data plane: decode-worker service + trainer-side feed.

Decode and training no longer share one host's cores: `RemoteClipFeed`
(feed.py) leases index spans to N `DecodeWorker` processes (worker.py,
console script ``pva-tpu-dataworker``) which stream ready clip-tensor
batches back over a length-prefixed zero-copy wire protocol (wire.py),
byte-identical to the local loader's stream and bounded by credit-based
back-pressure. See docs/INPUT_PIPELINE.md § disaggregated data plane.
"""

from pytorchvideo_accelerate_tpu.dataplane.feed import (  # noqa: F401
    NoWorkersError,
    RemoteClipFeed,
    spawn_worker,
)
from pytorchvideo_accelerate_tpu.dataplane.wire import (  # noqa: F401
    Frame,
    WireError,
    recv_frame,
    send_frame,
)
