"""Adaptive micro-batching in front of the accelerator.

One request is one clip; the chip wants full buckets. The batcher sits
between them: a bounded queue feeding a single flush thread that launches a
batch when `max_batch_size` requests are waiting OR the oldest has waited
`max_wait_ms` — the standard serving latency/throughput dial (low wait =
interactive latency, high wait = training-like fill ratios). Each launch is
padded UP to the engine's nearest compiled bucket with zero rows + a mask
(the eval path's masked-row convention), and per-request futures resolve
with exactly their own row — padded rows are sliced away host-side and can
never reach a response.

Single flush thread by design: the accelerator executes one batch at a time
anyway, requests stay strictly FIFO, and every stats observation happens on
one thread. Callers block on `Future.result(timeout)`; a full queue raises
`QueueFullError` at submit time (the HTTP front maps it to 503) instead of
growing tail latency without bound.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from pytorchvideo_accelerate_tpu import obs
from pytorchvideo_accelerate_tpu.obs import trace
from pytorchvideo_accelerate_tpu.reliability.faults import fault_point
from pytorchvideo_accelerate_tpu.serving.engine import CLIP_KEYS, clip_key
from pytorchvideo_accelerate_tpu.utils.logging import get_logger
from pytorchvideo_accelerate_tpu.utils.sync import (
    make_queue,
    make_thread,
    shared_state,
)

logger = get_logger("pva_tpu")


class QueueFullError(RuntimeError):
    """Request queue at serve.max_queue — shed load instead of buffering.

    Carries `retry_after_s` so the HTTP front can tell a well-behaved
    client when to come back (503 + Retry-After)."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


@dataclass
class _Request:
    clip: Dict[str, np.ndarray]
    future: Future
    t_enqueue: float
    key: tuple  # clip geometry: only same-shaped requests batch together
    # the submitter's trace context, carried WITH the payload across the
    # queue hop (None when tracing is disarmed or the caller is untraced)
    ctx: Optional[object] = None


_STOP = object()


@shared_state("max_batch_size", "max_wait_s", "stats")
class MicroBatcher:
    """Bounded request queue + flush thread over an `InferenceEngine`."""

    def __init__(self, engine, *, max_batch_size: Optional[int] = None,
                 max_wait_ms: float = 5.0, max_queue: int = 256, stats=None,
                 heartbeat=None, retry_after_s: float = 1.0):
        self.retry_after_s = float(retry_after_s)
        self.engine = engine
        # obs watchdog pinger: called once per flush-loop iteration (idle
        # included), so a wedged flush thread — where EVERY request stalls
        # — is detected; a closed batcher goes silent by design
        self._heartbeat = heartbeat
        # collection cap: the largest compiled bucket (so a full collection
        # pads to fill ratio 1.0), optionally tightened by the caller
        top = engine.buckets[-1]
        self.max_batch_size = min(max_batch_size or top, top)
        self.max_wait_s = max(max_wait_ms, 0.0) / 1e3
        self.stats = stats
        self._q: "queue.Queue" = make_queue(maxsize=max(max_queue, 1))
        self._closed = threading.Event()
        self._thread = make_thread(
            target=self._loop, name="pva-serve-batcher", daemon=True)
        self._thread.start()

    # --- client side ------------------------------------------------------

    def submit(self, clip: Dict[str, np.ndarray]) -> Future:
        """Enqueue ONE clip — leaves (T, H, W, C) or (V, T, H, W, C) — and
        get a Future resolving to its fp32 logits (num_classes,)."""
        clips = {k: np.asarray(v) for k, v in clip.items() if k in CLIP_KEYS}  # pva: disable=host-sync -- request payload is host-side (JSON/numpy), no device value
        if not clips:
            raise ValueError("request has neither 'video' nor 'slow'/'fast'")
        for k, v in clips.items():
            if v.ndim not in (4, 5):
                raise ValueError(
                    f"clip {k!r} must be (T,H,W,C) or (V,T,H,W,C), "
                    f"got shape {v.shape}")
        if self._closed.is_set():
            raise RuntimeError("batcher is closed")
        req = _Request(
            clip=clips, future=Future(), t_enqueue=time.monotonic(),
            key=clip_key(clips), ctx=trace.capture(),
        )
        try:
            self._q.put_nowait(req)
        except queue.Full:
            if self.stats is not None:
                self.stats.observe_rejected()
            raise QueueFullError(
                f"request queue full ({self._q.maxsize}); retry later",
                retry_after_s=self.retry_after_s,
            ) from None
        if self._closed.is_set() and not req.future.done():
            # close() may have drained the queue between our closed-check
            # and the put: nothing will serve this request — fail it fast
            # instead of leaving the caller to hit its own timeout
            try:
                req.future.set_exception(RuntimeError("batcher closed"))
            except Exception:  # lost the race to the flush thread: resolved
                pass
        return req.future

    def queue_depth(self) -> int:
        return self._q.qsize()

    def drain(self, timeout_s: float = 10.0) -> bool:
        """Wait for the queue to flush (drain-on-SIGTERM: stop ADMITTING
        upstream first, then let in-flight futures resolve). Returns True
        when the queue emptied within the budget; the subsequent `close()`
        join lets the flush thread finish the batch it is running."""
        deadline = time.monotonic() + max(timeout_s, 0.0)
        while time.monotonic() < deadline:
            if self._q.qsize() == 0:
                return True
            time.sleep(0.01)
        return self._q.qsize() == 0

    def close(self) -> None:
        """Stop the flush thread; pending requests are failed, not dropped
        silently."""
        if self._closed.is_set():
            return
        self._closed.set()
        try:
            self._q.put_nowait(_STOP)  # wake a blocked get()
        except queue.Full:
            pass  # the loop's bounded get() re-checks _closed within 100 ms
        self._thread.join(timeout=30.0)
        leftovers: List[_Request] = []
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is not _STOP:
                leftovers.append(item)
        for req in leftovers:
            req.future.set_exception(RuntimeError("batcher closed"))

    # --- flush thread -----------------------------------------------------

    def _loop(self) -> None:
        while not self._closed.is_set():
            if self._heartbeat is not None:
                self._heartbeat()
            try:
                first = self._q.get(timeout=0.1)
            except queue.Empty:
                continue
            if first is _STOP:
                break
            batch = [first]
            deadline = first.t_enqueue + self.max_wait_s
            while len(batch) < self.max_batch_size:
                wait = deadline - time.monotonic()
                if wait <= 0:
                    break
                try:
                    nxt = self._q.get(timeout=wait)
                except queue.Empty:
                    break
                if nxt is _STOP:
                    self._closed.set()
                    break
                batch.append(nxt)
            # "serve_flush" span: group + pad + forward + resolve — the
            # whole accelerator-side path of the serving flush thread
            with obs.span("serve_flush"):
                self._flush(batch)
        # drain-on-close happens in close(); anything arriving after the
        # loop exits is failed there

    def _flush(self, batch: List[_Request]) -> None:
        # only identically-shaped requests share a forward (mixed view
        # counts / geometries each get their own padded launch)
        groups: Dict[tuple, List[_Request]] = {}
        for req in batch:
            groups.setdefault(req.key, []).append(req)
        for reqs in groups.values():
            try:
                self._run(reqs)
            except Exception as e:  # noqa: BLE001 - fail the requests, not the thread
                logger.exception("serving batch failed")
                for req in reqs:
                    if not req.future.done():
                        req.future.set_exception(e)

    def _run(self, reqs: List[_Request]) -> None:
        # chaos hook: an injected raise fails THIS batch's futures (the
        # 500 path) without touching the flush thread — exactly what a
        # real engine/transfer failure does. Disarmed: one global read.
        fault_point("serve.flush")
        # claim each future before doing device work: a caller-cancelled
        # future (the HTTP front's request-timeout path) drops out of the
        # batch here, and a successful claim makes later cancel() attempts
        # fail instead of racing set_result below
        reqs = [r for r in reqs if r.future.set_running_or_notify_cancel()]
        if not reqs:
            return
        n = len(reqs)
        bucket = self.engine.bucket_for(n)
        stacked: Dict[str, np.ndarray] = {}
        for k in reqs[0].clip:
            rows = np.stack([r.clip[k] for r in reqs])
            if bucket > n:  # zero rows, masked out below
                pad = np.zeros((bucket - n,) + rows.shape[1:], rows.dtype)
                rows = np.concatenate([rows, pad], axis=0)
            stacked[k] = rows
        # the masked-row convention of the eval path: 1.0 = real request,
        # 0.0 = padding. The engine's pure forward ignores it; it documents
        # (and lets debug tooling assert) which rows are live.
        stacked["mask"] = np.asarray(  # pva: disable=host-sync -- builds the mask from a Python list, host-side by construction
            [1.0] * n + [0.0] * (bucket - n), np.float32)
        # tracing: every traced request gets its queue wait as a trace
        # event, and the device dispatch runs under the head request's
        # context so the engine-side spans join its trace. Disarmed: one
        # global read + a None check (trace.attach/span return a shared
        # no-op).
        rt = trace.get_tracer()
        head_ctx = None
        if rt is not None:
            now_w, now_m = time.time(), time.monotonic()
            for req in reqs:
                if req.ctx is not None:
                    if head_ctx is None:
                        head_ctx = req.ctx
                    waited = now_m - req.t_enqueue
                    rt.event(req.ctx, "queue_wait", now_w - waited, waited)
        with trace.attach(head_ctx):
            with trace.span("device_dispatch", batch=n, bucket=bucket):
                logits = self.engine.predict(stacked)
        done = time.monotonic()
        # padded rows are sliced away here — a response can only ever carry
        # logits[i] for the request that submitted row i
        latencies = []
        for i, req in enumerate(reqs):
            latencies.append(done - req.t_enqueue)
            req.future.set_result(logits[i])
        if self.stats is not None:
            self.stats.observe_batch(
                n, bucket, latencies,
                trace_ids=[getattr(r.ctx, "trace_id", None) for r in reqs])
