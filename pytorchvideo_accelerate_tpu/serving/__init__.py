"""Serving subsystem: checkpoint -> endpoint.

The training stack ends at a `Checkpointer` artifact; this package turns one
into a servable endpoint (the TPU-serving half of the Gemma-on-TPU recipe
and the actor side of the Podracer actor/learner split — PAPERS.md):

- `engine.InferenceEngine` — params pinned to the mesh (EMA-resolved), a
  cache of jitted forward functions keyed by (batch bucket, view count),
  and the SAME view-averaging protocol as `evaluate()`;
- `batcher.MicroBatcher` — bounded request queue with adaptive
  micro-batching (flush on size or deadline, pad to the nearest bucket,
  masked padded rows) returning per-request futures;
- `stats.ServingStats` — rolling latency percentiles, queue depth,
  batch-fill ratio, throughput, load-shed counters;
- `admission.AdmissionController` — healthy/degraded/draining state
  machine: queue-depth load shedding (503 + Retry-After) and the
  drain-on-SIGTERM latch (docs/RELIABILITY.md);
- `server.InferenceServer` — stdlib HTTP front (`/predict`, `/healthz`,
  `/stats`) and the `pva-tpu-serve` CLI.

See docs/SERVING.md.
"""

from pytorchvideo_accelerate_tpu.serving.admission import (  # noqa: F401
    AdmissionController,
)
from pytorchvideo_accelerate_tpu.serving.batcher import (  # noqa: F401
    MicroBatcher,
    QueueFullError,
)
from pytorchvideo_accelerate_tpu.serving.engine import InferenceEngine  # noqa: F401
from pytorchvideo_accelerate_tpu.serving.quantize import (  # noqa: F401
    dequantize_tree,
    quantize_tree,
)
from pytorchvideo_accelerate_tpu.serving.stats import ServingStats  # noqa: F401
