"""StubEngine: the one host-side `InferenceEngine` double.

Every harness that exercises the serving/fleet CONTROL PLANE (batcher,
scheduler, router, HTTP front, stats) without paying for a jax model —
the tsan stress scenario, the chaos serve/replica_kill legs, the loadgen
selftest — needs the same four-attribute engine surface. One definition
here, so an engine-interface change (a new required attribute) breaks one
import instead of silently drifting across N inline copies.

Tests may still define richer local doubles (row-tagging, launch
recording); product harnesses use this one.
"""

from __future__ import annotations

import time
from typing import Tuple

import numpy as np

from pytorchvideo_accelerate_tpu.utils.sync import make_lock, shared_state


class StubEngine:
    """Bucket geometry + a host-side forward; `tag` fills column 1 of the
    logits so callers can tell WHICH engine answered (hot-swap probes),
    `forward_s` makes service time measurable (deadline sheds, queue
    buildup)."""

    model_name = "stub"
    input_dtype = "float32"

    def __init__(self, tag: float = 0.0, forward_s: float = 0.001,
                 buckets: Tuple[int, ...] = (2, 4), num_classes: int = 4):
        self.tag = float(tag)
        self.forward_s = float(forward_s)
        self.buckets = tuple(buckets)
        self.num_classes = int(num_classes)
        self.compiled_keys: tuple = ()

    def bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        raise ValueError(
            f"batch of {n} exceeds the largest bucket {self.buckets[-1]}")

    def predict(self, batch) -> np.ndarray:
        if self.forward_s > 0:
            time.sleep(self.forward_s)
        rows = next(iter(v for k, v in batch.items() if k != "mask"))
        n = rows.shape[0]
        out = np.zeros((n, self.num_classes), np.float32)
        if self.num_classes > 1:
            out[:, 1] = self.tag
        return out


def stub_stream_logits(window: np.ndarray, num_classes: int,
                       tag: float = 0.0) -> np.ndarray:
    """The stub's deterministic per-window verdict: logits[0] is a pure
    function of the WINDOW CONTENTS (mean over all pixels), so a chaos/
    test client can verify a replica resumed a session at the correct
    window position by recomputing the expectation from its own
    resendable window. One module-level definition — the server-side stub
    and every client-side reference import the same arithmetic."""
    out = np.zeros((num_classes,), np.float32)
    out[0] = np.asarray(window, np.float64).mean()
    if num_classes > 1:
        out[1] = tag
    return out


@shared_state("_rings")
class StubStreamEngine(StubEngine):
    """Session-capable stub: the streaming CONTROL-PLANE double (affinity
    routing, /stream, scheduler session launches, chaos replica kills)
    without a jax model. Host-side numpy rings mirror the real
    `StreamingEngine` window semantics — establish from a window, roll by
    stride per advance, deterministic logits over the rolled window via
    `stub_stream_logits` — so 'resumed at the correct position' is a
    checkable equality, not a liveness hand-wave."""

    supports_sessions = True

    def __init__(self, tag: float = 0.0, forward_s: float = 0.001,
                 buckets: Tuple[int, ...] = (2, 4), num_classes: int = 4):
        super().__init__(tag=tag, forward_s=forward_s, buckets=buckets,
                         num_classes=num_classes)
        self._lock = make_lock("StubStreamEngine._lock")
        self._rings: dict = {}  # sid -> (T, H, W, C) window, in order

    def advance_batch(self, items) -> list:
        if self.forward_s > 0:
            time.sleep(self.forward_s)
        out = []
        for item in items:
            sid = str(item.get("sid") or "")
            frames = item.get("frames")
            window = item.get("window")
            with self._lock:
                ring = self._rings.get(sid)
                if ring is None or (frames is None and window is not None):
                    if window is None:
                        from pytorchvideo_accelerate_tpu.streaming.session import (  # noqa: E501
                            SessionUnknownError,
                        )

                        out.append(SessionUnknownError(
                            f"stub session {sid!r} unknown; resend window"))
                        continue
                    # establish/re-establish: the resendable window is the
                    # CURRENT window (inclusive of this request's newest
                    # frames, the client contract) — use it verbatim
                    ring = np.asarray(window, np.float32)
                elif frames is not None:
                    f = np.asarray(frames, np.float32)
                    ring = np.concatenate([ring[f.shape[0]:], f], 0)
                if item.get("end"):
                    self._rings.pop(sid, None)
                else:
                    self._rings[sid] = ring
            out.append(stub_stream_logits(ring, self.num_classes, self.tag))
        return out
