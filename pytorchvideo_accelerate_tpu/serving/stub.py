"""StubEngine: the one host-side `InferenceEngine` double.

Every harness that exercises the serving/fleet CONTROL PLANE (batcher,
scheduler, router, HTTP front, stats) without paying for a jax model —
the tsan stress scenario, the chaos serve/replica_kill legs, the loadgen
selftest — needs the same four-attribute engine surface. One definition
here, so an engine-interface change (a new required attribute) breaks one
import instead of silently drifting across N inline copies.

Tests may still define richer local doubles (row-tagging, launch
recording); product harnesses use this one.
"""

from __future__ import annotations

import time
from typing import Tuple

import numpy as np


class StubEngine:
    """Bucket geometry + a host-side forward; `tag` fills column 1 of the
    logits so callers can tell WHICH engine answered (hot-swap probes),
    `forward_s` makes service time measurable (deadline sheds, queue
    buildup)."""

    model_name = "stub"
    input_dtype = "float32"

    def __init__(self, tag: float = 0.0, forward_s: float = 0.001,
                 buckets: Tuple[int, ...] = (2, 4), num_classes: int = 4):
        self.tag = float(tag)
        self.forward_s = float(forward_s)
        self.buckets = tuple(buckets)
        self.num_classes = int(num_classes)
        self.compiled_keys: tuple = ()

    def bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        raise ValueError(
            f"batch of {n} exceeds the largest bucket {self.buckets[-1]}")

    def predict(self, batch) -> np.ndarray:
        if self.forward_s > 0:
            time.sleep(self.forward_s)
        rows = next(iter(v for k, v in batch.items() if k != "mask"))
        n = rows.shape[0]
        out = np.zeros((n, self.num_classes), np.float32)
        if self.num_classes > 1:
            out[:, 1] = self.tag
        return out
