"""Serving observability: rolling latency percentiles + batcher health.

The serving-side sibling of the trainer's perf dict (input_wait_frac,
steps_per_sec): `snapshot()` returns a flat {str: float} the trackers
already know how to log (trainer/tracking.py TrackerHub.log) and the
`/stats` endpoint returns verbatim. Windowed values (percentiles,
fill ratio, throughput) describe the *current* traffic over the last N
completed requests.

Counters and the latency histogram live in an `obs.registry.Registry`
owned by this object: the `/metrics` Prometheus endpoint renders that
registry and `/stats` reads the SAME counter objects, so the two surfaces
cannot drift. Rejections are labeled by cause — "400" (bad request),
"503" (queue full), "504" (request budget exceeded) — and `/stats`
exposes both the aggregate (`rejected`) and the per-cause split.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Dict, Optional, Sequence

from pytorchvideo_accelerate_tpu.obs.registry import DEFAULT_BUCKETS, Registry
from pytorchvideo_accelerate_tpu.utils.sync import make_lock, shared_state

# request latencies are enqueue -> response: sub-ms (cache-hot tiny model)
# through multi-second (cold compile, deep queue) — the shared bounds plus
# a 30s tail for the request_timeout_s budget region
LATENCY_BUCKETS = DEFAULT_BUCKETS + (30.0,)


def _percentile(sorted_vals: Sequence[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted sequence."""
    if not sorted_vals:
        return 0.0
    idx = max(0, min(len(sorted_vals) - 1,
                     int(round(q / 100.0 * len(sorted_vals) + 0.5)) - 1))
    return float(sorted_vals[idx])


@shared_state("queue_depth_fn", "_lat", "_fills", "_slowest")
class ServingStats:
    """Thread-safe rolling serving metrics.

    - latency percentiles (p50/p95/p99, ms) over the last `window`
      completed requests, measured enqueue -> response (queue wait +
      batching wait + device time — what the caller experiences);
    - batch-fill ratio: real rows / padded bucket rows over the window —
      1.0 means every launch was a full bucket, low values mean the
      max_wait_ms deadline is flushing underfilled batches;
    - throughput: completed requests/sec over the window span;
    - queue depth: live gauge read from the batcher at snapshot time;
    - cumulative counters (registry-backed, shared with `/metrics`):
      requests, batches, rejected{cause}, errors, compiles.
    """

    def __init__(self, window: int = 1024,
                 queue_depth_fn: Optional[Callable[[], int]] = None,
                 registry: Optional[Registry] = None,
                 latency_buckets: Optional[Sequence[float]] = None):
        self._lock = make_lock("ServingStats._lock")
        self._lat = deque(maxlen=max(window, 1))     # (done_ts, latency_s)
        self._fills = deque(maxlen=max(window, 1))   # (n_real, bucket)
        # top-K (latency_s, trace_id) among TRACED completions — the
        # `/stats` slowest_traces view that turns a bad percentile into a
        # concrete trace id to pull from the merged timeline
        self._slowest: list = []
        self.queue_depth_fn = queue_depth_fn
        self._started = time.monotonic()
        # registry-backed counters/histogram: the single source of truth
        # for BOTH /stats and the /metrics Prometheus rendering. A private
        # Registry per ServingStats: multiple engines in one process (the
        # bench) must not share counters, and the queue-depth/uptime gauge
        # callbacks are per-instance (a shared registry would keep only the
        # last instance's callback). Aggregate across servers at the
        # scraper, not here.
        self.registry = registry or Registry()
        self._c_requests = self.registry.counter(
            "pva_serving_requests_total", "requests completed successfully")
        self._c_batches = self.registry.counter(
            "pva_serving_batches_total", "batches launched on the engine")
        self._c_rejected = self.registry.counter(
            "pva_serving_rejected_total",
            "requests rejected before completion, by HTTP cause",
            labelnames=("cause",))
        self._c_errors = self.registry.counter(
            "pva_serving_errors_total",
            "requests failed by an engine/batch error (HTTP 500)")
        # load-shedding split OUT of the generic 503 bucket: a shed is the
        # admission controller working as designed (degraded/draining
        # state, serving/admission.py), not a hard queue-full failure —
        # operators must be able to tell the two apart on /stats + /metrics
        self._c_shed = self.registry.counter(
            "pva_serving_shed_total",
            "requests shed by admission control (503 + Retry-After), "
            "by service state", labelnames=("state",))
        self._c_compiles = self.registry.counter(
            "pva_serving_compiled_buckets_total",
            "new (bucket, views) shapes compiled by the engine")
        # bucket ladder: explicit per-instance boundaries win, then any
        # registered family default (obs.registry.set_family_buckets),
        # then the shared serving ladder — the per-family configurability
        # the one-size-for-all LATENCY_BUCKETS lacked
        from pytorchvideo_accelerate_tpu.obs.registry import family_buckets

        self._h_latency = self.registry.histogram(
            "pva_serving_request_latency_seconds",
            "enqueue-to-response latency of completed requests",
            buckets=(tuple(latency_buckets) if latency_buckets
                     else family_buckets("pva_serving_request_latency_seconds",
                                         default=LATENCY_BUCKETS)))
        self.registry.gauge(
            "pva_serving_queue_depth",
            "requests queued but not yet batched").set_function(
                lambda: float(self.queue_depth_fn())
                if self.queue_depth_fn is not None else 0.0)
        self.registry.gauge(
            "pva_serving_uptime_seconds",
            "seconds since this ServingStats was created").set_function(
                lambda: time.monotonic() - self._started)

    _SLOWEST_KEEP = 8

    def observe_batch(self, n_real: int, bucket: int,
                      latencies_s: Sequence[float],
                      trace_ids: Optional[Sequence[Optional[str]]] = None,
                      ) -> None:
        """`trace_ids` (parallel to `latencies_s`, entries may be None)
        links each completion to its sampled trace: the latency histogram
        pins the trace id as the bucket's exemplar, and the top-K slowest
        land in `slowest_traces()` — a p99 sample becomes a greppable
        trace instead of an anonymous number."""
        now = time.monotonic()
        self._c_requests.inc(len(latencies_s))
        self._c_batches.inc()
        for i, lat in enumerate(latencies_s):
            tid = (trace_ids[i] if trace_ids is not None
                   and i < len(trace_ids) else None)
            self._h_latency.observe(lat, trace_id=tid)
        with self._lock:
            self._fills.append((int(n_real), int(bucket)))
            for i, lat in enumerate(latencies_s):
                self._lat.append((now, float(lat)))
                tid = (trace_ids[i] if trace_ids is not None
                       and i < len(trace_ids) else None)
                if tid:
                    self._slowest.append((float(lat), str(tid)))
            if len(self._slowest) > self._SLOWEST_KEEP:
                self._slowest.sort(reverse=True)
                del self._slowest[self._SLOWEST_KEEP:]

    def slowest_traces(self, k: int = 5) -> list:
        """Top-k traced completions by latency: [{trace_id, latency_ms}]
        (served on `/stats`; NOT part of the flat snapshot() dict — the
        tracker-facing surface stays {str: float})."""
        with self._lock:
            worst = sorted(self._slowest, reverse=True)[:k]
        return [{"trace_id": tid, "latency_ms": round(lat * 1e3, 3)}
                for lat, tid in worst]

    def observe_rejected(self, cause: str = "503", n: int = 1) -> None:
        """A request shed before completion; `cause` is the HTTP status the
        caller saw: "400" bad request, "503" queue full, "504" budget."""
        self._c_rejected.inc(n, cause=str(cause))

    def observe_shed(self, state: str = "degraded", n: int = 1) -> None:
        """A request shed by admission control BEFORE it touched the
        queue (503 + Retry-After, serving/admission.py); `state` is the
        controller state that shed it ("degraded" | "draining")."""
        self._c_shed.inc(n, state=str(state))

    def observe_error(self, n: int = 1) -> None:
        """A request failed by an engine/batch exception (HTTP 500)."""
        self._c_errors.inc(n)

    def observe_compile(self) -> None:
        self._c_compiles.inc()

    def window(self) -> tuple:
        """Raw rolling-window samples, for cross-replica merging:
        ``(latencies [(done_ts, latency_s)], fills [(n_real, bucket)])``.
        Percentiles of per-replica percentiles would be wrong (a hot
        replica's tail vanishes into a cool replica's median); the fleet
        router pools the raw samples instead (`merge`)."""
        with self._lock:
            return list(self._lat), list(self._fills)

    def snapshot_labels(self, label: str) -> Dict[str, float]:
        """Per-replica snapshot with every key prefixed ``label/`` — the
        tracker-facing twin of the registry's replica labels, so one
        TrackerHub.log call can carry the whole fleet without collisions."""
        return {f"{label}/{k}": v for k, v in self.snapshot().items()}

    @staticmethod
    def merge(stats_list: Sequence["ServingStats"],
              extra: Optional[Dict[str, float]] = None) -> Dict[str, float]:
        """Fleet-level aggregate across per-replica ServingStats.

        Percentiles are computed over the POOLED raw latency windows
        (`window()`), never by averaging per-replica percentiles; counters
        sum. Sheds are counted exactly once, where they happened: each
        replica's `shed` counts requests ITS admission/deadline machinery
        shed, and router-level sheds (requests that never reached any
        replica) arrive via `extra` (e.g. ``{"router_shed": n}``) and are
        deliberately NOT folded into the summed `shed` — folding them in
        would double-count every shed the router already re-tried against
        a second replica's admission door."""
        keys = ("requests", "batches", "errors", "rejected",
                "rejected_400", "rejected_503", "rejected_504", "shed",
                "compiled_buckets")
        out: Dict[str, float] = {k: 0.0 for k in keys}
        lat: list = []
        fills: list = []
        for st in stats_list:
            snap = st.snapshot()
            for k in keys:
                out[k] += snap.get(k, 0.0)
            w_lat, w_fills = st.window()
            lat.extend(w_lat)
            fills.extend(w_fills)
        vals = sorted(v for _, v in lat)
        out["p50_ms"] = round(_percentile(vals, 50) * 1e3, 3)
        out["p95_ms"] = round(_percentile(vals, 95) * 1e3, 3)
        out["p99_ms"] = round(_percentile(vals, 99) * 1e3, 3)
        real = sum(n for n, _ in fills)
        padded = sum(b for _, b in fills)
        out["batch_fill_ratio"] = (round(real / padded, 4) if padded
                                   else 0.0)
        lat.sort(key=lambda s: s[0])
        if len(lat) >= 2 and lat[-1][0] > lat[0][0]:
            out["throughput_rps"] = round(
                (len(lat) - 1) / (lat[-1][0] - lat[0][0]), 3)
        else:
            out["throughput_rps"] = 0.0
        out["replicas"] = float(len(stats_list))
        out.update(extra or {})
        return out

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            lat = list(self._lat)
            fills = list(self._fills)
        out: Dict[str, float] = {
            "requests": self._c_requests.total(),
            "batches": self._c_batches.total(),
            "errors": self._c_errors.total(),
            "compiled_buckets": self._c_compiles.total(),
            "uptime_s": round(time.monotonic() - self._started, 3),
        }
        # per-cause rejection split, sourced from the same labeled counter
        # /metrics renders — the two surfaces cannot disagree. ONE locked
        # read (samples()) feeds both the split and the aggregate: the old
        # total() + three value() calls took the counter lock four times,
        # so a rejection landing mid-snapshot could make `rejected` differ
        # from the sum of its own split (pva-tpu-lint window-read review).
        rejected = {labels.get("cause"): v
                    for labels, v in self._c_rejected.samples()}
        out["rejected"] = float(sum(rejected.values()))
        for cause in ("400", "503", "504"):
            out[f"rejected_{cause}"] = float(rejected.get(cause, 0.0))
        # sheds (admission control) are NOT in the rejected split above:
        # rejected_503 stays "hard queue full", shed is the controller
        # degrading on purpose — the same split /metrics renders
        out["shed"] = self._c_shed.total()
        vals = sorted(v for _, v in lat)
        out["p50_ms"] = round(_percentile(vals, 50) * 1e3, 3)
        out["p95_ms"] = round(_percentile(vals, 95) * 1e3, 3)
        out["p99_ms"] = round(_percentile(vals, 99) * 1e3, 3)
        real = sum(n for n, _ in fills)
        padded = sum(b for _, b in fills)
        out["batch_fill_ratio"] = round(real / padded, 4) if padded else 0.0
        # window-span throughput: requests completed per second between the
        # oldest and newest entries still in the window (0 when the window
        # holds fewer than 2 completions — no span to divide by)
        if len(lat) >= 2 and lat[-1][0] > lat[0][0]:
            out["throughput_rps"] = round(
                (len(lat) - 1) / (lat[-1][0] - lat[0][0]), 3)
        else:
            out["throughput_rps"] = 0.0
        if self.queue_depth_fn is not None:
            try:
                out["queue_depth"] = float(self.queue_depth_fn())
            except Exception:  # a closing batcher must not break /stats
                out["queue_depth"] = 0.0
        return out
