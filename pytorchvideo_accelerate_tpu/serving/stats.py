"""Serving observability: rolling latency percentiles + batcher health.

The serving-side sibling of the trainer's perf dict (input_wait_frac,
steps_per_sec): `snapshot()` returns a flat {str: float} the trackers
already know how to log (trainer/tracking.py TrackerHub.log) and the
`/stats` endpoint returns verbatim. Everything is windowed (last N
completed requests) so the numbers describe the *current* traffic, not the
process lifetime; counters (requests/batches/compiles) are cumulative.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, Optional, Sequence


def _percentile(sorted_vals: Sequence[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted sequence."""
    if not sorted_vals:
        return 0.0
    idx = max(0, min(len(sorted_vals) - 1,
                     int(round(q / 100.0 * len(sorted_vals) + 0.5)) - 1))
    return float(sorted_vals[idx])


class ServingStats:
    """Thread-safe rolling serving metrics.

    - latency percentiles (p50/p95/p99, ms) over the last `window`
      completed requests, measured enqueue -> response (queue wait +
      batching wait + device time — what the caller experiences);
    - batch-fill ratio: real rows / padded bucket rows over the window —
      1.0 means every launch was a full bucket, low values mean the
      max_wait_ms deadline is flushing underfilled batches;
    - throughput: completed requests/sec over the window span;
    - queue depth: live gauge read from the batcher at snapshot time;
    - cumulative counters: requests, batches, rejected, compiles (new
      (bucket, views) shapes hitting the engine's jit cache).
    """

    def __init__(self, window: int = 1024,
                 queue_depth_fn: Optional[Callable[[], int]] = None):
        self._lock = threading.Lock()
        self._lat = deque(maxlen=max(window, 1))     # (done_ts, latency_s)
        self._fills = deque(maxlen=max(window, 1))   # (n_real, bucket)
        self.queue_depth_fn = queue_depth_fn
        self.requests = 0
        self.batches = 0
        self.rejected = 0
        self.compiles = 0
        self._started = time.monotonic()

    def observe_batch(self, n_real: int, bucket: int,
                      latencies_s: Sequence[float]) -> None:
        now = time.monotonic()
        with self._lock:
            self.requests += len(latencies_s)
            self.batches += 1
            self._fills.append((int(n_real), int(bucket)))
            for lat in latencies_s:
                self._lat.append((now, float(lat)))

    def observe_rejected(self, n: int = 1) -> None:
        with self._lock:
            self.rejected += n

    def observe_compile(self) -> None:
        with self._lock:
            self.compiles += 1

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            lat = list(self._lat)
            fills = list(self._fills)
            out: Dict[str, float] = {
                "requests": float(self.requests),
                "batches": float(self.batches),
                "rejected": float(self.rejected),
                "compiled_buckets": float(self.compiles),
                "uptime_s": round(time.monotonic() - self._started, 3),
            }
        vals = sorted(v for _, v in lat)
        out["p50_ms"] = round(_percentile(vals, 50) * 1e3, 3)
        out["p95_ms"] = round(_percentile(vals, 95) * 1e3, 3)
        out["p99_ms"] = round(_percentile(vals, 99) * 1e3, 3)
        real = sum(n for n, _ in fills)
        padded = sum(b for _, b in fills)
        out["batch_fill_ratio"] = round(real / padded, 4) if padded else 0.0
        # window-span throughput: requests completed per second between the
        # oldest and newest entries still in the window (0 when the window
        # holds fewer than 2 completions — no span to divide by)
        if len(lat) >= 2 and lat[-1][0] > lat[0][0]:
            out["throughput_rps"] = round(
                (len(lat) - 1) / (lat[-1][0] - lat[0][0]), 3)
        else:
            out["throughput_rps"] = 0.0
        if self.queue_depth_fn is not None:
            try:
                out["queue_depth"] = float(self.queue_depth_fn())
            except Exception:  # a closing batcher must not break /stats
                out["queue_depth"] = 0.0
        return out
