"""int8 weight quantization for the serving tier (`serve.quantization`).

The Gemma-on-TPU fine-tune/serve comparison (PAPERS.md) quantifies what
full-precision serving leaves on the table: weights dominate a serving
replica's HBM residency, artifact size, and hot-swap transfer bytes.
This module implements the classic weight-only recipe — **int8 weights,
per-channel absmax scales, full-precision activations** (bf16 under the
default compute policy):

- `quantize_tree(params)` — every conv/dense "kernel" leaf above a size
  floor becomes `{"q8": int8, "q8_scale": f32}`: `scale[c] =
  absmax(w[..., c]) / 127` per OUTPUT channel (the last axis of every
  flax kernel layout in this zoo), `q = round(w / scale)` clipped to
  [-127, 127]. Biases, norm scales/biases, and BN running stats stay
  fp — they are a rounding error of the byte budget and quantizing
  norm statistics is where weight-only schemes actually lose accuracy.
- `dequantize_tree(tree, dtype)` — the in-graph inverse: `q * scale`
  in an f32 island, downcast once to the compute dtype. The engine
  calls it INSIDE the jitted forward, so the int8 tree is what lives
  pinned in HBM (4x smaller) and XLA is free to fuse the dequant into
  the weight read of each conv.

Quantization is applied at `export_inference` time (a baked int8
artifact — `meta.quantization` records it) or on the fly when a
full-precision artifact is loaded into an engine with
`serve.quantization=int8`. Both routes produce bit-identical quantized
weights (same absmax arithmetic in f32). The quality gate lives in
tests/test_zquant.py: int8-served top-1 within a stated tolerance of
full-precision serving on the tiny CPU-mesh e2e, padded rows and
multi-view folding unchanged. "off" leaves every byte of the engine's
behavior identical to the pre-quantization path.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy as np

from pytorchvideo_accelerate_tpu.precision import end_island, f32_island

Q_KEY = "q8"
SCALE_KEY = "q8_scale"
QUANT_MODES = ("off", "int8")

# leaves below this many elements stay fp: biases/norm vectors are noise
# in the byte budget and carry outsized accuracy weight
MIN_QUANT_SIZE = 1024


def is_quant_leaf(x: Any) -> bool:
    """True for the {"q8": ..., "q8_scale": ...} marker dicts."""
    return isinstance(x, dict) and set(x.keys()) == {Q_KEY, SCALE_KEY}


def _eligible(name: str, arr) -> bool:
    # conv/dense weights are all named "kernel" in this zoo (flax layout,
    # output features on the LAST axis — including the depthwise
    # (kt,kh,kw,1,C) layout); everything else is norm/bias/stat state
    return (name == "kernel" and getattr(arr, "ndim", 0) >= 2
            and int(np.size(arr)) >= MIN_QUANT_SIZE)


def quantize_array(w) -> Dict[str, np.ndarray]:
    """Per-output-channel absmax int8 quantization of one weight array."""
    w32 = f32_island(np.asarray(w))
    axes = tuple(range(w32.ndim - 1))
    absmax = np.max(np.abs(w32), axis=axes)
    scale = f32_island(absmax / 127.0)
    # an all-zero channel must not divide by zero; its q rows are zero
    safe = f32_island(np.where(scale > 0, scale, 1.0))
    q = np.clip(np.rint(w32 / safe), -127, 127).astype(np.int8)
    return {Q_KEY: q, SCALE_KEY: safe}


def quantize_tree(params: Any) -> Tuple[Any, int]:
    """Walk a params dict-tree; returns (quantized tree, #leaves
    quantized). Already-quantized leaves pass through unchanged (the
    idempotence a hot-swap of a baked artifact relies on)."""
    n = 0

    def walk(node, name=""):
        nonlocal n
        if is_quant_leaf(node):
            return node
        if isinstance(node, dict):
            return {k: walk(v, k) for k, v in node.items()}
        if _eligible(name, node):
            n += 1
            return quantize_array(node)
        return node

    return walk(params), n


def dequantize_tree(tree: Any, dtype) -> Any:
    """In-graph inverse: q * scale in an f32 island, one downcast to the
    compute dtype (the int8-weight / bf16-activation contract). Works on
    jax arrays inside jit and on numpy trees alike."""
    import jax

    def deq(x):
        if is_quant_leaf(x):
            return end_island(f32_island(x[Q_KEY]) * x[SCALE_KEY], dtype)
        return x

    return jax.tree_util.tree_map(deq, tree, is_leaf=is_quant_leaf)


def quantize_kv(kv):
    """Per-token-row absmax int8 quantization of streaming KV-ring
    activations (streaming/engine.py KV rings, `serve.quantization=int8`):
    `kv` (..., dim) -> (q8 int8 same shape, scale f32 (...,)). Unlike the
    weight path this quantizes ACTIVATIONS — per-token scales (one per
    (layer, k/v, slot, spatial) row) keep the round-trip error bounded by
    each token's own magnitude, so one outlier token cannot flatten its
    neighbours' resolution. In-graph (jit) and numpy callers both work."""
    import jax.numpy as jnp

    kv32 = f32_island(kv)
    absmax = jnp.max(jnp.abs(kv32), axis=-1)
    scale = absmax / 127.0
    # an all-zero row must not divide by zero; its q entries are zero
    safe = jnp.where(scale > 0, scale, 1.0)
    q = jnp.clip(jnp.rint(kv32 / safe[..., None]), -127, 127).astype(jnp.int8)
    return q, safe


def dequantize_kv(q, scale, dtype):
    """In-graph inverse of `quantize_kv`: q * scale per token row in an
    f32 island, one downcast to the compute dtype (the int8-KV / fp-query
    contract the incremental attention step reads the ring through)."""
    return end_island(f32_island(q) * scale[..., None], dtype)


def quantized_leaf_count(tree: Any) -> int:
    import jax

    return sum(1 for leaf in jax.tree_util.tree_leaves(
        tree, is_leaf=is_quant_leaf) if is_quant_leaf(leaf))


def quant_bytes(tree: Any) -> Dict[str, int]:
    """{quantized, fp} payload bytes — the serving-memory win, reported
    by the engine log and the kbench record."""
    import jax

    q = fp = 0
    for leaf in jax.tree_util.tree_leaves(tree, is_leaf=is_quant_leaf):
        if is_quant_leaf(leaf):
            q += int(np.size(leaf[Q_KEY])) + 4 * int(np.size(leaf[SCALE_KEY]))
        else:
            fp += int(np.asarray(leaf).nbytes)
    return {"quantized": q, "fp": fp}
