"""Admission control + service health state machine.

The graceful-degradation front of the serving tier (docs/RELIABILITY.md):
a `healthy / degraded / draining` state machine driven by queue depth and
the drain signal, deciding per request whether to admit or shed.

Why shed EARLY (at `shed_hwm` requests queued, not at the hard queue
bound): once the queue is deep, every admitted request inherits the whole
queue's latency — the batcher keeps launching full buckets either way, so
admitting more work past the high-water mark buys zero throughput and
buys p99 collapse. A `503 + Retry-After` at the door is the cheapest
response the server can produce and tells a well-behaved client exactly
when to come back. Hysteresis (`recover_lwm` < `shed_hwm`) keeps the
state from flapping at the boundary.

`draining` is terminal-ish: entered on SIGTERM (server drain path), sheds
everything, and `/healthz` goes non-200 so load balancers stop routing
while in-flight futures flush.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

from pytorchvideo_accelerate_tpu.utils.sync import make_lock, shared_state

HEALTHY, DEGRADED, DRAINING = "healthy", "degraded", "draining"


@shared_state("_state")
class AdmissionController:
    """Queue-depth load shedding with hysteresis + a drain latch.

    Thread-safe: `admit()` runs on every HTTP handler thread while
    `start_draining()` arrives from a signal handler's helper thread.
    """

    def __init__(self, max_queue: int, shed_frac: float = 0.9,
                 recover_frac: float = 0.5, retry_after_s: float = 1.0,
                 on_state_change: Optional[Callable[[str, str], None]] = None):
        if not 0.0 < shed_frac <= 1.0:
            raise ValueError(f"shed_frac must be in (0, 1], got {shed_frac}")
        if not 0.0 <= recover_frac <= shed_frac:
            raise ValueError(
                f"recover_frac must be in [0, shed_frac], got {recover_frac}")
        self.max_queue = max(int(max_queue), 1)
        self.shed_hwm = max(int(self.max_queue * shed_frac), 1)
        self.recover_lwm = int(self.max_queue * recover_frac)
        self.retry_after_s = float(retry_after_s)
        self.on_state_change = on_state_change  # (old, new) observer
        # live depth source (e.g. MicroBatcher.queue_depth): lets state()
        # reads drive degraded->healthy recovery on an IDLE server — after
        # a burst, clients back off exactly as Retry-After told them to,
        # so without this /healthz would report degraded forever until the
        # next /predict happened to call admit()
        self.queue_depth_fn: Optional[Callable[[], int]] = None
        self._lock = make_lock("AdmissionController._lock")
        self._state = HEALTHY

    # --- state ------------------------------------------------------------

    def state(self) -> str:
        with self._lock:
            state = self._state
        if state == DEGRADED and self.queue_depth_fn is not None:
            try:
                depth = int(self.queue_depth_fn())
            except Exception:  # pragma: no cover - a broken probe can't shed
                return state
            if depth <= self.recover_lwm:
                self._transition(HEALTHY)
                return HEALTHY
        return state

    def _transition(self, new: str) -> None:
        """Caller holds no lock; observer runs outside it (it may log)."""
        with self._lock:
            old = self._state
            if old == new or old == DRAINING:  # draining never un-drains
                return
            self._state = new
        if self.on_state_change is not None:
            try:
                self.on_state_change(old, new)
            except Exception:  # pragma: no cover - observer must not shed
                pass

    def start_draining(self) -> None:
        """Drain latch (SIGTERM): every subsequent request sheds; the
        in-flight ones keep their futures."""
        self._transition(DRAINING)

    # --- the per-request decision -----------------------------------------

    def admit(self, queue_depth: int) -> Tuple[bool, float]:
        """(admit?, retry_after_s). Called BEFORE submit with the live
        queue depth; also drives the healthy<->degraded hysteresis."""
        with self._lock:
            state = self._state
        if state == DRAINING:
            return False, self.retry_after_s
        if queue_depth >= self.shed_hwm:
            self._transition(DEGRADED)
            return False, self.retry_after_s
        if state == DEGRADED and queue_depth <= self.recover_lwm:
            self._transition(HEALTHY)
        return True, 0.0
