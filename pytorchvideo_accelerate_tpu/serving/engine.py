"""The inference engine: pinned weights + bucketed compiled forwards.

Checkpoint-to-endpoint, step 1 of 2 (the batcher is step 2): restore a
params-only `export_inference` artifact (EMA-resolved at export — the same
weights `evaluate()` scores), pin the params to the device mesh with the
training stack's own `shard_params` rules, and serve batched forwards
through a cache of jitted functions keyed by (batch bucket, view count).

Why buckets: XLA compiles per shape. A serving batch of every possible size
would compile on demand at request time (seconds of tail latency); instead
the batcher pads every launch UP to the nearest bucket — multiples of the
mesh's data-shard count, doubling up to `max_batch_size` — so steady-state
traffic only ever hits already-compiled executables. Padded rows ride a
mask (the eval path's masked-metrics convention) and are stripped by the
batcher before responses resolve.

Parity: the forward is `_constrain_batch` -> `device_normalize_batch` ->
`multiview_logits` over the eval weights — the exact op sequence of
`make_eval_step` (trainer/steps.py) minus the metric sums, so serving
logits (and therefore top-1) match `evaluate()`.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pytorchvideo_accelerate_tpu.obs import memory as obs_memory
from pytorchvideo_accelerate_tpu.parallel.mesh import data_shard_count, make_mesh
from pytorchvideo_accelerate_tpu.parallel.sharding import shard_batch, shard_params
from pytorchvideo_accelerate_tpu.trainer.steps import (
    _constrain_batch,
    device_normalize_batch,
    model_inputs,
    multiview_logits,
)
from pytorchvideo_accelerate_tpu.utils.logging import get_logger
from pytorchvideo_accelerate_tpu.utils.sync import make_lock

logger = get_logger("pva_tpu")

# the batch-dict clip leaves (single definition — batcher.py and server.py
# import it, so request filtering can't diverge across the three layers)
CLIP_KEYS = ("video", "slow", "fast")

# compiled-executable cache bound: every distinct request geometry costs a
# synchronous compile and permanent executable memory, so arbitrary client
# shapes must hit a ceiling instead of growing the cache without limit
MAX_COMPILED_KEYS = 64


def _executable_bytes(compiled) -> int:
    """Best-effort device footprint of an AOT-compiled executable (code
    size via `memory_analysis()`; 0 when the backend reports none — the
    CPU path, where the ledger stays estimate-only anyway)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return 0
    return int(getattr(ma, "generated_code_size_in_bytes", 0) or 0)


def clip_key(clips: Dict[str, Any]) -> tuple:
    """Geometry key for a clip dict: ((name, shape), ...) sorted by name —
    the unit of batch grouping (batcher) and forward-cache keying (engine)."""
    return tuple((k, tuple(np.shape(clips[k]))) for k in sorted(clips))


def _round_up(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


def compute_buckets(max_batch_size: int, shards: int) -> Tuple[int, ...]:
    """Padding targets: shard-aligned sizes on a doubling ladder up to
    max_batch_size.

    Every bucket must divide over the mesh's batch axes (shard_batch places
    the batch dim across `data*fsdp` devices), and the ladder must be
    shard-aligned AND terminating on ANY shard count — so each rung is the
    smallest shard multiple >= a power-of-two TARGET (the `lcm`-style
    rounding `bench_multichip` uses for its global batch, PR 7) instead of
    a raw double of the previous rung: raw power-of-two doubling never
    lands on a 3/6/10-shard multiple (the data dims of 12/24/40-device
    slices), which is exactly how PR 7 found the non-terminating variant
    of this loop. Doubling targets keep the compiled-executable count
    logarithmic in max_batch_size; duplicate rungs (every target below the
    shard count rounds up to it) collapse."""
    shards = max(int(shards), 1)
    top = _round_up(max(max_batch_size, 1), shards)
    buckets = []
    target = 1
    while True:
        b = _round_up(target, shards)
        if b >= top:
            break
        if not buckets or b != buckets[-1]:
            buckets.append(b)
        target *= 2
    buckets.append(top)
    return tuple(buckets)


class InferenceEngine:
    """Batched forward passes over mesh-pinned weights.

    Construct directly from in-memory pieces (benchmarks, tests) or via
    `from_artifact` (the serving path). `predict` takes a host batch dict —
    clip leaves shaped (B, T, H, W, C) or (B, V, T, H, W, C) with an
    optional "mask" — and returns fp32 logits (B, num_classes) for EVERY
    row, padded ones included; the batcher is responsible for never
    resolving a padded row into a response.
    """

    def __init__(self, model, params, batch_stats, mesh=None, *,
                 num_classes: int, max_batch_size: int = 8,
                 device_normalize=None, input_dtype: str = "float32",
                 model_name: str = "", stats=None,
                 quantization: str = "off", compute_dtype=None):
        from pytorchvideo_accelerate_tpu.serving.quantize import (
            QUANT_MODES,
            quant_bytes,
            quantize_tree,
            quantized_leaf_count,
        )

        if quantization not in QUANT_MODES:
            raise ValueError(
                f"serve.quantization must be one of {QUANT_MODES}, got "
                f"{quantization!r} (docs/SERVING.md § quantization)")
        self.model = model
        self.mesh = mesh if mesh is not None else make_mesh()
        self.num_classes = int(num_classes)
        self.model_name = model_name
        self.input_dtype = input_dtype
        self.stats = stats
        self._device_normalize = device_normalize
        self.quantization = quantization
        # int8 weights dequantize to THIS dtype inside the jitted forward
        # (bf16 activations under the default policy — serving/quantize.py)
        self._compute_dtype = (compute_dtype if compute_dtype is not None
                               else jnp.bfloat16)
        self.shards = data_shard_count(self.mesh)
        self.buckets = compute_buckets(max_batch_size, self.shards)
        if quantization == "int8" and not quantized_leaf_count(params):
            # on-the-fly quantization of a full-precision tree (loaded fp
            # artifact / direct construction); baked artifacts arrive
            # already quantized and pass through idempotently
            params, n = quantize_tree(params)
            logger.info("engine: quantized %d weight leaves to int8 "
                        "(%s)", n, quant_bytes(params))
        # pin the weights to the mesh once (replicated / fsdp-sharded per
        # the training rules); every forward reuses the same pinned arrays
        # — for int8 engines the PINNED tree is the int8 one (4x less HBM);
        # dequantization happens per-forward inside the compiled graph
        self.params = shard_params(self.mesh, params)
        self.batch_stats = shard_params(self.mesh, batch_stats or {})
        # MemoryLedger components: the pinned weight tree (keyed by model
        # family so ModelBudget can read *measured* footprints) and the
        # compiled-bucket executable cache. `release_memory()` is the
        # retire hook — an engine dropped without it shows up as ledger
        # drift, which is the point.
        self._mem_component = f"model_weights:{model_name or 'engine'}"
        self._compiled_component = f"engine_compiled:{model_name or 'engine'}"
        self._weight_bytes = (obs_memory.tree_nbytes(self.params)
                              + obs_memory.tree_nbytes(self.batch_stats))
        obs_memory.register(self._mem_component, self._weight_bytes)
        self._fns: Dict[tuple, Callable] = {}
        self._lock = make_lock("InferenceEngine._lock")
        # set by from_artifact: the training run's resolved TrainConfig
        # (clip geometry for warmup, provenance for /healthz debugging)
        self.artifact_config = None

    # --- construction -----------------------------------------------------

    @classmethod
    def from_artifact(cls, path: str, mesh=None, *,
                      max_batch_size: Optional[int] = None, stats=None,
                      quantization: Optional[str] = None
                      ) -> "InferenceEngine":
        """Restore an `export_inference` artifact (trainer/checkpoint.py)
        into a ready engine: rebuild the model from the artifact's resolved
        config, load the EMA-resolved params, pin them to the mesh.

        `quantization` resolution (docs/SERVING.md § quantization):
        explicit argument > the artifact's baked `meta.quantization` >
        the artifact-embedded `serve.quantization`. A baked-int8 artifact
        always serves int8 — the fp weights no longer exist — so an
        explicit "off" against one logs a warning instead of lying."""
        from pytorchvideo_accelerate_tpu.config import TrainConfig, config_from_dict
        from pytorchvideo_accelerate_tpu.models import create_model
        from pytorchvideo_accelerate_tpu.precision import policy_compute_dtype
        from pytorchvideo_accelerate_tpu.trainer.checkpoint import load_inference

        params, batch_stats, meta = load_inference(path)
        cfg = (config_from_dict(meta["config"]) if meta.get("config")
               else TrainConfig())
        num_classes = int(meta.get("num_classes") or cfg.model.num_classes)
        if not num_classes:
            raise ValueError(
                f"artifact {path} carries no num_classes (meta.json) and "
                "its config has none — cannot size the classifier head")
        cfg.model.num_classes = num_classes
        mesh = mesh if mesh is not None else make_mesh()
        model = create_model(cfg.model, cfg.mixed_precision, mesh=mesh)
        art_q = meta.get("quantization") or "off"
        eff_q = (quantization if quantization is not None
                 else (art_q if art_q != "off" else cfg.serve.quantization))
        if art_q == "int8" and eff_q == "off":
            logger.warning(
                "artifact %s is baked int8; the fp weights no longer "
                "exist — serving int8 despite quantization='off'", path)
            eff_q = "int8"
        # u8-trained runs ship raw uint8 clips and normalize in-graph
        # (data.host_cast='u8'); serving must apply the identical affine
        u8 = cfg.data.host_cast == "u8"
        engine = cls(
            model, params, batch_stats, mesh,
            num_classes=num_classes,
            max_batch_size=(max_batch_size if max_batch_size is not None
                            else cfg.serve.max_batch_size),
            device_normalize=((cfg.data.mean, cfg.data.std) if u8 else None),
            input_dtype="uint8" if u8 else "float32",
            model_name=meta.get("model") or cfg.model.name,
            stats=stats,
            quantization=eff_q,
            compute_dtype=policy_compute_dtype(cfg.mixed_precision),
        )
        engine.artifact_config = cfg
        logger.info(
            "engine: %s step %s, %d classes, ema_resolved=%s, buckets=%s "
            "over %d-shard mesh, quantization=%s",
            engine.model_name, meta.get("step"), num_classes,
            meta.get("ema_resolved"), engine.buckets, engine.shards,
            engine.quantization)
        return engine

    # --- forward ----------------------------------------------------------

    def bucket_for(self, n: int) -> int:
        """Smallest compiled bucket holding `n` rows."""
        for b in self.buckets:
            if b >= n:
                return b
        raise ValueError(
            f"batch of {n} exceeds the largest bucket {self.buckets[-1]} "
            f"(serve.max_batch_size)")

    def _make_forward(self) -> Callable:
        from pytorchvideo_accelerate_tpu.serving.quantize import (
            dequantize_tree,
        )

        mesh, norm, model = self.mesh, self._device_normalize, self.model
        quantized = self.quantization == "int8"
        compute_dtype = self._compute_dtype

        def forward(params, batch_stats, batch):
            if quantized:
                # in-graph dequant: the HBM-resident tree stays int8 and
                # XLA fuses q*scale into each weight read (bf16 compute)
                params = dequantize_tree(params, compute_dtype)
            batch = _constrain_batch(batch, mesh, leading_micro=False)
            batch = device_normalize_batch(batch, norm)
            logits = multiview_logits(
                lambda x: model.apply(
                    {"params": params, "batch_stats": batch_stats},
                    x, train=False),
                model_inputs(batch),
            )
            return logits.astype(jnp.float32)

        return forward

    def predict(self, batch: Dict[str, Any]) -> np.ndarray:
        """fp32 logits (B, num_classes) for a host batch. B must be one of
        `self.buckets` (the batcher guarantees this; direct callers pad
        themselves). Non-clip keys ("mask", "label") are ignored — masking
        is a host-side responsibility of the batcher."""
        clips = {k: np.asarray(batch[k]) for k in CLIP_KEYS if k in batch}
        if not clips:
            raise ValueError("batch has neither 'video' nor 'slow'/'fast'")
        n = next(iter(clips.values())).shape[0]
        if n not in self.buckets:
            raise ValueError(
                f"batch size {n} is not a compiled bucket {self.buckets}; "
                "pad to bucket_for(n) first")
        key = clip_key(clips)
        placed = shard_batch(self.mesh, clips)
        fn = self._fns.get(key)
        if fn is None:
            with self._lock:
                fn = self._fns.get(key)
                if fn is None:
                    if len(self._fns) >= MAX_COMPILED_KEYS:
                        raise ValueError(
                            f"engine already compiled {len(self._fns)} "
                            "distinct request geometries; refusing a new "
                            "one (clients should send the serving "
                            "geometry, see /healthz)")
                    # one executable per key: the cache maps every
                    # (bucket, views, geometry) the service has seen to
                    # its own compiled executable, and membership is the
                    # "already compiled" signal for stats/warmup. AOT
                    # (lower -> compile) so the executable's device
                    # footprint is ledgered at the allocation site; the
                    # lazy jit object is the fallback when the backend
                    # refuses AOT (its bytes then stay unattributed —
                    # visible in the residual, never fabricated).
                    fn = jax.jit(self._make_forward())
                    try:
                        compiled = fn.lower(self.params, self.batch_stats,
                                            placed).compile()
                        obs_memory.register(self._compiled_component,
                                            _executable_bytes(compiled))
                        fn = compiled
                    except Exception:
                        pass
                    self._fns[key] = fn
                    if self.stats is not None:
                        self.stats.observe_compile()
                    logger.info("engine: compiling forward for %s", key)
        return np.asarray(fn(self.params, self.batch_stats, placed))

    def warmup(self, sample_clip: Dict[str, np.ndarray]) -> None:
        """Pre-compile every bucket for one request geometry so first
        requests never pay a compile: `sample_clip` is ONE request's clip
        dict ((T,H,W,C) or (V,T,H,W,C) leaves)."""
        for b in self.buckets:
            batch = {k: np.broadcast_to(v, (b,) + tuple(np.shape(v))).copy()
                     for k, v in sample_clip.items()}
            self.predict(batch)

    @property
    def compiled_keys(self) -> tuple:
        return tuple(self._fns)

    def release_memory(self) -> None:
        """Return this engine's ledger components (pinned weights +
        compiled-bucket executables) — the retire hook for hot-swap /
        fleet teardown. An engine dropped without it leaves its bytes
        attributed, which the drift/residual gauges surface."""
        obs_memory.release(self._mem_component)
        obs_memory.release(self._compiled_component)
