"""Stdlib HTTP front + the `pva-tpu-serve` CLI.

Endpoints:
  POST /predict  — body {"video": nested-list clip} (or {"slow":…,"fast":…}
                   for SlowFast), clip shaped (T,H,W,C) or (V,T,H,W,C);
                   responds {"logits": […], "top1": k, "latency_ms": x}.
  GET  /healthz  — liveness + model identity (load balancers poll this).
  GET  /stats    — ServingStats.snapshot(): p50/p95/p99 latency, queue
                   depth, batch-fill ratio, throughput, compile count,
                   uptime, rejected split by cause (400/503/504).
  GET  /metrics  — Prometheus text exposition of the same registry the
                   /stats counters read from (obs/registry.py): request/
                   batch/rejection counters, latency histogram, queue
                   depth + uptime gauges. Point a scraper here.
  POST /drain    — controller endpoint (fleet/control/): flip admission
                   to DRAINING (healthz 503, new work sheds, in-flight
                   flushes) WITHOUT tearing the process down — the fleet
                   autoscaler re-homes sessions then reaps separately.

Deliberately stdlib (`http.server.ThreadingHTTPServer`): zero new
dependencies, and the concurrency story is honest — handler threads only
parse JSON and block on a batcher future; all accelerator work is
serialized behind a single flush thread (the continuous-batching
`fleet/scheduler.Scheduler` by default — deadlines, priority classes,
EDF launches; `--serve.scheduler micro` restores the MicroBatcher
policy). Error mapping: bad request -> 400, shed/queue full -> 503
(+ Retry-After; deadline sheds resolve the future the same way), request
budget exceeded -> 504 (+ Retry-After).

Degradation (serving/admission.py, docs/RELIABILITY.md): a
healthy/degraded/draining state machine sits in front of the batcher —
queue depth past the high-water mark sheds with `503 + Retry-After`
BEFORE latency collapses, `/healthz` reports the state (and goes 503
while draining so load balancers stop routing), and SIGTERM on the CLI
path drains: stop admitting, flush in-flight futures, exit 0.
"""

from __future__ import annotations

import json
import signal
import threading
import time
from concurrent.futures import TimeoutError as FutureTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Sequence
from urllib.parse import parse_qs, urlparse

import numpy as np

from pytorchvideo_accelerate_tpu import obs
from pytorchvideo_accelerate_tpu.obs import alerts as obs_alerts
from pytorchvideo_accelerate_tpu.obs import history as obs_history
from pytorchvideo_accelerate_tpu.obs import memory as obs_memory
from pytorchvideo_accelerate_tpu.obs import profiler as obs_profiler
from pytorchvideo_accelerate_tpu.obs import trace
from pytorchvideo_accelerate_tpu.serving.admission import (
    DRAINING,
    AdmissionController,
)
from pytorchvideo_accelerate_tpu.serving.batcher import MicroBatcher, QueueFullError
from pytorchvideo_accelerate_tpu.serving.engine import CLIP_KEYS, InferenceEngine
from pytorchvideo_accelerate_tpu.serving.stats import ServingStats
from pytorchvideo_accelerate_tpu.utils.logging import get_logger

logger = get_logger("pva_tpu")


class _Handler(BaseHTTPRequestHandler):
    server_version = "pva-tpu-serve/0.4"
    protocol_version = "HTTP/1.1"

    # route access logs to the package logger instead of stderr spam
    def log_message(self, fmt, *args):  # noqa: D102
        logger.debug("http: " + fmt, *args)

    def _reply(self, code: int, payload: dict,
               headers: Optional[Dict[str, str]] = None) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if self.close_connection:
            # say it explicitly (shed-before-body-read leaves the request
            # stream unread, so this connection cannot be reused): clients
            # must not wait on a keep-alive that will never come
            self.send_header("Connection", "close")
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _reject(self, code: int, message: str, retry_after_s: float,
                headers: Optional[Dict[str, str]] = None) -> None:
        """503/504 with Retry-After: the cheapest response the server can
        produce, and it tells a well-behaved client when to come back."""
        hdrs = {"Retry-After": str(max(int(round(retry_after_s)), 1))}
        if headers:
            hdrs.update(headers)
        self._reply(code, {"error": message, "retry_after_s": retry_after_s},
                    headers=hdrs)

    def do_GET(self):  # noqa: N802 - stdlib API
        srv: "InferenceServer" = self.server.owner
        if self.path == "/healthz":
            eng = srv.engine
            state = srv.admission.state()
            health = {
                # the state machine IS the health answer: "healthy",
                # "degraded" (shedding, still 200 — the replica works,
                # don't kill it), "draining" (503 — stop routing here)
                "status": state,
                "model": eng.model_name,
                "num_classes": eng.num_classes,
                "input_dtype": eng.input_dtype,
                "buckets": list(eng.buckets),
                "platform": srv.platform,
                "queue_depth": srv.batcher.queue_depth(),
                # streaming capability: whether /stream serves sessions
                # here (routers/load balancers may key affinity on it)
                "streaming": bool(getattr(srv.batcher,
                                          "supports_sessions", False)),
            }
            if srv.expected_spec is not None:  # per-request (T, H, W, C)
                health["clip_spec"] = {k: list(v[1:])
                                       for k, v in srv.expected_spec.items()}
            self._reply(503 if state == DRAINING else 200, health)
        elif self.path == "/stats":
            snap = srv.stats.snapshot()
            # slowest TRACED completions ride /stats (not the flat
            # snapshot dict — trackers keep their {str: float} surface):
            # a bad p99 here names trace ids to pull from the merged
            # timeline (docs/OBSERVABILITY.md § exemplar→trace)
            slowest = srv.stats.slowest_traces()
            if slowest:
                snap["slowest_traces"] = slowest
            self._reply(200, snap)
        elif self.path == "/metrics":
            body = srv.stats.registry.render().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif self.path.split("?", 1)[0] == "/history":
            # pva-tpu-hbm: the scrape-tick ring as JSON series. Optional
            # query: ?window_s=SECONDS trims the trailing window, ?keys=a,b
            # restricts to named flat scrape keys. 503 while the history
            # ring is disarmed (obs.enabled=false / history_ticks=0) — a
            # scraper must be able to tell "off" from "empty".
            hist = obs_history.get_history()
            if hist is None:
                self._reply(503, {"error": "metrics history disarmed "
                                           "(obs.history_ticks=0?)"})
                return
            try:
                q = parse_qs(urlparse(self.path).query)
                window_s = (float(q["window_s"][0])
                            if "window_s" in q else None)
                keys = (sorted({k for tok in q["keys"]
                                for k in tok.split(",") if k})
                        if "keys" in q else None)
            except (ValueError, TypeError) as e:
                self._reply(400, {"error": f"bad query: {e}"})
                return
            payload = hist.to_json(keys=keys, window_s=window_s)
            engine = obs_alerts.get_engine()
            if engine is not None:
                payload["alerts"] = engine.snapshot()["rules"]
                payload["alerts_active"] = engine.active()
            self._reply(200, payload)
        else:
            self._reply(404, {"error": f"no route {self.path}"})

    def do_POST(self):  # noqa: N802 - stdlib API
        srv: "InferenceServer" = self.server.owner
        if self.path == "/stream":
            self._do_stream(srv)
            return
        if self.path == "/drain":
            # controller-initiated drain (fleet/control/autoscaler.py):
            # flip admission to DRAINING — /healthz goes 503 so pollers
            # route around, new work sheds, in-flight futures keep
            # flushing — but do NOT tear the server down: the controller
            # re-homes the replica's live sessions first and reaps the
            # process itself once outstanding work has flushed. Reading
            # the (empty) body keeps the keep-alive connection clean.
            length = int(self.headers.get("Content-Length", 0))
            if length:
                self.rfile.read(length)
            srv.admission.start_draining()
            obs.get_recorder().record("serving", "drain-requested")
            self._reply(200, {"draining": True,
                              "status": srv.admission.state(),
                              "queue_depth": srv.batcher.queue_depth()})
            return
        if self.path.split("?", 1)[0] == "/profile":
            self._do_profile(srv)
            return
        if self.path != "/predict":
            self._reply(404, {"error": f"no route {self.path}"})
            return
        # admission control BEFORE the body is even read (serving/
        # admission.py): a shed must be the cheapest response the server
        # can produce — under real overload, json.loads of a multi-MB clip
        # per shed request would saturate the host CPU anyway. The unread
        # body forces a connection close (can't reuse the stream).
        admitted, retry_after = srv.admission.admit(
            srv.batcher.queue_depth())
        if not admitted:
            state = srv.admission.state()
            srv.stats.observe_shed(state)
            self.close_connection = True
            self._reject(503, f"load shed (service {state}); retry later",
                         retry_after)
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(length) or b"{}")
            clip = {k: np.asarray(body[k], dtype=srv.engine.input_dtype)
                    for k in CLIP_KEYS if k in body}
            if not clip:
                raise ValueError(
                    "body needs 'video' (or 'slow'+'fast') nested lists")
            srv.check_geometry(clip)
            # per-request scheduling hints (fleet/scheduler.py): forwarded
            # only to deadline-aware fronts — a plain MicroBatcher treats
            # every request the same by design, so the keys are ignored
            kwargs = {}
            if getattr(srv.batcher, "supports_priority", False):
                if "priority" in body:
                    kwargs["priority"] = str(body["priority"])
                if "deadline_ms" in body:
                    kwargs["deadline_ms"] = float(body["deadline_ms"])
        except (ValueError, TypeError, KeyError) as e:
            srv.stats.observe_rejected("400")
            self._reply(400, {"error": f"bad request: {e}"})
            return
        # distributed tracing (obs/trace.py): continue an incoming
        # `traceparent` (the head already sampled it) or start a fresh
        # head-sampled trace; the submit below captures the context into
        # the request, so the scheduler/batcher spans join this trace.
        # Sheds above stay untraced on purpose — a shed must remain the
        # cheapest response the server can produce.
        rt = trace.get_tracer()
        handle = None
        if rt is not None:
            tp = self.headers.get("traceparent")
            handle = rt.continue_trace(tp, "http_predict") if tp else None
            if handle is None:
                # absent OR malformed/unsampled header: fall back to the
                # local head-sampling decision (a corrupt header must
                # degrade to "normal sampling", never disable tracing)
                handle = rt.start("http_predict")
        tid = handle.ctx.trace_id if handle is not None else None
        # sampled responses echo the id so clients/log pipelines can join
        # their records to the server-side trace
        echo = {"x-pva-trace-id": tid} if tid else None
        with (handle if handle is not None else trace.NOOP):
            try:
                future = srv.batcher.submit(clip, **kwargs)
            except QueueFullError as e:
                # the batcher already counted this one (cause "503")
                self._reject(503, str(e), e.retry_after_s, headers=echo)
                return
            except ValueError as e:
                srv.stats.observe_rejected("400")
                self._reply(400, {"error": f"bad request: {e}"},
                            headers=echo)
                return
            t0 = time.monotonic()
            try:
                logits = future.result(timeout=srv.request_timeout_s)
            except FutureTimeout:
                if future.cancel():
                    # shed before the engine touched it: a true rejection
                    srv.stats.observe_rejected("504")
                else:
                    # lost the cancel race: the flush thread already claimed
                    # the request and will count it as completed — counting a
                    # 504 too would double-book it across the requests/
                    # rejected partition. Record the budget miss separately.
                    obs.get_recorder().warn(
                        "504 after engine claim (request completed but "
                        "client timed out)", budget_s=srv.request_timeout_s)
                # traced rejections echo the id too: a 504 is exactly the
                # tail-latency failure whose server-side trace an operator
                # needs to find
                self._reject(
                    504, f"request exceeded {srv.request_timeout_s}s budget",
                    srv.admission.retry_after_s, headers=echo)
                return
            except QueueFullError as e:
                # shed AFTER admission: the continuous-batching scheduler's
                # shed-before-deadline-miss (fleet/scheduler.ShedError) or a
                # fleet router with no routable capacity resolves the FUTURE
                # with the shed — same 503 + Retry-After contract as a
                # submit-time shed, never a 500 and never a burned 504 budget
                self._reject(503, str(e), e.retry_after_s, headers=echo)
                return
            except Exception as e:  # noqa: BLE001 - batch failure surfaced per-request
                srv.stats.observe_error()
                self._reply(500, {"error": f"inference failed: {e}"},
                            headers=echo)
                return
            self._reply(200, {
                "logits": np.asarray(logits, np.float32).tolist(),
                "top1": int(np.argmax(logits)),
                "latency_ms": round((time.monotonic() - t0) * 1e3, 3),
            }, headers=echo)

    def _do_profile(self, srv: "InferenceServer") -> None:
        """POST /profile?seconds=S — pva-tpu-hbm on-demand capture: start a
        jax.profiler trace window on the LIVE server, stopped by a
        background timer and published atomically as
        <output_dir>/profile_<tag>/ (obs/profiler.py). 202 with the
        pending tag when the capture starts, 409 while one is already in
        flight (one capture at a time — traces are expensive), 503 when
        the profiler is disarmed."""
        length = int(self.headers.get("Content-Length", 0))
        if length:  # keep the keep-alive stream clean
            self.rfile.read(length)
        prof = obs_profiler.get_profiler()
        if prof is None:
            self._reply(503, {"error": "profiler disarmed (obs.enabled "
                                       "off or no output_dir)"})
            return
        try:
            q = parse_qs(urlparse(self.path).query)
            seconds = float(q.get("seconds", ["3"])[0])
            if not 0 < seconds <= 120:
                raise ValueError("seconds must be in (0, 120]")
        except (ValueError, TypeError) as e:
            self._reply(400, {"error": f"bad query: {e}"})
            return
        tag = prof.capture_for(seconds)
        if tag is None:
            self._reply(409, {"error": "a profile capture is already "
                                       "running", "busy": True})
            return
        obs.get_recorder().record("profile", "capture-requested",
                                  seconds=seconds, tag=tag)
        self._reply(202, {"capturing": True, "seconds": seconds,
                          "tag": tag})

    def _do_stream(self, srv: "InferenceServer") -> None:
        """POST /stream — one incremental session advance (docs/SERVING.md
        § streaming). Body: ``{"session": id, "frames": [s new frames],
        "window": optional resendable (T,H,W,C), "stride": int,
        "end": bool, "priority"/"deadline_ms": as /predict}``. Responds
        with the logits over the session's rolling window. Error map:
        admission/budget shed -> 503 + Retry-After (like /predict),
        malformed -> 400, session unknown with no window -> 409 (resend
        the window), budget miss -> 504."""
        from pytorchvideo_accelerate_tpu.streaming.session import (
            SessionUnknownError,
        )

        # same shed-before-body-read admission as /predict: a shed must
        # stay the cheapest response under overload
        admitted, retry_after = srv.admission.admit(
            srv.batcher.queue_depth())
        if not admitted:
            state = srv.admission.state()
            srv.stats.observe_shed(state)
            self.close_connection = True
            self._reject(503, f"load shed (service {state}); retry later",
                         retry_after)
            return
        if not getattr(srv.batcher, "supports_sessions", False):
            srv.stats.observe_rejected("400")
            self._reply(400, {"error": "this replica serves no streaming "
                                       "sessions (serve.streaming off)"})
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(length) or b"{}")
            sid = str(body.get("session") or "")
            if not sid:
                raise ValueError("body needs a 'session' id")
            clip = {}
            if body.get("frames") is not None:
                clip["video"] = np.asarray(body["frames"],
                                           dtype=srv.engine.input_dtype)
            session = {"sid": sid, "end": bool(body.get("end"))}
            if body.get("window") is not None:
                session["window"] = np.asarray(
                    body["window"], dtype=srv.engine.input_dtype)
            if body.get("stride") is not None:
                session["stride"] = int(body["stride"])
            kwargs: dict = {"session": session}
            if "priority" in body:
                kwargs["priority"] = str(body["priority"])
            if "deadline_ms" in body:
                kwargs["deadline_ms"] = float(body["deadline_ms"])
        except (ValueError, TypeError, KeyError) as e:
            srv.stats.observe_rejected("400")
            self._reply(400, {"error": f"bad request: {e}"})
            return
        rt = trace.get_tracer()
        handle = None
        if rt is not None:
            tp = self.headers.get("traceparent")
            handle = rt.continue_trace(tp, "http_stream") if tp else None
            if handle is None:
                handle = rt.start("http_stream")
        tid = handle.ctx.trace_id if handle is not None else None
        echo = {"x-pva-trace-id": tid} if tid else None
        with (handle if handle is not None else trace.NOOP):
            try:
                future = srv.batcher.submit(clip, **kwargs)
            except QueueFullError as e:
                self._reject(503, str(e), e.retry_after_s, headers=echo)
                return
            except ValueError as e:
                srv.stats.observe_rejected("400")
                self._reply(400, {"error": f"bad request: {e}"},
                            headers=echo)
                return
            t0 = time.monotonic()
            try:
                logits = future.result(timeout=srv.request_timeout_s)
            except FutureTimeout:
                if future.cancel():
                    srv.stats.observe_rejected("504")
                else:
                    obs.get_recorder().warn(
                        "504 after engine claim (stream advance completed "
                        "but client timed out)",
                        budget_s=srv.request_timeout_s)
                self._reject(
                    504, f"request exceeded {srv.request_timeout_s}s budget",
                    srv.admission.retry_after_s, headers=echo)
                return
            except QueueFullError as e:
                self._reject(503, str(e), e.retry_after_s, headers=echo)
                return
            except SessionUnknownError as e:
                # not a bad request: the client holds the stream and can
                # re-establish — 409 tells it to resend its window
                self._reply(409, {"error": str(e)}, headers=echo)
                return
            except ValueError as e:
                srv.stats.observe_rejected("400")
                self._reply(400, {"error": f"bad request: {e}"},
                            headers=echo)
                return
            except Exception as e:  # noqa: BLE001 - per-request failure
                srv.stats.observe_error()
                self._reply(500, {"error": f"inference failed: {e}"},
                            headers=echo)
                return
            self._reply(200, {
                "logits": np.asarray(logits, np.float32).tolist(),
                "top1": int(np.argmax(logits)),
                "session": sid,
                "latency_ms": round((time.monotonic() - t0) * 1e3, 3),
            }, headers=echo)


class InferenceServer:
    """ThreadingHTTPServer wrapper owning engine + batcher + stats."""

    def __init__(self, engine: InferenceEngine, batcher: MicroBatcher,
                 stats: ServingStats, host: str = "127.0.0.1", port: int = 0,
                 request_timeout_s: float = 30.0,
                 expected_spec: Optional[dict] = None,
                 watchdog=None, admission: Optional[AdmissionController] = None,
                 drain_grace_s: float = 10.0):
        import jax

        self.engine = engine
        self.batcher = batcher
        self.stats = stats
        self.watchdog = watchdog  # obs.Watchdog over the flush thread
        self.request_timeout_s = request_timeout_s
        self.drain_grace_s = drain_grace_s
        if admission is None:  # direct construction (tests, embedding)
            q = getattr(batcher, "_q", None)
            admission = AdmissionController(
                max_queue=getattr(q, "maxsize", 0) or 256)
        if admission.queue_depth_fn is None:
            # idle degraded->healthy recovery on /healthz reads
            admission.queue_depth_fn = batcher.queue_depth
        self.admission = admission
        # clip-name -> (1, T, H, W, C) from the artifact's config (None =
        # accept any geometry; direct/bench construction)
        self.expected_spec = expected_spec
        self.platform = jax.devices()[0].platform
        self.httpd = ThreadingHTTPServer((host, port), _Handler)
        self.httpd.daemon_threads = True
        self.httpd.owner = self  # handler back-reference
        self._thread = None
        # pva-tpu-hbm: the burn-rate alert engine needs a control cadence;
        # a ticker thread starts with the server when one is armed (each
        # tick also appends a scrape to the /history ring). 0 disables.
        self.alert_tick_s = 1.0
        self._tick_stop: Optional[threading.Event] = None
        self._tick_thread = None

    def _start_alert_ticker(self) -> None:
        engine = obs_alerts.get_engine()
        if engine is None or self.alert_tick_s <= 0 \
                or self._tick_thread is not None:
            return
        from pytorchvideo_accelerate_tpu.utils.sync import make_thread

        stop = threading.Event()

        def _loop():
            while not stop.wait(self.alert_tick_s):
                try:
                    engine.tick()
                except Exception:  # noqa: BLE001 - ticker must survive
                    logger.exception("alert tick failed")

        self._tick_stop = stop
        self._tick_thread = make_thread(target=_loop,
                                        name="pva-serve-alerts",
                                        daemon=True)
        self._tick_thread.start()

    @property
    def address(self) -> tuple:
        """Actual (host, port) bound — port 0 resolves here."""
        return self.httpd.server_address[:2]

    def check_geometry(self, clip: dict) -> None:
        """400-guard: requests must carry the serving geometry. Every new
        shape the engine sees costs a synchronous compile on the batch
        thread (and a cached executable forever), so when the artifact
        declared its clip spec, off-spec requests are rejected up front —
        only the view count (leading axis of a rank-5 clip) is free."""
        if self.expected_spec is None:
            return
        if sorted(clip) != sorted(self.expected_spec):
            raise ValueError(
                f"request clips {sorted(clip)} != served model's "
                f"{sorted(self.expected_spec)}")
        for k, v in clip.items():
            want = tuple(self.expected_spec[k][1:])  # (T, H, W, C)
            got = tuple(v.shape[-4:]) if v.ndim == 5 else tuple(v.shape)
            if got != want:
                raise ValueError(
                    f"clip {k!r} geometry {tuple(v.shape)} does not match "
                    f"the served model's (T,H,W,C)={want} "
                    "(an optional leading view axis is allowed)")

    def start(self) -> "InferenceServer":
        """Serve on a background thread (tests / embedding)."""
        from pytorchvideo_accelerate_tpu.utils.sync import make_thread

        self._thread = make_thread(
            target=self.httpd.serve_forever, name="pva-serve-http",
            daemon=True)
        self._thread.start()
        self._start_alert_ticker()
        return self

    def drain(self, grace_s: Optional[float] = None) -> None:
        """Graceful shutdown: stop admitting (every /predict sheds with
        503 + Retry-After, /healthz goes 503 so LBs stop routing), flush
        the in-flight futures within the grace budget, then close."""
        self.admission.start_draining()
        drained = self.batcher.drain(
            self.drain_grace_s if grace_s is None else grace_s)
        if not drained:
            logger.warning("drain: queue not empty at grace deadline; "
                           "remaining requests will be failed by close()")
        self.close()

    def _install_drain_handler(self) -> None:
        """SIGTERM -> drain (CLI path only). This REPLACES the recorder's
        dump-only SIGTERM hook, so the PR 3 evidence is written here
        explicitly: record the signal, dump the flight ring, then drain."""

        def on_term(signum, frame):
            logger.info("SIGTERM: draining (stop admitting, flush "
                        "in-flight, exit 0)")
            obs.get_recorder().record("signal", "SIGTERM-drain")
            obs.get_recorder().dump()  # flight_record.json still lands
            trace.dump()  # the trace ring too (no-op when disarmed)
            # httpd.shutdown() must run off the serve_forever thread
            from pytorchvideo_accelerate_tpu.utils.sync import make_thread

            make_thread(target=self.drain, name="pva-serve-drain",
                        daemon=True).start()

        try:
            signal.signal(signal.SIGTERM, on_term)
        except (ValueError, OSError):  # not the main thread: no drain hook
            pass

    def serve_forever(self, drain_on_sigterm: bool = True) -> None:
        """Serve on the calling thread (the CLI path)."""
        if drain_on_sigterm and self.drain_grace_s > 0:
            self._install_drain_handler()
        self._start_alert_ticker()
        try:
            self.httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            self.close()

    def close(self) -> None:
        # idempotent: the drain path closes, then serve_forever's finally
        # closes again on its way out
        if getattr(self, "_closed", False):
            return
        self._closed = True
        if self._tick_stop is not None:
            self._tick_stop.set()
            if self._tick_thread is not None:
                self._tick_thread.join(timeout=5.0)
            self._tick_thread = self._tick_stop = None
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        self.batcher.close()
        if self.watchdog is not None:
            self.watchdog.stop()
            self.watchdog = None


def build_server(cfg) -> InferenceServer:
    """serve.* config block -> a ready (not yet started) InferenceServer."""
    import jax

    from pytorchvideo_accelerate_tpu import obs

    s = cfg.serve
    if not s.checkpoint:
        raise SystemExit(
            "serving needs --serve.checkpoint pointing at an "
            "export_inference artifact (see docs/SERVING.md)")
    if cfg.cpu:
        jax.config.update("jax_platforms", "cpu")
    # telemetry spine: same config block as training (obs.*); the watchdog
    # covers the single flush thread — a wedged compile or stuck H2D there
    # stalls EVERY request, and without a heartbeat it stalls silently
    obs.configure(enabled=cfg.obs.enabled,
                  capacity=cfg.obs.flight_recorder_events)
    if cfg.obs.enabled and cfg.obs.trace_sample_rate > 0:
        # distributed tracing: head-sample this fraction of /predict
        # requests (incoming traceparent headers are always continued);
        # the ring dumps to <output_dir>/trace_ring.json on SIGTERM-drain
        trace.configure_tracing(cfg.obs.trace_sample_rate, seed=cfg.seed,
                                capacity=cfg.obs.trace_ring_events,
                                output_dir=cfg.checkpoint.output_dir)
    watchdog = None
    if cfg.obs.enabled:
        # flight-record destination + SIGTERM/excepthook dump hooks for the
        # serving process too (checkpoint.output_dir defaults to "."): a
        # killed or wedged server leaves the same evidence file a training
        # run does (pva-tpu-doctor --obs-dir reads it)
        obs.get_recorder().install(cfg.checkpoint.output_dir)
        if cfg.obs.watchdog_timeout_s > 0:
            watchdog = obs.Watchdog(
                cfg.obs.watchdog_timeout_s,
                output_dir=cfg.checkpoint.output_dir,
                recorder=obs.get_recorder(),
                collector=obs.get_collector()).start()
    # pva-tpu-hbm: on-demand profiler capture for the serving process
    # (POST /profile); ledger + history/alerts arm just after stats is
    # built below — their gauges belong in the ServingStats registry so
    # /metrics and /history carry them (it is per-instance by design).
    if cfg.obs.enabled:
        obs_profiler.configure(output_dir=cfg.checkpoint.output_dir,
                               recorder=obs.get_recorder())
    latency_buckets = None
    if s.latency_buckets_ms:
        try:
            latency_buckets = sorted(
                float(b) / 1e3 for b in s.latency_buckets_ms.split(",") if b)
        except ValueError:
            raise SystemExit(
                f"--serve.latency_buckets_ms {s.latency_buckets_ms!r}: "
                "expected comma-separated millisecond bounds, e.g. "
                "'5,10,25,50,100,250,1000'")
    stats = ServingStats(window=s.stats_window,
                         latency_buckets=latency_buckets)
    if cfg.obs.enabled and cfg.obs.memory_ledger:
        # the engines' weight pins / compiled caches / session rings
        # register through the module hooks into this singleton; its
        # pva_hbm_* gauges ride the serving /metrics + /history
        obs_memory.configure(recorder=obs.get_recorder(),
                             registry=stats.registry)
    if cfg.obs.enabled and cfg.obs.history_ticks > 0:
        # burn-rate SLO rules over the serving series (obs/alerts.py
        # default_rules); the server's ticker thread drives the cadence
        hist = obs_history.configure(capacity=cfg.obs.history_ticks,
                                     registry=stats.registry)
        obs_alerts.configure(history=hist, registry=stats.registry,
                             recorder=obs.get_recorder())
    engine = InferenceEngine.from_artifact(
        s.checkpoint, max_batch_size=s.max_batch_size, stats=stats,
        quantization=s.quantization if s.quantization != "off" else None)
    spec = None
    if engine.artifact_config is not None:
        # pre-compile every bucket for the training run's clip geometry so
        # the first requests never pay a compile (multi-view variants of
        # the same geometry still compile on first arrival); the same spec
        # then 400-guards /predict against off-geometry requests
        from pytorchvideo_accelerate_tpu.models import model_input_spec

        spec = model_input_spec(engine.artifact_config.model,
                                engine.artifact_config.data)
        sample = {k: np.zeros(shape[1:], engine.input_dtype)
                  for k, shape in spec.items()}
        logger.info("warmup: compiling buckets %s for %s",
                    engine.buckets, {k: v.shape for k, v in sample.items()})
        engine.warmup(sample)
    front_engine = engine
    if s.streaming:
        # stateful streaming mode (streaming/engine.py): /stream advances
        # run incrementally against device-resident session rings; the
        # scheduler batches them across sessions. /predict still serves
        # stateless one-shot requests through the same wrapped engine.
        from pytorchvideo_accelerate_tpu.streaming import StreamingEngine

        front_engine = StreamingEngine(
            engine, session_budget_mb=s.stream_session_budget_mb,
            session_ttl_s=s.stream_session_ttl_s,
            retry_after_s=s.retry_after_s,
            trunk=s.stream_trunk)
        if s.scheduler != "edf":
            raise SystemExit(
                "--serve.streaming needs the continuous-batching "
                "scheduler (--serve.scheduler edf); the MicroBatcher has "
                "no session launch path")
        if spec is not None and "video" in spec:
            # pre-compile establish+advance at every (stride, bucket) for
            # the served geometry: a first advance compiling on the flush
            # thread would stall the launch AND poison the service-time
            # EWMA into transient deadline sheds (serve.stream_strides)
            _, t, h, w, c = spec["video"]
            for tok in s.stream_strides.split(","):
                if not tok.strip():
                    continue
                try:
                    n = front_engine.warmup_stream(t, h, w, c,
                                                   int(tok))
                    logger.info("stream warmup: stride %s -> %d "
                                "compiled steps", tok.strip(), n)
                except Exception as e:  # noqa: BLE001 - invalid stride for this model
                    logger.warning("stream warmup skipped stride %s: %s",
                                   tok.strip(), e)
    heartbeat = watchdog.beat_fn("serve_batcher") if watchdog else None
    if s.scheduler == "edf":
        # the continuous-batching scheduler (fleet/scheduler.py) is the
        # default hot path: deadlines + priority classes + EDF launches +
        # shed-before-deadline-miss; serve.max_wait_ms becomes the
        # batch-class coalescing dial (realtime is work-conserving)
        from pytorchvideo_accelerate_tpu.fleet.scheduler import Scheduler

        batcher = Scheduler(
            front_engine, max_queue=s.max_queue, stats=stats,
            realtime_deadline_ms=s.realtime_deadline_ms,
            batch_deadline_ms=s.batch_deadline_ms,
            batch_max_wait_ms=s.max_wait_ms,
            retry_after_s=s.retry_after_s, heartbeat=heartbeat)
    elif s.scheduler == "micro":
        batcher = MicroBatcher(
            engine, max_wait_ms=s.max_wait_ms, max_queue=s.max_queue,
            stats=stats, retry_after_s=s.retry_after_s,
            heartbeat=heartbeat)
    else:
        raise SystemExit(
            f"unknown --serve.scheduler {s.scheduler!r} (edf | micro)")
    stats.queue_depth_fn = batcher.queue_depth
    admission = AdmissionController(
        max_queue=s.max_queue, shed_frac=s.shed_queue_frac,
        recover_frac=s.recover_queue_frac, retry_after_s=s.retry_after_s,
        on_state_change=lambda old, new: (
            logger.warning("serving state %s -> %s", old, new),
            obs.get_recorder().record("serving", "state-change",
                                      old=old, new=new)))
    return InferenceServer(engine, batcher, stats, host=s.host, port=s.port,
                           request_timeout_s=s.request_timeout_s,
                           expected_spec=spec, watchdog=watchdog,
                           admission=admission,
                           drain_grace_s=s.drain_grace_s)


def main(argv: Optional[Sequence[str]] = None) -> None:
    """`pva-tpu-serve --serve.checkpoint PATH [--serve.port N ...]`."""
    from pytorchvideo_accelerate_tpu.config import parse_cli

    cfg = parse_cli(argv)
    server = build_server(cfg)
    host, port = server.address
    logger.info("serving %s on http://%s:%d (/predict /healthz /stats)",
                server.engine.model_name, host, port)
    print(f"pva-tpu-serve: http://{host}:{port}  model="
          f"{server.engine.model_name} buckets={server.engine.buckets}",
          flush=True)
    server.serve_forever()


if __name__ == "__main__":
    main()
