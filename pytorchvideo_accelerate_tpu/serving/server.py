"""Stdlib HTTP front + the `pva-tpu-serve` CLI.

Endpoints:
  POST /predict  — body {"video": nested-list clip} (or {"slow":…,"fast":…}
                   for SlowFast), clip shaped (T,H,W,C) or (V,T,H,W,C);
                   responds {"logits": […], "top1": k, "latency_ms": x}.
  GET  /healthz  — liveness + model identity (load balancers poll this).
  GET  /stats    — ServingStats.snapshot(): p50/p95/p99 latency, queue
                   depth, batch-fill ratio, throughput, compile count,
                   uptime, rejected split by cause (400/503/504).
  GET  /metrics  — Prometheus text exposition of the same registry the
                   /stats counters read from (obs/registry.py): request/
                   batch/rejection counters, latency histogram, queue
                   depth + uptime gauges. Point a scraper here.

Deliberately stdlib (`http.server.ThreadingHTTPServer`): zero new
dependencies, and the concurrency story is honest — handler threads only
parse JSON and block on a batcher future; all accelerator work is
serialized behind the MicroBatcher's single flush thread. Error mapping:
bad request -> 400, queue full -> 503, request budget exceeded -> 504.
"""

from __future__ import annotations

import json
import time
from concurrent.futures import TimeoutError as FutureTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Sequence

import numpy as np

from pytorchvideo_accelerate_tpu import obs
from pytorchvideo_accelerate_tpu.serving.batcher import MicroBatcher, QueueFullError
from pytorchvideo_accelerate_tpu.serving.engine import CLIP_KEYS, InferenceEngine
from pytorchvideo_accelerate_tpu.serving.stats import ServingStats
from pytorchvideo_accelerate_tpu.utils.logging import get_logger

logger = get_logger("pva_tpu")


class _Handler(BaseHTTPRequestHandler):
    server_version = "pva-tpu-serve/0.4"
    protocol_version = "HTTP/1.1"

    # route access logs to the package logger instead of stderr spam
    def log_message(self, fmt, *args):  # noqa: D102
        logger.debug("http: " + fmt, *args)

    def _reply(self, code: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 - stdlib API
        srv: "InferenceServer" = self.server.owner
        if self.path == "/healthz":
            eng = srv.engine
            health = {
                "status": "ok",
                "model": eng.model_name,
                "num_classes": eng.num_classes,
                "input_dtype": eng.input_dtype,
                "buckets": list(eng.buckets),
                "platform": srv.platform,
            }
            if srv.expected_spec is not None:  # per-request (T, H, W, C)
                health["clip_spec"] = {k: list(v[1:])
                                       for k, v in srv.expected_spec.items()}
            self._reply(200, health)
        elif self.path == "/stats":
            self._reply(200, srv.stats.snapshot())
        elif self.path == "/metrics":
            body = srv.stats.registry.render().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self._reply(404, {"error": f"no route {self.path}"})

    def do_POST(self):  # noqa: N802 - stdlib API
        srv: "InferenceServer" = self.server.owner
        if self.path != "/predict":
            self._reply(404, {"error": f"no route {self.path}"})
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(length) or b"{}")
            clip = {k: np.asarray(body[k], dtype=srv.engine.input_dtype)
                    for k in CLIP_KEYS if k in body}
            if not clip:
                raise ValueError(
                    "body needs 'video' (or 'slow'+'fast') nested lists")
            srv.check_geometry(clip)
        except (ValueError, TypeError, KeyError) as e:
            srv.stats.observe_rejected("400")
            self._reply(400, {"error": f"bad request: {e}"})
            return
        try:
            future = srv.batcher.submit(clip)
        except QueueFullError as e:
            # the batcher already counted this one (cause "503")
            self._reply(503, {"error": str(e)})
            return
        except ValueError as e:
            srv.stats.observe_rejected("400")
            self._reply(400, {"error": f"bad request: {e}"})
            return
        t0 = time.monotonic()
        try:
            logits = future.result(timeout=srv.request_timeout_s)
        except FutureTimeout:
            if future.cancel():
                # shed before the engine touched it: a true rejection
                srv.stats.observe_rejected("504")
            else:
                # lost the cancel race: the flush thread already claimed
                # the request and will count it as completed — counting a
                # 504 too would double-book it across the requests/
                # rejected partition. Record the budget miss separately.
                obs.get_recorder().warn(
                    "504 after engine claim (request completed but client "
                    "timed out)", budget_s=srv.request_timeout_s)
            self._reply(504, {
                "error": f"request exceeded {srv.request_timeout_s}s budget"})
            return
        except Exception as e:  # noqa: BLE001 - batch failure surfaced per-request
            srv.stats.observe_error()
            self._reply(500, {"error": f"inference failed: {e}"})
            return
        self._reply(200, {
            "logits": np.asarray(logits, np.float32).tolist(),
            "top1": int(np.argmax(logits)),
            "latency_ms": round((time.monotonic() - t0) * 1e3, 3),
        })


class InferenceServer:
    """ThreadingHTTPServer wrapper owning engine + batcher + stats."""

    def __init__(self, engine: InferenceEngine, batcher: MicroBatcher,
                 stats: ServingStats, host: str = "127.0.0.1", port: int = 0,
                 request_timeout_s: float = 30.0,
                 expected_spec: Optional[dict] = None,
                 watchdog=None):
        import jax

        self.engine = engine
        self.batcher = batcher
        self.stats = stats
        self.watchdog = watchdog  # obs.Watchdog over the flush thread
        self.request_timeout_s = request_timeout_s
        # clip-name -> (1, T, H, W, C) from the artifact's config (None =
        # accept any geometry; direct/bench construction)
        self.expected_spec = expected_spec
        self.platform = jax.devices()[0].platform
        self.httpd = ThreadingHTTPServer((host, port), _Handler)
        self.httpd.daemon_threads = True
        self.httpd.owner = self  # handler back-reference
        self._thread = None

    @property
    def address(self) -> tuple:
        """Actual (host, port) bound — port 0 resolves here."""
        return self.httpd.server_address[:2]

    def check_geometry(self, clip: dict) -> None:
        """400-guard: requests must carry the serving geometry. Every new
        shape the engine sees costs a synchronous compile on the batch
        thread (and a cached executable forever), so when the artifact
        declared its clip spec, off-spec requests are rejected up front —
        only the view count (leading axis of a rank-5 clip) is free."""
        if self.expected_spec is None:
            return
        if sorted(clip) != sorted(self.expected_spec):
            raise ValueError(
                f"request clips {sorted(clip)} != served model's "
                f"{sorted(self.expected_spec)}")
        for k, v in clip.items():
            want = tuple(self.expected_spec[k][1:])  # (T, H, W, C)
            got = tuple(v.shape[-4:]) if v.ndim == 5 else tuple(v.shape)
            if got != want:
                raise ValueError(
                    f"clip {k!r} geometry {tuple(v.shape)} does not match "
                    f"the served model's (T,H,W,C)={want} "
                    "(an optional leading view axis is allowed)")

    def start(self) -> "InferenceServer":
        """Serve on a background thread (tests / embedding)."""
        from pytorchvideo_accelerate_tpu.utils.sync import make_thread

        self._thread = make_thread(
            target=self.httpd.serve_forever, name="pva-serve-http",
            daemon=True)
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        """Serve on the calling thread (the CLI path)."""
        try:
            self.httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            self.close()

    def close(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        self.batcher.close()
        if self.watchdog is not None:
            self.watchdog.stop()
            self.watchdog = None


def build_server(cfg) -> InferenceServer:
    """serve.* config block -> a ready (not yet started) InferenceServer."""
    import jax

    from pytorchvideo_accelerate_tpu import obs

    s = cfg.serve
    if not s.checkpoint:
        raise SystemExit(
            "serving needs --serve.checkpoint pointing at an "
            "export_inference artifact (see docs/SERVING.md)")
    if cfg.cpu:
        jax.config.update("jax_platforms", "cpu")
    # telemetry spine: same config block as training (obs.*); the watchdog
    # covers the single flush thread — a wedged compile or stuck H2D there
    # stalls EVERY request, and without a heartbeat it stalls silently
    obs.configure(enabled=cfg.obs.enabled,
                  capacity=cfg.obs.flight_recorder_events)
    watchdog = None
    if cfg.obs.enabled:
        # flight-record destination + SIGTERM/excepthook dump hooks for the
        # serving process too (checkpoint.output_dir defaults to "."): a
        # killed or wedged server leaves the same evidence file a training
        # run does (pva-tpu-doctor --obs-dir reads it)
        obs.get_recorder().install(cfg.checkpoint.output_dir)
        if cfg.obs.watchdog_timeout_s > 0:
            watchdog = obs.Watchdog(
                cfg.obs.watchdog_timeout_s,
                output_dir=cfg.checkpoint.output_dir,
                recorder=obs.get_recorder(),
                collector=obs.get_collector()).start()
    stats = ServingStats(window=s.stats_window)
    engine = InferenceEngine.from_artifact(
        s.checkpoint, max_batch_size=s.max_batch_size, stats=stats)
    spec = None
    if engine.artifact_config is not None:
        # pre-compile every bucket for the training run's clip geometry so
        # the first requests never pay a compile (multi-view variants of
        # the same geometry still compile on first arrival); the same spec
        # then 400-guards /predict against off-geometry requests
        from pytorchvideo_accelerate_tpu.models import model_input_spec

        spec = model_input_spec(engine.artifact_config.model,
                                engine.artifact_config.data)
        sample = {k: np.zeros(shape[1:], engine.input_dtype)
                  for k, shape in spec.items()}
        logger.info("warmup: compiling buckets %s for %s",
                    engine.buckets, {k: v.shape for k, v in sample.items()})
        engine.warmup(sample)
    batcher = MicroBatcher(
        engine, max_wait_ms=s.max_wait_ms, max_queue=s.max_queue,
        stats=stats,
        heartbeat=(watchdog.beat_fn("serve_batcher") if watchdog else None))
    stats.queue_depth_fn = batcher.queue_depth
    return InferenceServer(engine, batcher, stats, host=s.host, port=s.port,
                           request_timeout_s=s.request_timeout_s,
                           expected_spec=spec, watchdog=watchdog)


def main(argv: Optional[Sequence[str]] = None) -> None:
    """`pva-tpu-serve --serve.checkpoint PATH [--serve.port N ...]`."""
    from pytorchvideo_accelerate_tpu.config import parse_cli

    cfg = parse_cli(argv)
    server = build_server(cfg)
    host, port = server.address
    logger.info("serving %s on http://%s:%d (/predict /healthz /stats)",
                server.engine.model_name, host, port)
    print(f"pva-tpu-serve: http://{host}:{port}  model="
          f"{server.engine.model_name} buckets={server.engine.buckets}",
          flush=True)
    server.serve_forever()


if __name__ == "__main__":
    main()
