"""Ulysses-style sequence parallelism: all-to-all head/sequence reshard.

The reference backbone ships this only as a DeepSpeed-Ulysses dataloader
adapter (accelerate accelerator.py:2486-2505) plus an external-deps test
(test_ds_alst_ulysses_sp.py) — never exercised by run.py. Here it is a real
attention backend, the complement of ring attention (SURVEY §5):

- ring: K/V blocks rotate hop-by-hop over ICI; comm volume ~ N·D per step,
  overlappable; works for any head count.
- ulysses: two `lax.all_to_all`s swap the sharded axis from tokens to heads,
  so each device runs *dense* attention for H/cp heads over the full
  sequence; comm is a single balanced all-to-all each way (great on a
  fully-connected ICI twisted torus), but requires H % cp == 0 and peak
  activation memory holds the full sequence for its head slice. When the
  head count doesn't divide the axis (MViT's 1-2-head early stages) it
  degrades to ring attention instead of failing.

Layouts (cp = context-axis size):
  local in : (B, N/cp, H,    D)   tokens sharded
  after a2a: (B, N,    H/cp, D)   heads sharded  -> dense attention
  after a2a: (B, N/cp, H,    D)   tokens sharded again
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh

from pytorchvideo_accelerate_tpu.ops.attention import fused_attention
from pytorchvideo_accelerate_tpu.parallel.collectives import axis_size
from pytorchvideo_accelerate_tpu.parallel.mesh import AXIS_CONTEXT, mesh_memo


def ulysses_attention(q, k, v, axis_name: str = AXIS_CONTEXT,
                      scale: Optional[float] = None,
                      nk_valid: Optional[int] = None):
    """All-to-all attention. Must run inside `shard_map` with `axis_name`
    bound; q/k/v are local token shards (B, N/cp, H, D). `nk_valid`: global
    count of real (unpadded) keys. Falls back to ring when H % cp != 0."""
    cp = axis_size(axis_name)
    H = q.shape[2]
    if H % cp != 0:
        from pytorchvideo_accelerate_tpu.parallel.ring_attention import ring_attention

        return ring_attention(q, k, v, axis_name=axis_name, scale=scale,
                              nk_valid=nk_valid)

    def to_heads(x):   # (B, N/cp, H, D) -> (B, N, H/cp, D)
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def to_tokens(x):  # (B, N, H/cp, D) -> (B, N/cp, H, D)
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    kg = to_heads(k)
    kmask = None
    if nk_valid is not None and nk_valid < kg.shape[1]:
        kmask = jnp.arange(kg.shape[1]) < nk_valid
    # fused (flash-chunked) local attention: peak memory O(N), not O(N^2) —
    # the whole point at the sequence lengths that motivate Ulysses
    out = fused_attention(to_heads(q), kg, to_heads(v), scale=scale, kmask=kmask)
    return to_tokens(out)


def make_ulysses_attention(mesh: Mesh, axis_name: Optional[str] = None):
    """Drop-in ulysses `attn(q, k, v)` for auto-sharded models under `jit` —
    same contract as `make_ring_attention` (token axis sharded over the
    mesh's CP axis, ragged lengths padded + masked, memoized on the
    mesh-identity store); see `make_cp_attention`."""
    from pytorchvideo_accelerate_tpu.parallel.ring_attention import make_cp_attention

    memo = mesh_memo(mesh, "ulysses_attention")
    attn = memo.get(axis_name)
    if attn is None:
        attn = memo[axis_name] = make_cp_attention(mesh, ulysses_attention,
                                                   axis_name)
    return attn
