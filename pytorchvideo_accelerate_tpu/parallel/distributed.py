"""Multi-host control plane.

Replaces the reference launch path (SURVEY §3.1): `accelerate launch` ->
torchelastic TCPStore rendezvous -> N processes with RANK/WORLD_SIZE env vars
-> `init_process_group("nccl")`. On TPU pods the runtime is one process per
host; `jax.distributed.initialize` wires the DCN control plane (coordinator
service), and device-level collectives need no further setup — they are
compiled into the step by XLA.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

from pytorchvideo_accelerate_tpu.utils.logging import get_logger

logger = get_logger("pva_tpu")

_INITIALIZED = False


def initialize_distributed(
    coordinator_address: str = "",
    num_processes: int = 0,
    process_id: int = -1,
) -> None:
    """Initialize the multi-host control plane if configured.

    Resolution order (mirrors accelerate's env-driven `_prepare_backend`,
    state.py:755-798, but for the JAX world):
      1. explicit args (from TrainConfig),
      2. env vars `PVA_COORDINATOR_ADDRESS` / `PVA_NUM_PROCESSES` /
         `PVA_PROCESS_ID` (the launch-env contract of `launch.py`),
      3. TPU-pod auto-detection: on Cloud TPU pods,
         `jax.distributed.initialize()` with no args self-configures from the
         metadata server; we only call it when a pod env is detectable.
      4. otherwise: single-process, no-op.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return

    coordinator_address = coordinator_address or os.environ.get("PVA_COORDINATOR_ADDRESS", "")
    num_processes = num_processes or int(os.environ.get("PVA_NUM_PROCESSES", "0"))
    env_pid = os.environ.get("PVA_PROCESS_ID", "")
    if process_id < 0 and env_pid:
        process_id = int(env_pid)

    if coordinator_address and num_processes > 1:
        if not 0 <= process_id < num_processes:
            raise ValueError(
                f"process_id must be in [0, {num_processes}) when a coordinator "
                f"is configured; got {process_id}. Set PVA_PROCESS_ID or "
                f"--process_id on every host."
            )
        # cross-process collectives on the CPU backend need gloo (the same
        # transport accelerate's 2-process CPU tests use, SURVEY §4.1);
        # harmless no-op on TPU where ICI/DCN collectives come from XLA
        if str(getattr(jax.config, "jax_platforms", "") or "").startswith("cpu"):
            try:
                jax.config.update("jax_cpu_collectives_implementation", "gloo")
            except Exception:  # older jax: flag absent, mpi-only builds
                logger.warning("could not enable gloo cpu collectives")
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        _INITIALIZED = True
        logger.info(
            "distributed: initialized process %d/%d (coordinator %s)",
            process_id, num_processes, coordinator_address,
        )
    elif len(os.environ.get("TPU_WORKER_HOSTNAMES", "").split(",")) > 1:
        # Multi-host TPU pod (>1 worker hostname): let JAX self-configure
        # from the TPU metadata.
        jax.distributed.initialize()
        _INITIALIZED = True
        logger.info("distributed: pod auto-init, process %d/%d",
                    jax.process_index(), jax.process_count())


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_main_process() -> bool:
    """`accelerator.is_main_process` equivalent (reference run.py:228)."""
    return jax.process_index() == 0


def main_print(*args, **kwargs) -> None:
    """`accelerator.print` equivalent (reference run.py:205,216)."""
    if is_main_process():
        print(*args, **kwargs)


def check_desync(fingerprint: float, name: str = "train_state") -> None:
    """Debug guard (SURVEY §5 race/failure detection): compare a scalar
    fingerprint (e.g. the params global-norm) across processes and raise if
    any host disagrees — catching silent replica divergence (bad hardware,
    non-deterministic data order) the way torch's DDP detects mismatched
    buckets. No-op single-process; out-of-band (DCN), so only call it at a
    debug cadence (TrainConfig.debug_desync wires it per epoch)."""
    if jax.process_count() == 1:
        return
    import numpy as np
    from jax.experimental import multihost_utils

    from pytorchvideo_accelerate_tpu.parallel.hangcheck import (
        collective_section,
    )

    with collective_section("desync_check", name=name):
        vals = np.asarray(
            multihost_utils.process_allgather(np.float32(fingerprint))
        ).reshape(-1)
    # equal_nan: all-NaN means the run diverged IDENTICALLY everywhere —
    # that's a NaN problem (debug_nans territory), not a desync
    agree = np.all((vals == vals[0]) | (np.isnan(vals) & np.isnan(vals[0])))
    if not agree:
        raise RuntimeError(
            f"cross-host desync on {name!r}: per-process fingerprints {vals.tolist()}"
        )
    if np.isnan(vals[0]):
        logger.warning("desync check on %r: fingerprint is NaN on all hosts "
                       "(consistent, but the run has diverged)", name)


def sync_global_devices(name: str = "barrier") -> None:
    """Host-level barrier (out-of-band, DCN) — for checkpoint/teardown fences."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        from pytorchvideo_accelerate_tpu.parallel.hangcheck import (
            collective_section,
        )

        with collective_section("barrier", name=name):
            multihost_utils.sync_global_devices(name)
