"""Ring attention: context-parallel attention over the mesh "context" axis.

Long-context plan from SURVEY §5: the reference stack only *hooks* context
parallelism (accelerate `_prepare_cp` accelerator.py:1658, `maybe_context_parallel`
accelerator.py:4111) and never exercises it; here it is a first-class backend.
For video transformers the token count is T·(H/p)·(W/p) (MViT-B at 32 frames /
224² is 8·14·14 ≈ 1.5k tokens; VideoMAE pretrain at 16·14·14 with longer clips
grows linearly in T), so sequence memory — activations and the O(N²) attention
— is the scaling wall. The TPU-native answer is blockwise ring attention:

- tokens sharded over the ``context`` mesh axis (each device holds N/cp tokens);
- K/V blocks rotate around the ring via ``lax.ppermute`` (XLA lowers this to
  neighbour-to-neighbour ICI transfers — no all-gather, no N² memory);
- each device accumulates its queries' attention with the *online softmax*
  (flash-attention style running max/sum), so the full score matrix never
  materializes.

Compute/communication overlap is XLA's job: the ppermute for step i+1 is
issued while step i's einsum runs (latency-hiding scheduler), matching the
hand-rolled double buffering in the published ring-attention kernels.

Sequences that don't divide the context axis are padded and masked (the mask
multiplies the softmax numerator as well as the logits: a *fully*-padded K
shard would otherwise contribute exp(logit - max) = exp(0) = 1 per column —
the classic streaming-softmax edge case).

Two entry points:
- `ring_attention(q, k, v, axis_name=...)` — call *inside* an active
  `shard_map` over the context axis (manual-SPMD region).
- `make_ring_attention(mesh)` — returns a drop-in attention fn for
  auto-sharded (jit) models: wraps the local kernel in `jax.shard_map`
  over ``context``, padding/masking ragged sequence lengths.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from pytorchvideo_accelerate_tpu.parallel.collectives import (
    axis_size,
    shard_map as _shard_map,
)
from pytorchvideo_accelerate_tpu.parallel.mesh import (
    AXIS_CONTEXT,
    batch_axes,
    cp_axis,
    mesh_memo,
)

NEG_INF = -1e30


def _online_block(q, k, v, kmask, o, l, m, scale):
    """One flash-attention accumulation block.

    q: (B, Nq, H, D); k/v: (B, Nk, H, D); kmask: (Nk,) bool or None;
    o: (B, Nq, H, D) f32 accumulator; l/m: (B, H, Nq) running sum/max.
    """
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    logits = logits.astype(jnp.float32) * scale
    if kmask is not None:
        logits = jnp.where(kmask[None, None, None, :], logits, NEG_INF)
    m_new = jnp.maximum(m, logits.max(axis=-1))
    p = jnp.exp(logits - m_new[..., None])          # (B, H, Nq, Nk)
    if kmask is not None:
        # kill the exp(NEG_INF - NEG_INF) = 1 case when every key is masked
        p = p * kmask[None, None, None, :]
    alpha = jnp.exp(m - m_new)                       # (B, H, Nq)
    l_new = l * alpha + p.sum(axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    o_new = o * alpha.transpose(0, 2, 1)[..., None] + pv
    return o_new, l_new, m_new


def ring_attention(q, k, v, axis_name: str = AXIS_CONTEXT,
                   scale: Optional[float] = None,
                   nk_valid: Optional[int] = None):
    """Blockwise ring attention. Must run inside `shard_map` with `axis_name`
    bound; q/k/v are the *local* sequence shards, shape (B, N_local, H, D).

    `nk_valid`: global number of real (unpadded) keys; when given, keys at
    global position >= nk_valid are masked out. Non-causal (video tokens are
    bidirectional — SlowFast/MViT classify, VideoMAE reconstructs).
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    steps = axis_size(axis_name)
    my = lax.axis_index(axis_name)
    blk = k.shape[1]

    B, Nq, H, D = q.shape
    o = jnp.zeros((B, Nq, H, D), jnp.float32)
    l = jnp.zeros((B, H, Nq), jnp.float32)
    m = jnp.full((B, H, Nq), NEG_INF, jnp.float32)
    perm = [(i, (i + 1) % steps) for i in range(steps)]

    def body(carry, s):
        o, l, m, k, v = carry
        if nk_valid is not None and nk_valid < steps * blk:
            # after s forward rotations this device holds the block that
            # started on device (my - s); mask its global key positions
            src = jnp.mod(my - s, steps)
            col = src * blk + jnp.arange(blk)
            kmask = col < nk_valid
        else:
            kmask = None
        o, l, m = _online_block(q, k, v, kmask, o, l, m, scale)
        # rotate K/V one hop around the ICI ring (neighbour-only transfer);
        # the last rotation is dead work but keeps the scan shape static
        k = lax.ppermute(k, axis_name, perm)
        v = lax.ppermute(v, axis_name, perm)
        return (o, l, m, k, v), None

    (o, l, m, _, _), _ = lax.scan(body, (o, l, m, k, v), jnp.arange(steps))
    out = o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def _pad_tokens(x, mult: int):
    pad = (-x.shape[1]) % mult
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0), (0, 0)))
    return x


def make_cp_attention(mesh: Mesh, local_fn,
                      axis_name: Optional[str] = None):
    """Shared jit-side wrapper for context-parallel attention kernels.

    `local_fn(q, k, v, axis_name=..., nk_valid=...)` is a manual-SPMD kernel
    (ring_attention / ulysses_attention). Opens a `shard_map` region over the
    context-parallel axis — `axis_name` or, when None, resolved from the
    mesh layout (the library mesh's ``context`` axis / the 2-D train mesh's
    ``model`` axis, parallel/mesh.cp_axis): the token axis of q/k/v is
    sharded there and heads/features are replicated w.r.t. it. The batch
    axis additionally stays sharded over the mesh's DP axes when the global
    batch divides them (the normal training case) and is replicated
    otherwise (tiny eval batches). Ragged sequence lengths (e.g. MViT's
    pooled K/V grids) are padded to a multiple of the axis size and masked
    inside the kernel.
    """
    if axis_name is None:
        axis_name = cp_axis(mesh)
    cp = mesh.shape[axis_name]
    daxes = batch_axes(mesh)
    dp = 1
    for a in daxes:
        dp *= mesh.shape[a]

    # bounded: distinct (batch_divisible, lengths) combos are few per model
    @functools.lru_cache(maxsize=64)
    def build(batch_divisible: bool, nk_valid: int, nk_padded: int):
        spec = P(daxes if batch_divisible else None, axis_name, None, None)
        mask = None if nk_valid == nk_padded else nk_valid
        return _shard_map(
            lambda q, k, v: local_fn(q, k, v, axis_name=axis_name,
                                     nk_valid=mask),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        )

    def attn(q, k, v):
        nq, nk = q.shape[1], k.shape[1]
        qp, kp, vp = _pad_tokens(q, cp), _pad_tokens(k, cp), _pad_tokens(v, cp)
        out = build(q.shape[0] % dp == 0, nk, kp.shape[1])(qp, kp, vp)
        return out[:, :nq]

    return attn


def make_ring_attention(mesh: Mesh, axis_name: Optional[str] = None):
    """Drop-in ring-attention `attn(q, k, v)` for auto-sharded models under
    `jit` (see `make_cp_attention`; `axis_name=None` resolves the CP axis
    from the mesh layout). Memoized on the mesh-identity store (an
    equality-keyed lru would serve a wrapper closed over a retired mesh
    after a mesh-reshape restore) so every attention layer / retrace
    reuses one wrapper and its shape cache."""
    memo = mesh_memo(mesh, "ring_attention")
    attn = memo.get(axis_name)
    if attn is None:
        attn = memo[axis_name] = make_cp_attention(mesh, ring_attention,
                                                   axis_name)
    return attn
