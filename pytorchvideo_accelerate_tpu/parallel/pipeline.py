"""SPMD pipeline parallelism over the mesh's model axis.

ROADMAP item 3's last unlanded leg: long-clip VideoMAE pretraining blows
past one chip's activation memory, and the repo already has every
prerequisite — `make_pretrain_step`, in-graph `lax.scan` gradient
accumulation, the named (data, model) train mesh, ring/ulysses context
parallelism — except a pipeline lane over the model axis. This module is
that lane, in the SPMD-friendly formulation ("Scaling Deep Learning
Training with MPMD Pipeline Parallelism", PAPERS.md, lowered onto the
pjit/GSPMD mesh idiom of the TPUv4 pjit paper):

- the transformer trunk's K structurally-identical blocks are stacked
  IN-GRAPH into per-stage sub-stacks: stage s (one model-axis slice)
  computes blocks [s·K/P, (s+1)·K/P) and nothing else, so each stage's
  working set is 1/P of the trunk's layer compute and the per-microbatch
  activation footprint — the memory lever that fits long-clip pretrain.
  (Trunk params stay replicated per device, the repo's status quo for
  every other lane; the model-axis-sharded stacked-params form
  miscompiles on the pinned jaxlib — see the in-function comment — and
  params are not the scarce resource here, activations are.)
- inside a `shard_map` over the mesh, a `lax.scan` runs the microbatch
  schedule: at tick t, stage 0 ingests microbatch t, every stage runs its
  local sub-stack (an inner `lax.scan` over its K/P blocks), and a
  `ppermute` rotates activations one stage forward. After M + P - 1 ticks
  every microbatch has drained through the last stage — steady-state
  keeps every stage busy, exactly the 1F1B occupancy picture, and plain
  reverse-mode autodiff through the scan replays the same schedule
  backwards (no custom VJP anywhere);
- the fill/drain ticks where a stage chews on garbage ARE the pipeline
  bubble: `analytic_bubble_frac(P, M) = (P-1)/(M+P-1)` per direction.
  More microbatches amortize it; the bench PIPELINE lane measures the
  realized fraction with a two-point (M, 2M) timing fit.

The param-tree contract that makes checkpoints interchange: the stacking
happens IN-GRAPH, per step, from the model's ordinary `block{i}` param
tree — TrainState, optimizer state, checkpoints, converted weights, and
the donation story are byte-identical to the unpipelined model, and a run
saved under a (data, P) pipelined mesh restores under (N, 1) or a single
chip through the existing mesh-portable restore path (trainer/
checkpoint.py, the PR 7 contract). Under GSPMD the stack lowers to a
local dynamic-slice per stage (params are replicated over the model
axis), and the stacked gradient's unstack transposes to the model-axis
all-gather that is this scheme's gradient-sync cost.

Composition rules (docs/PARALLELISM.md § pipeline):
- on the 2-D (data, model) train mesh the pipeline SPENDS the model
  axis, so it excludes Megatron TP and ring/ulysses CP (both want the
  same axis); on the 4-axis library mesh the stages ride `tensor` while
  CP keeps its own `context` axis — inside the pipelined region the
  blocks call the CP kernels in their already-inside-a-shard_map form
  (`axis_name=`, ops/attention.py convention), so pipeline x CP composes;
- block functions must be rng-free and shape-preserving (pre-LN ViT
  blocks are; MViT's multiscale schedule is validated per cut — see
  models/mvit.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pytorchvideo_accelerate_tpu.parallel.collectives import shard_map
from pytorchvideo_accelerate_tpu.parallel.mesh import batch_axes, model_axis


@dataclass(frozen=True)
class PipelinePlan:
    """Static description of one pipelined trunk execution.

    Frozen + hashable on purpose: models carry it as a flax module
    attribute (like `context_mesh`), and step builders close over it.

    stages        — P, the stage count; must equal the mesh's model-axis
                    size (each stage is one model-axis slice).
    microbatches  — M, microbatches streamed through the stages per step.
                    The trainer reuses the gradient-accumulation
                    micro-batch axis for this by default (config.py
                    `parallel.pipeline_microbatches`).
    mesh          — the device mesh the shard_map runs over.
    axis          — the mesh axis carrying stages ("model" on the 2-D
                    train mesh, "tensor" on the library mesh).
    cp_axis       — when ring/ulysses context parallelism composes with
                    the pipeline (library mesh only), the axis the token
                    dim is sharded over INSIDE the pipelined region; the
                    blocks then run their attention in `axis_name=` form.
    """

    stages: int
    microbatches: int
    mesh: Mesh
    axis: str
    cp_axis: Optional[str] = None

    @property
    def active(self) -> bool:
        return self.stages > 1

    def covers(self, n_blocks: int) -> bool:
        """Can this plan partition an `n_blocks` stack into equal stages?
        (The VideoMAE decoder opts out of pipelining when its 4 narrow
        blocks don't divide by P, rather than failing the whole model.)"""
        return n_blocks % self.stages == 0


def analytic_bubble_frac(stages: int, microbatches: int) -> float:
    """Idle fraction of the fill/drain schedule, per direction:
    (P-1)/(M+P-1). The forward scan runs M+P-1 ticks of which M are
    useful per stage; plain autodiff replays the same shape backwards, so
    the whole-step fraction is the same number. 0.0 for P=1."""
    p, m = int(stages), int(microbatches)
    if p <= 1:
        return 0.0
    return (p - 1) / (m + p - 1)


def stage_cuts(n_blocks: int, stages: int) -> List[Tuple[int, int]]:
    """[start, end) block ranges per stage — equal contiguous chunks, the
    only partition the stacked-leading-dim sharding can express."""
    if stages < 1:
        raise ValueError(f"pipeline stages must be >= 1, got {stages}")
    if n_blocks % stages:
        raise ValueError(
            f"cannot cut {n_blocks} blocks into {stages} equal pipeline "
            f"stages: {n_blocks} % {stages} != 0 (pick a stage count that "
            "divides the trunk depth)")
    size = n_blocks // stages
    return [(s * size, (s + 1) * size) for s in range(stages)]


def make_plan(mesh: Mesh, stages: int, microbatches: int = 0,
              accum_steps: int = 1,
              cp_axis_name: Optional[str] = None) -> PipelinePlan:
    """Resolve config knobs into a PipelinePlan against a concrete mesh.

    `microbatches=0` means auto: reuse the gradient-accumulation
    micro-batch count when accumulation is on (the data pipeline already
    lays the batch out micro-first), else 2·P — enough that the analytic
    bubble stays under (P-1)/(3P-1) ≈ 1/3 by default; raise it for less.
    """
    axis = model_axis(mesh)
    if axis is None:
        raise ValueError(
            f"pipeline_stages={stages} needs a model-parallel mesh axis "
            f"('model' or 'tensor'); mesh has {tuple(mesh.axis_names)}")
    if mesh.shape[axis] != stages:
        raise ValueError(
            f"pipeline_stages={stages} must equal the mesh's {axis!r} axis "
            f"size ({mesh.shape[axis]}): stages are placed one per "
            f"{axis}-slice — set --mesh.model {stages} (train mesh) or "
            f"--mesh.tensor {stages} (library mesh)")
    if cp_axis_name is not None and cp_axis_name == axis:
        raise ValueError(
            "pipeline stages and context parallelism both want the "
            f"{axis!r} axis: on the 2-D train mesh they are mutually "
            "exclusive — use the 4-axis library mesh (tensor=P for "
            "stages, context=C for CP) to compose them")
    m = int(microbatches) or (int(accum_steps) if accum_steps > 1
                              else 2 * int(stages))
    if m < 1:
        raise ValueError(f"pipeline_microbatches must be >= 1, got {m}")
    return PipelinePlan(stages=int(stages), microbatches=m, mesh=mesh,
                        axis=axis, cp_axis=cp_axis_name)


def validate_homogeneous_blocks(block_params: Sequence[Any]) -> None:
    """Homogeneity is a hard requirement of the stage pipeline — one
    block function runs every slice of a uniformly-shaped sub-stack — so
    a mismatching tree (MViT's dim-doubling stage starts, a stray pool
    conv) fails here with the offending block named instead of as a
    shape error deep inside shard_map. Pure metadata checks, no ops."""
    blocks = list(block_params)
    if not blocks:
        raise ValueError("no blocks to pipeline")
    ref = jax.tree_util.tree_structure(blocks[0])
    ref_avals = [(np.shape(leaf), jnp.result_type(leaf))
                 for leaf in jax.tree_util.tree_leaves(blocks[0])]
    for i, b in enumerate(blocks[1:], start=1):
        if jax.tree_util.tree_structure(b) != ref:
            raise ValueError(
                f"pipeline stages need a homogeneous block stack: block {i}"
                f"'s param tree structure differs from block 0's "
                "(heterogeneous trunks — MViT stage starts, pooled blocks "
                "— cannot stack; see docs/PARALLELISM.md § pipeline)")
        for j, leaf in enumerate(jax.tree_util.tree_leaves(b)):
            if (np.shape(leaf), jnp.result_type(leaf)) != ref_avals[j]:
                raise ValueError(
                    f"pipeline stages need a homogeneous block stack: "
                    f"block {i} leaf #{j} has shape/dtype "
                    f"{np.shape(leaf)}/{jnp.result_type(leaf)} vs block "
                    f"0's {ref_avals[j][0]}/{ref_avals[j][1]}")


def stack_block_params(block_params: Sequence[Any]) -> Any:
    """Stack per-block param trees along a new leading (block) axis
    (validated homogeneous first — `validate_homogeneous_blocks`)."""
    blocks = list(block_params)
    validate_homogeneous_blocks(blocks)
    return jax.tree.map(lambda *ls: jnp.stack(ls), *blocks)


def unstack_block_params(stacked: Any, n_blocks: int) -> List[Any]:
    """Inverse of `stack_block_params` (host-side checkpoint tooling and
    tests; the train path never materializes the unstacked form — the
    stack's AD transpose does it implicitly)."""
    return [jax.tree.map(lambda a, i=i: a[i], stacked)
            for i in range(n_blocks)]


def _data_shards(mesh: Mesh) -> int:
    n = 1
    for a in batch_axes(mesh):
        n *= mesh.shape[a]
    return n


def pipeline_blocks(block_fn: Callable[[Any, Any], Any],
                    block_params: Sequence[Any], x, plan: PipelinePlan):
    """Run `x` through a stack of homogeneous blocks as a P-stage SPMD
    pipeline (module docstring has the schedule picture).

    block_fn(one_block_params, h) -> h  — pure, rng-free, shape- and
    dtype-preserving (validated at trace time). `x`: (B, ...) activations,
    batch-sharded over the mesh's data axes; with `plan.cp_axis` set, dim
    1 (the token dim) is sharded over that axis inside the region and the
    blocks must use the `axis_name=` attention convention.

    Returns activations identical in shape/sharding contract to `x`
    (replicated over the stage axis, like every other activation the
    surrounding jit computes redundantly per model slice).
    """
    mesh, axis = plan.mesh, plan.axis
    stages = int(plan.stages)
    micro = int(plan.microbatches)
    blocks = list(block_params)
    stage_cuts(len(blocks), stages)  # divisibility, with the clear error
    if mesh.shape[axis] != stages:
        raise ValueError(
            f"plan has {stages} stages but mesh axis {axis!r} is "
            f"{mesh.shape[axis]}-wide")
    dshards = _data_shards(mesh)
    batch = int(x.shape[0])
    if batch % (dshards * micro):
        raise ValueError(
            f"global batch {batch} must divide data_shards x microbatches "
            f"= {dshards} x {micro}: each data slice re-slices its local "
            "batch into the pipeline's microbatches")

    # Validate homogeneity up front (the clear error): metadata only —
    # an actual stack here would put a dead full-trunk copy in every
    # traced (and a real one in every eager) pipelined apply.
    validate_homogeneous_blocks(blocks)
    n_stage = len(blocks) // stages

    # EVERY in/out spec mentions EVERY mesh axis its value touches, via
    # explicit leading tile dims for the axes a value is replicated over
    # (block params over data/context and the stage axis, x over the stage
    # axis). Why not lean on shard_map's unmentioned-axis replication
    # accounting: reverse-mode AD of replicated-in operands through the
    # pinned jax's check_rep=False rewrite machinery over-psums their
    # cotangents once per nesting level of this body's scans (measured:
    # block grads x dshards^3 on a (data, model) mesh — found while
    # building the P=2 parity test). And why each block rides in as its
    # own tiled input instead of one model-axis-sharded (K, ...) stack:
    # the pinned jaxlib's SPMD partitioner miscompiles an IN-GRAPH
    # `jnp.stack` (concatenate) feeding the manual-computation boundary
    # whenever a tile dim shards over the data axis — output values come
    # back multiplied by mesh.size (fingerprinted: exactly
    # `mesh.size * correct`); a pre-stacked jit *argument* compiles fine,
    # but the in-graph stack is non-negotiable (it is what keeps the
    # param tree identical to the unpipelined model). Per-block tiles
    # sidestep the bug at the cost of replicating trunk params over the
    # stage axis (the status quo for every other lane in this repo — the
    # pipeline's memory win is the per-microbatch ACTIVATION footprint
    # and the schedule, not param bytes; revisit the stacked form on a
    # fixed jaxlib). The `broadcast_to` tiles cost nothing under GSPMD
    # (each shard holds the one copy it already had) and their transposes
    # are plain reduce_sums over the sharded tile dims — lowered to the
    # cross-shard all-reduce that IS this scheme's gradient sync.
    daxes = batch_axes(mesh)
    cp = plan.cp_axis if (plan.cp_axis is not None and x.ndim >= 3) else None
    cp_size = mesh.shape[cp] if cp is not None else 1
    lead = (dshards,) + ((cp_size,) if cp is not None else ()) + (stages,)
    lead_spec = (daxes,) + ((cp,) if cp is not None else ()) + (axis,)
    n_lead = len(lead)

    def tile_leaf(a):
        t = jnp.broadcast_to(a[(None,) * n_lead], lead + a.shape)
        spec = P(*lead_spec, *([None] * a.ndim))
        return lax.with_sharding_constraint(t, NamedSharding(mesh, spec))

    tiled = tuple(jax.tree.map(tile_leaf, b) for b in blocks)
    tiled_specs = tuple(
        jax.tree.map(lambda a: P(*lead_spec,
                                 *([None] * (a.ndim - n_lead))), b)
        for b in tiled)

    x_dims = [daxes] + [None] * (x.ndim - 1)
    if cp is not None:
        x_dims[1] = cp  # token dim sharded inside the region
    x_tiled = lax.with_sharding_constraint(
        jnp.broadcast_to(x[None], (stages,) + x.shape),
        NamedSharding(mesh, P(axis, *x_dims)))
    x_spec = P(axis, *x_dims)
    out_spec = P(axis, *x_dims)
    last = stages - 1

    def body(bps, xt):
        # strip the tile dims (each device holds one replica of every
        # block), stack locally — plain XLA inside the manual region, no
        # partitioner involvement — and slice out THIS stage's contiguous
        # sub-stack by stage id
        sid = lax.axis_index(axis)
        locals_ = [jax.tree.map(lambda a: a.reshape(a.shape[n_lead:]), b)
                   for b in bps]
        full = jax.tree.map(lambda *ls: jnp.stack(ls), *locals_)
        bp = jax.tree.map(
            lambda a: lax.dynamic_slice_in_dim(a, sid * n_stage, n_stage,
                                               axis=0), full)
        xl = xt.reshape(xt.shape[1:])
        b_loc = xl.shape[0]
        xm = xl.reshape((micro, b_loc // micro) + xl.shape[1:])

        def run_stage(h):
            def blk(c, p):
                y = block_fn(p, c)
                if y.shape != c.shape or y.dtype != c.dtype:
                    raise ValueError(
                        f"pipelined block_fn must preserve shape/dtype: "
                        f"{c.shape}/{c.dtype} -> {y.shape}/{y.dtype}")
                return y, None

            h, _ = lax.scan(blk, h, bp)
            return h

        def tick(carry, t):
            h, out = carry
            # stage 0 ingests microbatch t (clipped past the drain — the
            # reprocessed garbage never reaches `out`); later stages use
            # the activation the ppermute rotated in last tick
            inp = lax.dynamic_index_in_dim(
                xm, jnp.clip(t, 0, micro - 1), 0, keepdims=False)
            h = jnp.where(sid == 0, inp, h)
            y = run_stage(h)
            # the last stage drains microbatch t-(P-1) once the fill is
            # done; other stages' writes are masked out
            oidx = jnp.clip(t - last, 0, micro - 1)
            write = jnp.logical_and(sid == last, t >= last)
            out = jnp.where(write,
                            lax.dynamic_update_index_in_dim(out, y, oidx, 0),
                            out)
            h = lax.ppermute(y, axis,
                             [(i, (i + 1) % stages) for i in range(stages)])
            return (h, out), None

        out0 = jnp.zeros_like(xm)
        (_, out), _ = lax.scan(tick, (jnp.zeros_like(xm[0]), out0),
                               jnp.arange(micro + last))
        # the drained activations live on the last stage; the out spec
        # carries the stage dim explicitly (zeros elsewhere)
        return out.reshape((1,) + xl.shape)

    fn = shard_map(body, mesh=mesh, in_specs=(tiled_specs, x_spec),
                   out_specs=out_spec)
    # reduce the stage dim instead of slicing it: non-last stages are
    # zeros, so the sum IS stage P-1's value, and a reduce over a sharded
    # dim lowers to the local reduce + all-reduce that hands every model
    # slice the full tensor (the replicated-over-stage-axis contract the
    # decoder/head/loss consumers need) — with the trivially correct
    # transpose (broadcast; the masked writes zero the non-last
    # cotangents in the backward scan).
    return fn(tiled, x_tiled).sum(axis=0)


def apply_pipelined_blocks(mod, tokens, *, prefix: str, depth: int,
                           template, plan: PipelinePlan,
                           apply_args: Tuple = ()):
    """Drive a bound flax module's named block stack through the stage
    pipeline — the ONE dispatch both transformer families share (videomae
    `run_vit_blocks`, the MViT block loop), so remat wrapping / param
    addressing / boundary constraints can't drift apart between them.

    Reads the `{prefix}{i}` param subtrees straight off `mod.variables` —
    the SAME trees the plain loop trains (param-tree identity is the
    checkpoint-interchange contract) — and applies `template` (an
    UNNAMED block module instance) to each as a pure function.
    `apply_args` are static extras after the activations (MViT's `train`
    flag). Honors `mod.remat` and re-anchors the output on
    `mod.shard_mesh` like the plain loops do."""
    from pytorchvideo_accelerate_tpu.parallel.sharding import constrain_block

    bp = [mod.variables["params"][f"{prefix}{i}"] for i in range(depth)]

    def block_fn(p, h):
        return template.apply({"params": p}, h, *apply_args)

    if mod.remat:
        block_fn = jax.checkpoint(block_fn)
    tokens = pipeline_blocks(block_fn, bp, tokens, plan)
    return constrain_block(tokens, mod.shard_mesh)


def stage_tag(mesh: Mesh, axis: Optional[str] = None) -> str:
    """Which pipeline stage(s) this PROCESS runs: "2/4" when its local
    devices sit on one model-axis slice (a real multi-host pipeline — the
    attribution the hang detector wants: a wedged dispatch on this host
    IS that stage wedging), "0-3/4" when several stages are local
    (single-process / forced-host runs). The one formatting every watched
    collective shares, mirroring hangcheck.host_tag()."""
    axis = axis or model_axis(mesh)
    if axis is None:
        return ""
    pos = list(mesh.axis_names).index(axis)
    local = {d for d in jax.local_devices() if d in mesh.devices.flat}
    coords = sorted({int(idx[pos])
                     for idx in np.ndindex(mesh.devices.shape)
                     if mesh.devices[idx] in local})
    total = mesh.shape[axis]
    if not coords:
        return f"?/{total}"
    if len(coords) == 1:
        return f"{coords[0]}/{total}"
    return f"{coords[0]}-{coords[-1]}/{total}"
