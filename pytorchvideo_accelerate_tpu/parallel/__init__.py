"""Distributed/parallel runtime: mesh construction, sharding rules, host
control plane, and collective helpers.

TPU-native replacement for the reference stack's distributed backbone
(SURVEY.md §2.2): `PartialState`/NCCL process groups/DDP Reducer become a
`jax.sharding.Mesh` + NamedSharding rules + XLA collectives compiled into the
step function.
"""

from pytorchvideo_accelerate_tpu.parallel.mesh import (  # noqa: F401
    AXIS_CONTEXT,
    AXIS_DATA,
    AXIS_FSDP,
    AXIS_MODEL,
    AXIS_TENSOR,
    BATCH_AXES,
    batch_axes,
    cp_axis,
    make_mesh,
    make_train_mesh,
    model_axis,
)
from pytorchvideo_accelerate_tpu.parallel.sharding import (  # noqa: F401
    batch_sharding,
    constrain_block,
    family_uses_tp,
    replicated,
    shard_batch,
    shard_params,
    shard_state,
)
from pytorchvideo_accelerate_tpu.parallel.distributed import (  # noqa: F401
    initialize_distributed,
    is_main_process,
    main_print,
    process_count,
    process_index,
)
from pytorchvideo_accelerate_tpu.parallel.pipeline import (  # noqa: F401
    PipelinePlan,
    analytic_bubble_frac,
    make_plan as make_pipeline_plan,
    pipeline_blocks,
    stage_cuts,
    stage_tag,
)
