"""Distributed/parallel runtime: mesh construction, sharding rules, host
control plane, and collective helpers.

TPU-native replacement for the reference stack's distributed backbone
(SURVEY.md §2.2): `PartialState`/NCCL process groups/DDP Reducer become a
`jax.sharding.Mesh` + NamedSharding rules + XLA collectives compiled into the
step function.
"""

from pytorchvideo_accelerate_tpu.parallel.mesh import (  # noqa: F401
    AXIS_CONTEXT,
    AXIS_DATA,
    AXIS_FSDP,
    AXIS_TENSOR,
    BATCH_AXES,
    make_mesh,
)
from pytorchvideo_accelerate_tpu.parallel.sharding import (  # noqa: F401
    batch_sharding,
    replicated,
    shard_batch,
    shard_params,
)
from pytorchvideo_accelerate_tpu.parallel.distributed import (  # noqa: F401
    initialize_distributed,
    is_main_process,
    main_print,
    process_count,
    process_index,
)
