"""Device-mesh construction.

Replaces the reference's process-group wiring (`accelerate/state.py:755-798`
backend selection + `torch.distributed.init_process_group`, SURVEY §3.2): in
the TPU design there is no user-visible process group — a `jax.sharding.Mesh`
over all devices defines every parallelism axis, and XLA lowers the
collectives onto ICI rings / DCN links from the sharding annotations alone.

Two mesh layouts ship:

TRAIN MESH (the trainer's backbone, `make_train_mesh`) — a named 2-D
`(data, model)` mesh, the pjit/TPUv4 GSPMD pattern ("Scalable Training of
Language Models using JAX pjit and TPUv4", PAPERS.md):
  data   — batch sharding; gradient psum implied by sharded autodiff.
  model  — the model-parallel axis. How it is spent is a per-model-family
           decision (parallel/sharding.py): transformer families split
           attention heads / MLP widths over it (Megatron TP), the
           context-parallel lane shards the token axis over it
           (ring/ulysses), and conv families replicate over it.
Checkpoints are mesh-portable across train-mesh shapes: a run saved under
(1, N) restores under (N, 1) or single-chip (trainer/checkpoint.py).

LIBRARY MESH (`make_mesh`) — the 4-axis research layout the parallel/
library and its tests exercise directly:
  data    — pure data parallelism (batch sharding; gradient psum implied by
            sharded autodiff). The only axis the reference exercises (its DDP
            path, SURVEY §2.4).
  fsdp    — sharded-DP: parameters/optimizer state sharded here (ZeRO/FSDP
            equivalent, accelerate accelerator.py:1912-1948); also shards the
            batch jointly with `data`.
  tensor  — tensor parallelism for transformer blocks (Megatron path in the
            backbone, accelerator.py:2506).
  context — sequence/context parallelism: ring attention / Ulysses all-to-all
            over the token axis (accelerate `_prepare_cp` accelerator.py:1658).

Downstream code stays portable across both layouts by resolving axes from
the mesh itself (`batch_axes` / `model_axis` / `cp_axis`) instead of
assuming a fixed axis tuple.
"""

from __future__ import annotations

import weakref
from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

from pytorchvideo_accelerate_tpu.config import MeshConfig

AXIS_DATA = "data"
AXIS_FSDP = "fsdp"
AXIS_TENSOR = "tensor"
AXIS_CONTEXT = "context"
AXIS_MODEL = "model"

# The global batch dimension is sharded over both DP-like axes, mirroring how
# FSDP data-sharding composes with DP in the backbone's device-mesh-aware
# dataloader (accelerate data_loader.py:1127-1163). Library-mesh constant;
# mesh-portable code calls `batch_axes(mesh)` instead.
BATCH_AXES = (AXIS_DATA, AXIS_FSDP)

MESH_AXIS_NAMES = (AXIS_DATA, AXIS_FSDP, AXIS_TENSOR, AXIS_CONTEXT)
TRAIN_MESH_AXIS_NAMES = (AXIS_DATA, AXIS_MODEL)

# Per-mesh memo store, keyed on mesh IDENTITY (id + liveness guard), never
# on Mesh equality. Why: jax Mesh.__eq__ compares axis names / shape /
# device list, so after a mesh-reshape restore an equal-but-distinct Mesh
# object would keep serving cached values (NamedShardings, shard_map
# wrappers) closed over the RETIRED mesh — semantically aliased layouts
# that defeat `sharding.mesh is mesh` identity reasoning. The weakref is a
# LIVENESS GUARD against id reuse, not the growth bound: cached values
# (NamedShardings, wrappers) reference their mesh, so an entry keeps its
# mesh reachable — the store is bounded instead by sweeping dead refs and
# then evicting oldest-first past _MESH_MEMO_MAX (values are pure caches;
# eviction of a live mesh's memo only costs a rebuild).
_mesh_memos: dict = {}  # id(mesh) -> (weakref to mesh, {namespace: {}})
_MESH_MEMO_MAX = 16  # distinct live meshes a process plausibly juggles


def mesh_memo(mesh: Mesh, namespace: str) -> dict:
    """Mutable memo dict tied to `mesh`'s identity, created on first use.
    Benign race: concurrent callers may build a value twice; last write
    wins and both are equivalent."""
    key = id(mesh)
    entry = _mesh_memos.get(key)
    if entry is None or entry[0]() is not mesh:
        entry = (weakref.ref(mesh), {})
        _mesh_memos[key] = entry
        if len(_mesh_memos) > _MESH_MEMO_MAX:
            for k in [k for k, e in _mesh_memos.items() if e[0]() is None]:
                del _mesh_memos[k]
            for k in list(_mesh_memos):
                if len(_mesh_memos) <= _MESH_MEMO_MAX:
                    break
                if k != key:
                    del _mesh_memos[k]
    return entry[1].setdefault(namespace, {})


def batch_axes(mesh: Mesh) -> tuple:
    """Axis names the (global) batch dimension shards over, resolved from
    the mesh itself: ("data", "fsdp") on the library mesh, ("data",) on the
    2-D train mesh. The portability seam for steps/prefetch/serving code."""
    return tuple(a for a in BATCH_AXES if a in mesh.axis_names)


def model_axis(mesh: Mesh) -> Optional[str]:
    """Name of the model-parallel (tensor/TP) axis on this mesh, or None:
    "model" on the train mesh, "tensor" on the library mesh."""
    if AXIS_MODEL in mesh.axis_names:
        return AXIS_MODEL
    if AXIS_TENSOR in mesh.axis_names:
        return AXIS_TENSOR
    return None


def cp_axis(mesh: Mesh) -> str:
    """Axis the context-parallel kernels shard the token dim over: the
    dedicated "context" axis on the library mesh; on the 2-D train mesh the
    CP lane spends the "model" axis (a per-run choice — the same axis is
    never simultaneously TP for params and CP for tokens; see
    parallel/sharding.py and docs/PARALLELISM.md)."""
    if AXIS_CONTEXT in mesh.axis_names:
        return AXIS_CONTEXT
    if AXIS_MODEL in mesh.axis_names:
        return AXIS_MODEL
    raise ValueError(
        f"mesh {tuple(mesh.axis_names)} has no context-parallel axis "
        "(expected 'context' or 'model')")


def resolve_mesh_shape(cfg: MeshConfig, n_devices: int) -> tuple:
    """Resolve -1 on the data axis and validate divisibility."""
    for name in ("fsdp", "tensor", "context"):
        if getattr(cfg, name) < 1:
            raise ValueError(f"mesh.{name} must be >= 1, got {getattr(cfg, name)}")
    if cfg.data != -1 and cfg.data < 1:
        raise ValueError(f"mesh.data must be >= 1 or -1 (infer), got {cfg.data}")
    explicit = cfg.fsdp * cfg.tensor * cfg.context
    data = cfg.data
    if data == -1:
        if n_devices % explicit != 0:
            raise ValueError(
                f"mesh axes fsdp*tensor*context={explicit} does not divide "
                f"device count {n_devices}"
            )
        data = n_devices // explicit
    total = data * explicit
    if total != n_devices:
        raise ValueError(
            f"mesh shape ({data},{cfg.fsdp},{cfg.tensor},{cfg.context}) "
            f"needs {total} devices, have {n_devices}"
        )
    return (data, cfg.fsdp, cfg.tensor, cfg.context)


def _device_grid(shape: tuple, devices: list):
    """Device array for a mesh shape. On TPU, `mesh_utils.create_device_mesh`
    picks a device ordering so the inner (rightmost) axes land on physically
    adjacent chips — keeping tensor/context collectives on fast ICI loops and
    the data axis on the outermost rings, per the scaling-book recipe."""
    try:
        return mesh_utils.create_device_mesh(shape, devices=devices)
    except (ValueError, AssertionError) as e:
        # CPU simulation / odd topologies: plain row-major reshape. On a real
        # TPU slice this forfeits the ICI-adjacency-aware ordering — warn so
        # a degraded collective layout is observable.
        if devices and devices[0].platform == "tpu":
            from pytorchvideo_accelerate_tpu.utils.logging import get_logger

            get_logger("pva_tpu").warning(
                "create_device_mesh failed for shape %s (%s); falling back to "
                "row-major device order — collective layout may be suboptimal",
                shape, e,
            )
        return np.asarray(devices).reshape(shape)


def make_mesh(cfg: Optional[MeshConfig] = None, devices: Optional[Sequence] = None) -> Mesh:
    """Build the 4-axis library mesh (data × fsdp × tensor × context)."""
    cfg = cfg or MeshConfig()
    devices = list(devices if devices is not None else jax.devices())
    shape = resolve_mesh_shape(cfg, len(devices))
    return Mesh(_device_grid(shape, devices), MESH_AXIS_NAMES)


def resolve_train_mesh_shape(cfg: MeshConfig, n_devices: int) -> tuple:
    """Resolve the 2-D (data, model) shape; -1 on `data` infers."""
    if cfg.model < 1:
        raise ValueError(f"mesh.model must be >= 1, got {cfg.model}")
    if cfg.data != -1 and cfg.data < 1:
        raise ValueError(f"mesh.data must be >= 1 or -1 (infer), got {cfg.data}")
    data = cfg.data
    if data == -1:
        if n_devices % cfg.model != 0:
            raise ValueError(
                f"mesh.model={cfg.model} does not divide device count "
                f"{n_devices}")
        data = n_devices // cfg.model
    if data * cfg.model != n_devices:
        raise ValueError(
            f"train mesh shape ({data},{cfg.model}) needs "
            f"{data * cfg.model} devices, have {n_devices}")
    return (data, cfg.model)


def make_train_mesh(cfg: Optional[MeshConfig] = None,
                    devices: Optional[Sequence] = None) -> Mesh:
    """The trainer's backbone mesh: named 2-D `(data, model)`.

    `model` is the single model-parallel axis; how it is spent (Megatron TP
    head/MLP splits, context-parallel token sharding, or plain replication
    for conv families) is decided per model family by parallel/sharding.py.
    Defaults (`model=1`, `data=-1`) give pure data parallelism over every
    device — the reference's DDP layout as a degenerate case.

    Back-compat: a MeshConfig that sets any of the legacy fsdp/tensor/
    context axes falls through to the 4-axis library mesh (every downstream
    consumer resolves axes from the mesh, so both layouts train)."""
    cfg = cfg or MeshConfig()
    if (cfg.fsdp, cfg.tensor, cfg.context) != (1, 1, 1):
        if cfg.model > 1:
            raise ValueError(
                "mesh.model is the 2-D train-mesh axis and cannot combine "
                "with the legacy fsdp/tensor/context axes — pick one layout")
        return make_mesh(cfg, devices)
    devices = list(devices if devices is not None else jax.devices())
    shape = resolve_train_mesh_shape(cfg, len(devices))
    return Mesh(_device_grid(shape, devices), TRAIN_MESH_AXIS_NAMES)


def data_shard_count(mesh: Mesh) -> int:
    """Number of batch shards (= reference `num_processes` for pure DP) —
    the product of the mesh's batch axes, on either mesh layout."""
    count = 1
    for a in batch_axes(mesh):
        count *= mesh.shape[a]
    return count
