"""Device-mesh construction.

Replaces the reference's process-group wiring (`accelerate/state.py:755-798`
backend selection + `torch.distributed.init_process_group`, SURVEY §3.2): in
the TPU design there is no user-visible process group — a `jax.sharding.Mesh`
over all devices defines every parallelism axis, and XLA lowers the
collectives onto ICI rings / DCN links from the sharding annotations alone.

Axes:
  data    — pure data parallelism (batch sharding; gradient psum implied by
            sharded autodiff). The only axis the reference exercises (its DDP
            path, SURVEY §2.4).
  fsdp    — sharded-DP: parameters/optimizer state sharded here (ZeRO/FSDP
            equivalent, accelerate accelerator.py:1912-1948); also shards the
            batch jointly with `data`.
  tensor  — tensor parallelism for transformer blocks (Megatron path in the
            backbone, accelerator.py:2506).
  context — sequence/context parallelism: ring attention / Ulysses all-to-all
            over the token axis (accelerate `_prepare_cp` accelerator.py:1658).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

from pytorchvideo_accelerate_tpu.config import MeshConfig

AXIS_DATA = "data"
AXIS_FSDP = "fsdp"
AXIS_TENSOR = "tensor"
AXIS_CONTEXT = "context"

# The global batch dimension is sharded over both DP-like axes, mirroring how
# FSDP data-sharding composes with DP in the backbone's device-mesh-aware
# dataloader (accelerate data_loader.py:1127-1163).
BATCH_AXES = (AXIS_DATA, AXIS_FSDP)

MESH_AXIS_NAMES = (AXIS_DATA, AXIS_FSDP, AXIS_TENSOR, AXIS_CONTEXT)


def resolve_mesh_shape(cfg: MeshConfig, n_devices: int) -> tuple:
    """Resolve -1 on the data axis and validate divisibility."""
    for name in ("fsdp", "tensor", "context"):
        if getattr(cfg, name) < 1:
            raise ValueError(f"mesh.{name} must be >= 1, got {getattr(cfg, name)}")
    if cfg.data != -1 and cfg.data < 1:
        raise ValueError(f"mesh.data must be >= 1 or -1 (infer), got {cfg.data}")
    explicit = cfg.fsdp * cfg.tensor * cfg.context
    data = cfg.data
    if data == -1:
        if n_devices % explicit != 0:
            raise ValueError(
                f"mesh axes fsdp*tensor*context={explicit} does not divide "
                f"device count {n_devices}"
            )
        data = n_devices // explicit
    total = data * explicit
    if total != n_devices:
        raise ValueError(
            f"mesh shape ({data},{cfg.fsdp},{cfg.tensor},{cfg.context}) "
            f"needs {total} devices, have {n_devices}"
        )
    return (data, cfg.fsdp, cfg.tensor, cfg.context)


def make_mesh(cfg: Optional[MeshConfig] = None, devices: Optional[Sequence] = None) -> Mesh:
    """Build the global mesh. On TPU, `mesh_utils.create_device_mesh` picks a
    device ordering so the inner (rightmost) axes land on physically adjacent
    chips — keeping tensor/context collectives on fast ICI loops and the data
    axis on the outermost rings, per the scaling-book recipe."""
    cfg = cfg or MeshConfig()
    devices = list(devices if devices is not None else jax.devices())
    shape = resolve_mesh_shape(cfg, len(devices))
    try:
        device_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except (ValueError, AssertionError) as e:
        # CPU simulation / odd topologies: plain row-major reshape. On a real
        # TPU slice this forfeits the ICI-adjacency-aware ordering — warn so
        # a degraded collective layout is observable.
        if devices and devices[0].platform == "tpu":
            from pytorchvideo_accelerate_tpu.utils.logging import get_logger

            get_logger("pva_tpu").warning(
                "create_device_mesh failed for shape %s (%s); falling back to "
                "row-major device order — collective layout may be suboptimal",
                shape, e,
            )
        device_array = np.asarray(devices).reshape(shape)
    return Mesh(device_array, MESH_AXIS_NAMES)


def data_shard_count(mesh: Mesh) -> int:
    """Number of batch shards (= reference `num_processes` for pure DP)."""
    return mesh.shape[AXIS_DATA] * mesh.shape[AXIS_FSDP]
