"""NamedSharding rules: the TPU-native `Accelerator.prepare()`.

Where the reference mutates objects (model -> DDP wrap at
`accelerate/accelerator.py:1877-1896`, loader -> BatchSamplerShard at
`data_loader.py:1252-1258`), here `prepare` is a set of pure functions that
place arrays: batches sharded over the DP axes, parameters replicated (or
sharded over `fsdp`), and everything else follows from XLA's propagation.
"""

from __future__ import annotations

import re
from typing import Any, Optional

import jax
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pytorchvideo_accelerate_tpu.parallel.mesh import (
    AXIS_FSDP,
    batch_axes,
    mesh_memo,
    model_axis,
)


def _cached_sharding(mesh: Mesh, spec: P) -> NamedSharding:
    """NamedSharding memo on the mesh-identity store (parallel/mesh.py
    mesh_memo — equality-keyed caching would alias a retired mesh after a
    mesh-reshape restore). `shard_batch` runs once per train/eval step
    (and, with the device prefetcher, on a background thread's critical
    path), so the {mesh, spec} pair is built once per mesh, not per call."""
    specs = mesh_memo(mesh, "namedshardings")
    sharding = specs.get(spec)
    if sharding is None:
        sharding = specs[spec] = NamedSharding(mesh, spec)
    return sharding


def batch_spec(mesh: Mesh, ndim: int = 1, leading_micro: bool = False) -> P:
    """PartitionSpec sharding the (global) batch dim over the mesh's DP
    axes — resolved per mesh layout (("data","fsdp") library mesh /
    ("data",) train mesh). `leading_micro`: a gradient-accumulation axis
    precedes the batch dim and stays unsharded."""
    axes = batch_axes(mesh)
    lead = (None, axes) if leading_micro else (axes,)
    return P(*lead, *([None] * (ndim - len(lead))))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Leading (batch) dim split over the DP axes — the `BatchSamplerShard`
    equivalent, but as a layout annotation instead of an index-stream slicer."""
    return _cached_sharding(mesh, batch_spec(mesh))


def replicated(mesh: Mesh) -> NamedSharding:
    return _cached_sharding(mesh, P())


def constrain_block(x, mesh: Optional[Mesh]):
    """`with_sharding_constraint` for an activation at a block boundary:
    batch dim pinned to the mesh's DP axes, everything else unsharded w.r.t.
    the constraint (XLA still free to propagate inside the block). The
    GSPMD anchor the transformer trunks drop between blocks so the
    partitioner re-converges on the (data × model) layout instead of
    drifting through pooled/resharded intermediates. No-op without a mesh
    (models built for single-device use/conversion parity)."""
    if mesh is None:
        return x
    return lax.with_sharding_constraint(
        x, _cached_sharding(mesh, batch_spec(mesh, x.ndim)))


def shard_batch(mesh: Mesh, batch: Any, micro_dim: bool = False) -> Any:
    """Place a host-global batch pytree onto the mesh, batch-dim sharded.

    `micro_dim=True` for gradient-accumulation batches shaped
    (accum, global_batch, ...): the accumulation axis stays unsharded (it is
    scanned over in-graph) and dim 1 is the sharded batch.

    Single-process: `batch` holds the full global batch (numpy). Multi-host:
    each process holds its local shard and we assemble the global array from
    per-host shards (`jax.make_array_from_process_local_data`), the moral
    equivalent of per-rank DataLoader shards feeding DDP.
    """
    sharding = (
        _cached_sharding(mesh, batch_spec(mesh, 2, leading_micro=True))
        if micro_dim else batch_sharding(mesh)
    )

    def place(x):
        x = np.asarray(x)
        if jax.process_count() == 1:
            return jax.device_put(x, sharding)
        return jax.make_array_from_process_local_data(sharding, x)

    return jax.tree.map(place, batch)


def fsdp_spec(shape, fsdp_size: int, min_size: int = 2**16) -> P:
    """Pick a PartitionSpec sharding the largest divisible dim over `fsdp`.

    Small arrays (BN scales, biases) stay replicated — same spirit as FSDP's
    size-based auto-wrap policy (accelerate's fsdp_auto_wrap_policy), without
    the module-tree machinery: sharding decisions are per-leaf and static.
    """
    shape = tuple(getattr(shape, "shape", shape))
    if fsdp_size <= 1 or np.prod(shape, dtype=np.int64) < min_size:
        return P()
    dims = sorted(range(len(shape)), key=lambda d: -shape[d])
    for d in dims:
        if shape[d] % fsdp_size == 0:
            spec = [None] * len(shape)
            spec[d] = AXIS_FSDP
            return P(*spec)
    return P()


# --- tensor parallelism (Megatron pattern over the model/tensor axis) -----
#
# Column-parallel layers (qkv, mlp_fc1) shard their output-features dim and
# bias; row-parallel layers (attention out-proj, mlp_fc2) shard the
# input-features dim with a replicated bias — the reference backbone's
# Megatron TP path (accelerate/accelerator.py:1580-1657, accelerator.py:2506)
# expressed as GSPMD layout rules: XLA derives the all-gather/reduce-scatter
# pairs from these annotations instead of hand-written comm hooks.
# The rules key on the module names shared by every transformer family in
# models/ (mvit.py / videomae.py ViTBlock): qkv, proj, mlp_fc1, mlp_fc2.
_TP_COLUMN = frozenset({"qkv", "mlp_fc1"})
_TP_ROW = frozenset({"proj", "mlp_fc2"})

# per-model-family use of the train mesh's `model` axis
# (docs/PARALLELISM.md): transformer families carry the qkv/proj/mlp module
# names the Megatron rules key on, so their attention heads and MLP widths
# split over `model`; every conv family replicates over it (their
# parallelism win is `data` + the fsdp library axis, not head splitting).
_TP_FAMILIES = ("mvit", "videomae")


def family_uses_tp(model_name: str) -> bool:
    """Does this model family spend the `model` axis on Megatron TP?"""
    return model_name.startswith(_TP_FAMILIES)


def _path_names(path) -> tuple:
    """Flax pytree key path -> tuple of name strings."""
    names = []
    for k in path:
        for attr in ("key", "idx", "name"):
            if hasattr(k, attr):
                names.append(str(getattr(k, attr)))
                break
        else:
            names.append(str(k))
    return tuple(names)


def tp_dim(names: tuple, shape: tuple, tensor_size: int) -> Optional[int]:
    """Which dim (if any) of this param shards over `tensor`."""
    if tensor_size <= 1 or len(names) < 2 or len(shape) < 1:
        return None
    module, leaf = names[-2], names[-1]
    # "proj" is also the name of classifier heads (x3d.py:138, resnet heads)
    # and CubeEmbed's patchifying conv (videomae.py:100) — only the attention
    # out-projection inside a transformer block is row-parallel
    if module == "proj" and not (
        len(names) >= 3
        and (names[-3] == "attn" or re.match(r"block\d+$", names[-3]))
    ):
        return None
    if module in _TP_COLUMN:
        if leaf == "kernel" and shape[-1] % tensor_size == 0:
            return len(shape) - 1
        if leaf == "bias" and shape[0] % tensor_size == 0:
            return 0
    if (module in _TP_ROW and leaf == "kernel" and len(shape) >= 2
            and shape[0] % tensor_size == 0):
        return 0
    return None


def param_sharding(mesh: Mesh, params: Any, min_size: int = 2**16,
                   tp: bool = True) -> Any:
    """Sharding tree for a param/opt-state pytree: replicated under pure DP,
    fsdp-sharded (ZeRO-3 equivalent) when the mesh carries an fsdp axis >1,
    and Megatron-style tensor-sharded over the mesh's model-parallel axis
    ("model" on the train mesh / "tensor" on the library mesh) for
    transformer qkv/proj/MLP params (composing with fsdp on a different dim
    where divisible).

    `tp=False` keeps every param off the model-parallel axis even when the
    names match — the context-parallel lane's layout (the model axis is
    spent on token sharding there, never on params; parallel/mesh.cp_axis)
    and the conv families' replicated-model-axis fallback."""
    fsdp_size = mesh.shape[AXIS_FSDP] if AXIS_FSDP in mesh.axis_names else 1
    tp_name = model_axis(mesh)
    tensor_size = mesh.shape[tp_name] if (tp and tp_name is not None) else 1

    def rule(path, x):
        shape = tuple(np.shape(x))
        d = tp_dim(_path_names(path), shape, tensor_size)
        if d is None:
            return NamedSharding(mesh, fsdp_spec(shape, fsdp_size, min_size))
        spec = [None] * len(shape)
        spec[d] = tp_name
        if fsdp_size > 1 and np.prod(shape, dtype=np.int64) >= min_size:
            for other in sorted(range(len(shape)), key=lambda i: -shape[i]):
                if other != d and shape[other] % fsdp_size == 0:
                    spec[other] = AXIS_FSDP
                    break
        return NamedSharding(mesh, P(*spec))

    return jax.tree_util.tree_map_with_path(rule, params)


def shard_params(mesh: Mesh, params: Any, min_size: int = 2**16,
                 tp: bool = True) -> Any:
    """Place a param pytree per `param_sharding`."""
    shardings = param_sharding(mesh, params, min_size, tp=tp)
    return jax.tree.map(jax.device_put, params, shardings)


def state_sharding_like(mesh: Mesh, state: Any, min_size: int = 2**16,
                        tp: bool = True) -> Any:
    """Sharding pytree for an arbitrary train-state pytree (params + opt
    state + scalars): scalars/small leaves replicated, big leaves fsdp-ruled."""
    return param_sharding(mesh, state, min_size, tp=tp)


def shard_state(mesh: Mesh, state: Any, min_size: int = 2**16,
                tp: bool = True) -> Any:
    """Place a WHOLE train-state pytree (params + opt state + step scalar +
    EMA) on the mesh with committed NamedShardings.

    Why every leaf and not just params: a freshly-built state mixes
    shard_params-placed params with leaves optax/TrainState created eagerly
    (step counter, schedule counts, momentum on some paths) that sit as
    *uncommitted single-device* arrays. The first jitted step returns every
    leaf committed to mesh-wide NamedShardings, so the SECOND call sees
    different input layouts and pays a full XLA recompile — one silent
    extra compile (minutes at production model sizes) per training run.
    Settling the layouts here makes call 2 hit call 1's executable; the
    `pva_train_recompiles` gauge (analysis/recompile_guard.py) is the
    regression tripwire."""
    shardings = state_sharding_like(mesh, state, min_size, tp=tp)
    return jax.tree.map(jax.device_put, state, shardings)
