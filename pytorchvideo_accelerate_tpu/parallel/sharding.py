"""NamedSharding rules: the TPU-native `Accelerator.prepare()`.

Where the reference mutates objects (model -> DDP wrap at
`accelerate/accelerator.py:1877-1896`, loader -> BatchSamplerShard at
`data_loader.py:1252-1258`), here `prepare` is a set of pure functions that
place arrays: batches sharded over the DP axes, parameters replicated (or
sharded over `fsdp`), and everything else follows from XLA's propagation.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pytorchvideo_accelerate_tpu.parallel.mesh import AXIS_FSDP, BATCH_AXES


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Leading (batch) dim split over the DP axes — the `BatchSamplerShard`
    equivalent, but as a layout annotation instead of an index-stream slicer."""
    return NamedSharding(mesh, P(BATCH_AXES))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, batch: Any, micro_dim: bool = False) -> Any:
    """Place a host-global batch pytree onto the mesh, batch-dim sharded.

    `micro_dim=True` for gradient-accumulation batches shaped
    (accum, global_batch, ...): the accumulation axis stays unsharded (it is
    scanned over in-graph) and dim 1 is the sharded batch.

    Single-process: `batch` holds the full global batch (numpy). Multi-host:
    each process holds its local shard and we assemble the global array from
    per-host shards (`jax.make_array_from_process_local_data`), the moral
    equivalent of per-rank DataLoader shards feeding DDP.
    """
    sharding = (
        NamedSharding(mesh, P(None, BATCH_AXES)) if micro_dim else batch_sharding(mesh)
    )

    def place(x):
        x = np.asarray(x)
        if jax.process_count() == 1:
            return jax.device_put(x, sharding)
        return jax.make_array_from_process_local_data(sharding, x)

    return jax.tree.map(place, batch)


def fsdp_spec(shape, fsdp_size: int, min_size: int = 2**16) -> P:
    """Pick a PartitionSpec sharding the largest divisible dim over `fsdp`.

    Small arrays (BN scales, biases) stay replicated — same spirit as FSDP's
    size-based auto-wrap policy (accelerate's fsdp_auto_wrap_policy), without
    the module-tree machinery: sharding decisions are per-leaf and static.
    """
    shape = tuple(getattr(shape, "shape", shape))
    if fsdp_size <= 1 or np.prod(shape, dtype=np.int64) < min_size:
        return P()
    dims = sorted(range(len(shape)), key=lambda d: -shape[d])
    for d in dims:
        if shape[d] % fsdp_size == 0:
            spec = [None] * len(shape)
            spec[d] = AXIS_FSDP
            return P(*spec)
    return P()


def param_sharding(mesh: Mesh, params: Any, min_size: int = 2**16) -> Any:
    """Sharding tree for a param/opt-state pytree: replicated under pure DP,
    fsdp-sharded (ZeRO-3 equivalent) when the fsdp axis is >1."""
    fsdp_size = mesh.shape[AXIS_FSDP]

    def rule(x):
        return NamedSharding(mesh, fsdp_spec(np.shape(x), fsdp_size, min_size))

    return jax.tree.map(rule, params)


def shard_params(mesh: Mesh, params: Any, min_size: int = 2**16) -> Any:
    """Place a param pytree per `param_sharding`."""
    shardings = param_sharding(mesh, params, min_size)
    return jax.tree.map(jax.device_put, params, shardings)


def state_sharding_like(mesh: Mesh, state: Any, min_size: int = 2**16) -> Any:
    """Sharding pytree for an arbitrary train-state pytree (params + opt
    state + scalars): scalars/small leaves replicated, big leaves fsdp-ruled."""
    return param_sharding(mesh, state, min_size)
