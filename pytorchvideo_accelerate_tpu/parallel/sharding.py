"""NamedSharding rules: the TPU-native `Accelerator.prepare()`.

Where the reference mutates objects (model -> DDP wrap at
`accelerate/accelerator.py:1877-1896`, loader -> BatchSamplerShard at
`data_loader.py:1252-1258`), here `prepare` is a set of pure functions that
place arrays: batches sharded over the DP axes, parameters replicated (or
sharded over `fsdp`), and everything else follows from XLA's propagation.
"""

from __future__ import annotations

import functools
import re
from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pytorchvideo_accelerate_tpu.parallel.mesh import (
    AXIS_FSDP,
    AXIS_TENSOR,
    BATCH_AXES,
)


@functools.lru_cache(maxsize=64)
def _cached_sharding(mesh: Mesh, spec: P) -> NamedSharding:
    """Memoized NamedSharding construction. `shard_batch` runs once per
    train/eval step (and, with the device prefetcher, on a background
    thread's critical path), so the {mesh, spec} -> NamedSharding pair is
    built once per mesh instead of per call. Mesh and PartitionSpec are both
    hashable; the handful of (mesh, spec) pairs a process ever sees fits
    comfortably in a small LRU."""
    return NamedSharding(mesh, spec)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Leading (batch) dim split over the DP axes — the `BatchSamplerShard`
    equivalent, but as a layout annotation instead of an index-stream slicer."""
    return _cached_sharding(mesh, P(BATCH_AXES))


def replicated(mesh: Mesh) -> NamedSharding:
    return _cached_sharding(mesh, P())


def shard_batch(mesh: Mesh, batch: Any, micro_dim: bool = False) -> Any:
    """Place a host-global batch pytree onto the mesh, batch-dim sharded.

    `micro_dim=True` for gradient-accumulation batches shaped
    (accum, global_batch, ...): the accumulation axis stays unsharded (it is
    scanned over in-graph) and dim 1 is the sharded batch.

    Single-process: `batch` holds the full global batch (numpy). Multi-host:
    each process holds its local shard and we assemble the global array from
    per-host shards (`jax.make_array_from_process_local_data`), the moral
    equivalent of per-rank DataLoader shards feeding DDP.
    """
    sharding = (
        _cached_sharding(mesh, P(None, BATCH_AXES)) if micro_dim
        else batch_sharding(mesh)
    )

    def place(x):
        x = np.asarray(x)
        if jax.process_count() == 1:
            return jax.device_put(x, sharding)
        return jax.make_array_from_process_local_data(sharding, x)

    return jax.tree.map(place, batch)


def fsdp_spec(shape, fsdp_size: int, min_size: int = 2**16) -> P:
    """Pick a PartitionSpec sharding the largest divisible dim over `fsdp`.

    Small arrays (BN scales, biases) stay replicated — same spirit as FSDP's
    size-based auto-wrap policy (accelerate's fsdp_auto_wrap_policy), without
    the module-tree machinery: sharding decisions are per-leaf and static.
    """
    shape = tuple(getattr(shape, "shape", shape))
    if fsdp_size <= 1 or np.prod(shape, dtype=np.int64) < min_size:
        return P()
    dims = sorted(range(len(shape)), key=lambda d: -shape[d])
    for d in dims:
        if shape[d] % fsdp_size == 0:
            spec = [None] * len(shape)
            spec[d] = AXIS_FSDP
            return P(*spec)
    return P()


# --- tensor parallelism (Megatron pattern over the `tensor` axis) ---------
#
# Column-parallel layers (qkv, mlp_fc1) shard their output-features dim and
# bias; row-parallel layers (attention out-proj, mlp_fc2) shard the
# input-features dim with a replicated bias — the reference backbone's
# Megatron TP path (accelerate/accelerator.py:1580-1657, accelerator.py:2506)
# expressed as GSPMD layout rules: XLA derives the all-gather/reduce-scatter
# pairs from these annotations instead of hand-written comm hooks.
# The rules key on the module names shared by every transformer family in
# models/ (mvit.py / videomae.py ViTBlock): qkv, proj, mlp_fc1, mlp_fc2.
_TP_COLUMN = frozenset({"qkv", "mlp_fc1"})
_TP_ROW = frozenset({"proj", "mlp_fc2"})


def _path_names(path) -> tuple:
    """Flax pytree key path -> tuple of name strings."""
    names = []
    for k in path:
        for attr in ("key", "idx", "name"):
            if hasattr(k, attr):
                names.append(str(getattr(k, attr)))
                break
        else:
            names.append(str(k))
    return tuple(names)


def tp_dim(names: tuple, shape: tuple, tensor_size: int) -> Optional[int]:
    """Which dim (if any) of this param shards over `tensor`."""
    if tensor_size <= 1 or len(names) < 2 or len(shape) < 1:
        return None
    module, leaf = names[-2], names[-1]
    # "proj" is also the name of classifier heads (x3d.py:138, resnet heads)
    # and CubeEmbed's patchifying conv (videomae.py:100) — only the attention
    # out-projection inside a transformer block is row-parallel
    if module == "proj" and not (
        len(names) >= 3
        and (names[-3] == "attn" or re.match(r"block\d+$", names[-3]))
    ):
        return None
    if module in _TP_COLUMN:
        if leaf == "kernel" and shape[-1] % tensor_size == 0:
            return len(shape) - 1
        if leaf == "bias" and shape[0] % tensor_size == 0:
            return 0
    if (module in _TP_ROW and leaf == "kernel" and len(shape) >= 2
            and shape[0] % tensor_size == 0):
        return 0
    return None


def param_sharding(mesh: Mesh, params: Any, min_size: int = 2**16) -> Any:
    """Sharding tree for a param/opt-state pytree: replicated under pure DP,
    fsdp-sharded (ZeRO-3 equivalent) when the fsdp axis is >1, and
    Megatron-style tensor-sharded over `tensor` for transformer qkv/proj/MLP
    params (composing with fsdp on a different dim where divisible)."""
    fsdp_size = mesh.shape[AXIS_FSDP]
    tensor_size = mesh.shape.get(AXIS_TENSOR, 1)

    def rule(path, x):
        shape = tuple(np.shape(x))
        d = tp_dim(_path_names(path), shape, tensor_size)
        if d is None:
            return NamedSharding(mesh, fsdp_spec(shape, fsdp_size, min_size))
        spec = [None] * len(shape)
        spec[d] = AXIS_TENSOR
        if fsdp_size > 1 and np.prod(shape, dtype=np.int64) >= min_size:
            for other in sorted(range(len(shape)), key=lambda i: -shape[i]):
                if other != d and shape[other] % fsdp_size == 0:
                    spec[other] = AXIS_FSDP
                    break
        return NamedSharding(mesh, P(*spec))

    return jax.tree_util.tree_map_with_path(rule, params)


def shard_params(mesh: Mesh, params: Any, min_size: int = 2**16) -> Any:
    """Place a param pytree per `param_sharding`."""
    shardings = param_sharding(mesh, params, min_size)
    return jax.tree.map(jax.device_put, params, shardings)


def state_sharding_like(mesh: Mesh, state: Any, min_size: int = 2**16) -> Any:
    """Sharding pytree for an arbitrary train-state pytree (params + opt
    state + scalars): scalars/small leaves replicated, big leaves fsdp-ruled."""
    return param_sharding(mesh, state, min_size)


def shard_state(mesh: Mesh, state: Any, min_size: int = 2**16) -> Any:
    """Place a WHOLE train-state pytree (params + opt state + step scalar +
    EMA) on the mesh with committed NamedShardings.

    Why every leaf and not just params: a freshly-built state mixes
    shard_params-placed params with leaves optax/TrainState created eagerly
    (step counter, schedule counts, momentum on some paths) that sit as
    *uncommitted single-device* arrays. The first jitted step returns every
    leaf committed to mesh-wide NamedShardings, so the SECOND call sees
    different input layouts and pays a full XLA recompile — one silent
    extra compile (minutes at production model sizes) per training run.
    Settling the layouts here makes call 2 hit call 1's executable; the
    `pva_train_recompiles` gauge (analysis/recompile_guard.py) is the
    regression tripwire."""
    shardings = state_sharding_like(mesh, state, min_size)
    return jax.tree.map(jax.device_put, state, shardings)
