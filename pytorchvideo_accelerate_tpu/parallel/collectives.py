"""Collective helpers.

The reference funnels collectives through a Python facade
(`accelerate/utils/operations.py:322-357` gather, `accelerator.py:3141,3178`
reduce/broadcast) calling NCCL. TPU-native, in-graph collectives are just
`lax.psum/pmean/all_gather/ppermute` under `shard_map` — XLA schedules them on
ICI. This module keeps (a) thin in-graph wrappers for code written with
`shard_map`, and (b) host-level out-of-band helpers for metric fetch across
processes.

Note the design inversion for metrics: the reference gathers *per-sample*
predictions to every rank and feeds a stateful torchmetrics object
(run.py:298) — which double-counts DistributedSampler padding (SURVEY §2.1).
Here eval metrics are accumulated inside the compiled step as (correct, total)
sums over the sharded batch (trainer/metrics.py), so there is nothing to
gather and no padding bias.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax


def psum(x: Any, axis_name) -> Any:
    return lax.psum(x, axis_name)


def pmean(x: Any, axis_name) -> Any:
    return lax.pmean(x, axis_name)


def all_gather(x: Any, axis_name, axis: int = 0, tiled: bool = True) -> Any:
    """`accelerator.gather` equivalent for shard_map code paths."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def ppermute_ring(x: Any, axis_name, shift: int = 1) -> Any:
    """Rotate values around the mesh axis ring (ring-attention building block)."""
    n = lax.axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def host_allgather(x: Any) -> Any:
    """Out-of-band cross-process gather (DCN), for host-side logging only."""
    if jax.process_count() == 1:
        return jax.tree.map(lambda a: jnp.asarray(a)[None], x)
    from jax.experimental import multihost_utils

    return multihost_utils.process_allgather(x)
