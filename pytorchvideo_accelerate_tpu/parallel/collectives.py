"""Collective helpers.

The reference funnels collectives through a Python facade
(`accelerate/utils/operations.py:322-357` gather, `accelerator.py:3141,3178`
reduce/broadcast) calling NCCL. TPU-native, in-graph collectives are just
`lax.psum/pmean/all_gather/ppermute` under `shard_map` — XLA schedules them on
ICI. This module keeps (a) thin in-graph wrappers for code written with
`shard_map`, and (b) host-level out-of-band helpers for metric fetch across
processes.

Note the design inversion for metrics: the reference gathers *per-sample*
predictions to every rank and feeds a stateful torchmetrics object
(run.py:298) — which double-counts DistributedSampler padding (SURVEY §2.1).
Here eval metrics are accumulated inside the compiled step as (correct, total)
sums over the sharded batch (trainer/metrics.py), so there is nothing to
gather and no padding bias.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def axis_size(axis_name) -> int:
    """`lax.axis_size` with a pinned-jax (0.4.x) fallback: `psum(1, axis)`
    is the classic static axis-size idiom (folds to a trace-time constant —
    no runtime collective)."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def shard_map(f, *, mesh, in_specs, out_specs):
    """Version-compat shard_map: `jax.shard_map(..., check_vma=)` is the
    current API, but the pinned jax (0.4.x) only ships
    `jax.experimental.shard_map.shard_map(..., check_rep=)` — and its
    deprecation shim raises AttributeError rather than forwarding.
    Replication checking stays off either way: the context-parallel kernels
    do their own masking, and the check rejects the padded-K path (see the
    all_gather note above)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def psum(x: Any, axis_name) -> Any:
    return lax.psum(x, axis_name)


def pmean(x: Any, axis_name) -> Any:
    return lax.pmean(x, axis_name)


def all_gather(x: Any, axis_name, axis: int = 0, tiled: bool = True) -> Any:
    """`accelerator.gather` equivalent for shard_map code paths.

    Note (jax >= 0.8 shard_map varying-mesh-axes checking): the gathered
    value is identical on every shard but still *tracked* as varying over
    `axis_name`, so returning it directly with `out_specs=P()` fails the
    static replication check. Either consume it inside the shard_map (the
    usual case — e.g. ring attention), or pass `check_vma=False` to
    `jax.shard_map` when you really want the replicated gather as an
    output (tested in tests/test_mesh_sharding.py)."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def ppermute_ring(x: Any, axis_name, shift: int = 1) -> Any:
    """Rotate values around the mesh axis ring (ring-attention building block)."""
    n = axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def host_allgather(x: Any) -> Any:
    """Out-of-band cross-process gather (DCN), for host-side logging only.

    Watched: a straggler host wedges every peer inside this call, so it
    runs under the collective-hang detector's attributed section
    (`parallel/hangcheck.py`) — a stall dumps `host_allgather host=i/n`
    instead of anonymous silence."""
    from pytorchvideo_accelerate_tpu.parallel.hangcheck import (
        collective_section,
    )

    with collective_section("host_allgather"):
        if jax.process_count() == 1:
            return jax.tree.map(lambda a: jnp.asarray(a)[None], x)
        from jax.experimental import multihost_utils

        return multihost_utils.process_allgather(x)


def host_broadcast(x: Any) -> Any:
    """`accelerator.broadcast` equivalent, host level: every process returns
    process 0's value (run-name, resolved checkpoint path, sampled seed —
    anything one host decides for all).

    Numeric leaves ride `multihost_utils.broadcast_one_to_all` and come back
    as numpy arrays on EVERY process — including single-process runs, so dev
    and pod behavior can't diverge. str/bytes leaves (which psum-based
    broadcast can't carry) are broadcast as length then a uint8 buffer and
    come back as str/bytes.

    Watched (`parallel/hangcheck.py`): the resume-time broadcast is the
    classic place a pod wedges when one host's checkpoint scan hangs —
    the hang detector attributes it per host instead of letting the
    external timeout kill blind."""
    from jax.experimental import multihost_utils

    from pytorchvideo_accelerate_tpu.parallel.hangcheck import (
        collective_section,
    )

    bcast_raw = multihost_utils.broadcast_one_to_all

    def bcast(v):
        with collective_section("host_broadcast"):
            return bcast_raw(v)

    leaves, treedef = jax.tree.flatten(x)
    out = list(leaves)
    num_idx = [i for i, v in enumerate(leaves)
               if not isinstance(v, (str, bytes))]
    str_idx = [i for i, v in enumerate(leaves) if isinstance(v, (str, bytes))]
    # one collective for ALL numeric leaves (broadcast_one_to_all takes a
    # pytree) + one for all string lengths; only the string buffers (rare)
    # need a round trip each, since their shapes depend on root's lengths
    if num_idx:
        nums = bcast([leaves[i] for i in num_idx])
        for i, v in zip(num_idx, nums):
            out[i] = np.asarray(v)
    if str_idx:
        raws = [leaves[i].encode("utf-8") if isinstance(leaves[i], str)
                else bytes(leaves[i]) for i in str_idx]
        lens = [int(n) for n in np.asarray(
            bcast(np.array([len(r) for r in raws], np.int64)))]
        for i, raw, n in zip(str_idx, raws, lens):
            buf = np.zeros(n, np.uint8)
            data = np.frombuffer(raw, np.uint8)
            buf[: min(data.size, n)] = data[:n]
            res = bytes(np.asarray(bcast(buf), np.uint8))
            out[i] = (res.decode("utf-8") if isinstance(leaves[i], str)
                      else res)
    return jax.tree.unflatten(treedef, out)


def host_reduce_sum(x: Any) -> Any:
    """`accelerator.reduce(op="sum")` equivalent for host-side counters
    (clips decoded, batches dropped): sums each leaf across processes."""
    gathered = host_allgather(x)
    return jax.tree.map(lambda a: a.sum(axis=0), gathered)
