"""Per-host collective-schedule recorder + cross-host differ.

The multi-host failure mode `pva-tpu-spmdcheck` exists for: N processes
must execute IDENTICAL ordered collective schedules, and the host that
skips one `psum` behind a `process_index()==0` branch (or a per-host
file-existence check, or an exception path) deadlocks the whole pod with
no evidence beyond "everything is wedged". hangcheck (PR 9) attributes
the wedge AFTER it happens; this module records the evidence that says
WHY: every `hangcheck.collective_section` entry appends one
`(tick, op, detail)` record to the installed recorder under the current
host label, and `diff_schedules` compares the per-host streams and
reports the FIRST divergence with both hosts' trailing windows — the
exact op one host issued that the other never did.

Arm/disarm follows the watchdog discipline in `parallel/hangcheck.py`:
disarmed (the default) costs ONE module-global read inside
`collective_section` and records nothing; `install_schedule_recorder`
routes every section entry here. Single-process lanes (the forced-host
MULTICHIP bench, chaos legs, the spmdcheck selftest) record several
EMULATED hosts by replaying the same deterministic segment under
`recorder.as_host(...)` labels — run-to-run schedule determinism is the
property a real pod needs from every host, so the emulation diffs the
real thing, it just manufactures the host axis sequentially.

See docs/STATIC_ANALYSIS.md § spmdcheck and docs/PARALLELISM.md
§ multi-host readiness.
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

from pytorchvideo_accelerate_tpu.utils.sync import make_lock

# entries shown on each side of a first-divergence report: enough trailing
# context to see the schedule drift without dumping whole epochs
DEFAULT_WINDOW = 5


class CollectiveScheduleRecorder:
    """Ordered (tick, op, detail) records per host label.

    One instance records one experiment; hosts are keyed by label
    (`host=i/n`, the `hangcheck.host_tag()` format). In a real pod every
    process records exactly one host; emulated lanes switch labels with
    `as_host` between replays of the same segment.
    """

    def __init__(self, host: str = "host=0/1"):
        self._lock = make_lock("CollectiveScheduleRecorder._lock")
        self._host = host
        self._schedules: Dict[str, List[Tuple[int, str, str]]] = {}

    # --- recording --------------------------------------------------------

    def record(self, op: str, detail: str = "") -> None:
        """Append one schedule point under the current host label (called
        by `hangcheck.collective_section` at section ENTRY — issue order,
        not completion order, is the schedule)."""
        with self._lock:
            sched = self._schedules.setdefault(self._host, [])
            sched.append((len(sched), str(op), str(detail)))

    def set_host(self, host: str) -> None:
        with self._lock:
            self._host = str(host)

    @contextmanager
    def as_host(self, host: str):
        """Record the enclosed segment under an emulated host label (the
        forced-host MULTICHIP lane / selftest replay mechanism)."""
        with self._lock:
            prev, self._host = self._host, str(host)
        try:
            yield self
        finally:
            with self._lock:
                self._host = prev

    # --- reading ----------------------------------------------------------

    def hosts(self) -> List[str]:
        with self._lock:
            return sorted(self._schedules)

    def schedule(self, host: Optional[str] = None) -> List[Tuple[int, str, str]]:
        with self._lock:
            return list(self._schedules.get(host or self._host, ()))

    def schedules(self) -> Dict[str, List[Tuple[int, str, str]]]:
        with self._lock:
            return {h: list(s) for h, s in self._schedules.items()}

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return {h: len(s) for h, s in self._schedules.items()}

    def clear(self) -> None:
        with self._lock:
            self._schedules.clear()

    def snapshot(self) -> dict:
        """Doctor view: per-host record counts + each host's last entry."""
        with self._lock:
            return {
                "hosts": sorted(self._schedules),
                "counts": {h: len(s) for h, s in self._schedules.items()},
                "last": {h: list(s[-1]) for h, s in self._schedules.items()
                         if s},
            }


def install_schedule_recorder(recorder: CollectiveScheduleRecorder) -> None:
    """Route every `collective_section` entry to `recorder` (arm)."""
    from pytorchvideo_accelerate_tpu.parallel import hangcheck

    hangcheck._set_schedule_recorder(recorder)


def uninstall_schedule_recorder() -> None:
    from pytorchvideo_accelerate_tpu.parallel import hangcheck

    hangcheck._set_schedule_recorder(None)


def current_recorder() -> Optional[CollectiveScheduleRecorder]:
    from pytorchvideo_accelerate_tpu.parallel import hangcheck

    return hangcheck._schedule_recorder()


# --- cross-host differ ------------------------------------------------------

def diff_schedules(schedules: Dict[str, List[Tuple[int, str, str]]],
                   window: int = DEFAULT_WINDOW) -> dict:
    """Compare per-host ordered schedules; report the first divergence.

    The lexicographically-first host is the reference (every host is
    equally authoritative on a healthy pod — identical schedules make the
    choice irrelevant, and a deterministic choice keeps the report
    stable). Returns::

        {"diverged": bool,
         "divergence_count": int,        # hosts that drifted from the ref
         "hosts": [...], "lengths": {host: n},
         "first_divergence": None | {
             "tick": int,                # first disagreeing position
             "hosts": {host: [tick, op, detail] | None},  # None = missing
             "window": {host: trailing entries up to the tick},
         }}

    A host whose schedule simply ENDS early counts as divergent at the
    first missing tick (`hosts[h] is None`) — that is the skipped-
    collective deadlock shape, not a benign short run.
    """
    hosts = sorted(schedules)
    report: dict = {
        "diverged": False,
        "divergence_count": 0,
        "hosts": hosts,
        "lengths": {h: len(schedules[h]) for h in hosts},
        "first_divergence": None,
    }
    if len(hosts) < 2:
        return report
    ref_host, others = hosts[0], hosts[1:]
    ref = schedules[ref_host]
    diverged_hosts = set()
    first: Optional[dict] = None
    for h in others:
        sched = schedules[h]
        n = max(len(ref), len(sched))
        for i in range(n):
            a = ref[i] if i < len(ref) else None
            b = sched[i] if i < len(sched) else None
            # compare (op, detail) — the tick is positional by construction
            if (a and a[1:]) == (b and b[1:]):
                continue
            diverged_hosts.add(h)
            if first is None or i < first["tick"]:
                first = {
                    "tick": i,
                    "hosts": {ref_host: list(a) if a else None,
                              h: list(b) if b else None},
                    "window": {
                        ref_host: [list(e) for e in ref[max(0, i - window):i + 1]],
                        h: [list(e) for e in sched[max(0, i - window):i + 1]],
                    },
                }
            break
    if diverged_hosts:
        report["diverged"] = True
        report["divergence_count"] = len(diverged_hosts)
        report["first_divergence"] = first
    return report


def publish_schedule_report(report: dict) -> None:
    """`pva_spmd_schedule_divergence` gauge + a flight-ring event on any
    divergence (the graphcheck/tsan publish discipline; telemetry stays
    optional)."""
    try:
        from pytorchvideo_accelerate_tpu import obs

        obs.get_registry().gauge(
            "pva_spmd_schedule_divergence",
            "hosts whose recorded collective schedule diverged from the "
            "reference in the last diff (0 == identical schedules)",
        ).set(float(report.get("divergence_count", 0)))
        if report.get("diverged"):
            first = report.get("first_divergence") or {}
            obs.get_recorder().record(
                "spmd", "schedule divergence",
                divergence_count=report.get("divergence_count", 0),
                tick=first.get("tick"),
                hosts={h: (e[1] if e else None)
                       for h, e in (first.get("hosts") or {}).items()})
    except Exception:  # telemetry must never fail the differ
        pass


def format_divergence(report: dict) -> str:
    """One readable paragraph per divergence report (chaos legs / CLI)."""
    if not report.get("diverged"):
        return (f"schedules identical across {len(report.get('hosts', []))} "
                f"host(s): {report.get('lengths')}")
    first = report.get("first_divergence") or {}
    lines = [f"collective-schedule divergence across "
             f"{report.get('divergence_count')} host(s) at tick "
             f"{first.get('tick')}:"]
    for h, entry in sorted((first.get("hosts") or {}).items()):
        if entry is None:
            lines.append(f"  {h}: <no collective issued — schedule ended>")
        else:
            lines.append(f"  {h}: op={entry[1]!r} detail={entry[2]!r}")
    for h, win in sorted((first.get("window") or {}).items()):
        tail = ", ".join(f"{t}:{op}" for t, op, _ in win)
        lines.append(f"  {h} trailing window: [{tail}]")
    return "\n".join(lines)
