"""Collective-hang detection for the multichip lane.

A wedged `psum` looks exactly like a slow input pipeline from the outside:
the process sits idle, the external `timeout -k` eventually kills it blind,
and the postmortem can't say whether a straggler host, a dead ICI link, or
a starved decode pool was at fault. The obs watchdog (PR 3) already knows
*that* nothing progressed; this module tells it *where*: every host-side
point where the trainer blocks on a mesh collective — the step dispatch
when the queue pushes back, the epoch-end value fetch, the out-of-band
host collectives in `parallel/collectives.py` — wraps itself in
`collective_section(op)`, an attributed `Watchdog.section` whose detail
carries the op name and this host's process index. On a stall the
watchdog's dump then reads

    [watchdog] collective wedged inside 'epoch_sync host=3/16 step=1200' ...

— per-host attribution BEFORE the external kill, distinguishing a wedged
collective from slow input (whose stall attributes to the prefetchers'
components instead). See docs/RELIABILITY.md § collective hangs.

Disarmed (no watchdog installed — the default), `collective_section` costs
three module-global reads (the watchdog slot, the schedule-recorder slot,
and the `collective.sync` fault point) and yields straight through: the
`utils/sync.py` / `faults.py` zero-overhead discipline. The
`collective.sync` fault point (kind ``delay``) is how `pva-tpu-chaos`'s
wedged-collective leg manufactures the straggler deterministically.

The schedule-recorder slot is `pva-tpu-spmdcheck`'s dynamic half
(parallel/schedule_recorder.py): armed, every section entry appends one
(tick, op, detail) record under the current host label, so a cross-host
diff can name the first collective one host issued that another never
did — the divergence evidence the static pass cannot produce.
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Optional

from pytorchvideo_accelerate_tpu.reliability.faults import fault_point

# the watchdog component every watched collective reports under — one name,
# so "the collective layer is wedged" is a single verdict with the op in
# the section detail (per-host: each process runs its own watchdog)
COMPONENT = "collective"

_watchdog = None
# pva-tpu-spmdcheck's schedule recorder (parallel/schedule_recorder.py);
# installed/uninstalled ONLY through that module's install helpers
_recorder = None


def _set_schedule_recorder(recorder) -> None:
    global _recorder
    _recorder = recorder


def _schedule_recorder():
    return _recorder


def install_collective_watch(watchdog) -> None:
    """Route watched collectives through `watchdog.section` (the trainer
    installs its obs watchdog here; None-safe no-op)."""
    global _watchdog
    _watchdog = watchdog


def uninstall_collective_watch() -> None:
    global _watchdog
    _watchdog = None


def current_watchdog() -> Optional[object]:
    return _watchdog


def host_tag() -> str:
    """`host=i/n` attribution — the ONE formatting of per-host identity
    every watched collective (and the trainer's step-dispatch section)
    shares, so stall dumps parse uniformly. Lazy: only built when a
    watchdog is live, and never dies of a pre-init backend."""
    try:
        import jax

        return f"host={jax.process_index()}/{jax.process_count()}"
    except Exception:  # pragma: no cover - pre-init / jax-free callers
        return "host=?"


@contextmanager
def collective_section(op: str, **info):
    """Attributed window around one host-blocking collective operation.

    The `collective.sync` fault point fires INSIDE the section (after the
    watchdog has marked it open) so an injected ``delay`` is
    indistinguishable from a real straggler to the detector — the chaos
    leg's whole point."""
    wd = _watchdog
    rec = _recorder
    if rec is not None:
        # record at ENTRY: issue order (not completion order) is the
        # schedule a pod's hosts must agree on
        rec.record(op, "".join(f" {k}={v}" for k, v in info.items()).strip())
    if wd is None:
        fault_point("collective.sync")
        yield
        return
    extra = "".join(f" {k}={v}" for k, v in info.items())
    with wd.section(COMPONENT, f"{op} {host_tag()}{extra}"):
        fault_point("collective.sync")
        yield
