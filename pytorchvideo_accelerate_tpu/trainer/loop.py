"""The training application: epoch loop, eval, resume, logging, teardown.

TPU-native re-design of the reference `training_function` (run.py:121-325),
preserving its control-surface semantics — checkpointing_steps int|"epoch",
resume-from-checkpoint (plus a working "auto"), limit_train/val_batches,
log_every, freeze_backbone, with_tracking, main-process progress bar — over
the pure-step runtime: mesh + sharded batches + compiled steps + orbax.
"""

from __future__ import annotations

import os
import time
from contextlib import nullcontext
from typing import Dict, Optional

import jax
import numpy as np

from pytorchvideo_accelerate_tpu import obs
from pytorchvideo_accelerate_tpu.obs import memory as obs_memory
from pytorchvideo_accelerate_tpu.analysis.recompile_guard import RecompileGuard
from pytorchvideo_accelerate_tpu.config import TrainConfig
from pytorchvideo_accelerate_tpu.data.manifest import from_list, scan_directory
from pytorchvideo_accelerate_tpu.data.pipeline import (
    ClipLoader,
    LoaderState,
    SyntheticClipSource,
    VideoClipSource,
)
from pytorchvideo_accelerate_tpu.data.device_prefetch import DevicePrefetcher
from pytorchvideo_accelerate_tpu.data.transforms import make_transform
from pytorchvideo_accelerate_tpu.models import create_model, model_input_spec
from pytorchvideo_accelerate_tpu.parallel.distributed import (
    initialize_distributed,
    is_main_process,
    main_print,
)
from pytorchvideo_accelerate_tpu.parallel.mesh import (
    cp_axis,
    data_shard_count,
    make_train_mesh,
    model_axis,
)
from pytorchvideo_accelerate_tpu.parallel.pipeline import (
    analytic_bubble_frac,
    make_plan as make_pipeline_plan,
    stage_tag,
)
from pytorchvideo_accelerate_tpu.parallel.sharding import (
    family_uses_tp,
    shard_params,
    shard_state,
)
from pytorchvideo_accelerate_tpu.parallel.hangcheck import (
    collective_section,
    host_tag as hangcheck_host_tag,
    install_collective_watch,
    uninstall_collective_watch,
)
from pytorchvideo_accelerate_tpu.reliability.faults import fault_point
from pytorchvideo_accelerate_tpu.reliability.guard import (
    TrainGuard,
    poison_batch,
)
from pytorchvideo_accelerate_tpu.reliability.preemption import (
    get_guard,
    record_emergency,
)
from pytorchvideo_accelerate_tpu.trainer.checkpoint import (
    Checkpointer,
    resolve_resume_path,
)
from pytorchvideo_accelerate_tpu.trainer.metrics import MeanLoss, SumMetrics
from pytorchvideo_accelerate_tpu.trainer.optim import build_lr_schedule, build_optimizer
from pytorchvideo_accelerate_tpu.trainer.steps import (
    make_eval_step,
    make_pretrain_eval_step,
    make_pretrain_step,
    make_train_step,
)
from pytorchvideo_accelerate_tpu.trainer.tracking import (
    DeferredStepLogger,
    TrackerHub,
)
from pytorchvideo_accelerate_tpu.trainer.train_state import TrainState
from pytorchvideo_accelerate_tpu.utils.bench_setup import fetch_loss
from pytorchvideo_accelerate_tpu.utils.logging import get_logger
from pytorchvideo_accelerate_tpu.utils.rng import RngManager, set_seed

logger = get_logger("pva_tpu")


def _parse_checkpointing_steps(value: str):
    """Reference parsing semantics (run.py:123-133): "" -> None, "epoch" ->
    "epoch", digits -> int, else error. "0" normalizes to None (disabled)
    here at parse time — the reference would crash on `step % 0`."""
    if not value:
        return None
    if value == "epoch":
        return "epoch"
    if value.isdigit():
        return int(value) or None
    raise ValueError(
        f"checkpointing_steps must be a number or 'epoch', got {value!r}"
    )


class Trainer:
    """Builds the whole stack from a TrainConfig and runs fit()."""

    def __init__(self, cfg: TrainConfig):
        self.cfg = cfg
        # self-supervised objective (VideoMAE): no labels, model computes its
        # own loss; the supervised path is the reference's only mode
        self.is_pretraining = cfg.model.name.endswith("_pretrain")
        self.checkpointing_steps = _parse_checkpointing_steps(
            cfg.checkpoint.checkpointing_steps
        )
        # telemetry spine (obs/): configured FIRST so construction-time
        # spans land in the window and the flight recorder catches
        # init-time crashes; the watchdog (opt-in deadline) is created
        # before the data stack so the prefetchers can ping it
        self.obs_on = cfg.obs.enabled
        obs.configure(enabled=cfg.obs.enabled,
                      capacity=cfg.obs.flight_recorder_events)
        # pva-tpu-hbm: arm the device-memory ledger (allocation sites in the
        # prefetch ring / engines / this file start accounting), the
        # scrape-tick history ring, and — only when a window is requested —
        # the on-demand profiler. All three are disarmed no-ops otherwise
        # (one module-global read per hook, the sync.py discipline).
        if self.obs_on and cfg.obs.memory_ledger:
            obs.memory.configure(recorder=obs.get_recorder())
        if self.obs_on and cfg.obs.history_ticks > 0:
            obs.history.configure(capacity=cfg.obs.history_ticks)
        # validate --obs.profile_steps at construction (a typo'd window must
        # fail now, not 3 epochs in); run-relative step offsets A..B
        self.profile_steps = obs.profiler.parse_steps(cfg.obs.profile_steps)
        if self.profile_steps is not None:
            obs.profiler.configure(output_dir=cfg.checkpoint.output_dir,
                                   recorder=obs.get_recorder())
        self.watchdog: Optional[obs.Watchdog] = None
        if self.obs_on and cfg.obs.trace_sample_rate > 0:
            # distributed tracing (obs/trace.py): head-sample train steps;
            # each sampled step becomes a root span tagged (epoch, gstep)
            # whose child spans (step/log/h2d) correlate with the
            # jax.profiler.StepTraceAnnotation window of the same gstep.
            # The ring dumps to <output_dir>/trace_ring.json at fit() exit.
            obs.trace.configure_tracing(
                cfg.obs.trace_sample_rate, seed=cfg.seed,
                capacity=cfg.obs.trace_ring_events,
                output_dir=cfg.checkpoint.output_dir)
        if self.obs_on:
            obs.get_recorder().install(cfg.checkpoint.output_dir)
            if cfg.obs.watchdog_timeout_s > 0:
                self.watchdog = obs.Watchdog(
                    cfg.obs.watchdog_timeout_s,
                    output_dir=cfg.checkpoint.output_dir,
                    recorder=obs.get_recorder(),
                    collector=obs.get_collector(),
                ).start()
                # collective-hang detection (parallel/hangcheck.py): every
                # watched mesh-collective boundary — step dispatch under
                # queue push-back, epoch-end value fetch, host collectives
                # — reports through an attributed watchdog section, so a
                # wedged psum dumps per-host evidence instead of silence
                install_collective_watch(self.watchdog)
        if cfg.cpu:
            jax.config.update("jax_platforms", "cpu")
        if cfg.device_init_timeout > 0 and not cfg.cpu:
            # fail loudly instead of wedging: device backend init can hang
            # forever (observed: PJRT client-create never returning while
            # the process shows no error — PROBES_r05.md). Probe in a
            # disposable subprocess first; raises with the diagnosis
            # recipe if init can't complete in time. (SURVEY §5 failure
            # detection — the reference's torch/NCCL stack fails loudly
            # on a bad device; jax would just sit there.)
            from pytorchvideo_accelerate_tpu.utils import device_doctor

            device_doctor.assert_device_reachable(
                cfg.device_init_timeout, log=logger.info)
        if cfg.debug_nans:
            jax.config.update("jax_debug_nans", True)
        if cfg.compilation_cache_dir:
            try:
                jax.config.update("jax_compilation_cache_dir",
                                  cfg.compilation_cache_dir)
            except Exception:  # flag availability varies by jax version
                logger.warning("persistent compilation cache unavailable "
                               "(jax_compilation_cache_dir rejected)")
            else:
                try:  # threshold flag is best-effort on top of the cache
                    jax.config.update(
                        "jax_persistent_cache_min_compile_time_secs", 1.0)
                except Exception:
                    logger.warning(
                        "compilation cache active, but min-compile-time "
                        "threshold flag unavailable (using jax defaults)")

        initialize_distributed(
            cfg.coordinator_address, cfg.num_processes, cfg.process_id
        )
        set_seed(cfg.seed)
        self.rng = RngManager(cfg.seed)
        # the 2-D (data, model) GSPMD backbone (parallel/mesh.py;
        # docs/PARALLELISM.md). A legacy fsdp/tensor/context MeshConfig
        # still resolves to the 4-axis library mesh — every consumer below
        # resolves axes from the mesh itself.
        self.mesh = make_train_mesh(cfg.mesh)
        # how the model axis is spent is a per-family decision
        # (parallel/sharding.py): transformer families split heads/MLP
        # widths over it (Megatron TP) — UNLESS the context-parallel lane
        # is on AND spends that same axis on token sharding (the 2-D train
        # mesh, where "model" is the CP axis; params replicated) — and
        # conv families replicate over it. On the library mesh CP has its
        # own "context" axis, so TP over "tensor" composes with it.
        self._cp = cfg.model.attention in ("ring", "ulysses")
        m_axis = model_axis(self.mesh)
        cp_spends_model_axis = (
            self._cp and m_axis is not None and cp_axis(self.mesh) == m_axis
        )
        # pipeline parallelism (parallel/pipeline.py): stages SPEND the
        # model axis — params stay replicated over it (no Megatron TP),
        # and on the 2-D train mesh CP is excluded too (make_plan raises);
        # on the library mesh CP keeps its own "context" axis and composes
        self.pipeline_plan = None
        if cfg.parallel.pipeline_stages > 1:
            self.pipeline_plan = make_pipeline_plan(
                self.mesh, cfg.parallel.pipeline_stages,
                microbatches=cfg.parallel.pipeline_microbatches,
                accum_steps=cfg.optim.gradient_accumulation_steps,
                cp_axis_name=cp_axis(self.mesh) if self._cp else None,
            )
        pipelined = self.pipeline_plan is not None
        self._tp = (family_uses_tp(cfg.model.name)
                    and not cp_spends_model_axis and not pipelined)
        m_size = self.mesh.shape[m_axis] if m_axis else 1
        mode = (f"pipelined ({self.pipeline_plan.stages} stages, "
                f"{self.pipeline_plan.microbatches} microbatches)"
                if pipelined
                else "context-parallel" if cp_spends_model_axis
                else "tensor-parallel" if self._tp else "replicated")
        main_print(
            f"mesh: {dict(self.mesh.shape)} over {self.mesh.size} "
            f"{jax.devices()[0].platform} devices, "
            f"{jax.process_count()} process(es)"
            + (f"; model axis ({m_size}): {mode}" if m_size > 1 else "")
        )

        self._build_data()
        self._build_model_and_steps()

        self.checkpointer: Optional[Checkpointer] = None
        if self.checkpointing_steps is not None or cfg.checkpoint.resume_from_checkpoint:
            ckpt_dir = os.path.join(cfg.checkpoint.output_dir, "checkpoints")
            resume_dir = resolve_resume_path(
                cfg.checkpoint.resume_from_checkpoint, ckpt_dir
            )
            self.checkpointer = Checkpointer(
                resume_dir or ckpt_dir,
                max_to_keep=cfg.checkpoint.max_to_keep,
                use_async=cfg.checkpoint.async_checkpoint,
                retries=cfg.reliability.ckpt_retries,
                retry_base_delay_s=cfg.reliability.retry_base_delay_s,
                retry_max_delay_s=cfg.reliability.retry_max_delay_s,
                retry_deadline_s=cfg.reliability.retry_deadline_s,
            )

        # self-healing guard (reliability/guard.py; docs/RELIABILITY.md
        # § divergence runbook): LKG ring + anomaly rollback + replay
        # bundles. None when disarmed — the step loop then does one
        # `is None` check (structural zero overhead).
        # Deliberately NOT a MemoryLedger component: the LKG ring is an
        # orbax checkpoint ring on DISK (<output_dir>/guard_lkg), and a
        # rollback's transient restore buffer replaces the live TrainState
        # already accounted above — registering either as HBM would fake
        # device bytes (docs/OBSERVABILITY.md § memory ledger).
        self.train_guard: Optional[TrainGuard] = None
        if cfg.guard.enabled:
            self.train_guard = TrainGuard(
                cfg.guard, output_dir=cfg.checkpoint.output_dir,
                mesh=self.mesh, tp=self._tp, config_dict=cfg.to_dict(),
                seed=cfg.seed)
            self.train_guard.quarantine = self.quarantine

        # user-registered checkpoint participants (reference
        # `accelerator.register_for_checkpointing`, run.py:199)
        self._registered: dict = {}
        self._flops_per_step: Optional[float] = None  # XLA cost model, lazy
        # analytic per-primitive counter (analysis/gc_flops.py): the
        # mfu_analytic numerator — non-null even where cost-model capture
        # fails, cross-checked against it by pva-tpu-graphcheck where not
        self._analytic_flops_per_step: Optional[float] = None

        self.trackers: Optional[TrackerHub] = None
        if cfg.tracking.with_tracking and is_main_process():
            run_name = (
                str(cfg.tracking.logging_dir)
                .replace(".", "").replace("/", "").replace("\\", "")
            )  # reference run-name derivation (run.py:229)
            self.trackers = TrackerHub(cfg.tracking.trackers,
                                       cfg.tracking.logging_dir,
                                       retries=cfg.reliability.tracker_retries)
            self.trackers.start(run_name, cfg.to_dict())

    # --- construction -----------------------------------------------------

    def _build_data(self) -> None:
        cfg = self.cfg
        d = cfg.data
        # bad-sample quarantine sidecar (data/manifest.Quarantine): built
        # for the real-video path when the guard is on; train and val
        # sources share it so a clip that corrupts either split is
        # sidelined for both
        self.quarantine = None
        is_slowfast = cfg.model.name.startswith("slowfast")
        # host-side cast to the compute dtype: halves clip bytes end to end
        # (worker -> shm ring -> host RAM -> HBM). For the supervised models
        # this is value-preserving (they cast inputs to bf16 on device
        # anyway); VideoMAE pretraining is excluded — its regression target
        # is computed in fp32 from the raw clip (videomae.py patchify), so a
        # host cast would quantize the objective itself.
        # "u8" goes further: clips stay raw uint8 through the geometric
        # transforms (4x less than fp32 everywhere host-side and over the
        # host->HBM link) and the jitted step applies the normalize affine
        # in-graph (steps.device_normalize_batch), where XLA fuses it into
        # the first conv. Supervised-only, same pretraining rationale.
        if d.host_cast not in ("auto", "fp32", "u8"):
            raise ValueError(
                f"data.host_cast must be 'auto', 'fp32' or 'u8', "
                f"got {d.host_cast!r}"
            )
        if d.host_cast == "u8" and self.is_pretraining:
            raise ValueError(
                "data.host_cast='u8' is supervised-only: the MAE target is "
                "computed from the raw clip in fp32 (videomae.py patchify)"
            )
        u8 = d.host_cast == "u8"
        bf16 = (cfg.mixed_precision in ("bf16", "fp16")
                and d.host_cast == "auto" and not self.is_pretraining)
        common = dict(
            num_frames=d.num_frames,
            is_slowfast=is_slowfast,
            slowfast_alpha=cfg.model.slowfast_alpha,
            min_short_side_scale=d.min_short_side_scale,
            max_short_side_scale=d.max_short_side_scale,
            crop_size=d.crop_size,
            mean=d.mean,
            std=d.std,
            horizontal_flip_p=d.horizontal_flip_p,
            output_dtype=("uint8" if u8
                          else "bfloat16" if bf16 else "float32"),
        )
        train_tf = make_transform(training=True, **common)
        self._device_normalize = train_tf.device_normalize

        # multi-view eval is supervised-only: the pretrain eval step scores
        # reconstructions clip-by-clip, so a view axis would just crash it
        eval_clips = 1 if self.is_pretraining else d.eval_num_clips
        eval_spatial = 1 if self.is_pretraining else d.eval_num_spatial_crops
        if self.is_pretraining and (d.eval_num_clips > 1
                                    or d.eval_num_spatial_crops > 1):
            main_print("multi-view eval options ignored for self-supervised "
                       "pretraining")
        val_tf = make_transform(training=False,
                                num_spatial_crops=eval_spatial, **common)

        train_manifest = None  # set by the real-video branch (dataplane spec)
        if d.synthetic:
            num_classes = cfg.model.num_classes or 4
            self.train_source = SyntheticClipSource(
                train_tf, num_videos=d.synthetic_num_videos,
                num_classes=num_classes, seed=cfg.seed,
            )
            self.val_source = SyntheticClipSource(
                val_tf, num_videos=max(d.synthetic_num_videos // 4, 4),
                num_classes=num_classes, seed=cfg.seed + 1,
                num_clips=eval_clips,
            )
        elif d.cache_dir:
            from pytorchvideo_accelerate_tpu.data.cache import CachedClipSource

            self.train_source = CachedClipSource(
                os.path.join(d.cache_dir, "train"), train_tf,
                cfg.clip_duration, training=True, seed=cfg.seed,
            )
            self.val_source = CachedClipSource(
                os.path.join(d.cache_dir, "val"), val_tf,
                cfg.clip_duration, training=False, seed=cfg.seed,
                num_clips=eval_clips,
            )
            num_classes = self.train_source.num_classes
        else:
            if cfg.guard.enabled and cfg.guard.quarantine_budget > 0:
                from pytorchvideo_accelerate_tpu.data.manifest import (
                    Quarantine,
                )

                self.quarantine = Quarantine(
                    os.path.join(cfg.checkpoint.output_dir,
                                 "quarantine.json"),
                    budget=cfg.guard.quarantine_budget)
            video_retry_kw = dict(
                decode_retries=cfg.reliability.decode_retries,
                retry_base_delay_s=cfg.reliability.retry_base_delay_s,
                quarantine=self.quarantine,
            )
            if d.train_list or d.val_list:
                if not (d.train_list and d.val_list):
                    raise ValueError(
                        "train_list and val_list must be set together "
                        "(mixing a list split with a scanned split would "
                        "give the two splits different label id spaces)")
                train_manifest = from_list(d.train_list, root=d.data_dir)
                val_manifest = from_list(d.val_list, root=d.data_dir)
                val_max = max(e.label for e in val_manifest.entries)
                if val_max >= train_manifest.num_classes:
                    raise ValueError(
                        f"val_list label {val_max} is outside the train "
                        f"list's class space (num_classes="
                        f"{train_manifest.num_classes}): out-of-range "
                        "labels would silently corrupt eval metrics")
            else:
                train_manifest = scan_directory(
                    os.path.join(d.data_dir, "train"))
                val_manifest = scan_directory(os.path.join(d.data_dir, "val"))
            num_classes = train_manifest.num_classes  # replaces run.py:185
            self.train_source = VideoClipSource(
                train_manifest, train_tf, cfg.clip_duration, training=True,
                seed=cfg.seed, **video_retry_kw,
            )
            self.val_source = VideoClipSource(
                val_manifest, val_tf, cfg.clip_duration, training=False,
                seed=cfg.seed, num_clips=eval_clips, **video_retry_kw,
            )
        self.num_classes = num_classes

        shards = data_shard_count(self.mesh)
        global_batch = d.batch_size * shards  # per-shard batch_size, DP-scaled
        loader_kw = dict(
            seed=cfg.seed,
            num_workers=d.num_workers,
            process_index=jax.process_index(),
            process_count=jax.process_count(),
            transport=d.transport,
        )
        self.train_loader = ClipLoader(
            self.train_source, global_batch,
            accum_steps=cfg.optim.gradient_accumulation_steps,
            shuffle=True, drop_last=True,
            prefetch_batches=d.prefetch_batches, **loader_kw,
        )
        self.val_loader = ClipLoader(
            self.val_source, global_batch, accum_steps=1,
            shuffle=False, drop_last=False,
            prefetch_batches=d.prefetch_batches, **loader_kw,
        )
        # disaggregated data plane (dataplane/; docs/INPUT_PIPELINE.md):
        # when configured, the train loader's decode moves to N worker
        # PROCESSES and a RemoteClipFeed slots in where the local iterator
        # feeds the device prefetcher — same epoch_items contract, same
        # LoaderState in checkpoints, byte-identical batches. The feed
        # records remote quarantine verdicts into the same sidecar.
        self.train_feed = None
        if d.dataplane_workers > 0:
            from pytorchvideo_accelerate_tpu.dataplane import spec as dpspec
            from pytorchvideo_accelerate_tpu.dataplane.feed import (
                RemoteClipFeed,
            )

            if d.cache_dir:
                raise ValueError(
                    "data.dataplane_workers is incompatible with "
                    "data.cache_dir: memmap cache reads are already cheaper "
                    "than the wire — disaggregate the decode path only")
            tspec = {**common, "training": True}
            if d.synthetic:
                src_spec = dpspec.synthetic_spec(
                    tspec, num_videos=d.synthetic_num_videos,
                    num_classes=num_classes, seed=cfg.seed)
            else:
                src_spec = dpspec.video_spec(
                    train_manifest, tspec, clip_duration=cfg.clip_duration,
                    training=True, seed=cfg.seed,
                    decode_retries=cfg.reliability.decode_retries,
                    retry_base_delay_s=cfg.reliability.retry_base_delay_s)
            from pytorchvideo_accelerate_tpu.dataplane.wire import (
                parse_address,
            )

            try:
                listen = parse_address(d.dataplane_listen)
            except ValueError as e:
                raise ValueError(f"--data.dataplane_listen: {e}") from None
            self.train_feed = RemoteClipFeed(
                self.train_loader, src_spec, spawn=d.dataplane_workers,
                listen=listen,
                credits=d.dataplane_credits, quarantine=self.quarantine,
                trace_config={"sample_rate": cfg.obs.trace_sample_rate,
                              "seed": cfg.seed},
            )
            main_print(
                f"dataplane: {d.dataplane_workers} decode worker(s) on "
                f"{self.train_feed.address[0]}:{self.train_feed.address[1]} "
                f"(credits={d.dataplane_credits})")
        # device-side prefetch: the step loops consume pre-placed mesh
        # batches; the H2D copy of batch N+1 overlaps compute of batch N
        # (depth 0 = synchronous placement, the A/B baseline)
        self.train_prefetch = DevicePrefetcher(
            self.train_feed or self.train_loader, self.mesh,
            depth=d.device_prefetch_depth,
            micro_dim=cfg.optim.gradient_accumulation_steps > 1,
            watchdog=self.watchdog, watchdog_name="prefetch_train",
        )
        # the val prefetcher's consumer wait nests inside the "eval" span,
        # so it gets a background-classed name (no double count in sums)
        self.val_prefetch = DevicePrefetcher(
            self.val_loader, self.mesh, depth=d.device_prefetch_depth,
            watchdog=self.watchdog, watchdog_name="prefetch_val",
            wait_name="eval_input_wait", h2d_name="eval_h2d",
        )

    def _build_model_and_steps(self) -> None:
        cfg = self.cfg
        if not cfg.model.num_classes:
            cfg.model.num_classes = self.num_classes
        self.model = create_model(cfg.model, cfg.mixed_precision,
                                  mesh=self.mesh,
                                  pipeline=self.pipeline_plan)
        # eval scores through an UNPIPELINED twin (identical param tree —
        # the plan is a lowering choice, not a param-tree one): eval is
        # forward-only, so there are no stored activations to fit, and the
        # val loader's ragged/padded tail batches need not divide into the
        # plan's microbatches
        self.eval_model = (create_model(cfg.model, cfg.mixed_precision,
                                        mesh=self.mesh)
                           if self.pipeline_plan is not None else self.model)

        spec = model_input_spec(cfg.model, cfg.data)
        import jax.numpy as jnp

        if "slow" in spec:
            sample = (jnp.zeros(spec["slow"]), jnp.zeros(spec["fast"]))
        else:
            sample = jnp.zeros(spec["video"])
        # init through a mesh-free twin on multi-device meshes: the param
        # tree is identical by contract (mesh/pipeline are lowering
        # choices — init values depend only on module structure + rng),
        # and the transformer families' block-boundary sharding
        # constraints would reject the batch-1 init sample on a data>1
        # TRAIN mesh (its leading dim can't divide the data axis — the
        # pipelined-videomae configuration hit this). The CP backends
        # keep the original mesh'd init: they REQUIRE the mesh at
        # construction, and their library-mesh init predates this twin.
        init_model = (create_model(cfg.model, cfg.mixed_precision)
                      if self.mesh.size > 1 and not self._cp
                      else self.model)
        variables = init_model.init(self.rng.init_key(), sample)

        steps_per_epoch = self.train_loader.steps_per_epoch()
        # T_max semantics: optimizer steps over the whole run (run.py:193-195,
        # with the scheduler-x-world quirk consciously fixed — optim.py)
        self.total_steps = max(steps_per_epoch * cfg.optim.num_epochs, 1)
        backbone_filter = getattr(type(self.model), "backbone_param_filter", None)
        self.tx = build_optimizer(
            cfg.optim, self.total_steps,
            backbone_filter=backbone_filter,
            freeze_backbone=cfg.model.freeze_backbone,
        )
        self.lr_schedule = build_lr_schedule(cfg.optim, self.total_steps)

        params = shard_params(self.mesh, variables["params"], tp=self._tp)
        batch_stats = shard_params(self.mesh, variables.get("batch_stats", {}),
                                   tp=self._tp)
        if not 0.0 <= cfg.optim.ema_decay < 1.0:
            raise ValueError(
                f"optim.ema_decay must be in [0, 1), got "
                f"{cfg.optim.ema_decay} (1.0 would freeze the EMA at the "
                "init weights while eval keeps scoring them)")
        self.state = TrainState.create(params, batch_stats, self.tx,
                                       ema=cfg.optim.ema_decay > 0)
        # settle EVERY leaf's layout (step counter, optax counts/momentum)
        # to committed mesh shardings, not just the shard_params-placed
        # params: otherwise the first step returns a differently-placed
        # state and the SECOND step pays a full silent XLA recompile
        # (found by the pva_train_recompiles guard; parallel/sharding.py
        # shard_state)
        self.state = shard_state(self.mesh, self.state, tp=self._tp)
        # pva-tpu-hbm ledger: the settled TrainState IS the trainer's
        # standing device pin (params + optimizer moments + EMA +
        # batch_stats) — measured leaf bytes, not an estimate. Pretrained
        # loading below replaces values in the same tree, so the byte
        # count registered here stays truthful.
        obs_memory.register("train_state", obs_memory.tree_nbytes(self.state))

        if cfg.model.pretrained and not cfg.model.pretrained_path:
            # unlike the reference there is no runtime hub fetch (zero
            # network dependency in the training job) — a converted
            # artifact path is required, so say so instead of silently
            # training from scratch
            logger.warning(
                "--model.pretrained set but --model.pretrained_path empty: "
                "training from scratch. Convert a checkpoint first "
                "(pva-tpu-convert SRC.pth OUT.npz) and pass its path.")
        if cfg.model.pretrained and cfg.model.pretrained_path:
            from pytorchvideo_accelerate_tpu.models.convert import load_pretrained

            merged, report = load_pretrained(
                cfg.model.pretrained_path,
                {"params": self.state.params,
                 "batch_stats": self.state.batch_stats},
                mesh=self.mesh, model=cfg.model.name, tp=self._tp,
            )
            self.state = self.state.replace(
                params=merged["params"], batch_stats=merged["batch_stats"],
                # the EMA must start from the loaded weights, not the
                # discarded random init it was copied from at create()
                ema_params=(jax.tree.map(jnp.copy, merged["params"])
                            if self.state.ema_params is not None else None),
            )
            main_print(
                f"pretrained: loaded {len(report['loaded'])} tensors, "
                f"kept {len(report['kept'])} fresh"
            )
            if report.get("interpolated"):
                main_print("pretrained: pos-embed grid interpolated to this "
                           "run's geometry: "
                           + ", ".join(report["interpolated"]))
            mism = report.get("mismatched", [])
            # head paths by model family: .../head/... (resnet/slowfast,
            # mvit, videomae) or X3D's top-level params/proj — exact
            # anchors only, so e.g. an MViT block's attn/proj mismatch is
            # NOT mistaken for a head swap
            nonhead = [p for p in mism
                       if "/head/" not in p
                       and p not in ("params/proj/kernel", "params/proj/bias")]
            if mism:
                main_print(f"pretrained: {len(mism)} shape-mismatched leaves "
                           "kept fresh (expected for a swapped head): "
                           + ", ".join(mism[:4])
                           + ("..." if len(mism) > 4 else ""))
            if nonhead:
                logger.warning(
                    "pretrained artifact has %d NON-head shape mismatches "
                    "(stale artifact from an older layout? regenerate with "
                    "models.convert): %s",
                    len(nonhead), ", ".join(nonhead[:8]),
                )

        if self.is_pretraining:
            self.train_step = make_pretrain_step(
                self.model, self.tx, self.mesh,
                accum_steps=cfg.optim.gradient_accumulation_steps,
                lr_schedule=self.lr_schedule,
                debug_asserts=cfg.debug_asserts,
                ema_decay=cfg.optim.ema_decay,
                health_metrics=self.obs_on,
                guard_skip=cfg.guard.enabled,
                pipeline=self.pipeline_plan,
            )
            self.eval_step = make_pretrain_eval_step(self.eval_model,
                                                     self.mesh)
        else:
            self.train_step = make_train_step(
                self.model, self.tx, self.mesh,
                accum_steps=cfg.optim.gradient_accumulation_steps,
                label_smoothing=cfg.optim.label_smoothing,
                lr_schedule=self.lr_schedule,
                debug_asserts=cfg.debug_asserts,
                device_normalize=self._device_normalize,
                mixup_alpha=cfg.optim.mixup_alpha,
                cutmix_alpha=cfg.optim.cutmix_alpha,
                ema_decay=cfg.optim.ema_decay,
                health_metrics=self.obs_on,
                guard_skip=cfg.guard.enabled,
                pipeline=self.pipeline_plan,
            )
            self.eval_step = make_eval_step(
                self.eval_model, self.mesh,
                label_smoothing=cfg.optim.label_smoothing,
                device_normalize=self._device_normalize,
            )

    def _capture_step_flops(self, global_batch, gstep: int) -> None:
        """Per-step FLOPs, both sources, once (after the first step so the
        executable cache is warm); feeds the epoch-end MFU line.

        Cost model first (XLA's own count — exact for what actually
        compiled, but capture availability varies by backend/version: the
        reason `mfu` was null on every suspect round), then the analytic
        per-primitive counter (analysis/gc_flops.py — shape arithmetic
        over the jaxpr, available everywhere the step traces). Both are
        stashed; the epoch-end block reports `mfu` from the cost model
        and `mfu_analytic` from the counter with `mfu_source` saying
        which one backs the headline."""
        self._flops_per_step = 0.0
        try:
            compiled = self.train_step.lower(
                self.state, global_batch, self.rng.step_key(gstep)
            ).compile()
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            self._flops_per_step = float(ca.get("flops", 0.0))
        except Exception:  # cost_analysis availability varies by backend
            pass
        self._analytic_flops_per_step = 0.0
        try:
            from pytorchvideo_accelerate_tpu.analysis.graphcheck import (
                analytic_step_flops,
            )

            flops, _caveats = analytic_step_flops(
                self.train_step,
                (self.state, global_batch, self.rng.step_key(gstep)))
            self._analytic_flops_per_step = float(flops)
        except Exception:  # a probe must never kill the training job
            pass

    def register_for_checkpointing(self, name: str, obj) -> None:
        """Add a custom object to every checkpoint (reference
        `accelerator.register_for_checkpointing(lr_scheduler)`, run.py:199).

        `obj` must expose `state_dict() -> dict` (JSON-serializable) and
        `load_state_dict(dict)`; its state is saved with each checkpoint and
        restored on resume, keyed by `name`."""
        if not (callable(getattr(obj, "state_dict", None))
                and callable(getattr(obj, "load_state_dict", None))):
            raise TypeError(
                f"{type(obj).__name__} needs state_dict()/load_state_dict() "
                "methods to be registered for checkpointing"
            )
        self._registered[name] = obj

    # --- resume -----------------------------------------------------------

    def _maybe_resume(self) -> int:
        """Restore state + data position; returns starting epoch."""
        if not (self.cfg.checkpoint.resume_from_checkpoint and self.checkpointer):
            return 0
        latest = self.checkpointer.latest_step()
        if jax.process_count() > 1:
            # the directory scan can race across hosts (a checkpoint landing
            # mid-scan -> host A sees step 200, host B step 100 or none, and
            # the collective restore diverges or hangs): process 0's answer
            # is authoritative for the whole pod (-1 encodes "none")
            from pytorchvideo_accelerate_tpu.parallel.collectives import (
                host_broadcast,
            )

            latest = int(host_broadcast(  # pva: disable=host-sync -- resume-time host collective, once per run before the step loop
                np.int64(-1 if latest is None else latest)))
            latest = None if latest < 0 else latest
        if latest is None:
            if self.cfg.checkpoint.resume_from_checkpoint == "auto":
                main_print("resume=auto: no checkpoint found, starting fresh")
                return 0
            raise FileNotFoundError(
                f"no checkpoint to resume in {self.checkpointer.directory}"
            )
        self.state, extra, step = self.checkpointer.restore(
            self.state, step=latest, mesh=self.mesh, tp=self._tp
        )
        main_print(f"resumed from checkpoint step {step}")
        for name, obj in self._registered.items():
            if name in extra.get("registered", {}):
                obj.load_state_dict(extra["registered"][name])
        data_state = LoaderState.from_dict(extra.get("data_state"))
        # epoch-end checkpoints restart at the next epoch (reference
        # `epoch_{i} -> starting_epoch=i+1`, run.py:218-219); mid-epoch ones
        # fast-forward the loader position (run.py:221-224, but O(1))
        self.train_loader.state = data_state
        return data_state.epoch

    def export_inference(self, path: str) -> str:
        """Write the params-only (EMA-resolved) serving artifact for the
        CURRENT in-memory state — the checkpoint-to-endpoint handoff
        (trainer/checkpoint.export_inference; serve it with
        `pva-tpu-serve --serve.checkpoint PATH`). With
        `--serve.quantization int8` the artifact is baked int8 at export
        (per-channel absmax; docs/SERVING.md § quantization)."""
        from pytorchvideo_accelerate_tpu.trainer.checkpoint import (
            export_inference,
        )

        return export_inference(
            path, self.state, config=self.cfg,
            meta={"num_classes": self.num_classes,
                  "model": self.cfg.model.name},
            quantization=self.cfg.serve.quantization,
        )

    def close(self) -> None:
        """Release loaders/checkpointer/trackers without running fit()
        (export-only and aborted constructions)."""
        if self.trackers:
            self.trackers.finish()
            self.trackers = None
        if self.checkpointer is not None:
            self.checkpointer.close()
            self.checkpointer = None
        if self.train_guard is not None:
            self.train_guard.close()
        if self.watchdog is not None:
            uninstall_collective_watch()
            self.watchdog.stop()
            self.watchdog = None
        if self.train_feed is not None:
            self.train_feed.close()
        self.train_loader.close()
        self.val_loader.close()

    # --- fit ----------------------------------------------------------------

    def _save(self, kind: str, epoch: int) -> None:
        if self.checkpointer is None:
            return
        with obs.span("ckpt"):
            self._save_inner(kind, epoch)

    def _save_inner(self, kind: str, epoch: int) -> None:
        self.checkpointer.save(
            int(self.state.step),  # pva: disable=host-sync -- checkpoint save is a deliberate sync point (inside the "ckpt" span)
            self.state,
            {
                "kind": kind,
                "epoch": epoch,
                "data_state": self.train_loader.state.to_dict(),
                "num_classes": self.num_classes,
                "model": self.cfg.model.name,
                "registered": {n: o.state_dict()
                               for n, o in self._registered.items()},
            },
        )

    def _emergency_save(self, epoch: int, reason: str = "") -> None:
        """Preemption grace (reliability/preemption.py): persist the exact
        consumed position — an orbax checkpoint (kind "preempt", the
        loader's consumed position in `extra`) plus the atomic
        `emergency_checkpoint.json` breadcrumb — and dump the flight ring.
        Creates a checkpointer on demand when checkpointing was off: a
        preempted run must still be resumable with `resume=auto`."""
        cfg = self.cfg
        if self.checkpointer is None:
            self.checkpointer = Checkpointer(
                os.path.join(cfg.checkpoint.output_dir, "checkpoints"),
                max_to_keep=cfg.checkpoint.max_to_keep,
                use_async=False, retries=cfg.reliability.ckpt_retries,
                retry_base_delay_s=cfg.reliability.retry_base_delay_s,
                retry_max_delay_s=cfg.reliability.retry_max_delay_s,
                retry_deadline_s=cfg.reliability.retry_deadline_s,
            )
        step = int(self.state.step)  # pva: disable=host-sync -- preemption exit path, the run is over
        if self.checkpointer.latest_step() != step:
            # != instead of unconditional: a checkpointing_steps boundary
            # may have saved this very step already (orbax refuses a
            # duplicate step; the data is on disk either way)
            self._save("preempt", epoch)
        self.checkpointer.wait()  # ON DISK before the process may exit
        record_emergency(cfg.checkpoint.output_dir, step=step, epoch=epoch,
                         checkpoint_dir=self.checkpointer.directory,
                         reason=reason)
        if self.obs_on:
            recorder = obs.get_recorder()
            recorder.record("preempt", "emergency checkpoint saved",
                            step=step, epoch=epoch)
            recorder.dump()
        main_print(
            f"preempted ({reason or 'requested'}): emergency checkpoint at "
            f"step {step}; resume with --resume_from_checkpoint auto")

    def _guard_rollback(self, action) -> None:
        """Execute a TrainGuard rollback verdict: restore the last-known-
        good state through the mesh-portable restore path and fast-forward
        the loader PAST the offending span (the anomalous batch's consumed
        `LoaderState` — replaying the same span into the same divergence
        would be a rollback loop by construction)."""
        state, step = self.train_guard.restore(self.state, action)
        self.state = state
        self.train_loader.state = LoaderState.from_dict(
            action.resume_position)
        if self.obs_on:
            obs.get_recorder().record(
                "guard", "rollback", lkg_step=step,
                resume=dict(action.resume_position), reason=action.reason)
        main_print(
            f"guard: rolled back to last-known-good step {step} "
            f"({action.reason}); loader fast-forwarded to epoch "
            f"{self.train_loader.state.epoch} position "
            f"{self.train_loader.state.position}"
            + (f"; replay bundle: {action.bundle_path}"
               if action.bundle_path else ""))

    def _run_eval(self, epoch: int) -> tuple:
        """One pass over the val loader with in-graph masked metric sums
        (shared by fit()'s per-epoch eval and evaluate());
        returns (top1, top5, mean_loss)."""
        val = SumMetrics()
        # from_start: eval is stateless — a prior early-broken pass (e.g.
        # limit_val_batches) must not make this one resume mid-epoch.
        # Batches arrive pre-placed on the mesh (device prefetch), so the
        # eval H2D transfers overlap eval compute the same way training's do.
        with obs.span("eval"):
            for step_in_epoch, batch in enumerate(
                    self.val_prefetch.epoch(epoch, from_start=True)):
                if self.watchdog is not None:
                    self.watchdog.heartbeat("train")
                val.update(self.eval_step(self.state, batch))
                if 0 <= self.cfg.data.limit_val_batches <= step_in_epoch + 1:
                    break
        return val.accuracy(), val.accuracy_top5(), val.mean_loss()

    def evaluate(self) -> dict:
        """Run the validation loop once, without training — for scoring a
        resumed or converted checkpoint (`--resume_from_checkpoint` /
        `--model.pretrained_path` decide the weights). Same in-graph masked
        metrics as fit()'s epoch eval; logs to the configured trackers and
        closes loaders/trackers before returning."""
        if not (self.cfg.checkpoint.resume_from_checkpoint
                or (self.cfg.model.pretrained
                    and self.cfg.model.pretrained_path)):
            logger.warning(
                "evaluate() without --resume_from_checkpoint or "
                "--model.pretrained_path: scoring freshly-initialized "
                "random weights — the result is meaningless.")
        try:
            if self.watchdog is not None:
                self.watchdog.start()  # re-arm after a prior fit()
                self.watchdog.heartbeat("train")
            self._maybe_resume()
            acc, acc5, loss = self._run_eval(epoch=0)
            if self.is_pretraining:
                result = {"val_recon_loss": loss}
                main_print(f"evaluate: val_recon_loss={loss:.4f}")
            else:
                result = {"val_accuracy": acc, "val_accuracy_top5": acc5,
                          "val_loss": loss}
                main_print(f"evaluate: val_acc={acc:.4f} val_acc5={acc5:.4f}")
            if self.trackers:
                self.trackers.log(result, step=int(self.state.step))  # pva: disable=host-sync -- evaluate() exit; metrics already drained this value's step
            return result
        finally:
            if self.trackers:
                self.trackers.finish()
            if self.checkpointer is not None:
                self.checkpointer.close()
            if self.watchdog is not None:
                self.watchdog.stop()
            if self.train_feed is not None:
                self.train_feed.close()
            self.train_loader.close()
            self.val_loader.close()

    def _obs_on_flush(self):
        """DeferredStepLogger hook mirroring the logged step metrics into
        the metric registry (obs/registry): grad/param-norm and
        update-ratio gauges plus a non-finite-loss counter. Sampled at
        log_every — a per-step host check would sync the async pipeline."""
        if not self.obs_on:
            return None
        reg = obs.get_registry()
        g_grad = reg.gauge("pva_train_grad_norm",
                           "global gradient norm (sampled at log_every)")
        g_param = reg.gauge("pva_train_param_norm",
                            "global parameter norm (sampled at log_every)")
        g_ratio = reg.gauge("pva_train_update_ratio",
                            "update-norm / param-norm (sampled at log_every)")
        c_nonfinite = reg.counter(
            "pva_train_nonfinite_loss_total",
            "non-finite loss values observed at log_every sampling")

        def on_flush(vals: Dict[str, float], step: int) -> None:
            if "grad_norm" in vals:
                g_grad.set(vals["grad_norm"])
            if "obs/param_norm" in vals:
                g_param.set(vals["obs/param_norm"])
            if "obs/update_ratio" in vals:
                g_ratio.set(vals["obs/update_ratio"])
            if vals.get("obs/nonfinite"):
                c_nonfinite.inc()
                obs.get_recorder().warn("non-finite loss", step=step)

        return on_flush

    def fit(self) -> dict:
        cfg = self.cfg
        starting_epoch = self._maybe_resume()
        steps_per_epoch = self.train_loader.steps_per_epoch()
        # host-side mirror of state.step: reading the device scalar
        # (int/float) every step would block on the step's result before
        # dispatching the next one, killing async-dispatch pipelining
        # (VERDICT r2 weak #4) — metrics are only fetched every `log_every`.
        # The ONE fetch here (fit() start, pre-loop) also seeds the progress
        # bar; it used to be fetched twice (pva-tpu-lint host-sync).
        gstep = int(self.state.step)  # pva: disable=host-sync -- one fetch at fit() start, before the step loop exists
        use_tqdm = is_main_process()
        if use_tqdm:
            from tqdm.auto import tqdm

            progress = tqdm(total=cfg.optim.num_epochs * steps_per_epoch,
                            initial=gstep)
        last_val_acc, last_train_loss = 0.0, float("nan")
        last_val_acc5, last_val_loss = 0.0, float("nan")
        last_perf: Dict[str, float] = {}
        # provenance labels are STRINGS: they ride fit()'s return dict
        # only, never last_perf — the trackers coerce values to float
        last_mfu_labels: Dict[str, str] = {}
        # train-section wall time per epoch (excludes eval/ckpt; epoch 0
        # includes compile) — lets benchmarks measure steady-state throughput
        epoch_train_times = []

        profiling = False
        # profile window is relative to THIS run's first step, so resumed
        # runs (gstep >> 0) still capture a trace
        run_start_step = gstep
        metrics = None
        # metric logging is one step delayed: the fetch happens after the
        # NEXT step has been dispatched, so logging never syncs the step
        # just dispatched (the old float(metrics["loss"]) blocked dispatch
        # at every log_every boundary)
        deferred = (DeferredStepLogger(self.trackers,
                                       on_flush=self._obs_on_flush())
                    if self.trackers else None)
        # steady-state recompile guard (analysis/recompile_guard): armed
        # after the first step of THIS run (the legitimate compile), then
        # any jit-cache growth is a mid-training XLA compile stall. Sampled
        # at every log_every boundary + epoch end into the
        # `pva_train_recompiles` gauge; fit() reports the count as
        # `train_recompiles` and bench.py --smoke asserts it stays 0 —
        # the runtime teeth behind pva-tpu-lint's static `recompile` rule.
        recompile_guard = RecompileGuard(self.train_step)
        # obs window accounting: the collector aggregates named spans; every
        # log_every boundary drains them into a per-window step-time
        # breakdown (obs/step_s, obs/input_wait_s, ...) logged through the
        # trackers, and epoch_spans carries the epoch totals for the perf
        # dict (obs_step_s / obs_input_wait_frac / obs_h2d_s — the numbers
        # bench.py reports on its headline line)
        collector = obs.get_collector() if self.obs_on else None
        epoch_spans: Dict[str, float] = {}

        def drain_spans(log_step=None, window_wall=None):
            if collector is None:
                return
            window = collector.pop_window()
            for name, (total, _count) in window.items():
                epoch_spans[name] = epoch_spans.get(name, 0.0) + total
            if log_step is None or not self.trackers or not window:
                return
            vals = {f"obs/{n}_s": t for n, (t, _c) in window.items()}
            if window_wall is not None:
                # consumer-side spans account the step loop's wall time;
                # background spans (h2d/decode) overlap it on worker
                # threads and are reported, not summed
                consumer = sum(t for n, (t, _c) in window.items()
                               if n not in obs.BACKGROUND_SPANS)
                vals["obs/window_wall_s"] = window_wall
                vals["obs/unattributed_s"] = window_wall - consumer
            self.trackers.log(vals, step=log_step)

        if collector is not None:
            collector.pop_window()  # init/resume spans: not this window's
        if self.watchdog is not None:
            self.watchdog.start()  # re-arm after a prior fit/evaluate
            self.watchdog.heartbeat("train")
        # preemption grace (reliability/preemption.py): SIGTERM/SIGINT set
        # an Event; the step loop polls it once per step (no locks, no
        # syncs) and exits through the emergency-save path below. NOTE the
        # semantics change vs PR 3: with the guard installed, the first
        # signal no longer falls through to the flight recorder's re-raise
        # death — it drains gracefully; a second signal still kills.
        guard = get_guard() if cfg.reliability.graceful_shutdown else None
        if guard is not None:
            guard.install()
        preempted = False
        # self-healing guard (reliability/guard.py): observed one step
        # behind dispatch (the deferred-fetch discipline), escalating
        # skip -> rollback-to-LKG -> GuardHalt; None = one check per step
        tguard = self.train_guard
        hang_watch = self.watchdog  # collective-hang attribution source
        host_tag = hangcheck_host_tag() if hang_watch is not None else ""
        if hang_watch is not None and self.pipeline_plan is not None:
            # pipelined layout: the step's stage-boundary collectives
            # (ppermute rotations, the stage-output reduce) are what a
            # wedged dispatch is actually stuck in, so the section detail
            # names the stage slice this host computes — the dump then
            # reads "stage i/P" before the external kill
            host_tag = (f"{host_tag} "
                        f"stage={stage_tag(self.mesh)}").strip()
        # distributed tracing: hoisted armed check — disarmed, the step
        # loop pays one bool test per step (obs.trace.NOOP is shared)
        traced = obs.trace.get_tracer() is not None
        # per-stage span attribution (pipelined runs): sampled train_step
        # trace roots carry the stage slice this process computes, so a
        # merged multi-host timeline separates stage timing without any
        # extra span nesting (nested consumer spans would double-count in
        # the window sum-to-wall contract)
        trace_tags = ({"stage": stage_tag(self.mesh)}
                      if self.pipeline_plan is not None else {})
        window_t0 = time.perf_counter()
        try:
            # while (not for): a guard rollback restores an EARLIER
            # (state, loader) position mid-epoch and re-enters the same —
            # or a previous — epoch from the fast-forwarded position
            epoch = starting_epoch
            while epoch < cfg.optim.num_epochs:
                if use_tqdm:
                    progress.set_description_str(f"Epoch: {epoch}")
                epoch_loss = MeanLoss()
                t_epoch = time.time()
                train_steps_this_epoch = 0
                rolled_back = False
                self.train_prefetch.pop_wait()  # epoch-scoped accounting
                # discard inter-epoch spans (epoch-end ckpt save, teardown):
                # they precede this epoch's first window and would otherwise
                # surface as a negative obs/unattributed_s in it
                drain_spans()
                epoch_spans.clear()
                window_t0 = time.perf_counter()

                # batches arrive pre-placed on the mesh: the device prefetch
                # thread overlaps the H2D copy of batch N+1 with compute of
                # batch N, so steady-state steps never block on the host link
                for step_in_epoch, global_batch in enumerate(
                        self.train_prefetch.epoch(epoch)):
                    if self.watchdog is not None:
                        self.watchdog.heartbeat("train")
                    if (cfg.profile and not profiling
                            and gstep - run_start_step == 2):
                        jax.profiler.start_trace(cfg.profile_dir)
                        profiling = True
                    # --obs.profile_steps A..B: run-relative capture window,
                    # published atomically as <output_dir>/profile_<tag>/
                    # (obs/profiler.py). Independent of cfg.profile above.
                    if (self.profile_steps is not None
                            and gstep - run_start_step
                            == self.profile_steps[0]):
                        prof = obs.profiler.get_profiler()
                        if prof is not None and not prof.busy:
                            prof.start(tag=f"steps_{self.profile_steps[0]}_"
                                           f"{self.profile_steps[1]}")
                    # chaos hook: "delay" = a slow dispatch, "raise" = a
                    # failing one, "nan" = poison the dispatched batch
                    # (the numeric divergence the guard ladder recovers
                    # from). Disarmed: one global read.
                    if fault_point("step.dispatch") == "nan":
                        global_batch = poison_batch(global_batch)
                    # "step" span = dispatch time; under async dispatch it
                    # absorbs compute only when the dispatch queue pushes
                    # back (or at compile), which is exactly the reading
                    # that matters for the per-window breakdown. With a
                    # watchdog live, STEADY-STATE dispatches also run
                    # inside an attributed "collective" section: queue
                    # push-back from a wedged mesh collective then dumps
                    # per-host evidence instead of anonymous silence. The
                    # first dispatch (the legitimate minutes-long XLA
                    # compile) is deliberately unwatched — attributing it
                    # would be the exact wedged-collective misverdict this
                    # detector exists to prevent; any LATER slow dispatch
                    # is either a real wedge or a recompile the
                    # recompile guard flags anyway.
                    # sampled steps become trace roots tagged (epoch,
                    # gstep) — the same coordinates the profiler's
                    # StepTraceAnnotation window carries, so a merged
                    # timeline and an XLA trace correlate by gstep
                    with (obs.trace.root("train_step", epoch=epoch,
                                         gstep=gstep, **trace_tags)
                          if traced else nullcontext()):
                        with (hang_watch.section(
                                "collective",
                                f"step_dispatch {host_tag} gstep={gstep}")
                              if hang_watch is not None
                              and recompile_guard.armed else nullcontext()):
                            with obs.span("step"):
                                with jax.profiler.StepTraceAnnotation(
                                        "train", step_num=gstep):
                                    self.state, metrics = self.train_step(
                                        self.state, global_batch,
                                        self.rng.step_key(gstep)
                                    )
                    gstep += 1
                    train_steps_this_epoch += 1
                    if not recompile_guard.armed:
                        # the first dispatch has returned, so its trace +
                        # compile are done: everything past this baseline
                        # is a steady-state recompile
                        recompile_guard.arm()
                    if deferred is not None:
                        # previous boundary's metrics: their step has retired
                        # behind the one just dispatched, so this fetch
                        # doesn't stall the pipeline
                        with obs.span("log"):
                            deferred.flush()
                    if tguard is not None:
                        # observe the PREVIOUS step's metrics (retired
                        # behind the dispatch above — never a pipeline
                        # stall) and stash this one; a rollback verdict
                        # breaks out, GuardHalt raises through
                        action = tguard.step(
                            gstep, metrics, global_batch,
                            self.train_loader.state, self.state)
                        if action is not None:
                            self._guard_rollback(action)
                            rolled_back = True
                            break
                    if self._flops_per_step is None:
                        # unconditional (not tracking-gated): fit()'s return
                        # dict and the bench harness both need FLOPs/step
                        with obs.span("compile_probe"):
                            self._capture_step_flops(global_batch, gstep)
                    if profiling and gstep - run_start_step >= 6:
                        jax.profiler.stop_trace()
                        profiling = False
                        main_print(f"profile trace written to {cfg.profile_dir}")
                    if (self.profile_steps is not None
                            and gstep - run_start_step
                            >= self.profile_steps[1]):
                        prof = obs.profiler.get_profiler()
                        if prof is not None and prof.busy:
                            out = prof.stop()
                            if out:
                                main_print(
                                    f"profile window written to {out}")

                    if use_tqdm:
                        progress.update(1)
                    # device scalar; the host->device sync happens at epoch end
                    # (MeanLoss.mean) or at the deferred log_every fetch
                    epoch_loss.update_async(metrics["loss"])
                    if deferred is not None and gstep % cfg.tracking.log_every == 0:
                        vals = {"train_loss_step": metrics["loss"],
                                "lr": metrics["lr"],
                                "grad_norm": metrics["grad_norm"]}
                        if self.obs_on:
                            # on-device health gauges ride the same
                            # deferred fetch (steps.py health_metrics)
                            vals["obs/param_norm"] = metrics["param_norm"]
                            vals["obs/update_ratio"] = metrics["update_ratio"]
                            vals["obs/nonfinite"] = metrics["nonfinite"]
                        deferred.defer(vals, step=gstep)
                    if self.obs_on and gstep % cfg.tracking.log_every == 0:
                        now = time.perf_counter()
                        drain_spans(log_step=gstep,
                                    window_wall=now - window_t0)
                        window_t0 = now
                        recompile_guard.sample()  # refresh the gauge
                        # append a scrape tick to the bounded history ring
                        # (obs/history.py) — the ledger's live gauges land
                        # in the same tick, so hbm series accrue for free
                        hist = obs.history.get_history()
                        if hist is not None:
                            hist.tick()
                    if (isinstance(self.checkpointing_steps, int)
                            and gstep % self.checkpointing_steps == 0):
                        self._save("step", epoch)
                        main_print(f"saved checkpoint at step {gstep}")
                    if guard is not None and guard.requested:
                        # finish-the-step-then-leave: the dispatch above
                        # has returned, so breaking here never abandons an
                        # in-flight optimizer update
                        preempted = True
                        break
                    if 0 <= cfg.data.limit_train_batches <= step_in_epoch + 1:
                        break
                if preempted:
                    # grace path: sync the last step's result, flush the
                    # pending log, persist, and leave — no eval, no
                    # further epochs, exit 0 (resume=auto lands here)
                    if metrics is not None:
                        with obs.span("sync"):
                            fetch_loss(metrics)
                    if deferred is not None:
                        deferred.flush()
                    self._emergency_save(
                        epoch, reason=guard.reason if guard else "")
                    break
                if metrics is not None and not rolled_back:
                    # value-fetch sync, never block_until_ready (acked
                    # early by forwarding backends — would end the epoch
                    # timer with work still queued; bench_setup.fetch_loss).
                    # Watched: a straggler host wedges HERE, so the hang
                    # detector attributes the fetch per host.
                    with obs.span("sync"):
                        with collective_section("epoch_sync", step=gstep):
                            fetch_loss(metrics)
                if deferred is not None:
                    with obs.span("log"):
                        deferred.flush()
                if tguard is not None and not rolled_back:
                    # the last step of the epoch is still pending in the
                    # guard; an anomaly there must not slip into the next
                    # epoch's LKG window unobserved
                    action = tguard.flush(self.state,
                                          self.train_loader.state)
                    if action is not None:
                        self._guard_rollback(action)
                        rolled_back = True
                if rolled_back:
                    # resume from the restored LKG: the loader already
                    # points PAST the offending span; restart window/span
                    # accounting so the next epoch's breakdown stays pure
                    gstep = int(self.state.step)  # pva: disable=host-sync -- anomaly-recovery path, once per rollback
                    metrics = None
                    drain_spans()
                    epoch_spans.clear()
                    window_t0 = time.perf_counter()
                    epoch = self.train_loader.state.epoch
                    continue
                epoch_train_times.append(time.time() - t_epoch)
                # time the step loop spent blocked waiting for the next
                # device batch — the number that proves (or disproves) the
                # transfer/compute overlap (input_wait_frac << 1)
                train_wait_s = self.train_prefetch.pop_wait()
                if self.obs_on:
                    # close the train section's residual window before eval
                    # so train windows stay pure (the sum-to-wall property
                    # only holds for windows without overlapping eval spans)
                    now = time.perf_counter()
                    drain_spans(log_step=gstep, window_wall=now - window_t0)
                    window_t0 = now

                # Evaluation (reference run.py:287-304, in-graph metric sums)
                last_val_acc, last_val_acc5, last_val_loss = \
                    self._run_eval(epoch)
                if self.obs_on:
                    # eval window: logged for the timeline (obs/eval_s), no
                    # sum contract (eval nests its own input waits)
                    now = time.perf_counter()
                    drain_spans(log_step=gstep)
                    window_t0 = now
                last_train_loss = epoch_loss.mean()
                val_str = (
                    f"val_recon_loss={last_val_loss:.4f}" if self.is_pretraining
                    else f"val_acc={last_val_acc:.4f} "
                         f"val_acc5={last_val_acc5:.4f}"
                )
                main_print(
                    f"epoch {epoch}: {val_str} "
                    f"train_loss={last_train_loss:.4f} "
                    f"({time.time() - t_epoch:.1f}s)"
                )
                # epoch throughput + (when XLA's cost model is available)
                # achieved TFLOP/s and MFU against the chip's bf16 peak —
                # computed unconditionally so fit()'s return dict carries
                # them even without --with_tracking
                steps_done = train_steps_this_epoch
                t_train = epoch_train_times[-1]
                if t_train > 0 and steps_done > 0:
                    sps = steps_done / t_train
                    last_perf = {
                        "steps_per_sec": sps,
                        "clips_per_sec": (
                            sps * self.train_loader.global_batch_size
                            * self.train_loader.accum_steps
                        ),
                        # fraction of the train section blocked on input:
                        # << 1 = the H2D overlap is real; -> 1 = the input
                        # pipeline (host decode or transfer), not the model,
                        # bounds throughput
                        "input_wait_s": train_wait_s,
                        "input_wait_frac": min(train_wait_s / t_train, 1.0),
                    }
                    # jit-cache growth since the post-first-step baseline;
                    # 0 is the only healthy steady-state reading. None =
                    # probe unavailable (future jax without _cache_size):
                    # the key stays present so consumers see "unknown"
                    # instead of a missing-key failure, and never a lying 0
                    last_perf["train_recompiles"] = recompile_guard.sample()
                    if self.pipeline_plan is not None:
                        # the analytic schedule numbers (the MEASURED
                        # bubble comes from the bench lane's two-point
                        # (M, 2M) timing fit — a single run can't separate
                        # fill/drain idle from per-tick compute)
                        plan = self.pipeline_plan
                        # host ints by construction (PipelinePlan fields)
                        last_perf["pipeline_stages"] = plan.stages
                        last_perf["pipeline_microbatches"] = (
                            plan.microbatches)
                        last_perf["pipeline_bubble_frac_analytic"] = (
                            analytic_bubble_frac(plan.stages,
                                                 plan.microbatches))
                        last_perf["pipeline_cps_per_chip"] = (
                            last_perf["clips_per_sec"] / self.mesh.size)
                        if self.obs_on:
                            obs.get_registry().gauge(
                                "pva_pipeline_bubble_frac",
                                "pipeline fill/drain idle fraction, "
                                "analytic (P-1)/(M+P-1)",
                            ).set(last_perf["pipeline_bubble_frac_analytic"])
                    if tguard is not None:
                        # guard verdicts ride the perf dict -> bench
                        # headline; a clean run asserts both are 0
                        last_perf.update(tguard.perf_keys())
                    if self.obs_on:
                        # the generalized, span-sourced successors of PR 1's
                        # one-off input_wait plumbing — the keys bench.py
                        # reports on its headline line
                        last_perf["obs_step_s"] = (
                            epoch_spans.get("step", 0.0) / steps_done)
                        last_perf["obs_input_wait_frac"] = min(
                            epoch_spans.get("input_wait", 0.0) / t_train, 1.0)
                        last_perf["obs_h2d_s"] = (
                            epoch_spans.get("h2d", 0.0) / steps_done)
                    if (self._flops_per_step
                            or self._analytic_flops_per_step):
                        from pytorchvideo_accelerate_tpu.utils.hw import (
                            resolve_peak,
                        )

                        # per-chip = whole-program FLOPs over the MESH's
                        # device count: flops_per_step is the global cost
                        # of one step, counted once — dividing by the mesh
                        # size attributes it across data AND model shards
                        # without double counting (a mesh smaller than
                        # jax.devices() must not dilute the number either)
                        n_dev = self.mesh.size
                        peak, peak_source = resolve_peak(jax.devices()[0])
                        if self._flops_per_step:
                            tflops = (self._flops_per_step * sps / 1e12
                                      / n_dev)
                            last_perf["tflops_per_sec_per_chip"] = tflops
                            if peak:
                                last_perf["mfu"] = tflops / peak
                        if self._analytic_flops_per_step and peak:
                            # the analytic counter (analysis/gc_flops.py):
                            # available everywhere the step traces, so the
                            # bench can headline a non-null MFU even where
                            # cost-model capture fails (the r03-r05 hole)
                            last_perf["mfu_analytic"] = (
                                self._analytic_flops_per_step * sps
                                / 1e12 / n_dev / peak)
                        if peak:
                            # which FLOPs source backs the MFU story, and
                            # which denominator: a "measured" peak is a
                            # calibrated matmul-rate proxy (utils/hw.py),
                            # never comparable to a datasheet fraction
                            last_mfu_labels = {
                                "mfu_source": (
                                    "costmodel" if self._flops_per_step
                                    else "analytic"),
                                "mfu_peak_source": peak_source,
                            }
                if self.trackers:
                    epoch_metrics = {"train_loss_epoch": last_train_loss,
                                     "epoch": epoch}
                    if self.is_pretraining:
                        epoch_metrics["val_recon_loss"] = last_val_loss
                    else:
                        epoch_metrics["accuracy"] = last_val_acc
                        epoch_metrics["accuracy_top5"] = last_val_acc5
                    epoch_metrics.update(last_perf)
                    self.trackers.log(epoch_metrics, step=epoch)
                if cfg.debug_desync:
                    import optax

                    from pytorchvideo_accelerate_tpu.parallel.distributed import (
                        check_desync,
                    )

                    check_desync(float(optax.global_norm(self.state.params)),  # pva: disable=host-sync -- opt-in --debug_desync path, once per epoch end
                                 name=f"params@epoch{epoch}")
                if self.checkpointing_steps == "epoch":
                    self._save("epoch", epoch)
                epoch += 1

        except BaseException as e:
            # the flight recorder's whole purpose: the recent span/metric
            # timeline survives the crash as <output_dir>/flight_record.json
            # (complementing the partial-profile flush below)
            if self.obs_on:
                recorder = obs.get_recorder()
                recorder.record("exception", type(e).__name__,
                                message=str(e)[:500], step=gstep)
                recorder.dump()  # install(output_dir) set the destination
            raise  # pva: disable=spmd-divergence -- crash path: this host is already dying; surviving hosts wedge ATTRIBUTABLY in their next hangcheck section
        finally:
            # flush a partial trace even when the run dies mid-window —
            # that trace is most valuable exactly when diagnosing a crash
            if profiling:
                jax.profiler.stop_trace()
                main_print(f"profile trace written to {cfg.profile_dir}")
            # likewise an unfinished --obs.profile_steps window: stop() still
            # publishes atomically (partial trace beats no trace on a crash)
            if self.profile_steps is not None:
                prof = obs.profiler.get_profiler()
                if prof is not None and prof.busy:
                    prof.stop()
            # the distributed-trace ring lands next to the flight record
            # (<output_dir>/trace_ring.json) on clean exit AND on a crash;
            # no-op when tracing is disarmed
            obs.trace.dump()
            if self.watchdog is not None:
                self.watchdog.clear("train")
                self.watchdog.stop()
            if guard is not None:
                guard.uninstall()  # restore the pre-fit signal handlers

        if self.trackers:
            self.trackers.finish()
        # final save (reference run.py:325, minus its NameError footgun);
        # a preempted run already persisted this exact step
        if not preempted:
            self._save("final", cfg.optim.num_epochs - 1)
        if self.checkpointer:
            self.checkpointer.close()
        if tguard is not None:
            tguard.close()  # fence the LKG ring's async saves
        if use_tqdm:
            progress.close()
        if self.train_feed is not None:
            self.train_feed.close()
        self.train_loader.close()
        self.val_loader.close()
        result = {"train_loss": last_train_loss, "steps": int(self.state.step),  # pva: disable=host-sync -- fit() exit: training is over, the sync is free
                  "epoch_train_times": epoch_train_times,
                  "flops_per_step": self._flops_per_step,
                  "analytic_flops_per_step": self._analytic_flops_per_step,
                  "preempted": preempted, **last_perf, **last_mfu_labels}
        if self.is_pretraining:
            result["val_recon_loss"] = last_val_loss
        else:
            result["val_accuracy"] = last_val_acc
            result["val_accuracy_top5"] = last_val_acc5
        return result
