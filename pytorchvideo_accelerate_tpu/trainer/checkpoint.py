"""Checkpoint/resume engine (orbax).

TPU-native replacement for accelerate's checkpoint engine (SURVEY §2.2-A8,
§3.6): `save_state`/`load_state` writing model.safetensors / optimizer.bin /
scheduler.bin / scaler.pt / random_states_{rank}.pkl becomes ONE orbax
composite per step: the whole TrainState pytree (params + BN stats + optax
state, sharding-aware, async) plus a JSON `extra` record (epoch, kind,
data-iterator state, config snapshot).

What the reference saves that we deliberately do NOT:
- GradScaler state — no scaler under bf16 (SURVEY §2.3-N7).
- Scheduler object — the LR schedule is a pure function of the step already
  inside `opt_state`.
- Per-process RNG pickles (checkpointing.py:154-179) — all randomness is
  derived from (seed, step/epoch) via fold_in (utils/rng.py), so resume
  re-derives identical streams from the restored step; the data-iterator
  position lives in `extra["data_state"]`.

Naming/resume semantics kept from the reference (run.py:123-133, 203-224):
`checkpointing_steps` = int | "epoch"; a checkpoint knows whether it was an
epoch-end or mid-epoch step save (`extra["kind"]`), and `resume="auto"`
scans for the latest — fixing the unreachable auto-find branch at
run.py:208-212.
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional, Tuple

import jax
import orbax.checkpoint as ocp

from pytorchvideo_accelerate_tpu.parallel.distributed import is_main_process
from pytorchvideo_accelerate_tpu.parallel.hangcheck import collective_section
from pytorchvideo_accelerate_tpu.reliability.atomic import (
    atomic_write,
    atomic_write_json,
)
from pytorchvideo_accelerate_tpu.reliability.retry import retry_call
from pytorchvideo_accelerate_tpu.utils.logging import get_logger

logger = get_logger("pva_tpu")

# serving artifact layout (export_inference/load_inference): a directory of
#   weights.npz  — flat {params/..., batch_stats/...} numpy arrays
#   meta.json    — format tag, step, ema_resolved, num_classes, model name,
#                  and the resolved TrainConfig dict
INFERENCE_FORMAT = "pva-tpu-inference-v1"
_WEIGHTS_FILE = "weights.npz"
_META_FILE = "meta.json"


def export_inference(path: str, state, config=None,
                     meta: Optional[dict] = None,
                     quantization: str = "off") -> str:
    """Write a params-only serving artifact: the checkpoint-to-endpoint
    handoff (serving/engine.py `InferenceEngine.from_artifact`).

    EMA-RESOLVED: when the state carries `ema_params` (--optim.ema_decay),
    those are the weights exported — the same weights `evaluate()` scores —
    so serving top-1 matches eval by construction. BN `batch_stats` ride
    along; optimizer state does NOT (the engine never builds an optimizer,
    and the artifact is a fraction of a full checkpoint's size). Plain-numpy
    npz + JSON: loadable with no orbax and no training stack.

    Both files land ATOMICALLY (tmp + fsync + os.replace, with write
    retries): a kill or disk hiccup mid-export can never leave a truncated
    artifact where a serving engine would find it — the exact failure the
    `ckpt.write` fault point injects in `pva-tpu-chaos`.

    `quantization="int8"` bakes a per-channel-absmax int8 weight artifact
    (serving/quantize.py): 4x smaller on disk and over the hot-swap wire,
    recorded in `meta.quantization` so the engine knows the fp weights no
    longer exist. "off" writes the full-precision artifact unchanged.
    """
    from pytorchvideo_accelerate_tpu.models.convert import save_converted
    from pytorchvideo_accelerate_tpu.serving.quantize import (
        QUANT_MODES,
        quantize_tree,
    )

    if quantization not in QUANT_MODES:
        raise ValueError(
            f"export quantization must be one of {QUANT_MODES}, got "
            f"{quantization!r}")
    # every host participates in the value fetch (device_get of sharded
    # leaves is a collective), but the artifact hits the shared directory
    # from process 0 ONLY — N hosts racing atomic_write on the same path
    # is artifact corruption (spmd-divergence ckpt-discipline)
    params = state.ema_params if state.ema_params is not None else state.params
    tree = jax.device_get({"params": params,
                           "batch_stats": state.batch_stats or {}})
    if quantization == "int8":
        tree["params"], n_q = quantize_tree(tree["params"])
        logger.info("export: quantized %d weight leaves to int8", n_q)
    info = {
        "format": INFERENCE_FORMAT,
        "step": int(jax.device_get(state.step)),
        "ema_resolved": state.ema_params is not None,
        "quantization": quantization,
        **(meta or {}),
    }
    if config is not None:
        info["config"] = config.to_dict()
    if is_main_process():
        os.makedirs(path, exist_ok=True)
        retry_call(
            lambda: atomic_write(os.path.join(path, _WEIGHTS_FILE),
                                 lambda tmp: save_converted(tree, tmp)),
            name="ckpt.write", retry_on=(OSError,))
        retry_call(
            lambda: atomic_write_json(os.path.join(path, _META_FILE), info),
            name="ckpt.write", retry_on=(OSError,))
        logger.info("exported inference artifact to %s (step %d, ema=%s)",
                    path, info["step"], info["ema_resolved"])
    return path


def load_inference(path: str) -> Tuple[dict, dict, dict]:
    """Load an `export_inference` artifact -> (params, batch_stats, meta)."""
    from pytorchvideo_accelerate_tpu.models.convert import load_converted

    meta_path = os.path.join(path, _META_FILE)
    if not os.path.exists(meta_path):
        raise FileNotFoundError(
            f"{path} is not an inference artifact (no {_META_FILE}). "
            "Produce one with Trainer.export_inference / "
            "--export_inference PATH; a full training checkpoint dir "
            "cannot be served directly."
        )
    with open(meta_path) as f:
        meta = json.load(f)
    if meta.get("format") != INFERENCE_FORMAT:
        raise ValueError(
            f"unknown inference artifact format {meta.get('format')!r} "
            f"in {path} (expected {INFERENCE_FORMAT})"
        )
    tree = load_converted(os.path.join(path, _WEIGHTS_FILE))
    return tree["params"], tree["batch_stats"], meta


class Checkpointer:
    """Step-indexed checkpoint manager over `output_dir`.

    Retention (`max_to_keep`) covers accelerate's
    `ProjectConfiguration.total_limit` semantics (accelerator.py:3622-3646);
    async saving overlaps the write with the next train steps and is fenced
    by `wait()`/`close()`.
    """

    def __init__(self, directory: str, max_to_keep: int = 0,
                 use_async: bool = True, retries: int = 3,
                 retry_base_delay_s: float = 0.05,
                 retry_max_delay_s: float = 2.0,
                 retry_deadline_s: float = 30.0):
        self.directory = os.path.abspath(directory)
        # total attempts per save dispatch: transient filesystem failures
        # (ENOSPC races, cold network mounts) retry with backoff instead
        # of killing the run mid-epoch (reliability/retry.py); the shape
        # kwargs mirror --reliability.retry_{base_delay,max_delay,deadline}_s
        self.retries = max(int(retries), 1)
        self.retry_base_delay_s = float(retry_base_delay_s)
        self.retry_max_delay_s = float(retry_max_delay_s)
        self.retry_deadline_s = float(retry_deadline_s)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep if max_to_keep > 0 else None,
            enable_async_checkpointing=use_async,
            create=True,
        )
        self._mgr = ocp.CheckpointManager(self.directory, options=options)

    def save(self, step: int, state: Any, extra: Optional[dict] = None) -> None:
        step = int(step)

        def save_once():
            # orbax's save(step) is NOT idempotent (it refuses a duplicate
            # step), so a retry must first check whether the failed attempt
            # actually committed — otherwise a transient OSError after the
            # commit point would turn attempt 2 into a misleading
            # "step already exists" crash instead of a recovery. (With
            # async checkpointing the dispatch below rarely fails itself —
            # background write errors surface in wait()/close(); the retry
            # mainly protects the sync path, e.g. the emergency save.)
            if step in (self._mgr.all_steps() or ()):
                return
            # the orbax save dispatch is a cross-host barrier (all
            # processes coordinate the composite write): attributed +
            # schedule-recorded like every host-blocking collective
            with collective_section("ckpt_save", step=step):
                self._mgr.save(
                    step,
                    args=ocp.args.Composite(
                        state=ocp.args.StandardSave(state),
                        extra=ocp.args.JsonSave(extra or {}),
                    ),
                )

        retry_call(save_once, name="ckpt.save", attempts=self.retries,
                   retry_on=(OSError,),
                   base_delay_s=self.retry_base_delay_s,
                   max_delay_s=self.retry_max_delay_s,
                   deadline_s=self.retry_deadline_s)

    def restore(
        self, state_template: Any, step: Optional[int] = None, mesh=None,
        tp: bool = True,
    ) -> Tuple[Any, dict, int]:
        """Restore `(state, extra, step)`; `state_template` (a matching
        pytree, e.g. a freshly-initialized TrainState) drives dtypes/shapes.
        Pass `mesh` to restore arrays directly into their mesh placement —
        without it, restored arrays are committed to the default device and
        will clash with mesh-sharded batches inside jit.

        MESH-PORTABLE: the restore target's layout comes from the CURRENT
        mesh, never the one the checkpoint was written under — orbax
        reshards at read time — so a run saved on a (1, N) train mesh
        resumes on (N, 1) or single-chip at the same step with identical
        values (docs/PARALLELISM.md runbook). The pipeline knob rides the
        same contract: a (data, P)-pipelined run's param tree is
        byte-identical to the unpipelined model's (parallel/pipeline.py),
        so it restores unpipelined on any shape — and back — with no
        conversion (tests/test_zpipeline.py round-trip; chaos leg
        preempt_pipeline). When a template leaf is
        already a committed array on this mesh (the trainer's
        freshly-built, shard_state-settled state), its OWN sharding is the
        target — that keeps every leaf bit-identical in layout to the
        no-resume path (per-family tp/cp decisions included), so the first
        post-resume step hits the same executable a fresh run compiles.
        Leaves without a usable sharding (host arrays, foreign-mesh relics)
        fall back to the parallel.sharding rules — `tp` mirrors the
        caller's per-family model-axis decision there, so a fallback can
        never TP-shard params a context-parallel or conv-family run keeps
        replicated."""
        if mesh is not None and state_template is not None:
            from jax.sharding import NamedSharding as _NS

            from pytorchvideo_accelerate_tpu.parallel.sharding import (
                state_sharding_like,
            )
            import jax.numpy as jnp

            computed = state_sharding_like(mesh, state_template, tp=tp)

            def target_sharding(x, fallback):
                s = getattr(x, "sharding", None)
                if isinstance(s, _NS) and s.mesh == mesh:
                    return s
                return fallback

            shardings = jax.tree.map(target_sharding, state_template,
                                     computed)
            state_template = jax.tree.map(
                lambda x, s: jax.ShapeDtypeStruct(
                    jnp.shape(x), jnp.asarray(x).dtype, sharding=s
                ),
                state_template,
                shardings,
            )
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(f"no checkpoint found in {self.directory}")
        # A truncated/partially-deleted step dir (files swept by a quota
        # job, a torn network mount) used to surface as a raw orbax
        # traceback and kill the resume. Instead: walk BACK through older
        # steps — losing the newest interval is recoverable, losing the
        # run is not — warning (+ flight-ring event) per unreadable step,
        # and only error cleanly when no intact step remains.
        candidates = sorted((s for s in (self._mgr.all_steps() or ())
                             if s <= int(step)), reverse=True) or [int(step)]
        if candidates[0] != int(step):
            logger.warning(
                "requested checkpoint step %s not present in %s; trying "
                "latest earlier step %s", step, self.directory,
                candidates[0])
        restored = None
        first_err: Optional[BaseException] = None
        for i, s in enumerate(candidates):
            try:
                with collective_section("ckpt_restore", step=int(s)):
                    restored = self._mgr.restore(
                        int(s),
                        args=ocp.args.Composite(
                            state=ocp.args.StandardRestore(state_template),
                            extra=ocp.args.JsonRestore(),
                        ),
                    )
            except Exception as e:  # noqa: BLE001 - classified below
                first_err = first_err or e
                # The walk-back is a SINGLE-PROCESS recovery: readability
                # is per-host (a torn mount on one host), so on a pod the
                # hosts could pick DIFFERENT fallback steps and wedge in
                # mismatched ckpt_restore collectives. Multi-process, fail
                # loudly instead — the gate is uniform (process_count), so
                # every surviving host raises out of its own restore or
                # times out attributably in the hangcheck section above.
                if i + 1 < len(candidates) and jax.process_count() == 1:
                    logger.warning(
                        "checkpoint step %s in %s is unreadable (%s: %s); "
                        "falling back to step %s",
                        s, self.directory, type(e).__name__,
                        str(e)[:200], candidates[i + 1])
                    try:
                        from pytorchvideo_accelerate_tpu.obs import (
                            get_recorder,
                        )

                        get_recorder().warn(
                            "checkpoint fallback", step=int(s),
                            next_step=int(candidates[i + 1]),
                            error=f"{type(e).__name__}: {e}"[:200])
                    except Exception:  # pragma: no cover - obs optional
                        pass
                    continue  # pva: disable=spmd-divergence -- single-process only: the process_count()==1 gate above is uniform, pods raise instead of walking back
                if isinstance(first_err, (ValueError, KeyError)):
                    # a structure/shape mismatch usually means an older
                    # model layout (0.4 changed videomae_b/mvit_b trees) —
                    # say so instead of the raw orbax error
                    raise RuntimeError(
                        f"checkpoint at {self.directory} step {step} does "
                        "not match the current model's parameter tree. If "
                        "it was written by an older version (<0.4 changed "
                        "videomae_b/mvit_b layouts), re-convert the "
                        "original weights or retrain; see MIGRATING.md "
                        "'Checkpoint layout changes'."
                    ) from first_err
                raise RuntimeError(
                    f"no intact checkpoint step in {self.directory}: "
                    f"step {step} (and every older step) failed to "
                    f"restore; first error: {type(first_err).__name__}: "
                    f"{first_err}"
                ) from first_err
            else:
                step = s
                break
        # Re-materialize every restored leaf into a fresh XLA-owned buffer
        # (.copy() preserves sharding). Orbax hands back arrays backed by
        # tensorstore-owned host memory; with the persistent compilation
        # cache enabled, donating those into the deserialized train step
        # corrupts the heap in the pinned jaxlib ("corrupted double-linked
        # list" / segfault a step or two after resume — reproduced by
        # pva-tpu-chaos's preempt leg, which resumes mid-epoch and trains).
        # One whole-state copy at resume time is noise next to restore IO.
        state = jax.tree.map(
            lambda a: a.copy() if isinstance(a, jax.Array) else a,
            restored["state"],
        )
        return state, dict(restored["extra"] or {}), int(step)

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return sorted(self._mgr.all_steps())

    def delete(self, step: int) -> None:
        """Drop one step from the manager (the TrainGuard's LKG ring
        replaces a revisited step index after a rollback; retention
        pruning itself stays orbax's `max_to_keep`)."""
        self._mgr.delete(int(step))

    def wait(self) -> None:
        with collective_section("ckpt_wait"):
            self._mgr.wait_until_finished()

    def close(self) -> None:
        with collective_section("ckpt_close"):
            self._mgr.wait_until_finished()
            self._mgr.close()


def resolve_resume_path(resume: str, output_dir: str) -> Optional[str]:
    """Map the reference's `--resume_from_checkpoint` forms onto a manager
    directory: "" -> None; "auto" -> output_dir if it has checkpoints; an
    explicit path -> that path (its parent manager dir if a step subdir was
    given, matching the reference's habit of pointing at `step_{i}/`)."""
    if not resume:
        return None
    if resume == "auto":
        return output_dir
    resume = resume.rstrip("/")
    base = os.path.basename(resume)
    if base.isdigit():  # orbax step dir
        return os.path.dirname(resume)
    for prefix in ("step_", "epoch_"):  # reference-style names (run.py:214-224)
        if base.startswith(prefix) and base[len(prefix):].isdigit():
            return os.path.dirname(resume)
    return resume


def resume_step_hint(resume: str) -> Optional[int]:
    """If the user pointed at a specific step/epoch dir, extract the step."""
    base = os.path.basename(resume.rstrip("/"))
    if base.isdigit():
        return int(base)
    if base.startswith("step_") and base[5:].isdigit():
        return int(base[5:])
    return None
